"""Persistent XLA compilation cache + compile-event observability.

Remote-compile latency dominates cold starts on tunneled TPU clients
(~30-60 s per program); the persistent cache turns restarts, resumes, and
repeated bench/eval runs into warm starts (measured with the axon plugin:
41.5 s cold → 3.0 s warm for a single jit). Library code never sets this —
only executables opt in, so embedding applications keep control.

Two distinct persistence layers live here:

- :func:`enable` points JAX's own persistent *compilation* cache (HLO →
  binary, keyed internally by XLA) at a directory — compiles are still
  paid, just faster.
- The **AOT disk tier** (``cfg.compile_cache_dir``; docs/SCALING.md
  "Persistent compile cache") serializes whole compiled executables via
  ``jax.experimental.serialize_executable`` so a warm process *skips the
  compile entirely*: :func:`aot_get` and :func:`observed` check the disk
  tier before building, and a fresh serve replica / re-meshed trainer /
  tune run deserializes in milliseconds what a cold one compiled in
  seconds. Off by default (``compile_cache_dir=""``) the tier costs
  nothing and the compiled programs are byte-identical to a build
  without it (tests/test_compile_cache_disk.py pins step-HLO identity).
  The cache may only ever make things faster — corrupt, stale, or
  fingerprint-mismatched entries fall back to a live compile, never an
  error.

:func:`observed` is the telemetry side (``cfg.obs``;
docs/OBSERVABILITY.md): a jitted step variant wrapped by it AOT-compiles
on its first call under a ``compile`` span (``source=disk|build``), and
the event — variant key, compile wall time, HLO cost-analysis
FLOPs/bytes, and the compiled program's collective accounting — is
reported through the observability registry. With observability off and
the disk tier off nothing here wraps anything: the jitted functions are
called exactly as before, so the off path is untouched.
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
import os
import sys
import threading
import time
from collections import OrderedDict
from pathlib import Path
from typing import Any, Callable, Mapping

from crosscoder_tpu.obs import trace

DISK_FORMAT = 1

# one process compiles, peers deserialize: a loser of the claim race waits
# at most this long for the leader's entry before compiling live anyway
_CLAIM_WAIT_S = float(os.environ.get("CROSSCODER_COMPILE_CACHE_WAIT_S", "120"))
# a claim older than this is a dead leader; stealable
_CLAIM_TTL_S = float(os.environ.get("CROSSCODER_COMPILE_CACHE_CLAIM_TTL_S",
                                    "600"))


def variant_key(metrics: bool, aux: bool, refresh: bool, *,
                enc: str = "dense", tenant: str = "") -> str:
    """Canonical compile-event key for one train-step variant.

    ``(metrics, aux, refresh)`` is the Trainer's compiled-variant cache
    tuple; ``enc`` names the encoder tier actually traced into the
    variant ("dense", "fused", "fused-int8" — cfg.fused_encoder /
    cfg.quant_encoder resolved at build time), so compile telemetry and
    the HLO cost-analysis report distinguish a fused step from a dense
    one instead of aliasing them under one label. ``tenant`` is the
    fleet scheduler's compile-bucket tag (train/fleet.py): a stacked
    cohort or a heterogeneous tenant signature appends its bucket name
    so per-tenant compile events stay distinguishable; solo-trainer
    keys (``tenant=""``) are byte-stable with the pre-fleet format.
    Every writer of a step-variant key goes through here — the single
    place the key format lives.
    """
    tag = f", tenant={tenant}" if tenant else ""
    return (f"train_step(metrics={metrics}, aux={aux}, "
            f"refresh={refresh}, enc={enc}{tag})")


def enable(cache_dir: str | None = None) -> str | None:
    """Point JAX's persistent compilation cache at ``cache_dir``.

    Default: ``$JAX_COMPILE_CACHE`` if set (empty string disables), else
    ``.jax_cache/`` next to the repo root. Returns the directory used, or
    ``None`` when disabled. Safe to call before or after backend init.
    """
    import jax

    if cache_dir is None:
        env = os.environ.get("JAX_COMPILE_CACHE")
        if env == "":
            return None
        cache_dir = env or os.path.join(
            os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__)))),
            ".jax_cache",
        )
    jax.config.update("jax_compilation_cache_dir", cache_dir)
    # cache EVERYTHING: the analysis entry points' first call is dominated
    # by many sub-second compiles (decoder norms, cosines, logit lens —
    # measured ~16 s of a 25 s dashboard first call through the tunnel)
    # that a 1.0 s threshold would silently re-pay in every process
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)
    return cache_dir


# ---------------------------------------------------------------------------
# in-memory memo: bounded LRU + lock


# guards every module-level table below; RLock because record_cost /
# cost_of / the eviction settle re-enter from under it
_LOCK = threading.RLock()

# in-flight builds: key → _Inflight; concurrent same-key callers get
# exactly one build (the serve warmup hammers this from a thread pool)
_INFLIGHT: dict[Any, "_Inflight"] = {}

# bounded LRU of AOT executables (insertion order = recency; hits
# move_to_end). 256 covers every ladder in the repo (8 serve buckets ×
# 2 stages, ≤ 8 step variants, a 32-point tune lattice) with wide margin;
# the bound exists so a pathological caller cannot leak executables.
_AOT_CACHE: "OrderedDict[Any, Any]" = OrderedDict()
_AOT_CACHE_CAP = 256

# key → {"flops": float, "bytes_accessed": float} for every executable
# that passed through here; the autotuner's stage-1 pricing and the
# report tooling query it via cost_of() instead of re-pulling
# cost_analysis() ad hoc
_COST_CACHE: dict[Any, dict[str, float]] = {}

# key → executable whose cost analysis has not been pulled yet: aot_get
# stashes here instead of paying cost_analysis() on the hot compile path
# (it is not free on large programs), and cost_of() settles on demand
_COST_PENDING: dict[Any, Any] = {}

# key → per-collective wire-byte dict parsed from the program's HLO,
# loaded from a disk-tier cost sidecar so tune's stage-1 pricing answers
# without compiling (or even deserializing) anything
_COLLECTIVES: dict[Any, dict[str, float]] = {}


class _Inflight:
    """One in-progress build: the owner resolves, waiters block on it."""

    __slots__ = ("event", "value", "error")

    def __init__(self) -> None:
        self.event = threading.Event()
        self.value: Any = None
        self.error: BaseException | None = None

    def wait(self) -> Any:
        self.event.wait()
        if self.error is not None:
            raise self.error
        return self.value


def _evict_memo_locked() -> None:
    """Drop least-recently-used executables past the cap (_LOCK held).
    A pending cost analysis settles before its executable is dropped so
    cost_of() keeps answering for evicted keys."""
    while len(_AOT_CACHE) > _AOT_CACHE_CAP:
        k, _ = _AOT_CACHE.popitem(last=False)
        exe = _COST_PENDING.pop(k, None)
        if exe is not None and k not in _COST_CACHE:
            _COST_CACHE[k] = extract_cost(exe)


def extract_cost(compiled: Any) -> dict[str, float]:
    """FLOPs / bytes-accessed of a compiled executable, normalized.

    The single place the repo reads ``compiled.cost_analysis()`` — older
    jax returns a list-wrapped dict, newer a bare dict, and either may
    omit keys; callers (obs compile events, the fleet policy's analytic
    ranking, bench's HBM-traffic numbers, the tune lattice) get a plain
    ``{"flops", "bytes_accessed"}`` dict with 0.0 for anything missing.
    Never raises: an executable without cost analysis prices as zeros.
    """
    try:
        cost = compiled.cost_analysis()
        if isinstance(cost, (list, tuple)):    # older jax returns [dict]
            cost = cost[0] if cost else {}
        return {
            "flops": float(cost.get("flops", 0.0) or 0.0),
            "bytes_accessed": float(cost.get("bytes accessed", 0.0) or 0.0),
        }
    except Exception:
        return {"flops": 0.0, "bytes_accessed": 0.0}


def record_cost(key: Any, compiled: Any) -> dict[str, float]:
    """Extract + memoize the cost analysis of ``compiled`` under ``key``
    (tuple AOT keys and string variant keys share one table)."""
    cost = extract_cost(compiled)
    with _LOCK:
        _COST_PENDING.pop(key, None)
        _COST_CACHE[key] = cost
    return cost


def cost_of(key: Any) -> dict[str, float] | None:
    """The memoized HLO cost analysis for a previously compiled variant,
    or ``None`` if nothing under ``key`` has compiled in this process
    AND the disk tier has no cost sidecar for it. Executables stashed
    lazily by :func:`aot_get` settle here on first query (under the
    module lock — safe against the background prewarm threads)."""
    with _LOCK:
        got = _COST_CACHE.get(key)
        if got is None and key in _COST_PENDING:
            got = record_cost(key, _COST_PENDING.pop(key))
    if got is None and _DISK is not None:
        dk = disk_key(key)
        side = _DISK.cost(dk) if dk is not None else None
        if side is not None:
            got = {"flops": float(side.get("flops", 0.0) or 0.0),
                   "bytes_accessed":
                       float(side.get("bytes_accessed", 0.0) or 0.0)}
            with _LOCK:
                _COST_CACHE[key] = got
                if isinstance(side.get("collectives"), dict):
                    _COLLECTIVES[key] = side["collectives"]
    return got


def collectives_of(key: Any) -> dict[str, float] | None:
    """Per-collective wire bytes for ``key`` if a disk-tier cost sidecar
    carried them (stored at build time from the program's HLO text) —
    lets tune's stage-1 pricing skip the HLO parse on warm runs. ``None``
    when unknown; callers fall back to parsing ``compiled.as_text()``."""
    with _LOCK:
        got = _COLLECTIVES.get(key)
    if got is None and _DISK is not None:
        dk = disk_key(key)
        side = _DISK.cost(dk) if dk is not None else None
        if side is not None and isinstance(side.get("collectives"), dict):
            got = side["collectives"]
            with _LOCK:
                _COLLECTIVES[key] = got
    return got


# ---------------------------------------------------------------------------
# disk-tier keying


class _Uncacheable(TypeError):
    """A key component with no stable canonical form (callable, live
    array, ...) — the entry stays memo-only, never wrongly shared."""


def _canon(o: Any) -> str:
    """Deterministic canonical string of a cache-key component.

    Covers everything the repo actually keys on: primitives, nested
    tuples/lists/dicts/sets, config dataclasses (LMConfig,
    CrossCoderConfig), and jax shardings (mesh axis topology + spec —
    never device ids, which differ across processes). Anything else
    raises :class:`_Uncacheable` and the executable stays memo-only —
    an unkeyable entry must never be persisted under a lossy key.
    """
    if o is None or isinstance(o, (bool, int, float, str, bytes)):
        return repr(o)
    if isinstance(o, (tuple, list)):
        return "(" + ",".join(_canon(x) for x in o) + ")"
    if isinstance(o, dict):
        items = sorted(o.items(), key=lambda kv: repr(kv[0]))
        return "{" + ",".join(f"{_canon(k)}:{_canon(v)}" for k, v in items) + "}"
    if isinstance(o, (set, frozenset)):
        return "s{" + ",".join(sorted(_canon(x) for x in o)) + "}"
    if dataclasses.is_dataclass(o) and not isinstance(o, type):
        return type(o).__name__ + _canon(dataclasses.asdict(o))
    mesh = getattr(o, "mesh", None)
    spec = getattr(o, "spec", None)
    if mesh is not None and spec is not None:      # NamedSharding-like
        return f"sharding({sorted(mesh.shape.items())},{spec})"
    raise _Uncacheable(f"no canonical form for {type(o).__name__}")


def backend_fingerprint() -> str:
    """The compile-environment identity a persisted executable is only
    valid under: jax/jaxlib versions, backend platform, and device kind.
    Part of every disk key AND stored in every entry — a version bump or
    hardware change makes old entries unreachable (key changes) and
    unloadable (stored fingerprint check), so stale binaries can never
    run. Deliberately NOT topology (device/process counts): topology is
    its own key component (the caller's mesh scope / aval signature), so
    the remesh prewarm can store entries for a topology this process
    does not have yet. Recomputed per call — a backend reset can change
    the answer mid-process."""
    import jax

    try:
        devs = jax.devices()
        kind = devs[0].device_kind if devs else "none"
        return (f"jax={jax.__version__},jaxlib={_jaxlib_version()},"
                f"backend={jax.default_backend()},device={kind}")
    except Exception:
        return f"jax={jax.__version__},backend=unknown"


def _jaxlib_version() -> str:
    try:
        import jaxlib

        return getattr(jaxlib, "__version__", "?")
    except Exception:
        return "?"


def disk_key(key: Any) -> str | None:
    """Content digest a memo key persists under: sha256 of the canonical
    key string + the backend fingerprint + the disk format version.
    ``None`` when any component has no canonical form — such entries
    stay in-memory only (correct, just not persistent)."""
    try:
        canon = _canon(key)
    except _Uncacheable:
        return None
    blob = f"v{DISK_FORMAT}\x1f{backend_fingerprint()}\x1f{canon}"
    return hashlib.sha256(blob.encode()).hexdigest()


def step_knob_projection(cfg_dict: Mapping[str, Any]) -> dict[str, Any]:
    """The step-program-relevant knob slice of a config dict — exactly
    ``tune.lattice.STEP_FIELDS``, the single source of truth for "which
    knobs change the compiled step". The trainer's disk scope hashes
    this projection, so two configs that differ only in data-plane knobs
    (refill_frac, log cadence, paths) share one disk entry while any
    step-shaping knob forks the key. The ``cache-key-completeness``
    contracts rule mechanically asserts every STEP_FIELDS knob feeds
    :func:`step_digest`."""
    from crosscoder_tpu.tune.lattice import STEP_FIELDS

    return {k: cfg_dict.get(k) for k in sorted(STEP_FIELDS)}


def step_digest(cfg_dict: Mapping[str, Any]) -> str:
    """Hash of :func:`step_knob_projection` — the step-knob component of
    a trainer disk key."""
    proj = step_knob_projection(cfg_dict)
    blob = json.dumps(proj, sort_keys=True, default=str)
    return hashlib.sha256(blob.encode()).hexdigest()[:16]


# ---------------------------------------------------------------------------
# disk tier


def _atomic_write(path: Path, data: bytes) -> None:
    """tmp + rename: readers never observe a torn entry (the
    serve/replica.py board discipline)."""
    tmp = path.with_suffix(path.suffix + f".tmp-{os.getpid()}")
    tmp.write_bytes(data)
    os.replace(tmp, path)


class DiskCache:
    """One directory of serialized AOT executables + cost sidecars.

    Layout (under ``<root>/v{DISK_FORMAT}/``):

    - ``<digest>.exec`` — pickle of ``{format, fingerprint, hlo_sha,
      payload, in_tree, out_tree}`` (``jax.experimental
      .serialize_executable`` triple plus validity metadata)
    - ``<digest>.cost.json`` — normalized HLO cost analysis
      (+ per-collective wire bytes when the HLO parse succeeds), so
      ``cost_of`` answers from disk without deserializing anything
    - ``<digest>.claim`` — compile-leader marker (claim-by-rename;
      exactly one winner, peers deserialize the winner's entry)
    - ``manifest.json`` — versioned advisory index
      ``{version, entries: {digest: {bytes, variant, topology, created,
      last_used}}}`` for the report tooling; eviction trusts the actual
      files, so a lost manifest update can never strand bytes

    Every failure mode — corrupt pickle, stale fingerprint, strict-mode
    HLO mismatch, unserializable executable, full disk — degrades to a
    live compile (a miss), never an error: the cache may only make
    things faster.
    """

    def __init__(self, root: str | os.PathLike, *,
                 max_bytes: int = 1 << 30, registry: Any = None) -> None:
        self.root = Path(root) / f"v{DISK_FORMAT}"
        self.root.mkdir(parents=True, exist_ok=True)
        self.max_bytes = int(max_bytes)
        self.registry = registry
        self.stats = {"disk_hit": 0, "disk_miss": 0, "evictions": 0}
        self._lock = threading.Lock()

    # -- counters --------------------------------------------------------

    def _count(self, what: str) -> None:
        with self._lock:
            self.stats[what] += 1
        if self.registry is not None:
            try:
                self.registry.count(f"compile/{what}")
            except Exception:
                pass

    # -- manifest (advisory; atomic read-modify-write) -------------------

    @property
    def manifest_path(self) -> Path:
        return self.root / "manifest.json"

    def manifest(self) -> dict:
        try:
            man = json.loads(self.manifest_path.read_text())
            if not isinstance(man, dict) or not isinstance(
                    man.get("entries"), dict):
                raise ValueError("ill-typed manifest")
            return man
        except (OSError, ValueError):
            # absent / torn / corrupt: advisory data, start fresh
            return {"version": DISK_FORMAT, "entries": {}}

    def _update_manifest(self, fn: Callable[[dict], None]) -> None:
        with self._lock:
            man = self.manifest()
            try:
                fn(man)
                _atomic_write(self.manifest_path,
                              json.dumps(man, sort_keys=True).encode())
            except OSError:
                pass        # manifest is advisory; the files are the truth

    # -- entries ---------------------------------------------------------

    def _exec_path(self, digest: str) -> Path:
        return self.root / f"{digest}.exec"

    def _cost_path(self, digest: str) -> Path:
        return self.root / f"{digest}.cost.json"

    def _claim_path(self, digest: str) -> Path:
        return self.root / f"{digest}.claim"

    def has(self, digest: str) -> bool:
        """Entry presence without deserializing (prewarm dedup check)."""
        return self._exec_path(digest).exists()

    def _discard(self, digest: str) -> None:
        for p in (self._exec_path(digest), self._cost_path(digest)):
            try:
                p.unlink()
            except OSError:
                pass
        self._update_manifest(lambda m: m["entries"].pop(digest, None))

    def load(self, digest: str, *, lower: Callable[[], Any] | None = None,
             verify: str = "off") -> Any | None:
        """Deserialize the entry under ``digest``, or ``None`` (a miss).

        Validity gates, each a silent fall-back to live compile:
        format/fingerprint mismatch (stale jaxlib, different topology),
        corrupt pickle or failed deserialize (entry discarded), and —
        ``verify="strict"`` — a re-lowering check that the stored
        program's HLO hash matches what ``lower()`` produces live now
        (unverifiable entries miss too, strict means strict).
        """
        path = self._exec_path(digest)
        try:
            blob = path.read_bytes()
        except OSError:
            self._count("disk_miss")
            return None
        try:
            import pickle

            rec = pickle.loads(blob)
            if (not isinstance(rec, dict)
                    or rec.get("format") != DISK_FORMAT
                    or rec.get("fingerprint") != backend_fingerprint()):
                self._count("disk_miss")
                return None
            if verify == "strict":
                stored = rec.get("hlo_sha")
                if stored is None or lower is None:
                    self._count("disk_miss")
                    return None
                live = hashlib.sha256(
                    lower().as_text().encode()).hexdigest()
                if live != stored:
                    print(f"[crosscoder_tpu] compile cache: strict verify "
                          f"REJECTED {digest[:12]} (stored HLO != live "
                          f"lowering); recompiling",
                          file=sys.stderr, flush=True)
                    self._discard(digest)
                    self._count("disk_miss")
                    return None
            from jax.experimental.serialize_executable import \
                deserialize_and_load

            exe = deserialize_and_load(rec["payload"], rec["in_tree"],
                                       rec["out_tree"])
        except Exception:
            # corrupt / undeserializable on this backend: drop it so the
            # next process pays the read even less
            self._discard(digest)
            self._count("disk_miss")
            return None
        self._count("disk_hit")
        now = time.time()
        try:
            os.utime(path, (now, now))      # LRU recency = file mtime
        except OSError:
            pass
        self._update_manifest(
            lambda m: m["entries"].get(digest, {}).__setitem__(
                "last_used", now)
            if digest in m["entries"] else None)
        return exe

    def store(self, digest: str, compiled: Any, *, variant: str = "",
              topology: str = "",
              lower: Callable[[], Any] | None = None) -> bool:
        """Serialize ``compiled`` under ``digest`` + write its cost
        sidecar; returns False (and persists nothing) when the
        executable does not round-trip through
        ``serialize_executable``."""
        try:
            import pickle

            from jax.experimental.serialize_executable import serialize

            payload, in_tree, out_tree = serialize(compiled)
            hlo_sha = None
            try:
                text = (lower().as_text() if lower is not None
                        else compiled.as_text())
                hlo_sha = hashlib.sha256(text.encode()).hexdigest()
            except Exception:
                text = None
            rec = {"format": DISK_FORMAT,
                   "fingerprint": backend_fingerprint(),
                   "hlo_sha": hlo_sha, "payload": payload,
                   "in_tree": in_tree, "out_tree": out_tree}
            blob = pickle.dumps(rec)
            _atomic_write(self._exec_path(digest), blob)
        except Exception as e:
            print(f"[crosscoder_tpu] compile cache: store of "
                  f"{variant or digest[:12]} skipped "
                  f"({type(e).__name__}: {e})"[:300],
                  file=sys.stderr, flush=True)
            return False
        side: dict[str, Any] = extract_cost(compiled)
        try:
            from crosscoder_tpu.parallel import comm_model

            hlo = text if text is not None else compiled.as_text()
            side["collectives"] = comm_model.collective_bytes(hlo)
        except Exception:
            pass
        try:
            _atomic_write(self._cost_path(digest),
                          json.dumps(side, sort_keys=True).encode())
        except (OSError, TypeError, ValueError):
            pass
        now = time.time()

        def _add(man: dict) -> None:
            man["entries"][digest] = {
                "bytes": len(blob), "variant": str(variant)[:120],
                "topology": str(topology)[:120],
                "created": now, "last_used": now,
            }
        self._update_manifest(_add)
        self._evict()
        return True

    def cost(self, digest: str) -> dict[str, Any] | None:
        """The cost sidecar under ``digest`` (no executable touched)."""
        try:
            side = json.loads(self._cost_path(digest).read_text())
            return side if isinstance(side, dict) else None
        except (OSError, ValueError):
            return None

    # -- byte-capped LRU eviction ---------------------------------------

    def _evict(self) -> None:
        """Drop oldest-used entries until total bytes fit
        ``max_bytes``. Recency/size come from the actual ``.exec``
        files (mtime touched on every hit), not the advisory manifest —
        a lost manifest update can never strand bytes on disk."""
        try:
            entries = []
            total = 0
            for p in self.root.glob("*.exec"):
                try:
                    st = p.stat()
                except OSError:
                    continue
                entries.append((st.st_mtime, st.st_size, p))
                total += st.st_size
            entries.sort()
            for _, size, p in entries:
                if total <= self.max_bytes:
                    break
                self._discard(p.name[:-len(".exec")])
                total -= size
                self._count("evictions")
        except OSError:
            pass

    # -- compile-leader claim (exactly one process builds) ---------------

    def claim(self, digest: str) -> bool:
        """Try to become the compile leader for ``digest``: write a tmp
        marker and link it into place — the rename-style atomic create
        of the ReplicaBoard drain protocol, exactly one winner. A claim
        older than the TTL belongs to a dead leader and is stolen."""
        path = self._claim_path(digest)
        tmp = path.with_suffix(f".tmp-{os.getpid()}")
        try:
            tmp.write_text(str(os.getpid()))
            try:
                os.link(tmp, path)
                return True
            except FileExistsError:
                try:
                    if time.time() - path.stat().st_mtime > _CLAIM_TTL_S:
                        os.replace(tmp, path)   # steal the stale claim
                        tmp = None
                        return True
                except OSError:
                    pass
                return False
            except OSError:
                # filesystem without hardlinks: O_EXCL fallback
                try:
                    fd = os.open(path, os.O_CREAT | os.O_EXCL | os.O_WRONLY)
                    os.close(fd)
                    return True
                except OSError:
                    return False
        except OSError:
            return True     # can't coordinate — compile locally, don't wait
        finally:
            if tmp is not None:
                try:
                    tmp.unlink()
                except OSError:
                    pass

    def release(self, digest: str) -> None:
        try:
            self._claim_path(digest).unlink()
        except OSError:
            pass

    def wait_for(self, digest: str, *,
                 lower: Callable[[], Any] | None = None,
                 verify: str = "off",
                 timeout_s: float | None = None) -> Any | None:
        """Claim-race loser path: poll for the leader's entry. Returns
        the deserialized executable, or ``None`` when the leader died
        (claim gone, no entry) or the timeout lapsed — caller compiles
        live (and does NOT store, to avoid stomping the leader)."""
        deadline = time.monotonic() + (
            _CLAIM_WAIT_S if timeout_s is None else timeout_s)
        while time.monotonic() < deadline:
            if self._exec_path(digest).exists():
                return self.load(digest, lower=lower, verify=verify)
            if not self._claim_path(digest).exists():
                return None         # leader gone without publishing
            time.sleep(0.05)
        return None


# ---------------------------------------------------------------------------
# module-level disk-tier state


_DISK: DiskCache | None = None
_VERIFY = "off"


def configure(cfg: Any = None, *, cache_dir: str | None = None,
              max_bytes: int | None = None, verify: str | None = None,
              registry: Any = None) -> DiskCache | None:
    """Point the AOT disk tier at ``cfg.compile_cache_dir`` (or the
    explicit ``cache_dir``; ``$CROSSCODER_COMPILE_CACHE_DIR`` as the
    tooling fallback). Empty directory → tier off (``None``), the
    default — every aot_get/observed path then skips all disk logic.
    Idempotent per directory; re-configuring rebinds the registry and
    byte cap in place so hit/miss counters survive. Called by the
    Trainer, the serve engine, and the tune calibrator on construction.
    """
    global _DISK, _VERIFY
    if cache_dir is None:
        cache_dir = str(getattr(cfg, "compile_cache_dir", "") or "")
    cache_dir = cache_dir or os.environ.get(
        "CROSSCODER_COMPILE_CACHE_DIR", "")
    if verify is None:
        verify = str(getattr(cfg, "compile_cache_verify", "off") or "off")
    if max_bytes is None:
        max_bytes = int(getattr(cfg, "compile_cache_max_bytes", 1 << 30))
    with _LOCK:
        _VERIFY = verify
        if not cache_dir:
            _DISK = None
            return None
        root = Path(cache_dir)
        if _DISK is not None and _DISK.root == root / f"v{DISK_FORMAT}":
            _DISK.max_bytes = int(max_bytes)
            if registry is not None:
                _DISK.registry = registry
            return _DISK
        try:
            _DISK = DiskCache(root, max_bytes=int(max_bytes),
                              registry=registry)
        except OSError as e:
            print(f"[crosscoder_tpu] compile cache: disk tier disabled "
                  f"({cache_dir!r} not usable: {e})",
                  file=sys.stderr, flush=True)
            _DISK = None
        return _DISK


def disk_enabled() -> bool:
    return _DISK is not None


def disk_cache() -> DiskCache | None:
    """The active disk tier, or ``None`` when off — the trainer's remesh
    prewarm stores target-topology entries through it directly."""
    return _DISK


def disk_entry_count() -> int:
    """Number of persisted executables in the active tier (0 when off)."""
    if _DISK is None:
        return 0
    try:
        return sum(1 for _ in _DISK.root.glob("*.exec"))
    except OSError:
        return 0


def _aval_sig(args: Any) -> tuple:
    """Shape/dtype/sharding signature of a call's argument tree — the
    part of an :func:`observed` disk key that the variant label and mesh
    scope do not already pin. Works on concrete arrays and
    ``ShapeDtypeStruct`` avals alike (the prewarm path keys abstractly,
    the live path concretely, and the two must collide)."""
    import jax

    sig = []
    for a in jax.tree_util.tree_leaves(args):
        shard = getattr(a, "sharding", None)
        try:
            s = _canon(shard) if shard is not None else ""
        except _Uncacheable:
            s = ""
        sig.append((tuple(getattr(a, "shape", ())),
                    str(getattr(a, "dtype", "")), s))
    return tuple(sig)


def observed_digest(key: str, disk_scope: Any, example_args: Any) -> str | None:
    """The disk digest an :func:`observed` wrapper for ``(key,
    disk_scope)`` called with ``example_args`` resolves to. The remesh
    prewarm computes this with abstract avals for the TARGET mesh and
    stores under it, so the post-rebuild first step's lookup — same
    label, same scope, equivalent avals — hits the prewarmed entry."""
    return disk_key(("observed", key, disk_scope, _aval_sig(example_args)))


def disk_stats() -> dict[str, int]:
    """Hit/miss/eviction counters of the active disk tier (zeros when
    off) — the bench compile_cache leg and warm-start smoke read these."""
    if _DISK is None:
        return {"disk_hit": 0, "disk_miss": 0, "evictions": 0}
    with _DISK._lock:
        return dict(_DISK.stats)


def _settle_from_disk(key: Any, dk: str | None, exe: Any) -> None:
    """After a disk hit: prime the cost tables from the sidecar so
    cost_of()/collectives_of() answer without touching the executable;
    fall back to lazy settling when no sidecar survived."""
    side = _DISK.cost(dk) if (_DISK is not None and dk) else None
    with _LOCK:
        if key not in _COST_CACHE:
            if side is not None:
                _COST_CACHE[key] = {
                    "flops": float(side.get("flops", 0.0) or 0.0),
                    "bytes_accessed":
                        float(side.get("bytes_accessed", 0.0) or 0.0)}
                if isinstance(side.get("collectives"), dict):
                    _COLLECTIVES[key] = side["collectives"]
            else:
                _COST_PENDING[key] = exe


def _variant_hint(key: Any) -> str:
    """Human-readable manifest label for a memo key."""
    if isinstance(key, tuple) and key and isinstance(key[0], str):
        return key[0]
    return str(key)[:80]


def _disk_acquire(dk: str | None, build: Callable[[], Any], *,
                  lower: Callable[[], Any] | None = None,
                  variant: str = "", topology: str = "",
                  span: Callable[[str], Any] | None = None):
    """The disk-tier acquisition protocol shared by :func:`aot_get` and
    :class:`_ObservedJit`: load → (claim → build+store | wait → load) →
    live build. Returns ``(executable, source)`` with source
    ``"disk" | "build"``. ``span(source)`` (optional) wraps the
    expensive part so the ``compile`` span's source attribute is
    honest."""
    disk = _DISK

    def _run(src: str, fn: Callable[[], Any]) -> Any:
        if span is not None:
            with span(src):
                return fn()
        return fn()

    if disk is None or dk is None:
        return _run("build", build), "build"
    exe = _run("disk", lambda: disk.load(dk, lower=lower, verify=_VERIFY))
    if exe is not None:
        return exe, "disk"
    if disk.claim(dk):
        try:
            exe = _run("build", build)
            disk.store(dk, exe, variant=variant, topology=topology,
                       lower=lower)
            return exe, "build"
        finally:
            disk.release(dk)
    exe = disk.wait_for(dk, lower=lower, verify=_VERIFY)
    if exe is not None:
        return exe, "disk"
    return _run("build", build), "build"    # leader died: compile, no store


# ---------------------------------------------------------------------------
# AOT memo


def aot_get(key: Any, build: Callable[[], Any],
            on_build: Callable[[Any], None] | None = None, *,
            on_load: Callable[[Any], None] | None = None,
            lower: Callable[[], Any] | None = None,
            topology: str = "") -> Any:
    """Process-wide memo of AOT-compiled executables, with an optional
    persistent tier underneath (:func:`configure`).

    ``build()`` must return ``jit_fn.lower(*args).compile()`` for the
    variant ``key`` describes (shapes/dtypes/shardings/statics — the
    caller owns key completeness). Dispatching through the returned
    executable skips the jit call path's tracing/cache machinery — the
    host-cost half of the refill engine's batched dispatch
    (docs/SCALING.md "Zero-bubble refill") — and keeps the donation and
    shardings of the jit it was lowered from: the compiled program is
    byte-identical to what the implicit jit call would have run.

    Thread-safe: the memo is a bounded LRU under a lock, and concurrent
    callers of the same key coalesce onto ONE build (the others block on
    it) — the serve engine's concurrent warmup and the trainer's remesh
    prewarm both hammer this from worker threads.

    ``on_build(key)`` fires only when ``build()`` actually ran — a true
    compile, neither a memo hit nor a disk-tier deserialize. The serve
    engine counts misses through it to assert its
    zero-compiles-after-warmup SLO (docs/SERVING.md): a steady-state
    request that eats a compile is a bucket-ladder bug, not a latency
    outlier. ``on_load(key)`` fires on a disk-tier hit. ``lower()``
    (optional, returns the lowered-but-uncompiled program) enables the
    strict-mode re-verify of disk entries; ``topology`` labels the
    manifest row.
    """
    with _LOCK:
        if key in _AOT_CACHE:
            _AOT_CACHE.move_to_end(key)
            return _AOT_CACHE[key]
        fl = _INFLIGHT.get(key)
        owner = fl is None
        if owner:
            fl = _INFLIGHT[key] = _Inflight()
    if not owner:
        return fl.wait()
    try:
        dk = disk_key(key) if _DISK is not None else None
        exe, src = _disk_acquire(dk, build, lower=lower,
                                 variant=_variant_hint(key),
                                 topology=topology)
    except BaseException as e:
        fl.error = e
        with _LOCK:
            _INFLIGHT.pop(key, None)
        fl.event.set()
        raise
    with _LOCK:
        _AOT_CACHE[key] = exe
        if src == "build":
            _COST_PENDING[key] = exe      # cost_of() settles on demand
        _evict_memo_locked()
        _INFLIGHT.pop(key, None)
    fl.value = exe
    fl.event.set()
    if src == "disk":
        _settle_from_disk(key, dk, exe)
        if on_load is not None:
            on_load(key)
    elif on_build is not None:
        on_build(key)
    return exe


def contracts_check(key: str, lowered: Any) -> None:
    """``CROSSCODER_CONTRACTS`` runtime hook: re-run the textual HLO
    contracts (no-f64, no-host-transfer; ``hlo_rules.check_compiled_text``)
    against the program actually being compiled, not just the variants the
    offline sweep lowers. Off (unset/empty): a single env read, nothing
    imported. ``1``: findings print to stderr. ``strict``: findings raise.
    """
    mode = os.environ.get("CROSSCODER_CONTRACTS", "")
    if mode not in ("1", "strict"):
        return
    try:
        from crosscoder_tpu.analysis.contracts.hlo_rules import \
            check_compiled_text
        findings = check_compiled_text(key, lowered.as_text())
    except Exception as e:  # noqa: BLE001 — the hook must not break compiles
        print(f"[crosscoder_tpu] contracts: runtime check of {key} "
              f"unavailable ({type(e).__name__}: {e})",
              file=sys.stderr, flush=True)
        return
    for f in findings:
        print(f"[crosscoder_tpu] contracts: {f}", file=sys.stderr, flush=True)
    if findings and mode == "strict":
        raise RuntimeError(
            f"CROSSCODER_CONTRACTS=strict: {len(findings)} contract "
            f"violation(s) in compiled program {key!r} (see stderr)")


class _ObservedJit:
    """A jitted callable whose FIRST call resolves the executable —
    from the disk tier when an entry exists (``compile`` span with
    ``source=disk``), else an explicit lower+compile (``source=build``;
    timed, spanned, reported, and persisted when the tier is on); later
    calls hit the compiled executable directly. The build path compiles
    the exact program ``jax.jit`` would have compiled implicitly on that
    same call — same donation, same shardings, same HLO — it only makes
    the compile event *visible*.

    ``obs`` may be ``None`` (disk tier on, observability off): spans go
    through the process-global tracer hook (a no-op by default) and no
    compile event is reported, but the disk tier still serves/saves.
    Any failure in the AOT/report path degrades to calling the wrapped
    jit directly: observability must never be able to break training.
    """

    def __init__(self, jit_fn: Any, key: str, obs: Any, *,
                 disk_scope: Any = None) -> None:
        self._jit_fn = jit_fn
        self._key = key
        self._obs = obs
        self._disk_scope = disk_scope
        self._compiled: Any | None = None

    def __call__(self, *args: Any):
        if self._compiled is not None:
            return self._compiled(*args)
        obs, key = self._obs, self._key
        tracer = obs.tracer if obs is not None else trace
        t0 = time.perf_counter()
        dk = None
        if _DISK is not None and self._disk_scope is not None:
            dk = observed_digest(key, self._disk_scope, args)
        box: dict[str, Any] = {}

        def lower_live():
            if "lowered" not in box:
                box["lowered"] = self._jit_fn.lower(*args)
            return box["lowered"]

        def build():
            return lower_live().compile()

        try:
            exe, src = _disk_acquire(
                dk, build, lower=lower_live, variant=key,
                topology=str(self._disk_scope or ""),
                span=lambda s: tracer.span("compile", variant=key, source=s))
        except Exception as e:
            print(f"[crosscoder_tpu] obs: AOT compile of {key} failed "
                  f"({type(e).__name__}: {e}); falling back to implicit "
                  f"jit compilation (event unreported)",
                  file=sys.stderr, flush=True)
            self._compiled = self._jit_fn
            return self._compiled(*args)
        # outside the try: in strict mode a contract violation must fail
        # the step, not degrade to implicit compilation
        if "lowered" in box:
            contracts_check(key, box["lowered"])
        if src == "build" and obs is not None:
            obs.on_compile(key, exe, time.perf_counter() - t0)
        elif src == "build":
            with _LOCK:
                _COST_PENDING[key] = exe
        else:
            _settle_from_disk(key, dk, exe)
        self._compiled = exe
        return exe(*args)


def observed(jit_fn: Any, key: str, obs: Any, *,
             disk_scope: Any = None) -> _ObservedJit:
    """Wrap a jitted function for compile-event reporting under the
    observability plane (``obs`` is a
    :class:`crosscoder_tpu.obs.Observability`, or ``None`` when only
    the disk tier wants the wrap). ``disk_scope`` scopes the persistent
    key — the trainer passes ``(mesh topology, step-knob projection
    hash)`` so a remeshed or re-knobbed run can never collide with this
    one's entries."""
    return _ObservedJit(jit_fn, key, obs, disk_scope=disk_scope)
