"""Persistent XLA compilation cache for entry points.

Remote-compile latency dominates cold starts on tunneled TPU clients
(~30-60 s per program); the persistent cache turns restarts, resumes, and
repeated bench/eval runs into warm starts (measured with the axon plugin:
41.5 s cold → 3.0 s warm for a single jit). Library code never sets this —
only executables opt in, so embedding applications keep control.
"""

from __future__ import annotations

import os


def enable(cache_dir: str | None = None) -> str | None:
    """Point JAX's persistent compilation cache at ``cache_dir``.

    Default: ``$JAX_COMPILE_CACHE`` if set (empty string disables), else
    ``.jax_cache/`` next to the repo root. Returns the directory used, or
    ``None`` when disabled. Safe to call before or after backend init.
    """
    import jax

    if cache_dir is None:
        env = os.environ.get("JAX_COMPILE_CACHE")
        if env == "":
            return None
        cache_dir = env or os.path.join(
            os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__)))),
            ".jax_cache",
        )
    jax.config.update("jax_compilation_cache_dir", cache_dir)
    # cache EVERYTHING: the analysis entry points' first call is dominated
    # by many sub-second compiles (decoder norms, cosines, logit lens —
    # measured ~16 s of a 25 s dashboard first call through the tunnel)
    # that a 1.0 s threshold would silently re-pay in every process
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)
    return cache_dir
