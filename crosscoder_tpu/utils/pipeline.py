"""Bounded in-flight pipeline driver for device→host streaming loops.

The recurring shape on a TPU host: dispatch device work chunk by chunk,
fetch each result to host — but fetching immediately serializes a device
round trip per chunk, and dispatching everything up front fills HBM with
queued intermediates. The fix everywhere (buffer refresh, norm
calibration, dashboard harvest) is the same bounded FIFO window.
"""

from __future__ import annotations

from typing import Callable, Iterable, TypeVar

T = TypeVar("T")

# chunks kept in flight: device compute overlaps the host fetch/scatter of
# earlier chunks (1 = fully serial)
DEFAULT_DEPTH = 3


def drive(produced: Iterable[T], drain: Callable[[T], None], depth: int = DEFAULT_DEPTH) -> None:
    """Consume ``produced`` (an iterator that DISPATCHES device work as it
    is advanced) keeping at most ``depth`` items in flight, calling
    ``drain`` on each in FIFO order."""
    inflight: list[T] = []
    for item in produced:
        inflight.append(item)
        if len(inflight) >= depth:
            drain(inflight.pop(0))
    for item in inflight:
        drain(item)
