"""Bounded in-flight pipeline driver for device→host streaming loops.

The recurring shape on a TPU host: dispatch device work chunk by chunk,
fetch each result to host — but fetching immediately serializes a device
round trip per chunk, and dispatching everything up front fills HBM with
queued intermediates. The fix everywhere (buffer refresh, norm
calibration, dashboard harvest) is the same bounded FIFO window.
"""

from __future__ import annotations

import contextlib
import threading
from typing import Callable, Iterable, TypeVar

T = TypeVar("T")

# see sharded_program_guard() — reentrant so a guarded serve may trigger
# a guarded refill on the same thread
_XLA_CPU_PROGRAM_LOCK = threading.RLock()


def sharded_program_guard():
    """Serialize collective-bearing program execution on XLA:CPU.

    Two programs with collectives executing concurrently on the same set
    of host devices can deadlock the CPU runtime: each program's
    per-device executions block in a collective rendezvous while
    occupying scheduler threads, starving the other program's remaining
    participants (``collective_ops_utils.h`` "waiting for all participants
    to arrive"). Hardware backends pipeline concurrent programs, so this
    returns a null context off-CPU. Dispatch is asynchronous — releasing
    the lock when the python call returns would not close the race — so
    on CPU a caller must also run :func:`finish_on_cpu` on the program's
    outputs before leaving the block."""
    import jax

    if jax.default_backend() == "cpu":
        return _XLA_CPU_PROGRAM_LOCK
    return contextlib.nullcontext()


def finish_on_cpu(tree) -> None:
    """Block until ``tree``'s arrays have finished computing, on the CPU
    backend only — the execute-to-completion half of
    :func:`sharded_program_guard` (a no-op elsewhere: hardware backends
    keep the async pipeline)."""
    import jax

    if jax.default_backend() == "cpu":
        jax.block_until_ready(tree)

# chunks kept in flight: device compute overlaps the host fetch/scatter of
# earlier chunks (1 = fully serial)
DEFAULT_DEPTH = 3


def drive(produced: Iterable[T], drain: Callable[[T], None], depth: int = DEFAULT_DEPTH) -> None:
    """Consume ``produced`` (an iterator that DISPATCHES device work as it
    is advanced) keeping at most ``depth`` items in flight, calling
    ``drain`` on each in FIFO order."""
    inflight: list[T] = []
    for item in produced:
        inflight.append(item)
        if len(inflight) >= depth:
            drain(inflight.pop(0))
    for item in inflight:
        drain(item)
