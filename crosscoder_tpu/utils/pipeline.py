"""Bounded in-flight pipeline driver for device→host streaming loops.

The recurring shape on a TPU host: dispatch device work chunk by chunk,
fetch each result to host — but fetching immediately serializes a device
round trip per chunk, and dispatching everything up front fills HBM with
queued intermediates. The fix everywhere (buffer refresh, norm
calibration, dashboard harvest) is the same bounded FIFO window.
"""

from __future__ import annotations

import contextlib
import threading
from typing import Callable, Iterable, TypeVar

T = TypeVar("T")

# see sharded_program_guard() — reentrant so a guarded serve may trigger
# a guarded refill on the same thread
_XLA_CPU_PROGRAM_LOCK = threading.RLock()


def sharded_program_guard():
    """Serialize collective-bearing program execution on XLA:CPU.

    Two programs with collectives executing concurrently on the same set
    of host devices can deadlock the CPU runtime: each program's
    per-device executions block in a collective rendezvous while
    occupying scheduler threads, starving the other program's remaining
    participants (``collective_ops_utils.h`` "waiting for all participants
    to arrive"). Hardware backends pipeline concurrent programs, so this
    returns a null context off-CPU. Dispatch is asynchronous — releasing
    the lock when the python call returns would not close the race — so
    on CPU a caller must also run :func:`finish_on_cpu` on the program's
    outputs before leaving the block."""
    import jax

    if jax.default_backend() == "cpu":
        return _XLA_CPU_PROGRAM_LOCK
    return contextlib.nullcontext()


def finish_on_cpu(tree) -> None:
    """Block until ``tree``'s arrays have finished computing, on the CPU
    backend only — the execute-to-completion half of
    :func:`sharded_program_guard` (a no-op elsewhere: hardware backends
    keep the async pipeline)."""
    import jax

    if jax.default_backend() == "cpu":
        jax.block_until_ready(tree)

# chunks kept in flight: device compute overlaps the host fetch/scatter of
# earlier chunks (1 = fully serial)
DEFAULT_DEPTH = 3


def drive(produced: Iterable[T], drain: Callable[[T], None], depth: int = DEFAULT_DEPTH) -> None:
    """Consume ``produced`` (an iterator that DISPATCHES device work as it
    is advanced) keeping at most ``depth`` items in flight, calling
    ``drain`` on each in FIFO order."""
    inflight: list[T] = []
    for item in produced:
        inflight.append(item)
        if len(inflight) >= depth:
            drain(inflight.pop(0))
    for item in inflight:
        drain(item)


class LaunchSequencer:
    """Ticketed program-launch ordering across threads.

    SPMD multi-process meshes require every process to ENQUEUE the same
    collective programs in the same order — two threads racing their
    dispatches resolve differently per host and deadlock the cross-host
    rendezvous (the reason the trainer historically disabled prefetch on
    pods). The fix: every launch site calls :meth:`reserve` on the MAIN
    thread, in program order — identical on every process by SPMD
    construction — and executes its launches under :meth:`turn`, which
    blocks until all earlier tickets have released. Reservation order is
    thereby the pod-wide launch order, regardless of which thread runs
    each launch or when the OS schedules it.

    Single-process runs don't need one (any interleaving is correct
    there); the trainer only builds a sequencer when
    ``multihost.needs_launch_tickets()`` says the mesh spans processes.
    """

    def __init__(self) -> None:
        self._cond = threading.Condition()
        self._next = 0       # next ticket to hand out
        self._head = 0       # lowest ticket not yet released
        self._released: set[int] = set()
        self._invalid = False

    def reserve(self) -> int:
        """Claim the next launch slot (call on the deciding thread, in
        program order)."""
        with self._cond:
            ticket = self._next
            self._next += 1
            return ticket

    @contextlib.contextmanager
    def turn(self, ticket: int):
        """Run a launch under its reserved slot: entry blocks until every
        earlier ticket has released; exit releases this one (also on
        exceptions, so a failed launch never wedges the sequence)."""
        with self._cond:
            while not self._invalid and self._head != ticket:
                self._cond.wait()
        try:
            yield
        finally:
            self.skip(ticket)

    def skip(self, ticket: int) -> None:
        """Release a ticket without running anything under it (a launch
        site that reserved but then bailed — e.g. a failed submit)."""
        with self._cond:
            self._released.add(ticket)
            while self._head in self._released:
                self._released.remove(self._head)
                self._head += 1
            self._cond.notify_all()

    def invalidate(self) -> None:
        """Retire the whole sequence at a mesh-epoch change (elastic
        shrink/grow re-mesh). Tickets reserved before the epoch change
        order launches against a backend that is about to be torn down:
        their ordering no longer means anything, but a ticket that was
        reserved and never released would block every later ``turn`` —
        including the quiesce drain of the old world's in-flight work —
        behind a turn that can never come. After ``invalidate`` every
        outstanding and future ticket passes straight through ``turn``
        (the trainer builds a FRESH sequencer for the new epoch's world,
        so post-remesh ordering starts clean)."""
        with self._cond:
            self._invalid = True
            self._cond.notify_all()


class QuantumDispatcher:
    """Dedicated dispatcher thread for refill harvest quanta.

    The refill engine's host cost is per-dispatch (~6-8 ms through a
    tunneled client); running those dispatches on the train loop's thread
    puts that cost inside the step cadence even when the device work
    overlaps perfectly. This offloads them: the serve path posts CREDIT
    (how many quanta the pacing schedule allows) via :meth:`submit` and
    returns immediately; the daemon thread spends accumulated credit by
    calling ``pump(credit)`` — which must take
    :func:`sharded_program_guard` itself around any program execution.

    :meth:`drain` quiesces: blocks until all posted credit is spent and
    the pump is idle, then re-raises any exception the pump hit (refill
    failures surface on the serve thread at the next cycle boundary, not
    as a dead daemon). Used by the buffer at cycle completion and before
    any state mutation that invalidates in-flight work (restore, forced
    refresh, close).

    FAIRNESS UNDER FAN-OUT (multi-tenant serving, train/fleet.py): extra
    consumers may register their own pumps via :meth:`add_channel` and
    post credit with ``submit(credit, channel=...)``. With one channel
    (every pre-fleet caller) the drain loop keeps the exact historical
    semantics — grab ALL accumulated credit, one pump call. With several,
    it services channels ROUND-ROBIN in bounded chunks of ``quantum``
    credits, so one slow consumer's backlog cannot starve the shared
    refill pump: the refill channel gets a turn after at most
    ``(n_channels - 1) * quantum`` foreign credits, regardless of how
    deep the slow channel's queue runs.
    """

    #: per-turn credit chunk per channel in multi-channel round-robin
    QUANTUM = 4

    def __init__(self, pump: Callable[[int], None], name: str = "refill-dispatch") -> None:
        self._cond = threading.Condition()
        # channel key None is the primary (legacy single-channel) pump
        self._pumps: dict[str | None, Callable[[int], None]] = {None: pump}
        self._credits: dict[str | None, int] = {None: 0}
        self._order: list[str | None] = [None]
        self._rr = 0
        self._busy = False
        self._closed = False
        self._error: BaseException | None = None
        self._thread = threading.Thread(target=self._run, name=name, daemon=True)
        self._thread.start()

    def add_channel(self, name: str, pump: Callable[[int], None]) -> None:
        """Register a named consumer channel with its own pump."""
        with self._cond:
            if self._closed:
                raise RuntimeError("QuantumDispatcher is closed")
            if name is None or name in self._pumps:
                raise ValueError(f"channel {name!r} invalid or already registered")
            self._pumps[name] = pump
            self._credits[name] = 0
            self._order.append(name)

    def _take_locked(self) -> tuple[str | None, int]:
        """Pick the next (channel, credit) to service; caller holds the
        lock and has established that some credit exists."""
        if len(self._order) == 1:
            # single channel: grab-all, exactly the pre-channel behavior
            credit, self._credits[None] = self._credits[None], 0
            return None, credit
        for _ in range(len(self._order)):
            ch = self._order[self._rr % len(self._order)]
            self._rr += 1
            if self._credits[ch] > 0:
                credit = min(self._credits[ch], self.QUANTUM)
                self._credits[ch] -= credit
                return ch, credit
        raise AssertionError("unreachable: credit vanished under the lock")

    def _run(self) -> None:
        while True:
            with self._cond:
                while not any(self._credits.values()) and not self._closed:
                    self._cond.wait()
                if self._closed and not any(self._credits.values()):
                    return
                ch, credit = self._take_locked()
                self._busy = True
            try:
                if self._error is None:
                    self._pumps[ch](credit)
            except BaseException as e:  # noqa: BLE001 — re-raised in drain()
                with self._cond:
                    self._error = e
            finally:
                with self._cond:
                    self._busy = False
                    self._cond.notify_all()

    def submit(self, credit: int, channel: str | None = None) -> None:
        """Post dispatch credit; returns immediately."""
        if credit <= 0:
            return
        with self._cond:
            if self._closed:
                raise RuntimeError("QuantumDispatcher is closed")
            if channel not in self._credits:
                raise ValueError(f"unknown channel {channel!r}")
            self._credits[channel] += credit
            self._cond.notify_all()

    def drain(self) -> None:
        """Block until idle (all credit spent, every channel); re-raise
        any pump error."""
        with self._cond:
            while any(self._credits.values()) or self._busy:
                self._cond.wait()
            if self._error is not None:
                err, self._error = self._error, None
                raise err

    def close(self) -> None:
        """Drain, then stop the thread (idempotent; swallows pump errors —
        close runs in teardown paths where raising would mask the primary
        failure)."""
        with self._cond:
            if self._closed and not self._thread.is_alive():
                return
            self._closed = True
            self._cond.notify_all()
        self._thread.join()
        with self._cond:
            self._error = None
