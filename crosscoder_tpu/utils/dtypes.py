"""Dtype-name mapping, mirroring the reference's DTYPES table
(reference ``crosscoder.py:12``, ``train.py:5``) in JAX terms."""

from __future__ import annotations

import jax.numpy as jnp

DTYPES = {
    "fp32": jnp.float32,
    "fp16": jnp.float16,
    "bf16": jnp.bfloat16,
}


def dtype_of(name: str) -> jnp.dtype:
    try:
        return DTYPES[name]
    except KeyError:
        raise ValueError(f"unknown dtype name {name!r}; expected one of {list(DTYPES)}") from None
