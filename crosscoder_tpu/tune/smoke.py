"""CPU tuner smoke (tier-1): tiny lattice, 2 calibration windows, one
artifact — end to end through the REAL search path.

``python -m crosscoder_tpu.tune.smoke`` runs the full two-stage tune on
a tiny shape (8 valid candidates over 3 data-plane knobs, matching the
ISSUE's nontrivial-lattice floor), asserts the winner's ``TUNED.json``
is produced, reloads it through :func:`~crosscoder_tpu.tune.artifact.
load_tuned` AND :func:`~crosscoder_tpu.tune.artifact.apply_tuned`, and
verifies the applied config carries exactly the pinned knobs. Exit 0 on
success, 1 on any failure — the tier-1 gate shape.
"""

from __future__ import annotations

import os
import sys
import tempfile


def main() -> int:
    from crosscoder_tpu.config import CrossCoderConfig
    from crosscoder_tpu.tune import apply_tuned, load_tuned, tune

    root = os.environ.get("TUNE_SMOKE_DIR") or tempfile.mkdtemp(
        prefix="tune_smoke_")
    cfg = CrossCoderConfig(
        d_in=8, dict_size=32, batch_size=32, enc_dtype="fp32",
        num_tokens=10**9, save_every=10**9, log_backend="null",
        checkpoint_dir=os.path.join(root, "ckpt"),
    )
    axes = {
        "prefetch": (False, True),
        "refill_frac": (0.25, 0.5),
        "refill_dispatch_batch": (4, 8),
    }
    out_path = os.path.join(root, "TUNED.json")
    art = tune(cfg, "train", axes=axes, top_k=2, out_path=out_path,
               steps=3, warmup=1, seed=0)

    if not os.path.exists(out_path):
        print("tune smoke: TUNED.json was not written", file=sys.stderr)
        return 1
    reloaded = load_tuned(out_path)                 # raises if malformed
    if reloaded.knobs != art.knobs:
        print(f"tune smoke: reloaded knobs {reloaded.knobs} != emitted "
              f"{art.knobs}", file=sys.stderr)
        return 1
    applied = apply_tuned(cfg, out_path)
    bad = {k: (getattr(applied, k), v) for k, v in art.knobs.items()
           if getattr(applied, k) != v}
    if bad:
        print(f"tune smoke: applied config disagrees with artifact: {bad}",
              file=sys.stderr)
        return 1
    if art.search["n_candidates"] < 8 or len(art.search["axes"]) < 3:
        print(f"tune smoke: lattice too small "
              f"({art.search['n_candidates']} candidates over "
              f"{len(art.search['axes'])} knobs)", file=sys.stderr)
        return 1
    print(f"tune smoke: OK — {art.search['n_candidates']} candidates, "
          f"winner {sorted(art.knobs.items())}, artifact {out_path}",
          file=sys.stderr)
    return 0


if __name__ == "__main__":
    sys.exit(main())
