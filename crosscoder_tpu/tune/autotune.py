"""The tune driver: lattice → static rank → calibrate top-K → pin winner.

One call — :func:`tune` — runs the whole two-stage search and emits the
pinned ``TUNED.json``. The default-knob candidate (the base config's own
values on every axis) is ALWAYS calibrated alongside the stage-1 top-K:
the winner is chosen on measured score, so a tuned artifact can never
ship knobs that measure worse than what the user already had — the
"tuned ≥ default" gate the bench leg asserts holds by construction.

Search accounting lands in the ``tune/*`` metric namespace
(docs/OBSERVABILITY.md): candidates enumerated/pruned/priced/calibrated,
contract rejections (``tune/rejected_contract``), and the emitted
artifact count — pass a registry to fold them into a run's metric
stream, or let the driver keep a private one.
"""

from __future__ import annotations

import json
import sys
from typing import Any

from crosscoder_tpu.obs.registry import MetricsRegistry
from crosscoder_tpu.tune.artifact import (TunedArtifact, config_hash,
                                          topology_key)
from crosscoder_tpu.tune.calibrate import contracts_gate, measure_window
from crosscoder_tpu.tune.lattice import (Candidate, default_axes,
                                         enumerate_lattice, price_candidate,
                                         rank_candidates)


def _note(msg: str) -> None:
    print(f"[crosscoder_tpu] tune: {msg}", file=sys.stderr, flush=True)


def tune(base_cfg: Any, objective: str = "train", *,
         axes: dict[str, tuple] | None = None, top_k: int = 2,
         out_path: str | None = None, n_devices: int | None = None,
         seed: int = 0, steps: int = 6, warmup: int = 2,
         registry: MetricsRegistry | None = None,
         measure: Any = None, gate: Any = None) -> TunedArtifact:
    """Run the two-stage search and return the pinned artifact.

    ``measure(cfg, steps=, warmup=, n_devices=)`` and ``gate(cfg, knobs=)``
    are injectable (tests rig races and violations through them); the
    defaults are the real :func:`~crosscoder_tpu.tune.calibrate.
    measure_window` / :func:`~crosscoder_tpu.tune.calibrate.
    contracts_gate`. ``out_path`` (when set) receives the artifact via
    the atomic writer. Raises ``ValueError`` when the lattice is empty
    or every calibrated candidate was rejected by the contracts gate.
    """
    import jax

    from crosscoder_tpu.utils import compile_cache

    reg = registry if registry is not None else MetricsRegistry()
    # persistent AOT tier: a re-run of a previously priced lattice
    # answers stage-1 costs from disk sidecars and deserializes the
    # calibration step executables instead of re-compiling them
    compile_cache.configure(base_cfg, registry=reg)
    measure = measure if measure is not None else measure_window
    gate = gate if gate is not None else contracts_gate
    if n_devices is None:
        n_devices = jax.device_count()
    axes = axes if axes is not None else default_axes(base_cfg, objective)

    # -- stage 1: enumerate + static rank -------------------------------
    cands, pruned = enumerate_lattice(base_cfg, axes)
    reg.count("tune/candidates", len(cands))
    if pruned:
        reg.count("tune/pruned_invalid", pruned)
    if not cands:
        raise ValueError(
            f"tune: every lattice point over axes {sorted(axes)} failed "
            f"config validation — nothing to search")
    ranked = rank_candidates(cands, objective, n_devices, seed)
    if not ranked:
        raise ValueError("tune: stage-1 pricing failed for every "
                         "candidate — nothing to calibrate")
    reg.count("tune/priced", len(ranked))
    _note(f"{objective}: {len(ranked)} candidates priced "
          f"({pruned} pruned invalid), calibrating top {top_k}")

    # -- calibration set: stage-1 top-K, plus the default knobs ---------
    to_calibrate = list(ranked[:max(1, top_k)])
    default_knobs = {k: getattr(base_cfg, k) for k in axes}
    if not any(c.knobs == default_knobs for c in to_calibrate):
        existing = next((c for c in ranked if c.knobs == default_knobs),
                        None)
        if existing is not None:
            to_calibrate.append(existing)
        else:
            try:
                dflt = Candidate(knobs=default_knobs, cfg=base_cfg,
                                 base_sig=ranked[0].base_sig)
                price_candidate(dflt, objective, n_devices)
                to_calibrate.append(dflt)
            except Exception as e:  # noqa: BLE001 — baseline is best-effort
                _note(f"default-knob baseline unpriceable "
                      f"({type(e).__name__}: {e}); calibrating top-K only")

    # -- stage 2: contracts gate + measured windows ---------------------
    audit: list[dict[str, Any]] = []
    survivors: list[tuple[Candidate, dict[str, float]]] = []
    n_rejected = 0
    for cand in to_calibrate:
        row = {"knobs": cand.knobs,
               "predicted_score": cand.predicted.get("score")}
        ok, findings = gate(cand.cfg, knobs=cand.knobs)
        if not ok:
            n_rejected += 1
            reg.count("tune/rejected_contract")
            row["gate"] = "rejected"
            row["findings"] = [str(f) for f in findings][:8]
            _note(f"REJECTED by contracts gate: {cand.label} "
                  f"({len(findings)} finding(s): "
                  f"{findings[0] if findings else ''})")
            audit.append(row)
            continue
        row["gate"] = "pass"
        measured = measure(cand.cfg, steps=steps, warmup=warmup,
                           n_devices=n_devices)
        reg.count("tune/calibrated")
        row["measured_score"] = measured.get("score")
        survivors.append((cand, measured))
        audit.append(row)
    if not survivors:
        raise ValueError(
            f"tune: all {len(to_calibrate)} calibrated candidates were "
            f"rejected by the contracts gate — refusing to emit an "
            f"artifact")

    # winner on MEASURED score; exact ties fall back to the stage-1
    # prediction, then the canonical knob JSON (fully deterministic)
    def key(item):
        cand, measured = item
        return (-float(measured.get("score", float("-inf"))),
                -float(cand.score if cand.score is not None
                       else float("-inf")),
                json.dumps(cand.knobs, sort_keys=True, default=str))

    survivors.sort(key=key)
    winner, measured = survivors[0]
    winner_cfg = base_cfg.replace(**winner.knobs)
    n_model = max(1, int(winner_cfg.model_axis_size))
    art = TunedArtifact(
        objective=objective,
        knobs=dict(winner.knobs),
        mesh={"n_devices": int(n_devices), "n_model": n_model,
              "n_data": max(1, int(n_devices) // n_model)},
        predicted=dict(winner.predicted),
        measured=dict(measured),
        gate={"rule_set": "analysis.contracts.hlo_rules",
              "checked": len(to_calibrate), "rejected": n_rejected},
        search={"axes": {k: list(v) for k, v in sorted(axes.items())},
                "n_candidates": len(cands), "n_pruned_invalid": pruned,
                "n_priced": len(ranked), "top_k": int(top_k),
                "seed": int(seed), "calibration_steps": int(steps),
                "topology": topology_key(n_devices, n_model),
                "candidates": audit},
        config_hash=config_hash(winner_cfg),
    )
    reg.count("tune/emitted")
    if out_path:
        art.save(out_path)
        _note(f"winner {winner.label} (measured score "
              f"{measured.get('score'):.4g}) pinned to {out_path}")
    return art
