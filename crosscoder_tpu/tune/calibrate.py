"""Stage-2 autotuner: measured calibration windows + the contracts gate.

Stage 1 ranks on a model; stage 2 believes only what it measures. Each
surviving candidate runs a short window through the REAL Trainer (the
production step, refill engine, prefetch worker — nothing mocked),
scored with the PR-5 telemetry the run would log anyway: the
``perf/step_ms`` span EMA and the refill bubble fraction. Before any
candidate is measured it passes the contracts gate — its step lowering
is checked against the full HLO rule set plus one tune-specific
identity: the candidate must lower byte-identically to its projection
onto :data:`~crosscoder_tpu.tune.lattice.STEP_FIELDS`, the exact
assumption stage-1 pricing used to share one compile across the
data-plane sub-lattice. A candidate that violates any contract is
discarded (``tune/rejected_contract``), never shipped.
"""

from __future__ import annotations

import dataclasses
import tempfile
import time
from typing import Any

from crosscoder_tpu.tune.lattice import STEP_FIELDS

# memo: projection-config JSON → lowered baseline text, so gating a 2^k
# data-plane lattice lowers the shared projection once, not k times
_PROJECTION_TEXTS: dict[str, str] = {}


def _field_defaults(cfg_type) -> dict[str, Any]:
    out: dict[str, Any] = {}
    for f in dataclasses.fields(cfg_type):
        if f.default is not dataclasses.MISSING:
            out[f.name] = f.default
        elif f.default_factory is not dataclasses.MISSING:  # type: ignore
            out[f.name] = f.default_factory()  # type: ignore
    return out


def _step_projection_cfg(cfg: Any, knobs: dict[str, Any]):
    """``cfg`` with every NON-step tuned knob reset to its dataclass
    default (the present-but-off state): the config whose compiled step
    the candidate claimed to share during stage-1 pricing. Step-relevant
    knobs and every untuned field carry over verbatim — fields like
    ``num_tokens`` bake schedule constants into the program and must not
    drift between the pair."""
    defaults = _field_defaults(type(cfg))
    reset = {k: defaults[k] for k in knobs
             if k not in STEP_FIELDS and k in defaults}
    return cfg.replace(**reset)


def contracts_gate(cfg: Any, knobs: dict[str, Any] | None = None
                   ) -> tuple[bool, list]:
    """Run the full HLO contract rule set over one candidate's lowered
    step. With ``knobs`` (the candidate's tuned assignment) the context
    also carries the tune-specific identity pair — candidate vs the same
    config with its data-plane knobs at defaults, the exact assumption
    stage-1 pricing used to share compiles. Returns
    ``(ok, error_findings)``; ``ok`` is False on ANY error-severity
    finding — including a crashed harness, which the engine itself
    converts into a finding (a candidate the gate cannot check is a
    candidate that does not ship)."""
    from crosscoder_tpu.analysis.contracts import hlo_rules
    from crosscoder_tpu.analysis.contracts.engine import run_rules

    ctx = hlo_rules.StepContext()
    text, n_leaves = hlo_rules.lower_step(cfg)
    quant_off = not (cfg.quant_encoder or cfg.quant_grads)
    ctx.texts["tune:candidate"] = text
    ctx.meta["tune:candidate"] = hlo_rules.VariantMeta(
        n_donated_leaves=n_leaves, quant_off=quant_off)
    ctx.jaxpr_consts["tune:candidate"] = hlo_rules.step_jaxpr_consts(cfg)

    proj = _step_projection_cfg(cfg, knobs or {})
    if proj is not cfg and proj.to_dict() != cfg.to_dict():
        import json as _json

        sig = _json.dumps(proj.to_dict(), sort_keys=True, default=str)
        base_text = _PROJECTION_TEXTS.get(sig)
        if base_text is None:
            base_text = _PROJECTION_TEXTS[sig] = (
                hlo_rules.lower_step_text(proj))
        ctx.texts["tune:step_projection"] = base_text
        ctx.meta["tune:step_projection"] = hlo_rules.VariantMeta(
            n_donated_leaves=n_leaves, quant_off=quant_off)
        ctx.jaxpr_consts["tune:step_projection"] = []
        # the stage-1 cost-sharing assumption, checked mechanically: the
        # candidate's data-plane knobs must not change the step program
        ctx.identity_pairs.append(
            ("tune:step_projection", "tune:candidate", "tune-data-plane"))

    report = run_rules(hlo_rules.HLO_RULES, ctx)
    errors = [f for f in report.findings if f.severity == "error"]
    return not errors, errors


def measure_window(cfg: Any, *, steps: int = 6, warmup: int = 2,
                   n_devices: int = 1) -> dict[str, float]:
    """One short calibration window through the real Trainer.

    The window runs with ``obs="on"`` regardless of the candidate's own
    obs setting (the telemetry IS the measurement; obs overhead is flat
    across candidates so the ranking is unbiased) into throwaway
    checkpoint/obs dirs, logging nothing. Scoring: the ``perf/step_ms``
    span EMA over the post-warmup steps, inflated by the measured refill
    bubble — ``effective_ms = step_ms / (1 - bubble)`` — so a candidate
    whose data-plane knobs starve the step loop loses even when its
    device program is fast. Score is acts/s/chip on the effective rate.
    """
    import jax

    from crosscoder_tpu.train.trainer import Trainer

    with tempfile.TemporaryDirectory(prefix="tune_cal_") as tmp:
        run_cfg = cfg.replace(
            obs="on", obs_dir="", checkpoint_dir=tmp, log_backend="null",
            save_every=10**9, num_tokens=10**12,
        )
        tr = Trainer(run_cfg)
        try:
            m = None
            for _ in range(max(1, warmup)):
                m = tr.step(full_metrics=False)
            jax.block_until_ready(m["loss"])
            tr._obs.take_blocked_s()            # reset the bubble clock
            t0 = time.perf_counter()
            for _ in range(max(1, steps)):
                m = tr.step(full_metrics=False)
            jax.block_until_ready(m["loss"])
            wall_s = max(1e-9, time.perf_counter() - t0)
            blocked_s = tr._obs.take_blocked_s()
            snap = tr._obs.registry.snapshot()
        finally:
            tr.close()
    step_ms = float(snap.get("perf/step_ms",
                             1e3 * wall_s / max(1, steps)))
    bubble = min(0.95, max(0.0, blocked_s / wall_s))
    effective_ms = step_ms / (1.0 - bubble)
    score = cfg.batch_size * 1e3 / (effective_ms * max(1, n_devices))
    return {
        "step_ms": step_ms,
        "bubble_frac": bubble,
        "effective_step_ms": effective_ms,
        "acts_per_sec_chip": score,
        "wall_s": wall_s,
        "steps": float(steps),
        "score": score,
    }
