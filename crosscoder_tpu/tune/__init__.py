"""Telemetry-driven autotuner (docs/TUNING.md).

The knob count has grown every PR — refill watermarks and dispatch
batching, quantized-plane block widths, serve bucket ladders, fleet
bucket caps — and every deployment scenario shipped hand-tuned defaults.
This package closes the loop with a two-stage search:

- **Stage 1 (static, no execution)** — :mod:`crosscoder_tpu.tune.lattice`
  enumerates the valid knob lattice straight from ``config.py``'s own
  validation rules (a candidate IS a constructed ``CrossCoderConfig``;
  anything ``__post_init__`` rejects is pruned, not special-cased) and
  prices each candidate with the analytical cost model the repo already
  carries: HLO cost-analysis FLOPs/bytes of the compiled step
  (:func:`crosscoder_tpu.utils.compile_cache.cost_of` via ``aot_get``
  lowering), the PR-2 wire-byte predictor for the DP gradient sync
  (:func:`crosscoder_tpu.parallel.comm_model.wire_bytes`), and the
  docs/SCALING.md refill/harvest cost models for the data-plane knobs.
- **Stage 2 (measured)** — :mod:`crosscoder_tpu.tune.calibrate` runs the
  top-K candidates as short calibration windows through the real Trainer,
  scoring with the PR-5 span EMAs (``perf/step_ms``) and the refill
  bubble fraction, with every candidate mechanically gated by the
  contracts engine — a tuned config that violates a contract is
  discarded (counted under ``tune/rejected_contract``), not shipped.

The winner is pinned as a reproducible ``TUNED.json``
(:mod:`crosscoder_tpu.tune.artifact`) that ``--tuned <path>`` loads back
through config resolution, and the elastic controller / fleet policy
consult per-topology cached artifacts on a remesh instead of carrying
stale knobs across a shape change.
"""

from crosscoder_tpu.tune.artifact import (TunedArtifact, apply_tuned,
                                          cached_artifact, config_hash,
                                          load_tuned, on_remesh,
                                          topology_key)
from crosscoder_tpu.tune.autotune import tune
from crosscoder_tpu.tune.calibrate import contracts_gate, measure_window
from crosscoder_tpu.tune.lattice import (Candidate, default_axes,
                                         enumerate_lattice, price_candidate,
                                         rank_candidates)

__all__ = [
    "TunedArtifact",
    "apply_tuned",
    "cached_artifact",
    "config_hash",
    "load_tuned",
    "on_remesh",
    "topology_key",
    "tune",
    "contracts_gate",
    "measure_window",
    "Candidate",
    "default_axes",
    "enumerate_lattice",
    "price_candidate",
    "rank_candidates",
]
