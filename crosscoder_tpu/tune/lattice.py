"""Stage-1 autotuner: enumerate the valid knob lattice, price it statically.

Candidates are real ``CrossCoderConfig`` objects: the lattice is the
cartesian product of the knob axes filtered by ``config.py``'s OWN
validation (``__post_init__`` raising prunes the point — no shadow copy
of the constraint rules lives here, so a new config constraint prunes
the lattice the day it lands). Pricing is compile-time-only analytics:

- **device terms** — HLO cost-analysis FLOPs / bytes-accessed of the
  compiled train step (one compile per DISTINCT step program: knobs
  outside :data:`STEP_FIELDS` are zero-cost-off by contract — the
  ``hlo-*-off-identity`` rules — so the whole data-plane sub-lattice
  shares one executable via ``compile_cache.aot_get``; stage-2's
  contracts gate re-verifies the assumption per shipped candidate);
- **DP-sync term** — the PR-2 wire-byte model
  (:func:`crosscoder_tpu.parallel.comm_model.wire_bytes`) over the
  compiled step's collectives at the candidate mesh width;
- **data-plane terms** — the docs/SCALING.md refill and harvest cost
  models ("Zero-bubble refill", "Harvest cost model", "Fleet
  amortization") for ``refill_frac`` / ``refill_overlap`` /
  ``refill_dispatch_batch`` / ``prefetch`` / ``quant_buffer``.

Absolute accuracy is irrelevant — only the RANKING matters (stage 2
measures the survivors) — but the constants match the comm_model /
fleet-policy prediction plane so every modeled number in the repo is
comparable.
"""

from __future__ import annotations

import dataclasses
import hashlib
import itertools
import json
import sys
from typing import Any

# modeled accelerator constants, shared with the prediction plane:
# v5e public numbers (parallel/comm_model.py, resilience/fleet.py)
PEAK_FLOPS = 197e12
HBM_GBPS = 819.0
# measured host cost of one harvest-quantum Python dispatch
# (docs/SCALING.md "Zero-bubble refill": ~6-8 ms trace+dispatch+donation)
HOST_DISPATCH_MS = 7.0
# reference harvest device cost per model-batch at the reference shape
# (docs/SCALING.md "Measured collective volumes": ~85 ms/model-batch)
HARVEST_REF_MS = 85.0
_REF_BATCH = 4096
# harvest quanta dispatched per serve at the bench-default segmentation
_QUANTA_PER_SERVE = 4
# fraction of the batched dispatcher's host cost that still contends
# with the serve path when offloaded (refill_overlap=on dispatcher thread)
_OFF_CRITICAL = 0.1

# Config fields that change the COMPILED STEP program. Everything else
# is host/data-plane and shares the step executable (the zero-cost-off
# contract); candidates are projected onto this set to key the AOT memo.
STEP_FIELDS = frozenset({
    "activation", "topk_k", "sparse_decode", "factored_decode",
    "sparse_bwd", "fused_encoder", "quant_encoder", "quant_grads",
    "quant_block", "batch_size", "dict_size", "d_in", "n_models",
    "hook_points", "enc_dtype", "master_dtype", "l1_coeff", "l0_coeff",
    "aux_k", "aux_every", "remat", "grad_clip", "shard_sources",
    "data_axis_size", "model_axis_size", "seed",
})

OBJECTIVES = ("train", "serve", "fleet")


@dataclasses.dataclass
class Candidate:
    """One lattice point: the knob assignment plus its validated config.

    ``base_sig`` identifies the base config the lattice was swept from
    (everything NOT on a knob axis); two candidates share a pricing
    compile only when both the base and their step-relevant knobs agree.
    """

    knobs: dict[str, Any]
    cfg: Any
    base_sig: str = ""
    predicted: dict[str, Any] = dataclasses.field(default_factory=dict)
    score: float | None = None

    @property
    def label(self) -> str:
        return ",".join(f"{k}={self.knobs[k]}" for k in sorted(self.knobs))


def default_axes(cfg: Any, objective: str = "train") -> dict[str, tuple]:
    """The stock knob axes per objective — the data-plane and ladder
    knobs every deployment scenario was hand-pinning. Values that the
    base config cannot validate are pruned at enumeration, so axes may
    be generous."""
    if objective == "train":
        return {
            "refill_overlap": ("off", "on"),
            "refill_dispatch_batch": (4, 8),
            "refill_frac": (0.25, 0.5),
            "prefetch": (False, True),
            "quant_buffer": (False, True),
        }
    if objective == "serve":
        return {
            "serve_max_batch": (8, 16, 32),
            "serve_max_wait_ms": (1.0, 2.0, 5.0),
            "page_size": tuple(p for p in (16, 32, 64)
                               if p <= cfg.seq_len and cfg.seq_len % p == 0)
                         or (cfg.page_size,),
        }
    if objective == "fleet":
        return {
            "fleet_max_buckets": (2, 4, 8),
            "refill_frac": (0.25, 0.5),
            "prefetch": (False, True),
        }
    raise ValueError(f"objective must be one of {OBJECTIVES}, "
                     f"got {objective!r}")


def enumerate_lattice(
    base_cfg: Any, axes: dict[str, tuple]
) -> tuple[list[Candidate], int]:
    """Cartesian product of ``axes`` over ``base_cfg``, keeping exactly
    the points ``CrossCoderConfig`` validation accepts. Returns
    ``(candidates, n_pruned_invalid)``. Deterministic: axes iterate in
    sorted-name order, values in the given order."""
    names = sorted(axes)
    base_dict = {k: v for k, v in base_cfg.to_dict().items()
                 if k not in axes}
    base_sig = hashlib.sha256(
        json.dumps(base_dict, sort_keys=True, default=str).encode()
    ).hexdigest()[:16]
    out: list[Candidate] = []
    pruned = 0
    for values in itertools.product(*(axes[n] for n in names)):
        knobs = dict(zip(names, values))
        try:
            cfg = base_cfg.replace(**knobs)
        except (ValueError, TypeError):
            pruned += 1
            continue
        out.append(Candidate(knobs=knobs, cfg=cfg, base_sig=base_sig))
    return out, pruned


# ---------------------------------------------------------------------------
# static pricing
# ---------------------------------------------------------------------------


def _step_signature(cand: Candidate) -> str:
    """The pricing-compile share key: the base config's identity plus the
    candidate's step-relevant knob values. Knobs outside
    :data:`STEP_FIELDS` are data-plane (zero-cost-off), so candidates
    differing only in those share one compiled step."""
    step_knobs = {k: v for k, v in sorted(cand.knobs.items())
                  if k in STEP_FIELDS}
    return cand.base_sig + "|" + json.dumps(step_knobs, sort_keys=True,
                                            default=str)


def _step_cost(cand: Candidate, n_devices: int) -> dict[str, float]:
    """FLOPs / bytes-accessed / wire-bytes of the candidate's step
    program, one compile per distinct :func:`_step_signature` via
    ``aot_get`` (so a 32-point data-plane lattice costs ONE compile)."""
    import jax

    from crosscoder_tpu.parallel import comm_model
    from crosscoder_tpu.parallel import mesh as mesh_lib
    from crosscoder_tpu.utils import compile_cache

    cfg = cand.cfg
    key = ("tune_step", _step_signature(cand))

    def build():
        mesh = mesh_lib.make_mesh(devices=jax.devices()[:1])
        return comm_model._compile_train_step(cfg, mesh)

    # disk-first (cfg.compile_cache_dir): a previously priced signature
    # answers from the persisted cost sidecar without compiling — or
    # even deserializing — anything
    cost = compile_cache.cost_of(key)
    comm = compile_cache.collectives_of(key)
    if cost is None or comm is None:
        compiled = compile_cache.aot_get(key, build)
        cost = (cost or compile_cache.cost_of(key)
                or compile_cache.record_cost(key, compiled))
        if comm is None:
            comm = comm_model.collective_bytes(compiled.as_text())
    n_model = max(1, int(cfg.model_axis_size))
    profile = comm_model.CommProfile("tune_step", n_devices, n_model, comm)
    n_data = max(1, n_devices // n_model)
    return {
        "flops": cost.get("flops", 0.0),
        "bytes_accessed": cost.get("bytes_accessed", 0.0),
        "wire_bytes": comm_model.wire_bytes(profile, axis_size=n_data),
    }


def _data_plane_ms(cfg: Any, device_ms: float) -> dict[str, float]:
    """The docs/SCALING.md refill cost model, per step.

    - harvest: serves per harvested row is ``0.5/refill_frac`` (the
      reference trigger fires at half-buffer), so the per-serve harvest
      share scales as ``2*refill_frac``;
    - host dispatch: a synchronous loop pays every quantum's host cost on
      the serve path; the overlap engine batches ``refill_dispatch_batch``
      quanta per dispatch and pumps them off-thread, leaving only
      residual contention plus whatever device time the step can't hide;
    - serve gather: the batch fetch, hidden entirely by ``prefetch``;
      ``quant_buffer`` reads ~0.51x the store bytes.
    """
    batch_scale = cfg.batch_size / _REF_BATCH
    harvest_dev_ms = HARVEST_REF_MS * batch_scale * (2.0 * cfg.refill_frac)
    q = _QUANTA_PER_SERVE
    gather_bytes = (cfg.batch_size * cfg.n_sources * cfg.d_in
                    * (1.04 if cfg.quant_buffer else 2.0))
    gather_ms = 1e3 * gather_bytes / (HBM_GBPS * 1e9)
    if cfg.refill_overlap == "on":
        k = max(1, int(cfg.refill_dispatch_batch))
        host_ms = q * HOST_DISPATCH_MS / k * _OFF_CRITICAL
        bubble_ms = max(0.0, harvest_dev_ms - device_ms)
    else:
        host_ms = q * HOST_DISPATCH_MS
        bubble_ms = harvest_dev_ms
    fetch_ms = 0.0 if cfg.prefetch else gather_ms
    return {
        "harvest_ms": harvest_dev_ms,
        "refill_host_ms": host_ms,
        "refill_bubble_ms": bubble_ms,
        "fetch_ms": fetch_ms,
    }


def price_candidate(
    cand: Candidate, objective: str = "train", n_devices: int = 1
) -> dict[str, Any]:
    """Stage-1 analytical price of one candidate for ``objective``.
    Fills ``cand.predicted`` / ``cand.score`` and returns the breakdown;
    higher score is better for every objective (latency objectives score
    the negated prediction)."""
    cfg = cand.cfg
    step = _step_cost(cand, n_devices)
    compute_ms = 1e3 * step["flops"] / PEAK_FLOPS
    hbm_ms = 1e3 * step["bytes_accessed"] / (HBM_GBPS * 1e9)
    device_ms = max(compute_ms, hbm_ms)
    wire_ms = 1e3 * step["wire_bytes"] / (HBM_GBPS * 1e9)
    plane = _data_plane_ms(cfg, device_ms)
    total_ms = (device_ms + wire_ms + plane["refill_host_ms"]
                + plane["refill_bubble_ms"] + plane["fetch_ms"])
    pred: dict[str, Any] = {
        "device_ms": device_ms, "wire_ms": wire_ms,
        "step_total_ms": total_ms, **step, **plane,
    }
    if objective == "train":
        score = cfg.batch_size * 1e3 / (total_ms * max(1, n_devices))
        pred["acts_per_sec_chip"] = score
    elif objective == "serve":
        b = int(cfg.serve_max_batch)
        nd = cfg.n_sources * cfg.d_in
        encode_ms = 1e3 * (2.0 * b * nd * cfg.dict_size) / PEAK_FLOPS
        # page granularity: a request pads its tail to a whole KV page
        page_waste = cfg.page_size / (2.0 * cfg.seq_len)
        prefill_ms = (HARVEST_REF_MS * (b / _REF_BATCH)
                      * (1.0 + page_waste))
        p99_ms = cfg.serve_max_wait_ms + prefill_ms + encode_ms
        pred.update(encode_ms=encode_ms, prefill_ms=prefill_ms,
                    p99_ms=p99_ms)
        score = -p99_ms
    elif objective == "fleet":
        n_tenants = max(1, len([t for t in cfg.fleet_tenants.split(";")
                                if t.strip()]) or 1)
        buckets = min(n_tenants, max(1, int(cfg.fleet_max_buckets)))
        round_ms = (plane["harvest_ms"] + plane["refill_host_ms"]
                    + buckets * (device_ms + wire_ms))
        score = n_tenants * cfg.batch_size * 1e3 / (
            round_ms * max(1, n_devices))
        pred.update(round_ms=round_ms, n_buckets=buckets,
                    agg_acts_per_sec_chip=score)
    else:
        raise ValueError(f"objective must be one of {OBJECTIVES}, "
                         f"got {objective!r}")
    pred["score"] = score
    cand.predicted = pred
    cand.score = score
    return pred


def rank_candidates(
    candidates: list[Candidate], objective: str = "train",
    n_devices: int = 1, seed: int = 0,
) -> list[Candidate]:
    """Price every candidate and return them best-first. Deterministic
    under a fixed seed: exact score ties break on a seeded hash of the
    knob assignment (stable across processes — never dict order). A
    candidate whose pricing compile fails is dropped with a stderr note,
    not a crash: pricing runs over arbitrary user axes."""
    priced: list[Candidate] = []
    for cand in candidates:
        try:
            price_candidate(cand, objective, n_devices)
            priced.append(cand)
        except Exception as e:  # noqa: BLE001 — user-supplied lattice
            print(f"[crosscoder_tpu] tune: pricing {cand.label} failed "
                  f"({type(e).__name__}: {e})"[:300],
                  file=sys.stderr, flush=True)

    def tie(c: Candidate) -> str:
        return hashlib.sha256(
            f"{seed}:{json.dumps(c.knobs, sort_keys=True, default=str)}"
            .encode()).hexdigest()

    priced.sort(key=lambda c: (-c.score, tie(c)))
    return priced
