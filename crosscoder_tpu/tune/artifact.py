"""The pinned ``TUNED.json`` artifact: schema, load/apply, topology cache.

A tune run ends in one small JSON document — the chosen knobs, the mesh
shape they were searched at, the stage-1 cost-model predictions, the
stage-2 measured scores, the contract-gate audit, and a hash of the
fully-resolved config — so a deployment pins *exactly* what the search
found, and ``--tuned <path>`` reproduces it through the normal config
resolution path. The artifact adds no hidden drift: loading a
``TUNED.json`` whose knobs equal the defaults lowers a byte-identical
step program (contracts rule ``hlo-tuned-config-identity``).

Artifacts are cached per topology (``TUNED.<topology>.json`` siblings of
the loaded artifact), so an elastic remesh to a previously-tuned shape
is a file read, not a re-search (:func:`on_remesh`).
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
import os
import sys
from pathlib import Path
from typing import Any

SCHEMA_VERSION = 1

# every key a well-formed artifact must carry, with its required type —
# scripts/tune_report.py and load_tuned() validate against this table
_REQUIRED: tuple[tuple[str, type], ...] = (
    ("version", int),
    ("objective", str),
    ("knobs", dict),
    ("mesh", dict),
    ("predicted", dict),
    ("measured", dict),
    ("gate", dict),
    ("search", dict),
    ("config_hash", str),
)


def topology_key(n_devices: int, n_model: int = 1) -> str:
    """Canonical topology tag a tuned artifact is keyed by: total device
    count plus the TP width (the two inputs that change the step program
    and the DP ring width — EQuARX's point that quantized-plane knobs
    interact with mesh shape and must be re-searched per topology)."""
    return f"d{int(n_devices)}m{int(n_model)}"


def config_hash(cfg: Any) -> str:
    """SHA-256 of the fully-resolved config JSON (minus the artifact path
    itself, which would make the hash self-referential)."""
    d = cfg.to_dict()
    d.pop("tuned", None)
    return hashlib.sha256(
        json.dumps(d, sort_keys=True, default=str).encode()
    ).hexdigest()


@dataclasses.dataclass
class TunedArtifact:
    """One pinned tune result (see module docstring for field meaning)."""

    objective: str
    knobs: dict[str, Any]
    mesh: dict[str, int]
    predicted: dict[str, Any] = dataclasses.field(default_factory=dict)
    measured: dict[str, Any] = dataclasses.field(default_factory=dict)
    gate: dict[str, Any] = dataclasses.field(default_factory=dict)
    search: dict[str, Any] = dataclasses.field(default_factory=dict)
    config_hash: str = ""
    version: int = SCHEMA_VERSION

    @property
    def topology(self) -> str:
        return topology_key(self.mesh.get("n_devices", 1),
                            self.mesh.get("n_model", 1))

    def to_dict(self) -> dict[str, Any]:
        d = dataclasses.asdict(self)
        d["topology"] = self.topology
        return d

    def save(self, path: str | Path) -> Path:
        """Atomic write (tmp + rename): a torn artifact must never load."""
        path = Path(path)
        path.parent.mkdir(parents=True, exist_ok=True)
        tmp = path.with_suffix(path.suffix + ".tmp")
        tmp.write_text(json.dumps(self.to_dict(), indent=2, sort_keys=True,
                                  default=str))
        os.replace(tmp, path)
        return path

    @classmethod
    def from_dict(cls, d: dict[str, Any]) -> "TunedArtifact":
        for key, typ in _REQUIRED:
            if key not in d:
                raise ValueError(f"TUNED artifact missing required key "
                                 f"{key!r}")
            if not isinstance(d[key], typ):
                raise ValueError(
                    f"TUNED artifact key {key!r} must be "
                    f"{typ.__name__}, got {type(d[key]).__name__}")
        if d["version"] != SCHEMA_VERSION:
            raise ValueError(f"TUNED artifact schema version {d['version']} "
                             f"!= supported {SCHEMA_VERSION}")
        if not d["knobs"]:
            raise ValueError("TUNED artifact has an empty knob set — "
                             "nothing to apply")
        fields = {f.name for f in dataclasses.fields(cls)}
        return cls(**{k: v for k, v in d.items() if k in fields})


def load_tuned(path: str | Path) -> TunedArtifact:
    """Parse + validate one artifact; raises ``ValueError`` on anything
    malformed (unreadable file, non-JSON, missing/ill-typed keys) so CLIs
    and CI can gate on artifact validity."""
    try:
        data = json.loads(Path(path).read_text())
    except OSError as e:
        raise ValueError(f"cannot read {path}: {e}")
    except json.JSONDecodeError as e:
        raise ValueError(f"{path} is not valid JSON: {e}")
    if not isinstance(data, dict):
        raise ValueError(f"{path}: top-level JSON must be an object")
    return TunedArtifact.from_dict(data)


def apply_tuned(cfg: Any, path: str | Path | None = None) -> Any:
    """Resolve ``cfg`` through a tuned artifact: the artifact's knobs are
    applied over ``cfg`` (re-validated by ``CrossCoderConfig.__post_init__``
    — a stale artifact whose knobs no longer pass validation fails loudly
    here, not three hours into a run). ``path`` defaults to ``cfg.tuned``;
    with neither set this is the identity. Knob names must be real config
    fields: an artifact knob that is not a field is a schema violation,
    not an ``extras`` passenger."""
    path = path if path is not None else getattr(cfg, "tuned", "")
    if not path:
        return cfg
    art = load_tuned(path)
    fields = {f.name for f in dataclasses.fields(type(cfg))}
    unknown = sorted(set(art.knobs) - fields)
    if unknown:
        raise ValueError(
            f"TUNED artifact {path} carries unknown knob(s) {unknown} — "
            f"not CrossCoderConfig fields")
    knobs = dict(art.knobs)
    # JSON has no tuples: restore tuple-typed fields before replace()
    for k, v in knobs.items():
        if isinstance(getattr(cfg, k), tuple) and isinstance(v, list):
            knobs[k] = tuple(v)
    return cfg.replace(tuned=str(path), **knobs)


# ---------------------------------------------------------------------------
# per-topology artifact cache (the re-tune-on-remesh lifecycle)
# ---------------------------------------------------------------------------


def cache_path(root: str | Path, topology: str) -> Path:
    return Path(root) / f"TUNED.{topology}.json"


def cached_artifact(root: str | Path, topology: str) -> TunedArtifact | None:
    """The pinned artifact for ``topology`` under ``root``, or None. A
    malformed cache entry is treated as a miss (reported to stderr), never
    an error — the remesh path must not die on a torn file."""
    p = cache_path(root, topology)
    if not p.exists():
        return None
    try:
        return load_tuned(p)
    except ValueError as e:
        print(f"[crosscoder_tpu] tune: ignoring malformed cached artifact "
              f"{p}: {e}", file=sys.stderr, flush=True)
        return None


def on_remesh(cfg: Any, n_devices: int) -> tuple[Any, str]:
    """The remesh hook (docs/TUNING.md "Re-tune on remesh").

    Called by the elastic controller when the world changes shape. With
    no pinned artifact (``cfg.tuned`` empty) it is a no-op. Otherwise:

    - if a cached ``TUNED.<topology>.json`` sibling exists for the NEW
      topology, its knobs replace the pinned ones (``cache_hit``);
    - if the pinned artifact was already searched at this topology, the
      knobs stand (``current``);
    - else the pinned knobs are STALE for this shape: the config is
      returned unchanged but flagged, so the caller can count it and
      schedule a re-tune (``stale``) — carrying stale hand-tuned knobs
      silently across a shape change is the failure mode this hook
      exists to prevent.

    Returns ``(cfg, status)`` with status in
    ``{"off", "current", "cache_hit", "stale"}``.
    """
    if not getattr(cfg, "tuned", ""):
        return cfg, "off"
    n_model = max(1, int(cfg.model_axis_size))
    topo = topology_key(n_devices, n_model)
    try:
        pinned = load_tuned(cfg.tuned)
    except ValueError:
        pinned = None
    if pinned is not None and pinned.topology == topo:
        return cfg, "current"
    cached = cached_artifact(Path(cfg.tuned).parent, topo)
    if cached is not None:
        path = cache_path(Path(cfg.tuned).parent, topo)
        print(f"[crosscoder_tpu] tune: remesh to {topo} — applying cached "
              f"artifact {path}", file=sys.stderr, flush=True)
        return apply_tuned(cfg, path), "cache_hit"
    print(f"[crosscoder_tpu] tune: remesh to {topo} — pinned artifact "
          f"{cfg.tuned} was searched at "
          f"{pinned.topology if pinned else 'unknown'}; knobs are STALE, "
          f"re-tune recommended", file=sys.stderr, flush=True)
    return cfg, "stale"
