"""Typed configuration for the TPU crosscoder framework.

The reference configures everything through a flat 24-key Python dict edited in
source (reference ``train.py:8-41``; its README says "I just set the cfg by
editing the code") and serializes that dict as JSON next to every checkpoint
(reference ``crosscoder.py:151-155``), making the cfg-JSON the de-facto schema.

Here the config is a typed dataclass that

- keeps the exact reference key names so published checkpoint cfg JSONs load
  unchanged (``seed`` ... ``hook_point``; see ``from_dict``),
- adds the TPU-native keys the reference lacks (``n_models`` generalized from
  the hardcoded 2 at reference ``crosscoder.py:32``; mesh axes; sparse-encode
  activation options for the Pallas kernels; multi-layer hook lists),
- round-trips unknown keys (``extras``) so foreign cfg JSONs survive
  load→save, and
- has a real CLI reflector (the reference ships one at ``utils.py:151-178``
  but never calls it, so ``run_training.sh``'s ``"$@"`` is silently dropped).
"""

from __future__ import annotations

import argparse
import dataclasses
import json
import os
from dataclasses import dataclass, field
from pathlib import Path
from typing import Any

from crosscoder_tpu.utils.dtypes import DTYPES


def _check_choice(field_name: str, value: Any,
                  choices: tuple[str, ...]) -> None:
    """Membership check for a string mode knob, with a difflib typo
    hint — every choice knob validates through here so the error shape
    lives in one place instead of a copy per knob."""
    if value in choices:
        return
    import difflib

    close = difflib.get_close_matches(str(value), choices, n=1)
    hint = f"; did you mean {close[0]!r}?" if close else ""
    raise ValueError(
        f"{field_name} must be {'|'.join(choices)}, got {value!r}{hint}"
    )

# dtype strings follow the reference's DTYPES table (reference crosscoder.py:12)
DTYPE_NAMES = tuple(DTYPES)

_ACTIVATIONS = ("relu", "topk", "jumprelu", "batchtopk")


@dataclass
class CrossCoderConfig:
    """Full training/analysis configuration.

    Field names and defaults mirror the reference dict (reference
    ``train.py:13-35``) so that parity runs and published cfg JSONs are
    drop-in; TPU-native additions are grouped at the bottom.
    """

    # --- reference keys (train.py:13-35), same names and defaults ---
    seed: int = 49
    batch_size: int = 4096          # activation rows per optimizer step
    buffer_mult: int = 128          # replay buffer = batch_size * buffer_mult rows
    lr: float = 5e-5
    num_tokens: int = 400_000_000   # total training token budget
    l1_coeff: float = 2.0           # weight on the decoder-norm-weighted L1
    beta1: float = 0.9
    beta2: float = 0.999
    dict_size: int = 2 ** 14        # crosscoder latent count (d_hidden)
    seq_len: int = 1024
    enc_dtype: str = "bf16"         # compute dtype of encode/decode
    model_name: str = "gemma-2-2b"
    site: str = "resid_pre"
    device: str = "tpu"             # kept for cfg-JSON compat; placement is mesh-driven
    model_batch_size: int = 4       # sequences per harvest forward
    log_every: int = 100
    save_every: int = 30000
    dec_init_norm: float = 0.08
    hook_point: str = "blocks.14.hook_resid_pre"
    wandb_project: str = ""
    wandb_entity: str = ""
    d_in: int = 2304                # residual stream width (gemma-2-2b d_model)

    # --- TPU-native extensions (no reference counterpart) ---
    n_models: int = 2               # reference hardcodes 2 (crosscoder.py:32)
    hook_points: tuple[str, ...] = ()   # multi-layer crosscoder: several hooks per model
    activation: str = "relu"        # relu | topk | jumprelu | batchtopk
    topk_k: int = 32                # k for (batch)topk activation. NB
                                    # batchtopk keeps ALL entries tied at
                                    # the global threshold, so its
                                    # effective L0 can exceed k·batch when
                                    # bf16 pre-acts tie there (topk proper
                                    # breaks ties by index and keeps
                                    # exactly k per row)
    sparse_decode: bool = False     # topk only: decode via the k active rows
                                    # (gather + custom-vjp) instead of the
                                    # dense [B,H]x[H,n,d] matmul
    factored_decode: str = "auto"   # topk + Pallas tier: decode FORWARD
                                    # through the k active rows (sparsify
                                    # kernel + gather), backward through
                                    # the same dense matmuls as the dense
                                    # path. "auto" = on for dict >= 2^17
                                    # (measured v5e crossover vs the dense
                                    # matmul: -8 ms at 2^17, +6 ms at
                                    # 2^16); "on"/"off" force. Requires
                                    # l1_coeff == 0 (see
                                    # models.crosscoder._factored_topk_forward)
    sparse_bwd: str = "auto"        # topk factored tier: replace the dense
                                    # backward matmuls (dW_dec, df, dW_enc)
                                    # with O(B·k) Pallas scatter-accumulate
                                    # gradients (ops/sparse_grad.py;
                                    # docs/SCALING.md "Sparse backward
                                    # plane"). "auto" = on when the
                                    # factored tier is active AND the
                                    # scatter kernel is live (TPU +
                                    # CROSSCODER_SPARSE_GRAD_PALLAS=1, or
                                    # interpret mode) AND shapes are
                                    # kernel-supported; "on" forces (also
                                    # forces the factored tier); "off"
                                    # never. Requires l1_coeff == 0 (the
                                    # factored tier's soundness gate).
    fused_encoder: str = "auto"     # fused encoder→TopK megakernel
                                    # (ops/fused_encoder_topk.py;
                                    # docs/SCALING.md "Fused encoder→
                                    # TopK"): the encoder matmul streams
                                    # dictionary tiles through VMEM and
                                    # top-k-reduces them in-kernel, so
                                    # the [B, dict] pre-act matrix never
                                    # round-trips HBM. topk: rides the
                                    # sparse-backward full-step scope
                                    # (requires factored tier + sparse_bwd
                                    # live; AuxK steps keep the dense
                                    # encode — the h-residual escape
                                    # hatch). batchtopk: fused global-
                                    # bisection count-then-emit. "auto" =
                                    # on when the kernel is live (TPU +
                                    # CROSSCODER_FUSED_TOPK_PALLAS=1 or
                                    # CROSSCODER_PALLAS=all, or interpret
                                    # mode) and shapes are supported;
                                    # "on"/"off" force. Zero-cost off
                                    # (step-HLO identity).
    quant_encoder: bool = False     # fused tier only: int8 block-scaled
                                    # encoder matmul inside the fused
                                    # kernel (per-block scales along the
                                    # contraction axis, ops/quant.py
                                    # layout) — ~0.5x weight-stream
                                    # bytes at a small selection-
                                    # agreement cost. Opt-in behind the
                                    # bench quality gate (the
                                    # --quant-grads discipline):
                                    # docs/SCALING.md has the procedure.
                                    # quant_block must divide
                                    # n_sources·d_in.
    jumprelu_theta: float = 0.001   # initial JumpReLU threshold
    jumprelu_bandwidth: float = 0.001  # STE bandwidth for the threshold gradient
    l0_coeff: float = 0.0           # jumprelu only: coefficient on the
                                    # rectangle-kernel-STE L0 penalty (the
                                    # JumpReLU paper's sparsity objective);
                                    # combine with l1_coeff=0 for pure-L0
                                    # training
    aux_k: int = 0                  # >0: AuxK dead-latent mitigation (the
                                    # standard TopK-SAE recipe, Gao et al.
                                    # 2024): an auxiliary loss reconstructs
                                    # the main reconstruction's residual
                                    # with the top aux_k DEAD latents
                                    # (steps_since_fired >= aux_dead_steps),
                                    # giving dead latents a gradient path
                                    # back to life. Typical: 2-16x topk_k.
    aux_k_coeff: float = 1.0 / 32.0  # weight on the (residual-normalized)
                                    # aux loss; 1/32 is the Gao et al.
                                    # default. Measured (ACT_QUALITY_r04):
                                    # at 10k steps the default holds eval
                                    # L2 but leaves dead fraction flat; a
                                    # concentrated setting (aux_k=2k,
                                    # coeff 0.25) cut dead latents
                                    # 85%->73% at slightly BETTER eval L2
                                    # — turn it up when revival matters.
    aux_dead_steps: int = 500       # a latent is "dead" after this many
                                    # consecutive steps without firing
                                    # (500 steps x batch 4096 ≈ 2M rows)
    aux_exact_rank: bool = False    # rank dead latents with exact top_k
                                    # instead of approx_max_k. Slow (the
                                    # exact [B,H] sort costs more than the
                                    # rest of the step at dict 2^15) —
                                    # engine-parity runs only, where the
                                    # torch oracle's exact ranking must
                                    # select identical aux latents
    aux_every: int = 1              # run the aux ranking+decode every Nth
                                    # step (fired-tracking stays per-step,
                                    # so deadness is always current). The
                                    # full aux path costs 2.2-2.7x a plain
                                    # TopK step (BENCH_r04 matrix); N
                                    # amortizes that to ~(N-1+2.7)/N — at
                                    # N=8, ~1.2x. 1 = the per-step Gao
                                    # et al. recipe. Quality under
                                    # amortization: artifacts/
                                    # ACT_QUALITY_r05.json.
    resample_every: int = 0         # >0: dead-latent RESAMPLING every Nth
                                    # step (Bricken et al. 2023's neuron
                                    # resampling, the alternative to AuxK):
                                    # dead latents' decoder rows re-init
                                    # from high-residual batch examples,
                                    # encoder rows aligned and downscaled,
                                    # b_enc zeroed, Adam moments reset.
                                    # Deadness = steps_since_fired >=
                                    # resample_dead_steps. Composes with
                                    # aux_k (either or both).
    resample_dead_steps: int = 0    # deadness threshold for resampling;
                                    # 0 = inherit aux_dead_steps
    resample_enc_scale: float = 0.2  # revived encoder norm as a fraction
                                    # of the mean ALIVE encoder norm.
                                    # 0.2 is the Bricken et al. SAE rule
                                    # (fire weakly, adapt gently) — but
                                    # under TopK a downscaled encoder can
                                    # never WIN the top-k selection race,
                                    # so revived latents cycle
                                    # resample→die→resample (measured:
                                    # ACT_QUALITY_r05 resample_30k, dead
                                    # 86% unchanged); 1.0 gives revived
                                    # latents full competitive scale
    batchtopk_threshold: float = 0.0   # >0: batchtopk EVAL mode — a fixed
                                    # global threshold (from
                                    # crosscoder.calibrate_batchtopk_threshold)
                                    # so per-example activations don't
                                    # depend on batch composition; 0 =
                                    # per-batch k·B-th threshold (training)
    data_axis_size: int = -1        # -1: all remaining devices on the data axis
    model_axis_size: int = 1        # tensor-parallel shards of the dict axis
    shard_sources: bool = False     # EP-style: shard the SOURCE axis
                                    # (n_models × n_hooked_layers) over the
                                    # 'model' mesh axis instead of the dict
                                    # axis — for many-model/many-layer diffs;
                                    # n_sources must divide by model_axis_size
    buffer_device: str = "host"     # replay store placement: host RAM (big
                                    # buffers, multi-host, analysis reads)
                                    # | "hbm": zero host↔device row traffic
                                    # — the reference's own placement
                                    # (buffer.py:18-22); on a multi-chip
                                    # mesh the store shards over the data
                                    # axis and serves batches pre-sharded
    shard_lm: bool = False          # tensor-parallel harvest: load/keep the
                                    # subject LMs' weights sharded over the
                                    # 'model' mesh axis (lm.tp_shardings) —
                                    # for pairs too big for one chip's HBM
                                    # (e.g. Gemma-2-9B, BASELINE config 3)
    seq_shards: int = 0             # >0: harvest forwards shard the SEQUENCE
                                    # axis over the mesh data axis (ring
                                    # attention), for contexts too long for
                                    # one chip; must equal the data-axis size
                                    # and divide seq_len. 0 = batch-sharded
                                    # harvest (default).
    harvest_runtime: str = "padded"  # LM-harvest forward runtime:
                                    # "padded" (default — every document
                                    # padded to seq_len, the reference's
                                    # layout, byte-identical to builds
                                    # without this knob) | "paged" — the
                                    # ragged/paged runtime (data/paging.py
                                    # + ops/paged_attention.py): mixed-
                                    # length documents pack into a dense
                                    # token plane (projections/MLP cost
                                    # proportional to REAL tokens), with
                                    # per-document ragged attention over
                                    # fixed-size KV pages. Bit-identical
                                    # hook activations to the padded path
                                    # at valid positions; pad positions
                                    # are emitted zeroed under an explicit
                                    # valid-length mask. docs/SCALING.md
                                    # "Harvest cost model".
    page_size: int = 64             # paged runtime: tokens per KV page
                                    # (the attention kernel's DMA/compute
                                    # quantum). Power of two dividing
                                    # seq_len; page-table overhead is
                                    # 4·seq_len/page_size bytes/sequence.
    grad_clip: float = 1.0          # reference hardcodes this (trainer.py:46)
    lr_decay_frac: float = 0.2      # linear lr decay over the last fraction (trainer.py:29-32)
    l1_warmup_frac: float = 0.05    # l1 warmup over the first fraction (trainer.py:36)
    norm_calib_batches: int = 100   # batches for norm calibration (buffer.py:45)
    refill_frac: float = 0.5        # buffer fraction re-harvested per refill
                                    # cycle. 0.5 = reference parity (1:1
                                    # harvest:serve, buffer.py:70-74). Lower
                                    # = each harvested row is served
                                    # ~0.5/refill_frac times — harvest is
                                    # ~2.4x the train step's FLOPs/row on
                                    # TPU, so 0.25 raises end-to-end
                                    # throughput ~1.4x at the cost of
                                    # fresher-data churn.
    checkpoint_dir: str = "./checkpoints"
    data_dir: str = "./data"
    dataset_name: str = "ckkissane/pile-lmsys-mix-1m-tokenized-gemma-2"
    log_backend: str = "auto"       # auto | wandb | jsonl | null
    profile_dir: str = ""           # non-empty: write jax.profiler traces here
    remat: bool = False             # jax.checkpoint the encode for memory;
                                    # the backward then re-runs it (incl.
                                    # the Pallas TopK kernel — measured
                                    # ~1.44x step time at topk dict 2^16
                                    # on v5e for roughly halved activation
                                    # memory)
    data_source: str = "gemma"      # gemma (paired-LM harvest) | synthetic
    model_names: tuple[str, ...] = ()  # HF ids to diff; default: (google/<model_name>, +"-it")
    resume: bool = False            # resume from the latest checkpoint version
    prefetch: bool = True           # overlap host batch gather with the device step
    refill_overlap: str = "off"     # off | on: zero-bubble refill engine
                                    # (docs/SCALING.md "Zero-bubble
                                    # refill"). "on" harvests refill
                                    # cycles into spare store rows while
                                    # the live rows serve (a logical→
                                    # physical row map swaps at cycle
                                    # boundaries — no data copy) and
                                    # batches/offloads the harvest
                                    # dispatch quanta; the served batch
                                    # stream stays byte-identical. Costs
                                    # ×(1 + refill_frac) store memory.
    refill_dispatch_batch: int = 4  # refill_overlap="on" only: harvest
                                    # dispatch quanta issued per Python
                                    # dispatch (one wide sub-scan program
                                    # instead of N narrow ones) — divides
                                    # the ~6-8 ms/dispatch host cost on
                                    # tunneled clients by this factor.
    stop_poll_every: int = 20       # multi-process only: steps between
                                    # allgathered stop-flag polls (the
                                    # SIGTERM coordinated stop). Each poll
                                    # is a host-blocking cross-host
                                    # collective, so per-step polling
                                    # would defeat async dispatch; 20
                                    # bounds the stop latency at ~20 steps
                                    # while costing <5% of steps a sync.
    # --- resilience (crosscoder_tpu/resilience; docs/resilience.md) ---
    guard_loss: bool = False        # divergence guard: at log_every
                                    # granularity (piggybacking the log
                                    # step's existing loss fetch — the
                                    # fast path gains NO host sync),
                                    # non-finite or spiking loss triggers
                                    # rollback to the last intact save +
                                    # skip of the poisoned data window
    loss_spike_factor: float = 10.0  # loss > factor × last healthy logged
                                    # loss counts as divergence
    max_rollbacks: int = 3          # rollbacks per train() before the
                                    # guard aborts loudly (a fault that
                                    # reproduces past the skipped window
                                    # is a bug, not a transient)
    keep_saves: int = 0             # >0: keep only the last k COMPLETE
                                    # saves per version dir (the retention
                                    # policy verified restore's fallback
                                    # assumes); 0 = unbounded (reference-
                                    # compatible). k >= 2 recommended so a
                                    # corrupt newest save has an intact
                                    # predecessor.
    harvest_timeout_s: float = 0.0  # >0: watchdog on the serve/harvest
                                    # path — escalating-patience stall
                                    # detection + exponential-backoff
                                    # retry of exceptions (resilience/
                                    # watchdog.py). 0 = off (default).
    harvest_retries: int = 3        # watchdog retry/extension budget
    harvest_backoff_s: float = 0.5  # base of the exponential retry backoff
    elastic: str = "off"            # off | on: elastic multihost membership
                                    # (resilience/elastic.py). "on" adds a
                                    # bounded liveness barrier at the
                                    # stop_poll_every cadence; when a peer
                                    # host dies mid-run the surviving
                                    # coordinator quiesces in-flight work,
                                    # re-meshes over its local devices
                                    # (mesh epoch +1), and resumes from the
                                    # newest verified save via restore-
                                    # with-respec. ZERO-COST off: the
                                    # compiled step is byte-identical
                                    # (hlo-elastic-off-identity).
    elastic_heartbeat_s: float = 1.0  # elastic="on": coordination-service
                                    # heartbeat interval (service + client)
                                    # — how fast a dead host is NOTICED;
                                    # detection fires after ~3 missed beats
    elastic_grace_s: float = 5.0    # elastic="on": bounded wait of each
                                    # liveness barrier — a peer slower than
                                    # this at a poll point is declared lost
                                    # (the slow-host SLO; >= heartbeat)
    elastic_suspect_probes: int = 2 # elastic="on": consecutive failed
                                    # liveness probes before peer loss is
                                    # DECLARED. Misses below the threshold
                                    # are absorbed (resilience/
                                    # elastic_suspects counter) so a flaky
                                    # or slow host triggers hysteresis, not
                                    # a remesh; torn-collective
                                    # confirmation stays immediate (a dead
                                    # peer mid-program is not a flake)
    elastic_grow: str = "off"       # off | on (requires elastic="on"):
                                    # scale back UP. The shrunk survivor
                                    # polls a filesystem rendezvous board
                                    # (<checkpoint_dir>/elastic_board) for
                                    # returned hosts, admits the debounced
                                    # set at a poll boundary (mesh epoch
                                    # +1), writes a boundary save both
                                    # sides restore, and re-forms the wider
                                    # world (docs/resilience.md "Elastic
                                    # scale-up"). ZERO-COST off: compiled
                                    # step byte-identical
                                    # (hlo-elastic-grow-off-identity)
    elastic_dwell_steps: int = 2    # elastic_grow="on": minimum steps the
                                    # current mesh epoch must dwell before
                                    # the next grow re-mesh — remesh-rate
                                    # hysteresis so flapping hosts cannot
                                    # thrash shrink/grow cycles
    elastic_grow_debounce: int = 2  # elastic_grow="on": consecutive polls
                                    # a rejoin candidate must stay FRESH on
                                    # the board (announce seq advancing)
                                    # before admission — a host that flaps
                                    # away mid-courtship is dropped, not
                                    # admitted
    elastic_policy: str = "fixed"   # fixed | score: mesh-shape policy on a
                                    # membership change (resilience/
                                    # fleet.py). fixed preserves
                                    # model_axis_size (TP width) and gives
                                    # the data axis every device; score
                                    # ranks candidate (data, model) splits
                                    # by the comm_model wire-byte model +
                                    # compiled-HLO cost analysis
    # --- multi-tenant fleet (train/fleet.py; docs/SCALING.md "Fleet
    # amortization"). Off by default and ZERO-COST off: none of these
    # knobs is read inside the compiled step, so the step lowering is
    # byte-identical to a build without them (contracts rule
    # hlo-fleet-off-identity).
    fleet: str = "off"              # off | on: run N crosscoder tenants
                                    # off ONE shared replay buffer — one
                                    # harvest stream, one serve gather per
                                    # cycle fanned out to every admitted
                                    # tenant, so the LM forward amortizes
                                    # across the whole sweep
    fleet_tenants: str = ""         # fleet="on" CLI sweep spec:
                                    # ';'-separated "name:k=v,k=v" tenant
                                    # overrides applied to the base config
                                    # (e.g. "a:seed=1;b:seed=2,l1_coeff=
                                    # 0.02;big:dict_size=65536"). seed/
                                    # l1_coeff-only variations stack under
                                    # one vmapped step; shape-changing
                                    # overrides compile into buckets
    fleet_max_buckets: int = 8      # fleet="on": cap on DISTINCT compiled
                                    # step signatures across heterogeneous
                                    # tenants (stacked cohorts count one) —
                                    # admission beyond the cap is refused
                                    # rather than compiling unboundedly
    # --- online serving (crosscoder_tpu/serve; docs/SERVING.md). Off by
    # default and ZERO-COST off: none of these knobs is read inside the
    # compiled train step, so the step lowering is byte-identical to a
    # build without them (contracts rule hlo-serve-off-identity).
    serve: str = "off"              # off | on: the online model-diffing
                                    # request path (serve/engine.py): token
                                    # streams admitted via ContinuousBatcher
                                    # into paged LM harvest slots, fused
                                    # encoder→TopK on the captured hooks,
                                    # per-request top-k latents + decoder-
                                    # norm diff scores returned — only
                                    # [B, k] ever leaves the device
    serve_max_batch: int = 8        # serve="on": micro-batch cap — the
                                    # largest AOT-prewarmed batch bucket;
                                    # power of two <= 128 so the bucket
                                    # ladder stays <= 8 compiled shapes
    serve_max_wait_ms: float = 5.0  # serve="on": deadline of the oldest
                                    # admitted request before a partial
                                    # plane flushes (flush on batch-full OR
                                    # this timer — deadline-aware
                                    # micro-batching)
    serve_queue: int = 64           # serve="on": bounded admission queue;
                                    # submits beyond it shed (429-style,
                                    # serve/shed_total) instead of growing
                                    # the queue unboundedly
    serve_shed_ms: float = 0.0      # serve="on", > 0: max queue wait —
                                    # queued requests older than this are
                                    # evicted (counted in serve/shed_total)
                                    # before a full queue sheds new arrivals
    # --- block-scaled int8 data plane (ops/quant.py; docs/SCALING.md
    # "Quantized data plane"). Both off by default and ZERO-COST off: the
    # compiled train step and the serve/refill paths are byte-identical to
    # a build without these fields (asserted in tests/test_quant.py).
    quant_buffer: bool = False      # replay store in block-scaled int8 +
                                    # f32 scales instead of bf16: ~0.51x
                                    # store bytes at quant_block=256, refill
                                    # chunks quantized at harvest time so
                                    # host↔device / ICI refill traffic
                                    # halves; the serve path dequantizes in
                                    # the same fused gather, so the trainer
                                    # still receives bf16 rows
    quant_grads: bool = False       # EQuARX-style quantized gradient
                                    # all-reduce under pure data
                                    # parallelism: per-device grads are
                                    # block-scaled int8 through an
                                    # all-to-all + all-gather pair (~2x
                                    # less grad-sync wire traffic than the
                                    # bf16 psum) with per-device error
                                    # feedback carried in TrainState.aux
                                    # ("quant_ef") so the compression bias
                                    # cancels across steps
    quant_block: int = 256          # elements per int8 scale block (the
                                    # last-axis granularity). Must divide
                                    # d_in when quant_buffer is on; store
                                    # overhead is 4/quant_block bytes/elem
    # --- observability (crosscoder_tpu/obs; docs/OBSERVABILITY.md) ---
    # Everything off by default and ZERO-COST off: with obs="off" the
    # compiled train step is byte-identical to a build without the plane
    # and no additional host↔device transfer happens anywhere (asserted
    # in tests/test_obs.py).
    obs: str = "off"                # "on": span tracer (Chrome trace-event
                                    # JSON under obs_dir, Perfetto-viewable,
                                    # host spans wrapped in jax.profiler
                                    # TraceAnnotations), perf/* + comm/*
                                    # registry metrics in the log stream
                                    # (incl. perf/refill_bubble_frac),
                                    # compile-event reporting, SIGUSR1
                                    # profiler windows
    obs_dir: str = ""               # telemetry output dir; default
                                    # <checkpoint_dir>/obs (trace.json,
                                    # profile/ windows)
    profile_steps: str = ""         # "start:stop": capture a jax.profiler
                                    # device trace around exactly steps
                                    # [start, stop) — absolute step
                                    # indices; independent of cfg.obs.
                                    # Empty + profile_dir set keeps the
                                    # legacy steps-10..14 window.
    log_print_every: int = 1        # echo every Nth metrics line to
                                    # STDERR (0 = never). The echo left
                                    # stdout so executables owning a
                                    # machine-readable stdout contract
                                    # (bench.py's one-JSON-line) can
                                    # construct a real logger safely.
    # AuxK dead-mask cadence: how often the trainer REFRESHES the dead-
    # latent mask that gates the aux ranking/decode. 1 (default) =
    # recompute every step (the exact Gao et al. recipe — required for
    # engine-parity runs); N > 1 = refresh every N steps and reuse the
    # cached mask between refreshes; 0 = refresh at cfg.log_every cadence.
    # Fired-tracking (steps_since_fired) updates every step regardless, so
    # a refresh always sees current deadness; between refreshes a revived
    # latent keeps its aux gradient for at most one cadence window (the
    # same staleness class as cfg.aux_every amortization, measured within
    # noise — artifacts/ACT_QUALITY_r05.json).
    aux_mask_every: int = 1
    chaos: str = ""                 # fault-injection spec (resilience/
                                    # chaos.py grammar; tests/staging
                                    # only). Empty = no chaos objects
                                    # constructed anywhere.
    tuned: str = ""                 # path to a pinned TUNED.json autotuner
                                    # artifact (docs/TUNING.md). --tuned
                                    # applies its knobs during from_cli
                                    # resolution (after --config-json,
                                    # before explicit flags); the elastic
                                    # controller re-checks it on remesh.
                                    # Empty = no tuner involvement.
    # --- persistent AOT executable cache (docs/SCALING.md "Persistent
    # compile cache"). Empty dir (default) = tier off, ZERO-COST: the
    # compiled step HLO and transfer counts are byte-identical to a
    # build without it (tests/test_compile_cache_disk.py).
    compile_cache_dir: str = ""     # directory for serialized AOT
                                    # executables + cost sidecars; serve
                                    # warmup, elastic remesh/grow, fleet
                                    # admission, and tune calibration
                                    # deserialize instead of compiling
    compile_cache_max_bytes: int = 1 << 30   # byte cap on the disk tier;
                                    # least-recently-used entries evict
                                    # past it (compile/evictions)
    compile_cache_verify: str = "off"   # off | strict: strict re-lowers
                                    # on every disk load and rejects an
                                    # entry whose stored HLO hash differs
                                    # from the live lowering

    # master-weight/Adam-moment dtype. fp32 (default) is a quality upgrade
    # over the reference; "bf16" reproduces the reference exactly (its params
    # AND torch-Adam moments are bf16, train.py:5 + crosscoder.py:30-34) and
    # cuts the optimizer's HBM traffic ~2x.
    master_dtype: str = "fp32"

    # unknown keys from foreign cfg JSONs, preserved on round-trip
    extras: dict[str, Any] = field(default_factory=dict)

    # ------------------------------------------------------------------
    def __post_init__(self) -> None:
        if self.enc_dtype not in DTYPE_NAMES:
            raise ValueError(f"enc_dtype must be one of {DTYPE_NAMES}, got {self.enc_dtype!r}")
        if self.activation not in _ACTIVATIONS:
            raise ValueError(f"activation must be one of {_ACTIVATIONS}, got {self.activation!r}")
        if self.n_models < 1:
            raise ValueError("n_models must be >= 1")
        if isinstance(self.hook_points, list):
            self.hook_points = tuple(self.hook_points)
        if isinstance(self.model_names, list):
            self.model_names = tuple(self.model_names)
        if self.data_source not in ("gemma", "synthetic"):
            raise ValueError(f"data_source must be 'gemma' or 'synthetic', got {self.data_source!r}")
        if self.master_dtype not in ("fp32", "bf16"):
            raise ValueError(f"master_dtype must be fp32 or bf16, got {self.master_dtype!r}")
        if (self.shard_sources and self.model_axis_size > 1
                and self.n_sources % self.model_axis_size != 0):
            raise ValueError(
                f"shard_sources: n_sources {self.n_sources} must divide by "
                f"model_axis_size {self.model_axis_size}"
            )
        # refill_frac is a FRACTION of the buffer: anything outside (0, 1]
        # is meaningless, and anything above 0.5 would let a refill cycle
        # overwrite rows the serve trigger (fixed at the reference's
        # half-buffer point, buffer.py:121) has not yet served
        if not (0.0 < self.refill_frac <= 1.0):
            raise ValueError(
                f"refill_frac must be a buffer fraction in (0, 1], got "
                f"{self.refill_frac}; 0.5 is reference parity (1:1 "
                f"harvest:serve), smaller values re-serve survivors "
                f"~0.5/refill_frac times"
            )
        if self.refill_frac > 0.5:
            raise ValueError(
                f"refill_frac must be <= 0.5 (the serve trigger fires at "
                f"half-buffer, so a larger refill would overwrite unserved "
                f"rows), got {self.refill_frac}; set 0.5 for reference "
                f"parity"
            )
        if self.buffer_device not in ("host", "hbm"):
            raise ValueError(
                f"buffer_device must be 'host' or 'hbm', got {self.buffer_device!r}"
            )
        if self.seq_shards < 0:
            raise ValueError("seq_shards must be >= 0")
        if self.shard_lm and self.model_axis_size < 2:
            raise ValueError(
                "shard_lm needs model_axis_size >= 2 (a 1-wide model axis "
                "shards nothing)"
            )
        if self.shard_lm and self.seq_shards > 1:
            raise ValueError(
                "shard_lm is incompatible with seq_shards: the seq-parallel "
                "harvest replicates LM params (its shard_map in_specs), "
                "which would silently all-gather the TP shards onto every "
                "device — the OOM shard_lm exists to prevent"
            )
        if self.seq_shards > 1 and self.seq_len % self.seq_shards != 0:
            raise ValueError(
                f"seq_shards {self.seq_shards} must divide seq_len {self.seq_len}"
            )
        _check_choice("harvest_runtime", self.harvest_runtime,
                      ("padded", "paged"))
        if self.page_size < 1 or self.page_size & (self.page_size - 1):
            below = 1 << max(0, self.page_size.bit_length() - 1)
            raise ValueError(
                f"page_size must be a power of two (the KV page is the "
                f"attention kernel's DMA/compute quantum), got "
                f"{self.page_size}; try {below} or {2 * below}"
            )
        if self.harvest_runtime == "paged":
            if self.seq_len < self.page_size:
                raise ValueError(
                    f"harvest_runtime='paged': seq_len {self.seq_len} is "
                    f"smaller than page_size {self.page_size} — a document "
                    f"cannot fill even one KV page; lower page_size to a "
                    f"power of two <= {self.seq_len}"
                )
            if self.seq_len % self.page_size != 0:
                divisors = [p for p in (16, 32, 64, 128, 256, 512)
                            if p <= self.seq_len and self.seq_len % p == 0]
                raise ValueError(
                    f"harvest_runtime='paged': page_size {self.page_size} "
                    f"must divide seq_len {self.seq_len} (the KV block "
                    f"layout is whole pages); try one of "
                    f"{divisors or 'a power-of-two divisor of seq_len'}"
                )
            if self.seq_shards > 1:
                raise ValueError(
                    "harvest_runtime='paged' is incompatible with "
                    "seq_shards: the paged plane packs the sequence axis "
                    "densely, while the seq-parallel harvest shards it "
                    "over the mesh — pick one"
                )
        if self.sparse_decode and self.activation != "topk":
            raise ValueError(
                f"sparse_decode requires activation='topk', got {self.activation!r}"
            )
        _check_choice("factored_decode", self.factored_decode,
                      ("auto", "on", "off"))
        if self.factored_decode == "on" and self.activation != "topk":
            raise ValueError(
                f"factored_decode='on' requires activation='topk', "
                f"got {self.activation!r}"
            )
        if self.factored_decode == "on" and self.l1_coeff != 0:
            raise ValueError(
                "factored_decode='on' requires l1_coeff=0: the factored "
                "forward's custom VJP carries no gradient path through "
                "(vals, idx), which a nonzero weighted-L1 objective needs"
            )
        _check_choice("sparse_bwd", self.sparse_bwd, ("auto", "on", "off"))
        if self.sparse_bwd == "on" and self.activation != "topk":
            raise ValueError(
                f"sparse_bwd='on' requires activation='topk' (the sparse "
                f"backward consumes the factored (vals, idx) the TopK tier "
                f"produces), got {self.activation!r}"
            )
        if self.sparse_bwd == "on" and self.l1_coeff != 0:
            raise ValueError(
                "sparse_bwd='on' requires l1_coeff=0: like the factored "
                "tier it extends, its custom VJP carries no gradient path "
                "through (vals, idx), which a nonzero weighted-L1 "
                "objective needs"
            )
        if self.sparse_bwd == "on" and self.sparse_decode:
            raise ValueError(
                "sparse_bwd='on' is incompatible with sparse_decode: the "
                "sparse backward extends the factored Pallas tier, not the "
                "legacy gather decode (which has its own custom VJP)"
            )
        _check_choice("fused_encoder", self.fused_encoder,
                      ("auto", "on", "off"))
        if self.fused_encoder == "on":
            if self.activation not in ("topk", "batchtopk"):
                raise ValueError(
                    f"fused_encoder='on' requires activation='topk' or "
                    f"'batchtopk' (the kernel IS a fused TopK/BatchTopK "
                    f"selection), got {self.activation!r}"
                )
            if self.activation == "topk":
                if self.sparse_bwd == "off":
                    raise ValueError(
                        "fused_encoder='on' with activation='topk' requires "
                        "sparse_bwd != 'off': the fused forward hands "
                        "(vals, idx) to the sparse backward plane — without "
                        "it the backward would need the dense pre-acts the "
                        "fusion exists to never materialize"
                    )
                if self.l1_coeff != 0:
                    raise ValueError(
                        "fused_encoder='on' with activation='topk' requires "
                        "l1_coeff=0 (the factored/sparse tier it rides "
                        "carries no gradient path through (vals, idx))"
                    )
                if self.sparse_decode:
                    raise ValueError(
                        "fused_encoder='on' is incompatible with "
                        "sparse_decode: the fused tier extends the factored "
                        "Pallas tier, not the legacy gather decode"
                    )
        if self.quant_encoder:
            if self.fused_encoder == "off":
                raise ValueError(
                    "quant_encoder requires fused_encoder != 'off': the "
                    "int8 block-scaled matmul lives INSIDE the fused "
                    "kernel; with the fused tier off the knob would "
                    "silently do nothing"
                )
            if self.activation != "topk":
                raise ValueError(
                    f"quant_encoder requires activation='topk': the int8 "
                    f"path lives in the fused TopK kernel only (BatchTopK "
                    f"stacks quantization error into a GLOBAL order "
                    f"statistic and stays exact), got {self.activation!r}"
                )
            nd = self.n_sources * self.d_in
            if self.quant_block % 128 or nd % self.quant_block:
                divisors = [b for b in (128, 256, 384, 512)
                            if nd % b == 0]
                raise ValueError(
                    f"quant_encoder: quant_block {self.quant_block} must be "
                    f"a multiple of 128 dividing n_sources*d_in = {nd} (the "
                    f"in-kernel int8 dot slices the contraction axis per "
                    f"block); try one of "
                    f"{divisors or 'a lane-aligned divisor'}"
                )
        if self.l0_coeff > 0 and self.activation != "jumprelu":
            raise ValueError(
                f"l0_coeff requires activation='jumprelu' (the rectangle-"
                f"kernel STE needs a threshold), got {self.activation!r}"
            )
        if self.batchtopk_threshold > 0 and self.activation != "batchtopk":
            raise ValueError(
                f"batchtopk_threshold requires activation='batchtopk', "
                f"got {self.activation!r}"
            )
        if self.aux_k < 0:
            raise ValueError(f"aux_k must be >= 0, got {self.aux_k}")
        if self.aux_k > self.dict_size:
            raise ValueError(
                f"aux_k {self.aux_k} cannot exceed dict_size {self.dict_size}"
            )
        if self.aux_k > 0 and self.aux_dead_steps < 1:
            raise ValueError("aux_dead_steps must be >= 1 when aux_k > 0")
        if self.aux_every < 1:
            raise ValueError(f"aux_every must be >= 1, got {self.aux_every}")
        if self.resample_every < 0 or self.resample_dead_steps < 0:
            raise ValueError(
                f"resample_every/resample_dead_steps must be >= 0, got "
                f"{self.resample_every}/{self.resample_dead_steps}"
            )
        if self.resample_every > 0 and self.resample_threshold_steps < 1:
            raise ValueError(
                "resampling needs a deadness threshold: set "
                "resample_dead_steps (or aux_dead_steps) >= 1"
            )
        if self.stop_poll_every < 1:
            raise ValueError(
                f"stop_poll_every must be >= 1, got {self.stop_poll_every}"
            )
        _check_choice("refill_overlap", self.refill_overlap, ("off", "on"))
        if self.refill_dispatch_batch < 1:
            raise ValueError(
                f"refill_dispatch_batch must be >= 1 (harvest quanta fused "
                f"per dispatch), got {self.refill_dispatch_batch}"
            )
        if self.loss_spike_factor <= 1.0:
            raise ValueError(
                f"loss_spike_factor must be > 1 (it multiplies the last "
                f"healthy loss), got {self.loss_spike_factor}"
            )
        if self.max_rollbacks < 0:
            raise ValueError(f"max_rollbacks must be >= 0, got {self.max_rollbacks}")
        if self.keep_saves < 0:
            raise ValueError(f"keep_saves must be >= 0 (0 = unbounded), got {self.keep_saves}")
        if self.guard_loss and self.keep_saves == 1:
            raise ValueError(
                "guard_loss with keep_saves=1 leaves rollback no fallback "
                "save when the newest is corrupt/poisoned; use keep_saves=0 "
                "(unbounded) or >= 2"
            )
        if self.harvest_timeout_s < 0:
            raise ValueError(f"harvest_timeout_s must be >= 0, got {self.harvest_timeout_s}")
        if self.harvest_retries < 0 or self.harvest_backoff_s < 0:
            raise ValueError(
                f"harvest_retries/harvest_backoff_s must be >= 0, got "
                f"{self.harvest_retries}/{self.harvest_backoff_s}"
            )
        _check_choice("elastic", self.elastic, ("off", "on"))
        if self.elastic == "on":
            if self.elastic_heartbeat_s <= 0:
                raise ValueError(
                    f"elastic_heartbeat_s must be > 0, got "
                    f"{self.elastic_heartbeat_s}"
                )
            if self.elastic_grace_s < self.elastic_heartbeat_s:
                raise ValueError(
                    f"elastic_grace_s ({self.elastic_grace_s}) must be >= "
                    f"elastic_heartbeat_s ({self.elastic_heartbeat_s}): the "
                    f"liveness barrier cannot declare a peer lost faster "
                    f"than the heartbeat can notice it"
                )
            if self.seq_shards > 1:
                raise ValueError(
                    "elastic='on' cannot run with seq_shards > 1: the "
                    "sequence-parallel harvest pins the mesh data axis to "
                    "seq_shards, which a survivor re-mesh cannot preserve"
                )
            if self.elastic_suspect_probes < 1:
                raise ValueError(
                    f"elastic_suspect_probes must be >= 1, got "
                    f"{self.elastic_suspect_probes} (1 = declare on the "
                    f"first failed probe, no hysteresis)"
                )
        _check_choice("elastic_grow", self.elastic_grow, ("off", "on"))
        _check_choice("elastic_policy", self.elastic_policy,
                      ("fixed", "score"))
        if self.elastic_grow == "on":
            if self.elastic != "on":
                raise ValueError(
                    "elastic_grow='on' requires elastic='on': scale-up "
                    "re-forms the world the elastic membership layer owns"
                )
            if not self.checkpoint_dir:
                raise ValueError(
                    "elastic_grow='on' requires checkpoint_dir: the rejoin "
                    "rendezvous board and the admission boundary save both "
                    "live under it (joiners hydrate from that save)"
                )
            if self.elastic_dwell_steps < 0:
                raise ValueError(
                    f"elastic_dwell_steps must be >= 0, got "
                    f"{self.elastic_dwell_steps}"
                )
            if self.elastic_grow_debounce < 1:
                raise ValueError(
                    f"elastic_grow_debounce must be >= 1, got "
                    f"{self.elastic_grow_debounce}"
                )
        _check_choice("fleet", self.fleet, ("off", "on"))
        if self.fleet == "on":
            if self.fleet_max_buckets < 1:
                raise ValueError(
                    f"fleet_max_buckets must be >= 1, got "
                    f"{self.fleet_max_buckets} (each stacked cohort and "
                    f"each heterogeneous tenant signature costs one "
                    f"compile bucket)"
                )
            if self.quant_grads:
                raise ValueError(
                    "fleet='on' is incompatible with quant_grads: the "
                    "stacked (vmapped) tenant step cannot nest the "
                    "shard_map quantized all-reduce; train quantized "
                    "sweeps as sequential solo runs"
                )
        elif self.fleet_tenants:
            raise ValueError(
                "fleet_tenants is set but fleet='off'; pass --fleet on "
                "(the spec would otherwise be silently ignored)"
            )
        _check_choice("serve", self.serve, ("off", "on"))
        if self.serve == "on":
            b = self.serve_max_batch
            if not 1 <= b <= 128 or b & (b - 1):
                raise ValueError(
                    f"serve_max_batch must be a power of two in [1, 128], "
                    f"got {b} (each bucket in the 1..serve_max_batch "
                    f"ladder is one AOT-prewarmed compiled shape; the "
                    f"ladder must stay <= 8 buckets)"
                )
            if self.serve_max_wait_ms < 0:
                raise ValueError(
                    f"serve_max_wait_ms must be >= 0, got "
                    f"{self.serve_max_wait_ms}"
                )
            if self.serve_queue < self.serve_max_batch:
                raise ValueError(
                    f"serve_queue ({self.serve_queue}) must be >= "
                    f"serve_max_batch ({self.serve_max_batch}): the queue "
                    f"must be able to hold at least one full micro-batch"
                )
            if self.serve_shed_ms < 0:
                raise ValueError(
                    f"serve_shed_ms must be >= 0 (0 disables queue-age "
                    f"eviction), got {self.serve_shed_ms}"
                )
        if self.quant_block < 1:
            raise ValueError(
                f"quant_block must be >= 1, got {self.quant_block}; 256 is "
                f"the default (4/256 bytes/element of f32-scale overhead)"
            )
        if self.quant_buffer and self.d_in % self.quant_block != 0:
            divisors = [b for b in (32, 64, 128, 256, 512)
                        if self.d_in % b == 0]
            raise ValueError(
                f"quant_buffer: quant_block {self.quant_block} must divide "
                f"d_in {self.d_in} (scales are per contiguous feature "
                f"block); try one of {divisors or 'a divisor of d_in'}"
            )
        if self.quant_grads and (self.model_axis_size > 1 or self.shard_sources):
            raise ValueError(
                "quant_grads supports pure data parallelism only "
                "(model_axis_size == 1, shard_sources off): the quantized "
                "all-reduce replaces the DP gradient psum; TP/EP grad "
                "slices keep the exact bf16/f32 psum"
            )
        if self.quant_grads and self.activation == "batchtopk":
            raise ValueError(
                "quant_grads is incompatible with activation='batchtopk': "
                "the quantized step computes per-device losses, but "
                "batchtopk's threshold is a GLOBAL-batch order statistic"
            )
        _check_choice("obs", self.obs, ("off", "on"))
        if self.log_print_every < 0:
            raise ValueError(
                f"log_print_every must be >= 0 (0 = never echo), got "
                f"{self.log_print_every}"
            )
        if self.profile_steps:
            from crosscoder_tpu.obs.profiler import parse_profile_steps

            parse_profile_steps(self.profile_steps)   # raises on a bad spec
        if self.aux_mask_every < 0:
            raise ValueError(
                f"aux_mask_every must be >= 0 (1 = per-step exact, N = "
                f"refresh every N steps, 0 = follow log_every), got "
                f"{self.aux_mask_every}"
            )
        _check_choice("compile_cache_verify", self.compile_cache_verify,
                      ("off", "strict"))
        if self.compile_cache_max_bytes <= 0:
            raise ValueError(
                f"compile_cache_max_bytes must be > 0 (the disk tier "
                f"needs a positive byte cap; disable the tier with "
                f"compile_cache_dir='' instead), got "
                f"{self.compile_cache_max_bytes}"
            )
        if self.compile_cache_dir:
            # fail at config time, not mid-warmup: the tier directory
            # must be creatable/writable on this host
            try:
                os.makedirs(self.compile_cache_dir, exist_ok=True)
            except OSError as e:
                raise ValueError(
                    f"compile_cache_dir {self.compile_cache_dir!r} is not "
                    f"creatable ({e}); point it at writable storage or "
                    f"leave it empty to disable the persistent compile "
                    f"cache"
                ) from e

    # --- derived quantities -------------------------------------------------
    @property
    def total_steps(self) -> int:
        """Optimizer steps for the token budget (reference trainer.py:14)."""
        return self.num_tokens // self.batch_size

    @property
    def aux_mask_cadence(self) -> int:
        """Resolved dead-mask refresh cadence in steps (``aux_mask_every``,
        with 0 meaning the ``log_every`` interval)."""
        return self.aux_mask_every if self.aux_mask_every >= 1 else self.log_every

    @property
    def resample_threshold_steps(self) -> int:
        """Deadness threshold for resampling (resample_dead_steps, falling
        back to aux_dead_steps)."""
        return self.resample_dead_steps or self.aux_dead_steps

    @property
    def n_layers_hooked(self) -> int:
        """Number of hook points per model (multi-layer crosscoders)."""
        return max(1, len(self.hook_points))

    @property
    def n_sources(self) -> int:
        """Size of the crosscoder's 'model' axis: models × hooked layers.

        A multi-layer crosscoder over L hook points of M models is represented
        as a single source axis of length M*L, which generalizes the
        reference's hardcoded pair.
        """
        return self.n_models * self.n_layers_hooked

    @property
    def hook_layer(self) -> int:
        """Layer index parsed from ``hook_point`` ('blocks.N.hook_resid_pre')."""
        return parse_hook_point(self.hook_point)[0]

    def resolved_hook_points(self) -> tuple[str, ...]:
        return self.hook_points if self.hook_points else (self.hook_point,)

    # --- (de)serialization --------------------------------------------------
    def to_dict(self) -> dict[str, Any]:
        """Flat JSON-ready dict using the reference's key names."""
        d = dataclasses.asdict(self)
        extras = d.pop("extras")
        d["hook_points"] = list(self.hook_points)
        d.update(extras)
        return d

    def to_json_str(self) -> str:
        """The single serialized form — every cfg JSON writer (to_json, the
        checkpointer's atomic write) goes through this."""
        return json.dumps(self.to_dict(), indent=2)

    def to_json(self, path: str | Path) -> None:
        Path(path).write_text(self.to_json_str())

    @classmethod
    def from_dict(cls, d: dict[str, Any]) -> "CrossCoderConfig":
        """Build from a flat dict; unknown keys (e.g. from the reference's
        published cfg JSONs) are preserved in ``extras``."""
        known = {f.name for f in dataclasses.fields(cls)} - {"extras"}
        kwargs = {k: v for k, v in d.items() if k in known}
        extras = {k: v for k, v in d.items() if k not in known}
        # published reference cfgs carry e.g. "device": "cuda:1" — keep it in
        # the field for round-trip but it has no effect on placement here.
        return cls(**kwargs, extras=extras)

    @classmethod
    def from_json(cls, path: str | Path) -> "CrossCoderConfig":
        return cls.from_dict(json.loads(Path(path).read_text()))

    def replace(self, **kwargs: Any) -> "CrossCoderConfig":
        return dataclasses.replace(self, **kwargs)

    # --- CLI ----------------------------------------------------------------
    @classmethod
    def from_cli(cls, argv: list[str] | None = None, base: "CrossCoderConfig | None" = None) -> "CrossCoderConfig":
        """Reflect config fields into argparse flags and apply overrides.

        This is the working version of the reference's dead CLI path
        (``utils.py:151-178`` is defined but never called from ``train.py``,
        so ``run_training.sh:4``'s ``"$@"`` is dropped on the floor).
        """
        base = base or cls()
        parser = argparse.ArgumentParser(description="crosscoder_tpu training config")
        parser.add_argument("--config-json", type=str, default=None, help="load a cfg JSON before applying flags")
        for f in dataclasses.fields(cls):
            if f.name == "extras":
                continue
            val = getattr(base, f.name)
            flag = f"--{f.name.replace('_', '-')}"
            if isinstance(val, bool):
                parser.add_argument(flag, type=_parse_bool, default=None)
            elif isinstance(val, tuple):
                parser.add_argument(flag, type=str, default=None, help="comma-separated list")
            elif isinstance(val, int):
                parser.add_argument(flag, type=int, default=None)
            elif isinstance(val, float):
                parser.add_argument(flag, type=float, default=None)
            else:
                parser.add_argument(flag, type=str, default=None)
        ns = parser.parse_args(argv)
        if ns.config_json:
            base = cls.from_json(ns.config_json)
        # tuned-artifact resolution order (docs/TUNING.md): defaults →
        # --config-json → TUNED.json knobs → explicit flags. The artifact
        # sits between the JSON and the flags so an operator can always
        # override a pinned knob from the command line; --tuned "" clears
        # an artifact a config JSON carried.
        tuned_path = ns.tuned if ns.tuned is not None else base.tuned
        if tuned_path:
            from crosscoder_tpu.tune.artifact import apply_tuned

            base = apply_tuned(base, tuned_path)
        elif ns.tuned == "":
            base = base.replace(tuned="")
        overrides: dict[str, Any] = {}
        for f in dataclasses.fields(cls):
            if f.name == "extras":
                continue
            v = getattr(ns, f.name, None)
            if v is not None:
                if isinstance(getattr(base, f.name), tuple):
                    v = tuple(x for x in v.split(",") if x)
                overrides[f.name] = v
        return base.replace(**overrides) if overrides else base


def known_attrs() -> frozenset[str]:
    """Every public name resolvable on a ``CrossCoderConfig`` instance:
    dataclass fields, properties, and methods. The static cfg-field lint
    (analysis/contracts/ast_lints.py) checks every ``cfg.<attr>`` read in
    the codebase against this surface, so a typo'd knob read fails lint
    instead of raising AttributeError three hours into a run."""
    names = {f.name for f in dataclasses.fields(CrossCoderConfig)}
    names.update(n for n in vars(CrossCoderConfig) if not n.startswith("_"))
    return frozenset(names)


def _parse_bool(s: str) -> bool:
    low = s.lower()
    if low in ("1", "true", "yes", "on"):
        return True
    if low in ("0", "false", "no", "off"):
        return False
    raise argparse.ArgumentTypeError(f"expected a boolean, got {s!r}")


def parse_hook_point(hook_point: str) -> tuple[int, str]:
    """Parse 'blocks.{L}.hook_{site}' → (L, site).

    The naming scheme follows the reference's TransformerLens hook strings
    (e.g. 'blocks.14.hook_resid_pre', reference train.py:32) so cfg JSONs and
    analysis code stay interoperable.
    """
    parts = hook_point.split(".")
    if len(parts) != 3 or parts[0] != "blocks" or not parts[2].startswith("hook_"):
        raise ValueError(f"unsupported hook point {hook_point!r}; expected 'blocks.N.hook_<site>'")
    return int(parts[1]), parts[2][len("hook_"):]


def get_default_cfg(d_in: int | None = None, **overrides: Any) -> CrossCoderConfig:
    """Default config, mirroring reference ``get_default_cfg`` (train.py:8-41).

    The reference injects ``d_in`` from the loaded model
    (``cfg["d_in"] = base_model.cfg.d_model``, train.py:38-40); pass it here
    the same way when a model is already loaded.
    """
    cfg = CrossCoderConfig(**overrides)
    if d_in is not None:
        cfg = cfg.replace(d_in=d_in)
    return cfg
