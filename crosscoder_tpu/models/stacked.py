"""Stacked-tenant plumbing for the fleet scheduler (train/fleet.py).

A fleet cohort of shape-identical tenants (same d_in/dict_size/k — they
differ only in seed or l1/aux hyperparameters) trains as ONE program: the
solo step body from :func:`crosscoder_tpu.train.trainer.make_step_body`
is ``jax.vmap``-ed over a leading tenant axis on the TrainState, with the
batch and norm scale broadcast (in_axes=None — the whole point: every
tenant trains on the SAME served batch, so the harvest and the H2D
transfer are paid once per cohort, not per tenant) and the per-tenant
``l1_base`` vector mapped. One compile, one dispatch per cohort step.

vmap of a batched einsum is the same einsum with one more batch dim — on
CPU and TPU the per-tenant lanes run the identical contraction the solo
step runs, which is what makes the per-tenant loss trajectories bitwise
equal to solo runs (asserted in tests/test_fleet.py).

Sharding: each stacked leaf gets the solo leaf's PartitionSpec with a
leading ``None`` (tenants replicate across the mesh; the dict/data axes
shard exactly as solo). Donation of the stacked state works unchanged —
the stacked step's output state aliases its input buffers.
"""

from __future__ import annotations

from functools import partial
from typing import Any, Callable, Sequence

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def stack_states(states: Sequence[Any]) -> Any:
    """Stack N structurally-identical TrainStates along a new leading
    tenant axis (leaf-wise ``jnp.stack``). Scalars (the step counter,
    Adam's count) become ``[N]`` vectors — cohort members step in
    lockstep but their values stay per-tenant."""
    if not states:
        raise ValueError("stack_states needs at least one state")
    return jax.tree_util.tree_map(lambda *xs: jnp.stack(xs), *states)


def unstack_state(stacked: Any, i: int) -> Any:
    """Tenant ``i``'s solo TrainState view of a stacked state (leaf-wise
    index on the leading axis) — used for per-tenant checkpointing and
    retirement restacking."""
    return jax.tree_util.tree_map(lambda a: a[i], stacked)


@partial(jax.jit, static_argnums=1)
def unstack_metrics(stacked: Any, n: int) -> list[Any]:
    """Split a vmapped step's stacked metrics into per-tenant trees in
    ONE dispatch. The naive per-member ``tree_map(a[i])`` costs
    ``n × n_leaves`` host dispatches per round, which dominated the
    fleet round at bench shapes; under jit the whole unstack is a single
    program (cached per metric structure)."""
    return [jax.tree_util.tree_map(lambda a, i=i: a[i], stacked)
            for i in range(n)]


def restack_without(stacked: Any, i: int) -> Any:
    """Drop tenant ``i`` from a stacked state (retirement: the survivors'
    cohort recompiles at N-1 but their per-tenant values carry over)."""
    return jax.tree_util.tree_map(
        lambda a: jnp.concatenate([a[:i], a[i + 1:]], axis=0), stacked
    )


def stacked_shardings(mesh: Mesh, solo_shardings: Any) -> Any:
    """Shardings for a stacked TrainState: each solo leaf's PartitionSpec
    with a leading ``None`` (tenant axis replicated, inner axes unchanged
    — the dict axis still shards over 'model', quant_ef is rejected by
    config validation before this can see one)."""
    return jax.tree_util.tree_map(
        lambda s: NamedSharding(mesh, P(None, *s.spec)),
        solo_shardings,
    )


def vmap_step(body: Callable[..., Any]) -> Callable[..., Any]:
    """Vectorize an ``l1_input`` step body over the tenant axis:
    ``(stacked_state, batch, scale, l1_vec) -> (stacked_state, metrics)``
    with batch/scale broadcast and state/l1 mapped. Metrics come back
    with a leading ``[N]`` axis — one slot per tenant."""
    return jax.vmap(body, in_axes=(0, None, None, 0), out_axes=(0, 0))


def stacked_l1_vector(l1_coeffs: Sequence[float]) -> jax.Array:
    """The cohort's per-tenant l1 base coefficients as a replicated f32
    vector (the traced ``l1_base`` input of the ``l1_input`` step)."""
    return jnp.asarray(list(l1_coeffs), jnp.float32)
