"""JAX Gemma-2 runtime with residual-stream capture and splicing.

This module replaces the reference's entire "external model runtime" layer —
TransformerLens ``HookedTransformer`` (reference ``train.py:45-55``,
``buffer.py:81-89``, ``nb:cell 29``) — with a TPU-native functional LM:

- ``forward(params, tokens, cfg, capture=..., edit=...)`` is ONE jittable,
  mesh-shardable function. ``capture`` replaces ``run_with_cache(
  names_filter=hook_point)``; ``edit`` replaces ``run_with_hooks(
  fwd_hooks=[(hook_point, fn)])`` used by the CE-recovered eval
  (reference ``nb:cell 29``'s ``splice_act_hook`` / ``zero_ablation_hook``).
- Hook names follow the reference's TransformerLens strings
  (``blocks.{L}.hook_resid_pre`` — reference ``train.py:32``) so configs and
  analysis code carry over unchanged.

TPU-first design decisions (why this is not a TransformerLens translation):

- Layers are STACKED pytrees run under ``lax.scan`` — one traced block,
  compiled once, instead of 26 unrolled layer graphs. Capture and editing
  inside the scan use arithmetic masking on the layer index (each requested
  layer matches exactly one slot of a preallocated capture buffer), so
  arbitrary hook layers cost one fused multiply-add per layer and the graph
  stays static — no Python callbacks in the hot path.
- All attention/MLP matmuls are bf16 einsums with fp32 accumulation
  (``preferred_element_type``) sized for the MXU; softmax/RMSNorm reductions
  run in fp32.
- Batch/sequence axes shard over the mesh ``data`` axis (harvest-side
  sharding, SURVEY.md component N5); params are replicated by default
  (Gemma-2-2B bf16 ≈ 5.2 GB/model fits one chip's HBM) — shardings are
  expressed at the call site, not baked in here.

Gemma-2 architecture facts implemented (validated against the HF
``transformers`` Gemma2 implementation by ``tests/test_lm.py``): RMSNorm with
(1+w) scaling in fp32; embedding scaled by sqrt(d_model); GeGLU MLP with
tanh-approximate GELU; GQA; RoPE; attention-logit softcapping (50.0) and
final-logit softcapping (30.0); alternating sliding-window/global attention
(even layers local); query scale ``query_pre_attn_scalar**-0.5``.
"""

from __future__ import annotations

import dataclasses
import functools
import math
import os
from dataclasses import dataclass
from typing import Any, Callable, Mapping, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from crosscoder_tpu.config import parse_hook_point
from crosscoder_tpu.utils.dtypes import dtype_of


def _put_global(tree, shardings):
    # collective-free host->mesh placement (multihost.put_global); local
    # alias avoids repeating the deferred import at three call sites
    from crosscoder_tpu.parallel import multihost

    return multihost.put_global(tree, shardings)

LMParams = dict[str, Any]


@dataclass(frozen=True)
class LMConfig:
    """Gemma-2 family architecture config."""

    vocab_size: int
    d_model: int
    n_layers: int
    n_heads: int
    n_kv_heads: int
    head_dim: int
    d_ff: int
    rope_theta: float = 10_000.0
    rms_eps: float = 1e-6
    attn_softcap: float = 50.0
    final_softcap: float = 30.0
    sliding_window: int = 4096
    query_pre_attn_scalar: float = 256.0
    dtype: str = "bf16"

    @classmethod
    def gemma2_2b(cls) -> "LMConfig":
        """Gemma-2-2B — the reference's subject model pair (train.py:10-12)."""
        return cls(
            vocab_size=256_000, d_model=2304, n_layers=26, n_heads=8,
            n_kv_heads=4, head_dim=256, d_ff=9216, query_pre_attn_scalar=256.0,
        )

    @classmethod
    def gemma2_9b(cls) -> "LMConfig":
        """Gemma-2-9B (d_model 3584) — BASELINE scale-out config 3."""
        return cls(
            vocab_size=256_000, d_model=3584, n_layers=42, n_heads=16,
            n_kv_heads=8, head_dim=256, d_ff=14_336, query_pre_attn_scalar=256.0,
        )

    @classmethod
    def gemma2_27b(cls) -> "LMConfig":
        """Gemma-2-27B — the family's largest member (NB: unlike 2B/9B its
        query scale is d_model/n_heads = 144, not head_dim)."""
        return cls(
            vocab_size=256_000, d_model=4608, n_layers=46, n_heads=32,
            n_kv_heads=16, head_dim=128, d_ff=36_864,
            query_pre_attn_scalar=144.0,
        )

    @classmethod
    def tiny(cls, vocab_size: int = 257, n_layers: int = 4) -> "LMConfig":
        """Deterministic test-sized config (the 'fake LM' of SURVEY.md §4 —
        same hook semantics as the real model, no 2.6B-param download)."""
        return cls(
            vocab_size=vocab_size, d_model=32, n_layers=n_layers, n_heads=4,
            n_kv_heads=2, head_dim=8, d_ff=64, sliding_window=8,
            query_pre_attn_scalar=8.0, dtype="fp32",
        )

    def replace(self, **kw: Any) -> "LMConfig":
        return dataclasses.replace(self, **kw)


_NAMED_CONFIGS = {
    "gemma-2-2b": LMConfig.gemma2_2b,
    "gemma-2-2b-it": LMConfig.gemma2_2b,
    "gemma-2-9b": LMConfig.gemma2_9b,
    "gemma-2-9b-it": LMConfig.gemma2_9b,
    "gemma-2-27b": LMConfig.gemma2_27b,
    "gemma-2-27b-it": LMConfig.gemma2_27b,
}


def config_for(model_name: str) -> LMConfig:
    """Architecture config by HF-style model name (reference train.py:25)."""
    key = model_name.split("/")[-1].lower()
    if key not in _NAMED_CONFIGS:
        raise ValueError(f"unknown model {model_name!r}; known: {sorted(_NAMED_CONFIGS)}")
    return _NAMED_CONFIGS[key]()


# ---------------------------------------------------------------------------
# params


def init_params(key: jax.Array, cfg: LMConfig) -> LMParams:
    """Random-init params (the fake-LM fixture; real runs use ``from_hf``).

    Layer leaves are stacked on a leading [n_layers] axis for ``lax.scan``.
    """
    dt = dtype_of(cfg.dtype)
    D, F, L = cfg.d_model, cfg.d_ff, cfg.n_layers
    qd, kd = cfg.n_heads * cfg.head_dim, cfg.n_kv_heads * cfg.head_dim
    ks = jax.random.split(key, 9)

    def nrm(k, shape, scale):
        return (jax.random.normal(k, shape, dtype=jnp.float32) * scale).astype(dt)

    return {
        "embed": nrm(ks[0], (cfg.vocab_size, D), D ** -0.5),
        "final_norm": jnp.zeros((D,), dt),
        "layers": {
            "attn_norm": jnp.zeros((L, D), dt),
            "post_attn_norm": jnp.zeros((L, D), dt),
            "pre_ffw_norm": jnp.zeros((L, D), dt),
            "post_ffw_norm": jnp.zeros((L, D), dt),
            "wq": nrm(ks[1], (L, D, qd), D ** -0.5),
            "wk": nrm(ks[2], (L, D, kd), D ** -0.5),
            "wv": nrm(ks[3], (L, D, kd), D ** -0.5),
            "wo": nrm(ks[4], (L, qd, D), qd ** -0.5),
            "w_gate": nrm(ks[5], (L, D, F), D ** -0.5),
            "w_up": nrm(ks[6], (L, D, F), D ** -0.5),
            "w_down": nrm(ks[7], (L, F, D), F ** -0.5),
        },
    }


def param_count(cfg: LMConfig) -> int:
    D, F, L = cfg.d_model, cfg.d_ff, cfg.n_layers
    qd, kd = cfg.n_heads * cfg.head_dim, cfg.n_kv_heads * cfg.head_dim
    per_layer = 4 * D + D * qd + 2 * D * kd + qd * D + 2 * D * F + F * D
    return cfg.vocab_size * D + D + L * per_layer


# ---------------------------------------------------------------------------
# numerics


def _rms_norm(x: jax.Array, w: jax.Array, eps: float) -> jax.Array:
    """Gemma RMSNorm: fp32 compute, (1 + w) scale."""
    xf = x.astype(jnp.float32)
    xf = xf * jax.lax.rsqrt(jnp.mean(jnp.square(xf), axis=-1, keepdims=True) + eps)
    return (xf * (1.0 + w.astype(jnp.float32))).astype(x.dtype)


def _softcap(x: jax.Array, cap: float) -> jax.Array:
    return cap * jnp.tanh(x / cap)


def _rope(x: jax.Array, positions: jax.Array, theta: float) -> jax.Array:
    """Rotate pairs (x[..., :d/2], x[..., d/2:]) — HF 'split-half' layout.

    x: [B, S, n_heads, head_dim]; positions: [S] (shared across the batch,
    the padded path) or [B, S] (per-token — the paged runtime's packed
    plane carries each document's own within-document positions).
    """
    d = x.shape[-1]
    freqs = 1.0 / (theta ** (jnp.arange(0, d // 2, dtype=jnp.float32) * 2.0 / d))
    ang = positions.astype(jnp.float32)[..., None] * freqs   # [(B,) S, d/2]
    cos = jnp.expand_dims(jnp.cos(ang), -2)                  # [(B,) S, 1, d/2]
    sin = jnp.expand_dims(jnp.sin(ang), -2)
    x1, x2 = x[..., : d // 2], x[..., d // 2:]
    xf1, xf2 = x1.astype(jnp.float32), x2.astype(jnp.float32)
    return jnp.concatenate(
        [xf1 * cos - xf2 * sin, xf2 * cos + xf1 * sin], axis=-1
    ).astype(x.dtype)


def _qkv(
    x: jax.Array, lp: Mapping[str, jax.Array], cfg: LMConfig, pos: jax.Array
) -> tuple[jax.Array, jax.Array, jax.Array]:
    """Project + RoPE: q [B,S,H,hd], k/v [B,S,KV,hd]. ``pos`` carries GLOBAL
    positions so sequence-sharded callers rotate correctly."""
    B, S, _ = x.shape
    H, KV, hd = cfg.n_heads, cfg.n_kv_heads, cfg.head_dim
    q = jnp.einsum("bsd,dq->bsq", x, lp["wq"], preferred_element_type=jnp.float32)
    k = jnp.einsum("bsd,dk->bsk", x, lp["wk"], preferred_element_type=jnp.float32)
    v = jnp.einsum("bsd,dk->bsk", x, lp["wv"], preferred_element_type=jnp.float32)
    q = _rope(q.astype(x.dtype).reshape(B, S, H, hd), pos, cfg.rope_theta)
    k = _rope(k.astype(x.dtype).reshape(B, S, KV, hd), pos, cfg.rope_theta)
    return q, k, v.astype(x.dtype).reshape(B, S, KV, hd)


def _attn_core(
    q: jax.Array, k: jax.Array, v: jax.Array, cfg: LMConfig,
    is_local: jax.Array, lengths: jax.Array | None = None,
) -> jax.Array:
    """Masked-softmax attention on projected heads: q [B, S, H, hd],
    k/v [B, S, KV, hd] → [B, S, H·hd] (pre output-projection).

    Delegates to the ONE attention-math implementation
    (:func:`crosscoder_tpu.ops.paged_attention.ragged_attention_reference`)
    with cfg-derived scalars, so the padded forward and the paged
    runtime's XLA path / kernel oracle can never drift apart. ``lengths``
    (the paged runtime's per-document valid token counts) adds a key-side
    validity mask — a no-op for valid queries (causal ⊆ in-length), which
    is what makes the paged XLA path bit-identical to the padded forward
    at valid positions (rows at t >= length are computed on whatever the
    gather clamped to, and discarded)."""
    from crosscoder_tpu.ops import paged_attention as pa

    return pa.ragged_attention_reference(
        q, k, v, lengths,
        scale=cfg.query_pre_attn_scalar ** -0.5,
        softcap=cfg.attn_softcap, window=cfg.sliding_window,
        is_local=is_local,
    )


def _attention(
    x: jax.Array, lp: Mapping[str, jax.Array], cfg: LMConfig, is_local: jax.Array
) -> jax.Array:
    """One attention sublayer on [B, S, D]. ``is_local`` selects the
    sliding-window mask (traced scalar — both masks are static precomputes)."""
    B, S, D = x.shape
    q, k, v = _qkv(x, lp, cfg, jnp.arange(S))
    out = _attn_core(q, k, v, cfg, is_local)
    return jnp.einsum("bsq,qd->bsd", out, lp["wo"], preferred_element_type=jnp.float32).astype(x.dtype)


def _mlp(x: jax.Array, lp: Mapping[str, jax.Array]) -> jax.Array:
    """GeGLU: gelu_tanh(x·W_gate) ⊙ (x·W_up) · W_down."""
    gate = jnp.einsum("bsd,df->bsf", x, lp["w_gate"], preferred_element_type=jnp.float32)
    up = jnp.einsum("bsd,df->bsf", x, lp["w_up"], preferred_element_type=jnp.float32)
    h = (jax.nn.gelu(gate, approximate=True) * up).astype(x.dtype)
    return jnp.einsum("bsf,fd->bsd", h, lp["w_down"], preferred_element_type=jnp.float32).astype(x.dtype)


def _block(
    resid: jax.Array, lp: Mapping[str, jax.Array], cfg: LMConfig, is_local: jax.Array,
    edit_attn: Callable[[jax.Array], jax.Array] | None = None,
    edit_mlp: Callable[[jax.Array], jax.Array] | None = None,
) -> tuple[jax.Array, jax.Array, jax.Array]:
    """One Gemma-2 transformer block (sandwich norms around attn and MLP).

    Returns ``(resid, attn_out, mlp_out)`` — the updated stream plus the two
    sublayer contributions exactly as they are ADDED to it (post the Gemma-2
    sandwich post-norms), which is what ``hook_attn_out``/``hook_mlp_out``
    capture: the intermediates exist anyway, so exposing them is free.
    ``edit_attn``/``edit_mlp`` intervene on a contribution BEFORE it joins
    the stream (and before its capture) — the sublayer-site analogue of the
    residual edits, used by CE-recovered evals of sublayer crosscoders."""
    a = _attention(_rms_norm(resid, lp["attn_norm"], cfg.rms_eps), lp, cfg, is_local)
    attn_out = _rms_norm(a, lp["post_attn_norm"], cfg.rms_eps)
    if edit_attn is not None:
        attn_out = edit_attn(attn_out)
    resid = resid + attn_out
    m = _mlp(_rms_norm(resid, lp["pre_ffw_norm"], cfg.rms_eps), lp)
    mlp_out = _rms_norm(m, lp["post_ffw_norm"], cfg.rms_eps)
    if edit_mlp is not None:
        mlp_out = edit_mlp(mlp_out)
    return resid + mlp_out, attn_out, mlp_out


# ---------------------------------------------------------------------------
# hooks: capture + edits


def splice_edit(resid: jax.Array, value: jax.Array) -> jax.Array:
    """Replace all post-BOS positions, keep position 0 clean — the
    reference's ``splice_act_hook`` (``act[:, 1:, :] = spliced_act``,
    nb:cell 29)."""
    return jnp.concatenate([resid[:, :1], value[:, 1:].astype(resid.dtype)], axis=1)


def zero_edit(resid: jax.Array, value: jax.Array) -> jax.Array:
    """Zero the whole hook activation — the reference's
    ``zero_ablation_hook`` (nb:cell 29)."""
    del value
    return jnp.zeros_like(resid)


def replace_edit(resid: jax.Array, value: jax.Array) -> jax.Array:
    return value.astype(resid.dtype)


@dataclass(frozen=True)
class Edit:
    """An activation intervention at one hook point.

    ``fn(resid, value) -> resid`` must be shape-preserving and jit-pure;
    ``value`` is a traced [B, S, d_model] operand (ignored by ``zero_edit``).
    """

    hook_point: str
    fn: Callable[[jax.Array, jax.Array], jax.Array]
    value: jax.Array | None = None


# hook-site codes (static, baked into the capture tuples)
_SITE_RESID, _SITE_ATTN, _SITE_MLP = 0, 1, 2


def _capture_into(
    buf: jax.Array | None, resid: jax.Array, i, cap_arr, site: int = _SITE_RESID,
    site_arr=None,
) -> jax.Array | None:
    """Accumulate ``resid`` into the capture slot whose (layer, site) equals
    ``(i, site)`` (one-hot over slots; shared by the dense and
    sequence-parallel paths)."""
    if buf is None:
        return None
    match = (cap_arr == i)
    if site_arr is not None:
        match = match & (site_arr == site)
    match = match.astype(resid.dtype)
    return buf + match[:, None, None, None] * resid[None]


def _unembed(params: LMParams, resid: jax.Array, cfg: LMConfig) -> jax.Array:
    """Final RMSNorm → tied unembedding → final-logit softcap."""
    x = _rms_norm(resid, params["final_norm"], cfg.rms_eps)
    logits = jnp.einsum("bsd,vd->bsv", x, params["embed"], preferred_element_type=jnp.float32)
    if cfg.final_softcap:
        logits = _softcap(logits, cfg.final_softcap)
    return logits


def _hook_layers(cfg: LMConfig, hook_points: Sequence[str]) -> tuple[tuple[int, int], ...]:
    """Map hook strings to capture ``(layer, site)`` pairs.

    Residual sites: ``resid_pre`` of layer L is the stream entering block L
    (slot (L, resid)); ``resid_post`` of L is ``resid_pre`` of L+1 (the
    final layer's post-stream is slot (n_layers, resid)). Sublayer sites
    (TransformerLens exposes these; the reference only ever uses
    ``resid_pre``, reference train.py:32): ``attn_out`` / ``mlp_out`` of
    layer L are the block's attention/MLP contributions as ADDED to the
    stream — i.e. after Gemma-2's post-sublayer sandwich norms."""
    pairs = []
    for hp in hook_points:
        layer, site = parse_hook_point(hp)
        if site == "resid_pre":
            code = _SITE_RESID
        elif site == "resid_post":
            layer, code = layer + 1, _SITE_RESID
        elif site == "attn_out":
            code = _SITE_ATTN
        elif site == "mlp_out":
            code = _SITE_MLP
        else:
            raise ValueError(
                f"unsupported hook site {site!r} "
                "(resid_pre/resid_post/attn_out/mlp_out)"
            )
        max_layer = cfg.n_layers if code == _SITE_RESID else cfg.n_layers - 1
        if not 0 <= layer <= max_layer:
            raise ValueError(f"hook layer {layer} out of range for {cfg.n_layers}-layer model")
        pairs.append((layer, code))
    return tuple(pairs)


def _scan_stop(pairs: tuple[tuple[int, int], ...]) -> int:
    """Layers that must run for every capture/edit to be observable: a
    resid slot at L needs blocks [0, L); a sublayer slot at L needs block L
    itself."""
    return max(
        (layer + (1 if code != _SITE_RESID else 0) for layer, code in pairs),
        default=0,
    )


# ---------------------------------------------------------------------------
# forward


@functools.partial(
    jax.jit,
    static_argnames=(
        "cfg", "capture", "edit_fns", "edit_layers", "return_logits", "n_scan"
    ),
)
def _forward_impl(
    params: LMParams,
    tokens: jax.Array,
    cfg: LMConfig,
    capture: tuple[tuple[int, int], ...],
    edit_fns: tuple[Callable, ...],
    edit_layers: tuple[tuple[int, int], ...],
    edit_values: tuple[jax.Array, ...],
    return_logits: bool,
    n_scan: int | None = None,
):
    B, S = tokens.shape
    D = cfg.d_model
    dt = dtype_of(cfg.dtype)
    if n_scan is None:
        n_scan = cfg.n_layers

    resid = params["embed"][tokens].astype(dt) * jnp.asarray(math.sqrt(D), dt)

    n_cap = len(capture)
    cap_arr = jnp.asarray([l for l, _ in capture], dtype=jnp.int32) if n_cap else None
    cap_sites = jnp.asarray([c for _, c in capture], dtype=jnp.int32) if n_cap else None
    # static: skip the sublayer-capture FMAs entirely on resid-only runs
    want_attn = any(c == _SITE_ATTN for _, c in capture)
    want_mlp = any(c == _SITE_MLP for _, c in capture)
    cap_buf = jnp.zeros((n_cap, B, S, D), dtype=dt) if n_cap else None
    edit_site_codes = tuple(c for _, c in edit_layers)      # static
    edit_arr = (
        jnp.asarray([l for l, _ in edit_layers], dtype=jnp.int32)
        if edit_layers else None
    )

    def apply_hooks(resid, i):
        # residual-site edits only; sublayer-site edits run inside _block
        for j, fn in enumerate(edit_fns):
            if edit_site_codes[j] != _SITE_RESID:
                continue
            edited = fn(resid, edit_values[j])
            resid = jnp.where(edit_arr[j] == i, edited, resid)
        return resid

    # TransformerLens-style stop_at_layer: scan only the blocks below the
    # highest needed layer (the reference harvests with FULL forwards even
    # for a mid-stack hook — reference buffer.py:81-89 — wasting every layer
    # above it; at blocks.14 of 26 that is ~46% of the forward FLOPs)
    stacked = jax.tree_util.tree_map(lambda x: x[:n_scan], params["layers"])
    layer_ids = jnp.arange(n_scan, dtype=jnp.int32)

    def body(carry, xs):
        resid, buf = carry
        lp, i = xs
        resid = apply_hooks(resid, i)
        buf = _capture_into(buf, resid, i, cap_arr, _SITE_RESID, cap_sites)
        is_local = (i % 2) == 0                             # even layers: sliding window

        def editor_for(site):
            # sublayer-site edits, applied to the contribution at its own
            # layer BEFORE it joins the stream (and before capture). The
            # site selection is static; layer matching is the same
            # one-hot where-chain as the residual edits.
            js = [j for j, c in enumerate(edit_site_codes) if c == site]
            if not js:
                return None

            def ed(out):
                for j in js:
                    edited = edit_fns[j](out, edit_values[j])
                    out = jnp.where(edit_arr[j] == i, edited, out)
                return out

            return ed

        resid, attn_out, mlp_out = _block(
            resid, lp, cfg, is_local,
            edit_attn=editor_for(_SITE_ATTN), edit_mlp=editor_for(_SITE_MLP),
        )
        if want_attn:
            buf = _capture_into(buf, attn_out, i, cap_arr, _SITE_ATTN, cap_sites)
        if want_mlp:
            buf = _capture_into(buf, mlp_out, i, cap_arr, _SITE_MLP, cap_sites)
        return (resid, buf), None

    (resid, cap_buf), _ = jax.lax.scan(body, (resid, cap_buf), (stacked, layer_ids))
    # virtual layer n_scan: resid_pre of the first unscanned block (== final
    # resid_post when n_scan == n_layers)
    resid = apply_hooks(resid, jnp.int32(n_scan))
    cap_buf = _capture_into(cap_buf, resid, jnp.int32(n_scan), cap_arr, _SITE_RESID, cap_sites)

    logits = _unembed(params, resid, cfg) if return_logits else None
    return logits, cap_buf


def forward(
    params: LMParams,
    tokens: jax.Array,
    cfg: LMConfig,
    *,
    capture: Sequence[str] = (),
    edits: Sequence[Edit] = (),
    return_logits: bool = True,
) -> tuple[jax.Array | None, dict[str, jax.Array]]:
    """Run the LM; returns ``(logits, cache)``.

    - ``capture``: hook-point strings to record — the ``run_with_cache(
      names_filter=...)`` equivalent (reference buffer.py:81-89). The cache
      maps each string to a [B, S, d_model] array.
    - ``edits``: interventions applied BEFORE capture at the same hook —
      the ``run_with_hooks`` equivalent (nb:cell 29). Residual sites edit
      the stream; ``attn_out``/``mlp_out`` sites edit that sublayer's
      contribution before it joins the stream (so CE-recovered splicing
      works for sublayer-trained crosscoders too).
    - ``return_logits=False`` skips the unembedding (the d_model→256k matmul
      dominates harvest FLOPs above the hook layer; harvesting never needs it).
    """
    cap_pairs = _hook_layers(cfg, capture)
    edit_pairs = _hook_layers(cfg, [e.hook_point for e in edits])
    edit_fns = tuple(e.fn for e in edits)
    zeros = None
    values = []
    for e in edits:
        if e.value is not None:
            values.append(e.value)
        else:
            if zeros is None:
                zeros = jnp.zeros((tokens.shape[0], tokens.shape[1], cfg.d_model), dtype_of(cfg.dtype))
            values.append(zeros)
    # without logits, nothing above the highest hooked layer is observable
    n_scan = (
        cfg.n_layers
        if return_logits
        else min(cfg.n_layers, max(_scan_stop(cap_pairs), _scan_stop(edit_pairs)))
    )
    logits, cap_buf = _forward_impl(
        params, tokens, cfg, cap_pairs, edit_fns, edit_pairs, tuple(values),
        return_logits, n_scan=n_scan,
    )
    cache = {hp: cap_buf[i] for i, hp in enumerate(capture)}
    return logits, cache


def loss_fn(logits: jax.Array, tokens: jax.Array) -> jax.Array:
    """Mean next-token cross-entropy — TransformerLens ``return_type="loss"``
    (the CE metric of the reference eval, nb:cell 29)."""
    logp = jax.nn.log_softmax(logits[:, :-1].astype(jnp.float32), axis=-1)
    tgt = tokens[:, 1:]
    nll = -jnp.take_along_axis(logp, tgt[..., None], axis=-1)[..., 0]
    return jnp.mean(nll)


def run_with_cache(
    params: LMParams, tokens: jax.Array, cfg: LMConfig, hook_points: Sequence[str]
) -> dict[str, jax.Array]:
    """Capture-only forward (no unembedding) — the harvest primitive."""
    _, cache = forward(params, tokens, cfg, capture=hook_points, return_logits=False)
    return cache


@functools.partial(jax.jit, static_argnames=("cfg", "capture"))
def _multi_cache_impl(params_tuple, tokens, cfg: LMConfig, capture: tuple[str, ...]):
    per_source = []
    for p in params_tuple:
        cache = run_with_cache(p, tokens, cfg, capture)
        per_source.extend(cache[hp] for hp in capture)
    return jnp.stack(per_source, axis=2)                   # [B, S, n_sources, D]


def run_with_cache_multi(
    params_seq: Sequence[LMParams],
    tokens: jax.Array,
    cfg: LMConfig,
    hook_points: Sequence[str],
) -> jax.Array:
    """All models' captures in ONE compiled dispatch:
    ``[B, S, n_models·n_hooks, d_model]``, source axis model-major.

    The reference runs one ``run_with_cache`` per model per chunk (reference
    ``buffer.py:81-89``) — two kernel launches and two host round trips where
    one suffices; under a remote TPU client the fixed per-dispatch cost is
    material (SURVEY.md §3.3 harvest path). Same architecture is required
    (the reference's models share it by construction, train.py:45-55).
    """
    return _multi_cache_impl(tuple(params_seq), tokens, cfg, tuple(hook_points))


def ce_loss(
    params: LMParams, tokens: jax.Array, cfg: LMConfig, edits: Sequence[Edit] = ()
) -> jax.Array:
    """CE of a (possibly intervened) forward — one number, on device."""
    logits, _ = forward(params, tokens, cfg, edits=edits)
    return loss_fn(logits, tokens)


# ---------------------------------------------------------------------------
# segmented harvest (sub-forward dispatch quanta for the refill pipeline)


@functools.partial(jax.jit, static_argnames=("cfg", "n_cap"))
def _seg_start_impl(params: LMParams, tokens: jax.Array, cfg: LMConfig, n_cap: int):
    B, S = tokens.shape
    dt = dtype_of(cfg.dtype)
    resid = params["embed"][tokens].astype(dt) * jnp.asarray(math.sqrt(cfg.d_model), dt)
    buf = jnp.zeros((n_cap, B, S, cfg.d_model), dt)
    return resid, buf


@functools.partial(
    jax.jit, static_argnames=("cfg", "capture", "k"), donate_argnums=(1, 2)
)
def _seg_scan_impl(
    params: LMParams, resid: jax.Array, buf: jax.Array, lo: jax.Array,
    cfg: LMConfig, capture: tuple[tuple[int, int], ...], k: int,
):
    """Blocks [lo, lo+k) of the capture forward, carrying (resid, buf).

    ``lo`` is TRACED (``dynamic_slice`` on the stacked layer leaves), so one
    compiled program serves every segment of a given width — no per-range
    recompiles and no pre-split param copies. Per-layer math is identical to
    ``_forward_impl``'s scan body (same ops in the same order); only the
    scan is cut into sub-scans."""
    n_cap = len(capture)
    cap_arr = jnp.asarray([l for l, _ in capture], jnp.int32) if n_cap else None
    cap_sites = jnp.asarray([c for _, c in capture], jnp.int32) if n_cap else None
    want_attn = any(c == _SITE_ATTN for _, c in capture)
    want_mlp = any(c == _SITE_MLP for _, c in capture)
    stacked = jax.tree_util.tree_map(
        lambda x: jax.lax.dynamic_slice_in_dim(x, lo, k, axis=0), params["layers"]
    )
    layer_ids = lo + jnp.arange(k, dtype=jnp.int32)

    def body(carry, xs):
        resid, buf = carry
        lp, i = xs
        buf = _capture_into(buf, resid, i, cap_arr, _SITE_RESID, cap_sites)
        is_local = (i % 2) == 0
        resid, attn_out, mlp_out = _block(resid, lp, cfg, is_local)
        if want_attn:
            buf = _capture_into(buf, attn_out, i, cap_arr, _SITE_ATTN, cap_sites)
        if want_mlp:
            buf = _capture_into(buf, mlp_out, i, cap_arr, _SITE_MLP, cap_sites)
        return (resid, buf), None

    (resid, buf), _ = jax.lax.scan(body, (resid, buf), (stacked, layer_ids))
    return resid, buf


@functools.partial(jax.jit, static_argnames=("cfg", "capture", "n_scan", "out_dtype"))
def _seg_finish_impl(
    resids: tuple, bufs: tuple, cfg: LMConfig,
    capture: tuple[tuple[int, int], ...], n_scan: int, out_dtype,
):
    """Virtual-layer capture per model + the model-major source stack —
    output shape/order identical to :func:`run_with_cache_multi`."""
    cap_arr = jnp.asarray([l for l, _ in capture], jnp.int32)
    cap_sites = jnp.asarray([c for _, c in capture], jnp.int32)
    outs = []
    for resid, buf in zip(resids, bufs):
        buf = _capture_into(buf, resid, jnp.int32(n_scan), cap_arr, _SITE_RESID, cap_sites)
        outs.extend(buf[i] for i in range(buf.shape[0]))
    out = jnp.stack(outs, axis=2)                  # [B, S, n_sources, D]
    return out.astype(out_dtype) if out_dtype is not None else out


class SegmentedHarvest:
    """:func:`run_with_cache_multi` as a sequence of ~equal small device
    dispatches instead of one monolithic one.

    Why: the replay buffer's incremental refill interleaves harvest
    forwards with train steps on ONE serial device queue. At Gemma-2-2B
    shapes a whole-chunk forward is ~108 ms of device time — an indivisible
    quantum that lands in whichever train step queues behind it, producing
    the measured 111 ms refresh bubble (BENCH_r04 e2e max-vs-median step).
    Splitting the forward into ``SEG_LAYERS``-block sub-scans (~10-15 ms
    each) lets the buffer meter harvest work evenly across serves; the math
    is the same per-layer op sequence, so results match the monolithic path
    (asserted by tests/test_lm.py). No reference counterpart — the
    reference harvests in one blocking stall (reference buffer.py:78-96).

    Protocol: ``step()`` dispatches one quantum (async, never blocks on the
    device) and returns False once the final stacked result has been
    dispatched; ``result()`` returns the ``[B, S, n_sources, D]`` capture
    array (dispatching any remainder first). ``n_steps`` is the total
    ``step()`` budget, for pacing.
    """

    # Harvest quantum granularity: layers per sub-scan. Trade (measured,
    # BENCH e2e, gemma-2-2b pair, 14 scanned layers): smaller segments
    # bound the refresh bubble tighter (a quantum lands inside whichever
    # train step queues behind it) but each segment dispatch costs host
    # time (~6-8 ms through a tunneled single-core client; ~100 us on a
    # production host) — sweep results in artifacts/ROUND5_NOTES.md §2.
    # None = resolve $CROSSCODER_SEG_LAYERS at USE time (default 3), so
    # the env knob works regardless of import order; setting the class
    # attribute to an int overrides both.
    SEG_LAYERS: int | None = None

    @classmethod
    def seg_layers(cls) -> int:
        if cls.SEG_LAYERS is not None:
            return cls.SEG_LAYERS
        return int(os.environ.get("CROSSCODER_SEG_LAYERS", "3"))

    def __init__(
        self,
        params_seq: Sequence[LMParams],
        tokens: jax.Array,
        cfg: LMConfig,
        hook_points: Sequence[str],
        out_dtype=None,
    ) -> None:
        self.params_seq = tuple(params_seq)
        self.tokens = tokens
        self.cfg = cfg
        self.capture = _hook_layers(cfg, tuple(hook_points))
        self.n_scan = min(cfg.n_layers, _scan_stop(self.capture))
        self.out_dtype = out_dtype
        # snapshot the granularity for the job's whole life: n_steps (the
        # pacing denominator) and the per-step slice width must agree even
        # if the knob changes while this job is in flight
        self._seg_layers = self.seg_layers()
        self.n_steps = self.count(cfg, hook_points, len(self.params_seq))
        self._model_idx = 0
        self._lo = 0
        self._resid = self._buf = None
        self._done_resids: list = []
        self._done_bufs: list = []
        self._out = None

    @classmethod
    def count(cls, cfg: LMConfig, hook_points: Sequence[str], n_models: int) -> int:
        """``step()`` calls a job over these hooks will need (for pacing)."""
        n_scan = min(cfg.n_layers, _scan_stop(_hook_layers(cfg, tuple(hook_points))))
        return n_models * max(1, -(-n_scan // cls.seg_layers()))

    def inflight(self):
        """Arrays dispatched but possibly still executing — for callers
        that must drive the pipeline to quiescence before releasing a
        dispatch guard (utils/pipeline.sharded_program_guard)."""
        return [x for x in (self._resid, self._buf, self._out)
                if x is not None]

    def step(self) -> bool:
        """Dispatch the next quantum; False once fully dispatched."""
        if self._out is not None:
            return False
        if self._resid is None:
            self._resid, self._buf = _seg_start_impl(
                self.params_seq[self._model_idx], self.tokens, self.cfg,
                len(self.capture),
            )
        if self._lo < self.n_scan:
            k = min(self._seg_layers, self.n_scan - self._lo)
            self._resid, self._buf = _seg_scan_impl(
                self.params_seq[self._model_idx], self._resid, self._buf,
                jnp.int32(self._lo), self.cfg, self.capture, k,
            )
            self._lo += k
        if self._lo >= self.n_scan:
            self._done_resids.append(self._resid)
            self._done_bufs.append(self._buf)
            self._resid = self._buf = None
            self._lo = 0
            self._model_idx += 1
            if self._model_idx == len(self.params_seq):
                self._out = _seg_finish_impl(
                    tuple(self._done_resids), tuple(self._done_bufs),
                    self.cfg, self.capture, self.n_scan, self.out_dtype,
                )
                self._done_resids = self._done_bufs = []
                return False
        return True

    def _scan_batched(self, k: int):
        """One ``k``-wide sub-scan dispatch through a pre-built donated
        executable (utils/compile_cache.aot_get): the AOT compile happens
        once per width, off the per-quantum path, and later dispatches
        skip the jit call machinery — the host-cost half of the refill
        engine's batched dispatch. Any AOT failure falls back to the
        plain jit call (same program, just dispatched the ordinary way)."""
        from crosscoder_tpu.utils import compile_cache

        params = self.params_seq[self._model_idx]
        args = (params, self._resid, self._buf, jnp.int32(self._lo))
        key = ("seg_scan", self.cfg, self.capture, k, self.tokens.shape,
               str(self._resid.dtype),
               getattr(self._resid, "sharding", None),
               getattr(params["embed"], "sharding", None))
        try:
            compiled = compile_cache.aot_get(
                key,
                lambda: _seg_scan_impl.lower(
                    *args, cfg=self.cfg, capture=self.capture, k=k
                ).compile(),
            )
        except Exception:   # noqa: BLE001 — AOT is an optimization only
            compiled = None
        if compiled is None:
            return _seg_scan_impl(*args, cfg=self.cfg, capture=self.capture, k=k)
        return compiled(*args)

    def step_many(self, quanta: int) -> tuple[int, bool]:
        """Advance by up to ``quanta`` dispatch quanta, FUSING consecutive
        same-model quanta into one wide sub-scan dispatch (``k`` up to
        ``quanta × SEG_LAYERS`` layers in a single compiled program) —
        the refill engine's batched dispatch (cfg.refill_dispatch_batch).

        Returns ``(quanta_consumed, alive)`` with the same accounting as
        ``quanta_consumed`` calls to :meth:`step`: the scan carry is
        sequential, so a k-wide sub-scan is bitwise identical to k/SEG
        narrow ones (asserted by tests/test_refill_overlap.py).
        """
        used = 0
        while used < quanta:
            if self._out is not None:
                return used, False
            if self._resid is None:
                self._resid, self._buf = _seg_start_impl(
                    self.params_seq[self._model_idx], self.tokens, self.cfg,
                    len(self.capture),
                )
            if self._lo < self.n_scan:
                n_q = min(quanta - used,
                          -(-(self.n_scan - self._lo) // self._seg_layers))
                k = min(n_q * self._seg_layers, self.n_scan - self._lo)
                self._resid, self._buf = self._scan_batched(k)
                self._lo += k
                used += n_q
            if self._lo >= self.n_scan:
                self._done_resids.append(self._resid)
                self._done_bufs.append(self._buf)
                self._resid = self._buf = None
                self._lo = 0
                self._model_idx += 1
                if self._model_idx == len(self.params_seq):
                    self._out = _seg_finish_impl(
                        tuple(self._done_resids), tuple(self._done_bufs),
                        self.cfg, self.capture, self.n_scan, self.out_dtype,
                    )
                    self._done_resids = self._done_bufs = []
                    return used, False
        return used, True

    def result(self) -> jax.Array:
        while self._out is None:
            self.step()
        return self._out


# ---------------------------------------------------------------------------
# paged/ragged harvest (continuous batching; cfg.harvest_runtime="paged")


def _paged_capture_one(
    params: LMParams,
    plane_tokens: jax.Array,      # [R, Sp] packed token plane
    pos2d: jax.Array,             # [R, Sp] within-document positions
    doc_idx: jax.Array,           # [D, S] flat plane index per document token
    plane_idx: jax.Array,         # [R, Sp] flat doc*S+t index per plane slot
    lengths: jax.Array,           # [D]
    cfg: LMConfig,
    capture: tuple[tuple[int, int], ...],
    n_scan: int,
    page_size: int,
    use_kernel: bool,
) -> jax.Array:
    """One model's capture forward over the PACKED token plane.

    Every position-local op (embedding, norms, Q/K/V/output projections,
    MLP, capture FMAs — ~93% of harvest FLOPs at Gemma-2-2B shapes) runs
    on the dense ``[R, Sp]`` plane, so its cost is proportional to real
    tokens. Attention runs per DOCUMENT: heads are gathered through
    ``doc_idx`` into per-document padded buffers, attended with the ragged
    length mask (XLA path — bit-identical to the padded forward at valid
    positions) or the ragged-paged-attention kernel
    (:mod:`crosscoder_tpu.ops.paged_attention`, page loop bounded by
    ``ceil(len/page_size)``), and scattered back through ``plane_idx``.
    Returns the capture buffer ``[n_cap, R, Sp, d_model]`` (still packed;
    the caller unpacks per document). Unused plane positions carry
    finite garbage (pad-token forwards) that no document ever gathers.
    """
    from crosscoder_tpu.ops import paged_attention as pa

    R, Sp = plane_tokens.shape
    D, S = doc_idx.shape
    dt = dtype_of(cfg.dtype)
    n_cap = len(capture)
    cap_arr = jnp.asarray([l for l, _ in capture], jnp.int32) if n_cap else None
    cap_sites = jnp.asarray([c for _, c in capture], jnp.int32) if n_cap else None
    want_attn = any(c == _SITE_ATTN for _, c in capture)
    want_mlp = any(c == _SITE_MLP for _, c in capture)

    resid = params["embed"][plane_tokens].astype(dt) * jnp.asarray(
        math.sqrt(cfg.d_model), dt
    )
    buf = jnp.zeros((n_cap, R, Sp, cfg.d_model), dt)

    def gather_docs(x):          # [R, Sp, ...] -> [D, S, ...]
        return x.reshape((R * Sp,) + x.shape[2:])[doc_idx]

    def scatter_plane(x):        # [D, S, ...] -> [R, Sp, ...]
        return x.reshape((D * S,) + x.shape[2:])[plane_idx]

    def attn_docs(qd, kd, vd, is_local):
        if not use_kernel:
            return _attn_core(qd, kd, vd, cfg, is_local, lengths=lengths)
        # the kernel bakes the window statically; the traced layer parity
        # selects between the two compiled instances
        def run(window):
            def fn(args):
                return pa.paged_attention(
                    *args, lengths, page_size=page_size,
                    scale=cfg.query_pre_attn_scalar ** -0.5,
                    softcap=cfg.attn_softcap, window=window,
                )
            return fn
        return jax.lax.cond(
            is_local, run(cfg.sliding_window), run(0), (qd, kd, vd)
        )

    def body(carry, xs):
        resid, buf = carry
        lp, i = xs
        buf = _capture_into(buf, resid, i, cap_arr, _SITE_RESID, cap_sites)
        is_local = (i % 2) == 0
        xn = _rms_norm(resid, lp["attn_norm"], cfg.rms_eps)
        q, k, v = _qkv(xn, lp, cfg, pos2d)
        a_docs = attn_docs(gather_docs(q), gather_docs(k), gather_docs(v),
                           is_local)
        a = scatter_plane(a_docs)
        a = jnp.einsum(
            "bsq,qd->bsd", a, lp["wo"], preferred_element_type=jnp.float32
        ).astype(dt)
        attn_out = _rms_norm(a, lp["post_attn_norm"], cfg.rms_eps)
        if want_attn:
            buf = _capture_into(buf, attn_out, i, cap_arr, _SITE_ATTN, cap_sites)
        resid = resid + attn_out
        mlp = _mlp(_rms_norm(resid, lp["pre_ffw_norm"], cfg.rms_eps), lp)
        mlp_out = _rms_norm(mlp, lp["post_ffw_norm"], cfg.rms_eps)
        if want_mlp:
            buf = _capture_into(buf, mlp_out, i, cap_arr, _SITE_MLP, cap_sites)
        resid = resid + mlp_out
        return (resid, buf), None

    stacked = jax.tree_util.tree_map(lambda x: x[:n_scan], params["layers"])
    layer_ids = jnp.arange(n_scan, dtype=jnp.int32)
    (resid, buf), _ = jax.lax.scan(body, (resid, buf), (stacked, layer_ids))
    return _capture_into(buf, resid, jnp.int32(n_scan), cap_arr, _SITE_RESID,
                         cap_sites)


@functools.partial(
    jax.jit,
    static_argnames=("cfg", "capture", "n_scan", "page_size", "use_kernel",
                     "pad_mode", "out_dtype"),
)
def _paged_multi_impl(
    params_tuple, plane_tokens, pos2d, doc_idx, plane_idx, lengths,
    cfg: LMConfig, capture: tuple[tuple[int, int], ...], n_scan: int,
    page_size: int, use_kernel: bool, pad_mode: str = "zero", out_dtype=None,
):
    D, S = doc_idx.shape
    n_cap = len(capture)
    outs = []
    for p in params_tuple:
        buf = _paged_capture_one(
            p, plane_tokens, pos2d, doc_idx, plane_idx, lengths, cfg,
            capture, n_scan, page_size, use_kernel,
        )
        flat = buf.reshape(n_cap, -1, cfg.d_model)
        docs = flat[:, doc_idx]                    # [n_cap, D, S, d_model]
        outs.extend(docs[i] for i in range(n_cap))
    out = jnp.stack(outs, axis=2)                  # [D, S, n_sources, d]
    t = jnp.arange(S)[None]                        # [1, S]
    if pad_mode == "zero":
        # the emitted stream carries an explicit valid-length mask
        # instead of the padded path's garbage pad rows
        valid = t < lengths[:, None]
        out = jnp.where(valid[:, :, None, None], out, jnp.zeros((), out.dtype))
    else:                                          # "wrap" (the replay buffer)
        # pad positions cycle the document's own post-BOS rows, so every
        # emitted row is a REAL activation and the replay store never
        # trains on zero vectors; single-token documents (no post-BOS
        # rows) fall back to their BOS row
        ln = lengths[:, None]
        src = jnp.where(t < ln, t, 1 + (t - 1) % jnp.maximum(ln - 1, 1))
        src = jnp.where((t >= ln) & (ln == 1), 0, src)
        out = jnp.take_along_axis(out, src[:, :, None, None], axis=1)
    return out.astype(out_dtype) if out_dtype is not None else out


def run_with_cache_multi_paged(
    params_seq: Sequence[LMParams],
    tokens,
    lengths,
    cfg: LMConfig,
    hook_points: Sequence[str],
    *,
    page_size: int,
    n_rows: int | None = None,
    row_multiple: int = 1,
    batch_sharding: Any | None = None,
    pad_mode: str = "zero",
    out_dtype=None,
) -> jax.Array:
    """All models' captures through the PAGED runtime: mixed-length
    documents (``tokens [D, seq_len]`` padded layout + per-document
    ``lengths``) are packed host-side into a dense token plane
    (:func:`crosscoder_tpu.data.paging.pack_chunk`), the forward runs on
    the plane with per-document ragged attention, and the result is
    unpacked back to the padded layout: ``[D, seq_len, n_models·n_hooks,
    d_model]``, source axis model-major — shape/order-compatible with
    :func:`run_with_cache_multi`, with positions at ``t >= lengths[d]``
    zeroed (``pad_mode="zero"``, the valid-length mask made material) or
    cycled from the document's own post-BOS rows (``pad_mode="wrap"`` —
    the replay buffer's choice, so no all-zero row ever becomes training
    data; single-token documents fall back to their BOS row).

    On an all-full-length chunk the packing is the identity layout and the
    output is BIT-identical to :func:`run_with_cache_multi` — the CPU
    parity gate ``tests/test_paging.py`` pins. On ragged chunks the plane
    has ``~sum(len)/seq_len`` rows instead of ``D``, so the projections/
    MLP (the dominant harvest cost) scale with real tokens; the Pallas
    ragged-paged-attention kernel (``CROSSCODER_PAGED_ATTN_PALLAS=1``)
    makes attention ragged too.
    """
    from crosscoder_tpu.data import paging

    cap_pairs = _hook_layers(cfg, tuple(hook_points))
    n_scan = min(cfg.n_layers, _scan_stop(cap_pairs))
    chunk = paging.pack_chunk(
        np.asarray(tokens), np.asarray(lengths),
        n_rows=n_rows, row_multiple=row_multiple,
    )
    from crosscoder_tpu.ops import paged_attention as pa

    use_kernel = pa.kernel_enabled() and pa.supported(
        chunk.n_docs, chunk.seq_len, cfg.n_heads, cfg.n_kv_heads,
        cfg.head_dim, page_size,
    )
    if batch_sharding is not None:
        plane = _put_global(chunk.tokens, batch_sharding)
    else:
        plane = jnp.asarray(chunk.tokens)
    if pad_mode not in ("zero", "wrap"):
        raise ValueError(f"pad_mode must be zero|wrap, got {pad_mode!r}")
    return _paged_multi_impl(
        tuple(params_seq), plane, jnp.asarray(chunk.pos),
        jnp.asarray(chunk.doc_idx), jnp.asarray(chunk.plane_idx),
        jnp.asarray(chunk.lengths), cfg, cap_pairs, n_scan, page_size,
        use_kernel, pad_mode, out_dtype,
    )


def paged_capture_aot(
    params_seq: Sequence[LMParams],
    chunk,
    cfg: LMConfig,
    hook_points: Sequence[str],
    *,
    page_size: int,
    pad_mode: str = "zero",
    out_dtype=None,
    on_build=None,
) -> jax.Array:
    """:func:`run_with_cache_multi_paged` for a PRE-PACKED fixed-shape
    chunk, dispatched through an AOT-compiled executable.

    ``chunk`` is a :class:`crosscoder_tpu.data.paging.PackedChunk` whose
    plane height the caller pinned (the serve engine's bucket ladder pins
    both the document count and the plane height per bucket, so every
    steady-state request hits a memoized executable). Numerics are the
    implicit-jit path's exactly — :func:`compile_cache.aot_get` compiles
    the same program ``jax.jit`` would have — the AOT hop only removes
    the per-call tracing/cache machinery from the latency path and makes
    compiles COUNTABLE (``on_build`` fires once per executable actually
    built; docs/SERVING.md "Zero compiles after warmup").
    """
    from crosscoder_tpu.ops import paged_attention as pa
    from crosscoder_tpu.utils import compile_cache

    cap_pairs = _hook_layers(cfg, tuple(hook_points))
    n_scan = min(cfg.n_layers, _scan_stop(cap_pairs))
    use_kernel = pa.kernel_enabled() and pa.supported(
        chunk.n_docs, chunk.seq_len, cfg.n_heads, cfg.n_kv_heads,
        cfg.head_dim, page_size,
    )
    if pad_mode not in ("zero", "wrap"):
        raise ValueError(f"pad_mode must be zero|wrap, got {pad_mode!r}")
    args = (
        tuple(params_seq), jnp.asarray(chunk.tokens),
        jnp.asarray(chunk.pos), jnp.asarray(chunk.doc_idx),
        jnp.asarray(chunk.plane_idx), jnp.asarray(chunk.lengths),
    )
    key = ("paged_capture", cfg, cap_pairs, n_scan, page_size, use_kernel,
           pad_mode, str(out_dtype), chunk.tokens.shape, chunk.doc_idx.shape,
           str(chunk.tokens.dtype), len(args[0]))
    def lower():
        return _paged_multi_impl.lower(
            *args, cfg=cfg, capture=cap_pairs, n_scan=n_scan,
            page_size=page_size, use_kernel=use_kernel, pad_mode=pad_mode,
            out_dtype=out_dtype,
        )

    compiled = compile_cache.aot_get(
        key, lambda: lower().compile(), on_build=on_build, lower=lower,
    )
    return compiled(*args)


# ---------------------------------------------------------------------------
# tensor-parallel harvest (models too big for one chip's HBM)


def tp_shardings(mesh, axis: str = "model") -> LMParams:
    """``NamedSharding`` pytree for TENSOR-PARALLEL LM params over
    ``mesh[axis]`` — the Megatron layout expressed as annotations only;
    GSPMD inserts the collectives (psum after ``wo``/``w_down``).

    The reference fits its 2.6B pair on one GPU (train.py:45-55), so it
    never needs this; BASELINE config 3 (Gemma-2-9B) does NOT fit one v5e
    chip (both models' sub-hook layers ≈ 16.6 GB bf16), which makes the
    harvest forward itself the thing to shard:

    - ``wq``/``wk``/``wv``: head (output) axis sharded — each shard owns a
      head group; the [B,S,heads,hd] reshape splits the sharded axis
      cleanly when ``n_heads`` (and ideally ``n_kv_heads``) divide the
      axis size.
    - ``wo``/``w_down``: CONTRACTING axis sharded — partial products psum.
    - ``w_gate``/``w_up``: hidden (output) axis sharded.
    - ``embed``: d_model axis sharded — the token lookup stays shard-local.
    - norms: replicated (tiny).
    """
    from jax.sharding import NamedSharding, PartitionSpec as P

    def ns(*spec):
        return NamedSharding(mesh, P(*spec))

    return {
        "embed": ns(None, axis),
        "final_norm": ns(None),
        "layers": {
            "attn_norm": ns(None, None),
            "post_attn_norm": ns(None, None),
            "pre_ffw_norm": ns(None, None),
            "post_ffw_norm": ns(None, None),
            "wq": ns(None, None, axis),
            "wk": ns(None, None, axis),
            "wv": ns(None, None, axis),
            "wo": ns(None, axis, None),
            "w_gate": ns(None, None, axis),
            "w_up": ns(None, None, axis),
            "w_down": ns(None, axis, None),
        },
    }


def shard_params_tp(params: LMParams, mesh, axis: str = "model") -> LMParams:
    """Place (or re-place) LM params in the tensor-parallel layout. The
    returned pytree feeds every forward/harvest entry point unchanged —
    jit picks the layout up from the arrays and partitions accordingly."""
    return _put_global(params, tp_shardings(mesh, axis))


# ---------------------------------------------------------------------------
# sequence-parallel forward (long-context harvest; SURVEY component N5)


def forward_seq_parallel(
    params: LMParams,
    tokens: jax.Array,
    cfg: LMConfig,
    mesh,
    *,
    axis_name: str = "data",
    capture: Sequence[str] = (),
    return_logits: bool = False,
) -> tuple[jax.Array | None, dict[str, jax.Array]]:
    """Gemma-2 forward with the SEQUENCE axis sharded over a mesh axis.

    The context-length analogue of :func:`forward`: the per-device score
    matrix shrinks by n², so contexts far beyond one chip's HBM harvest
    fine — attention runs as an exact ring (K/V blocks rotate over ICI via
    ``ppermute``; :mod:`crosscoder_tpu.parallel.ring_attention`), every
    other op is position-local. Params are replicated; ``tokens [B, S]``
    must have S divisible by the axis size. Capture semantics match
    :func:`forward` (cache values come back as globally-stitched arrays);
    activation *edits* are a short-context eval feature and are not
    supported here.

    Numerics are asserted equal to the dense forward by
    ``tests/test_ring_attention.py``.
    """
    _check_seq_divisible(tokens, mesh, axis_name)
    cap_layers = _hook_layers(cfg, tuple(capture))
    fn = _seq_parallel_fn(cfg, mesh, axis_name, cap_layers, return_logits)
    logits, cap_buf = fn(params, tokens)
    cache = {hp: cap_buf[i] for i, hp in enumerate(capture)}
    return logits, cache


def _check_seq_divisible(tokens: jax.Array, mesh, axis_name: str) -> None:
    n = mesh.shape[axis_name]
    if tokens.shape[1] % n != 0:
        raise ValueError(
            f"seq len {tokens.shape[1]} not divisible by {n} sequence shards"
        )


def _seq_local_body(
    params, tok_local, cfg: LMConfig, axis_name: str, n: int,
    cap_layers: tuple[tuple[int, int], ...], return_logits: bool,
):
    """Per-shard forward over the local sequence slice (shared by the
    single-model and fused multi-model sequence-parallel entry points).

    Mirrors ``_forward_impl``'s stop-at-layer: without logits, nothing above
    the highest captured layer is observable, so the scan is truncated there
    — at blocks.14 of Gemma-2-2B's 26 layers that is ~46% of the layer
    FLOPs, and long-context harvest is exactly where it matters.
    """
    from crosscoder_tpu.parallel.ring_attention import ring_attention

    dt = dtype_of(cfg.dtype)
    n_cap = len(cap_layers)
    scale = cfg.query_pre_attn_scalar ** -0.5
    n_scan = cfg.n_layers if return_logits else min(
        cfg.n_layers, _scan_stop(cap_layers)
    )

    B, Sl = tok_local.shape
    cap_arr = jnp.asarray([l for l, _ in cap_layers], jnp.int32) if n_cap else None
    cap_sites = jnp.asarray([c for _, c in cap_layers], jnp.int32) if n_cap else None
    want_attn = any(c == _SITE_ATTN for _, c in cap_layers)
    want_mlp = any(c == _SITE_MLP for _, c in cap_layers)
    idx = jax.lax.axis_index(axis_name)
    pos = idx * Sl + jnp.arange(Sl)
    resid = params["embed"][tok_local].astype(dt) * jnp.asarray(
        math.sqrt(cfg.d_model), dt
    )
    buf = jnp.zeros((n_cap, B, Sl, cfg.d_model), dt) if n_cap else None

    def body(carry, xs):
        resid, buf = carry
        lp, i = xs
        buf = _capture_into(buf, resid, i, cap_arr, _SITE_RESID, cap_sites)
        is_local = (i % 2) == 0
        xn = _rms_norm(resid, lp["attn_norm"], cfg.rms_eps)
        q, k, v = _qkv(xn, lp, cfg, pos)
        a = ring_attention(
            q, k, v, axis_name=axis_name, n_shards=n, scale=scale,
            softcap=cfg.attn_softcap, sliding_window=cfg.sliding_window,
            is_local=is_local,
        ).reshape(B, Sl, cfg.n_heads * cfg.head_dim)
        a = jnp.einsum(
            "bsq,qd->bsd", a, lp["wo"], preferred_element_type=jnp.float32
        ).astype(dt)
        attn_out = _rms_norm(a, lp["post_attn_norm"], cfg.rms_eps)
        if want_attn:
            buf = _capture_into(buf, attn_out, i, cap_arr, _SITE_ATTN, cap_sites)
        resid = resid + attn_out
        mlp = _mlp(_rms_norm(resid, lp["pre_ffw_norm"], cfg.rms_eps), lp)
        mlp_out = _rms_norm(mlp, lp["post_ffw_norm"], cfg.rms_eps)
        if want_mlp:
            buf = _capture_into(buf, mlp_out, i, cap_arr, _SITE_MLP, cap_sites)
        resid = resid + mlp_out
        return (resid, buf), None

    stacked = jax.tree_util.tree_map(lambda x: x[:n_scan], params["layers"])
    layer_ids = jnp.arange(n_scan, dtype=jnp.int32)
    (resid, buf), _ = jax.lax.scan(body, (resid, buf), (stacked, layer_ids))
    buf = _capture_into(buf, resid, jnp.int32(n_scan), cap_arr, _SITE_RESID, cap_sites)
    logits = _unembed(params, resid, cfg) if return_logits else None
    return logits, buf


@functools.lru_cache(maxsize=32)
def _seq_parallel_fn(
    cfg: LMConfig, mesh, axis_name: str, cap_layers: tuple[tuple[int, int], ...], return_logits: bool
):
    """Compile-once builder for the sequence-parallel forward (keyed on
    everything that changes the traced program; token/batch shapes go
    through the inner jit's normal shape-keyed cache)."""
    from crosscoder_tpu.parallel import shard_map_compat as shard_map
    from jax.sharding import PartitionSpec as P

    n = mesh.shape[axis_name]
    n_cap = len(cap_layers)

    def local_fn(params, tok_local):
        return _seq_local_body(
            params, tok_local, cfg, axis_name, n, cap_layers, return_logits
        )

    out_logits_spec = P(None, axis_name, None) if return_logits else P()
    out_cap_spec = P(None, None, axis_name, None) if n_cap else P()
    return jax.jit(shard_map(
        local_fn,
        mesh=mesh,
        in_specs=(P(), P(None, axis_name)),
        out_specs=(out_logits_spec, out_cap_spec),
        check_vma=False,
    ))


@functools.lru_cache(maxsize=32)
def _seq_parallel_multi_fn(
    cfg: LMConfig, mesh, axis_name: str, cap_layers: tuple[tuple[int, int], ...]
):
    """Fused multi-model sequence-parallel capture: ONE jitted shard_map
    dispatch runs every model's truncated forward over the same local token
    slice — the sequence-sharded analogue of ``_multi_cache_impl``, keeping
    the per-dispatch fixed cost (material under a remote TPU client) at one
    per chunk. (Kept separate from ``_seq_parallel_fn``: the out-tree is a
    single stacked capture array, not the (logits, buffer) pair; the model
    count keys the inner jit's retrace via the params-tuple length.)"""
    from crosscoder_tpu.parallel import shard_map_compat as shard_map
    from jax.sharding import PartitionSpec as P

    n = mesh.shape[axis_name]

    def local_fn(params_tuple, tok_local):
        bufs = []
        for p in params_tuple:
            _, buf = _seq_local_body(
                p, tok_local, cfg, axis_name, n, cap_layers, False
            )
            bufs.append(buf)                       # each [n_cap, B, Sl, D]
        out = jnp.concatenate(bufs, axis=0)        # model-major sources
        return jnp.transpose(out, (1, 2, 0, 3))    # [B, Sl, n_sources, D]

    return jax.jit(shard_map(
        local_fn,
        mesh=mesh,
        in_specs=(P(), P(None, axis_name)),
        out_specs=P(None, axis_name, None, None),
        check_vma=False,
    ))


def run_with_cache_multi_seq_parallel(
    params_seq: Sequence[LMParams],
    tokens: jax.Array,
    cfg: LMConfig,
    hook_points: Sequence[str],
    mesh,
    *,
    axis_name: str = "data",
) -> jax.Array:
    """All models' captures with the SEQUENCE axis sharded over ``axis_name``
    (ring attention): ``[B, S, n_models·n_hooks, d_model]``, source axis
    model-major — shape/order-compatible with :func:`run_with_cache_multi`,
    in one compiled dispatch."""
    _check_seq_divisible(tokens, mesh, axis_name)
    cap_layers = _hook_layers(cfg, tuple(hook_points))
    fn = _seq_parallel_multi_fn(cfg, mesh, axis_name, cap_layers)
    return fn(tuple(params_seq), tokens)


# ---------------------------------------------------------------------------
# HF weight conversion (torch checkpoint → stacked JAX pytree)


def from_torch_state_dict(
    sd: Mapping[str, Any], cfg: LMConfig, dtype: str | None = None,
    shardings: LMParams | None = None,
) -> LMParams:
    """Convert an HF-transformers Gemma2 ``state_dict`` to our stacked layout.

    Works on anything indexable with ``.numpy()``-able values (torch CPU
    tensors or numpy arrays). HF projections are [out, in]; ours are [in, out].

    ``shardings`` (a :func:`tp_shardings`-shaped pytree of NamedShardings)
    places each leaf DIRECTLY in its sharded layout as it is converted —
    peak device memory is one shard per leaf, never the whole model, which
    is what lets a pair bigger than one chip's HBM (BASELINE config 3) be
    loaded at all. Without it, leaves go to the default device whole.
    """
    dt = dtype_of(dtype or cfg.dtype)

    def get(name: str) -> np.ndarray:
        v = sd[name]
        if hasattr(v, "detach"):
            v = v.detach().to("cpu").float().numpy()
        return np.asarray(v, dtype=np.float32)

    def leaf(path: tuple[str, ...], arr: np.ndarray) -> jax.Array:
        arr = arr.astype(np.dtype(dt), copy=False)   # host-side cast (ml_dtypes)
        if shardings is None:
            return jnp.asarray(arr)
        sh = shardings
        for k in path:
            sh = sh[k]
        return _put_global(arr, sh)

    def stack(key: str, fmt: str, transpose: bool) -> jax.Array:
        mats = [get(fmt.format(i)) for i in range(cfg.n_layers)]
        arr = np.stack([m.T if transpose else m for m in mats])
        return leaf(("layers", key), arr)

    p = "model.layers.{}."
    return {
        "embed": leaf(("embed",), get("model.embed_tokens.weight")),
        "final_norm": leaf(("final_norm",), get("model.norm.weight")),
        "layers": {
            "attn_norm": stack("attn_norm", p + "input_layernorm.weight", False),
            "post_attn_norm": stack("post_attn_norm", p + "post_attention_layernorm.weight", False),
            "pre_ffw_norm": stack("pre_ffw_norm", p + "pre_feedforward_layernorm.weight", False),
            "post_ffw_norm": stack("post_ffw_norm", p + "post_feedforward_layernorm.weight", False),
            "wq": stack("wq", p + "self_attn.q_proj.weight", True),
            "wk": stack("wk", p + "self_attn.k_proj.weight", True),
            "wv": stack("wv", p + "self_attn.v_proj.weight", True),
            "wo": stack("wo", p + "self_attn.o_proj.weight", True),
            "w_gate": stack("w_gate", p + "mlp.gate_proj.weight", True),
            "w_up": stack("w_up", p + "mlp.up_proj.weight", True),
            "w_down": stack("w_down", p + "mlp.down_proj.weight", True),
        },
    }


def from_hf(
    model_name_or_path: str, cfg: LMConfig | None = None,
    shardings: LMParams | None = None,
) -> tuple[LMParams, LMConfig]:
    """Load Gemma-2 weights from a local HF checkpoint dir or the hub cache
    (the reference loads via TransformerLens ``from_pretrained_no_processing``,
    train.py:45-55). Gated behind an import so offline/test runs never touch
    the hub.

    Pass ``shardings=lm.tp_shardings(mesh)`` for models that do NOT fit one
    chip (BASELINE config 3): each leaf is placed straight into its
    tensor-parallel shards during conversion, so peak per-device memory is
    the sharded footprint, never the whole model.
    """
    import transformers  # deferred: heavyweight

    model = transformers.AutoModelForCausalLM.from_pretrained(
        model_name_or_path, torch_dtype="bfloat16"  # keep host peak at ckpt size
    )
    hf_cfg = model.config
    if cfg is None:
        cfg = LMConfig(
            vocab_size=hf_cfg.vocab_size,
            d_model=hf_cfg.hidden_size,
            n_layers=hf_cfg.num_hidden_layers,
            n_heads=hf_cfg.num_attention_heads,
            n_kv_heads=hf_cfg.num_key_value_heads,
            head_dim=hf_cfg.head_dim,
            d_ff=hf_cfg.intermediate_size,
            rope_theta=hf_cfg.rope_theta,
            rms_eps=hf_cfg.rms_norm_eps,
            attn_softcap=hf_cfg.attn_logit_softcapping,
            final_softcap=hf_cfg.final_logit_softcapping,
            sliding_window=hf_cfg.sliding_window,
            query_pre_attn_scalar=float(hf_cfg.query_pre_attn_scalar),
        )
    params = from_torch_state_dict(model.state_dict(), cfg, shardings=shardings)
    return params, cfg
