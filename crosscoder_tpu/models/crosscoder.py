"""The crosscoder: a sparse dictionary tied across N model/layer sources.

Re-implements, TPU-first, the numeric contract of the reference
``CrossCoder`` module (reference ``crosscoder.py:24-130``):

- params ``W_enc [n, d_in, d_hidden]``, ``W_dec [d_hidden, n, d_in]``,
  ``b_enc [d_hidden]``, ``b_dec [n, d_in]`` — same leaf names as the torch
  ``state_dict`` so the checkpoint converter is trivial, but with the source
  axis ``n`` generalized from the reference's hardcoded 2
  (reference ``crosscoder.py:32``) to any ``n_models × n_hooked_layers``.
- init: ``W_dec`` rows drawn N(0,1) then rescaled to ``dec_init_norm`` per
  (latent, source) (reference ``crosscoder.py:36-53``); ``W_enc`` initialized
  as the transpose of ``W_dec`` (reference ``crosscoder.py:54-58``); biases 0.
- encode/decode as single einsums that XLA maps onto the MXU
  (reference ``crosscoder.py:69-89``), with fp32 accumulation.
- ``get_losses`` reproducing the reference's loss surface exactly
  (reference ``crosscoder.py:96-130``): summed-square-error L2 (mean over
  batch), explained variance overall and per source (eps 1e-8),
  **decoder-norm-weighted** L1 (reference ``crosscoder.py:123-126``), and L0.

Design notes (why this is not a torch translation):

- Everything is a pure function over a params pytree — no module object, no
  device state; ``jax.jit``/``pjit`` owns placement. Sharding is expressed
  separately (mesh + NamedSharding rules in the parallel layer) and
  propagates through these einsums, so the same code is the single-chip and
  the multi-chip kernel.
- Compute dtype (``enc_dtype``, usually bf16 for the MXU) is separated from
  loss dtype (always fp32, matching the reference's upcast at
  ``crosscoder.py:104``).
- Sparse activations (TopK / JumpReLU / BatchTopK) are first-class via
  :mod:`crosscoder_tpu.ops.activations`, with a Pallas kernel path for the
  TopK inner loop; the reference has only dense ReLU.
"""

from __future__ import annotations

import functools
from typing import Any, NamedTuple

import jax
import jax.numpy as jnp

from crosscoder_tpu.config import CrossCoderConfig
from crosscoder_tpu.ops import activations as act_ops
from crosscoder_tpu.utils.dtypes import dtype_of

Params = dict[str, jax.Array]


class LossOutput(NamedTuple):
    """Loss surface of one batch (shapes as the reference returns them,
    reference ``crosscoder.py:15-22``); all fp32."""

    l2_loss: jax.Array                    # scalar: mean over batch of summed sq err
    l1_loss: jax.Array                    # scalar: decoder-norm-weighted L1
    l0_loss: jax.Array                    # scalar: mean active latents
    explained_variance: jax.Array         # [batch]
    explained_variance_per_source: jax.Array  # [n_sources, batch] (ref: _A/_B pair)
    # jumprelu + cfg.l0_coeff only: the rectangle-kernel-STE L0 penalty
    # term (differentiable in θ; equals l0_loss in value). 0.0 elsewhere.
    l0_penalty: jax.Array | float = 0.0
    # AuxK only (cfg.aux_k > 0 and a dead_mask was passed): the
    # residual-normalized auxiliary reconstruction loss over dead latents,
    # and the [d_hidden] bool of latents that fired on this batch (the
    # trainer's steps_since_fired update). 0.0 / None elsewhere.
    aux_loss: jax.Array | float = 0.0
    fired: jax.Array | None = None


def init_params(key: jax.Array, cfg: CrossCoderConfig, dtype: jnp.dtype | None = None) -> Params:
    """Initialize crosscoder params.

    Matches the reference init semantics (reference ``crosscoder.py:33-62``):
    decoder rows are standard-normal rescaled so each (latent, source) row has
    norm ``dec_init_norm``; the encoder starts as the decoder transpose; biases
    start at zero. (The reference draws W_dec twice and keeps the second draw,
    ``crosscoder.py:36-49`` — RNG noise we deliberately do not replicate.)

    ``dtype`` defaults to ``cfg.enc_dtype`` (the reference stores params in
    the compute dtype, ``crosscoder.py:30-34``); the Trainer passes fp32 to
    keep master weights + Adam moments in fp32 and casts to ``enc_dtype``
    per-step inside the loss (mixed precision the TPU way, rather than the
    reference's all-bf16 torch Adam).
    """
    n, d_in, d_hidden = cfg.n_sources, cfg.d_in, cfg.dict_size
    dtype = dtype_of(cfg.enc_dtype) if dtype is None else dtype
    w = jax.random.normal(key, (d_hidden, n, d_in), dtype=jnp.float32)
    w = w / jnp.linalg.norm(w, axis=-1, keepdims=True) * cfg.dec_init_norm
    params: Params = {
        "W_dec": w.astype(dtype),
        "W_enc": jnp.transpose(w, (1, 2, 0)).astype(dtype),
        "b_enc": jnp.zeros((d_hidden,), dtype=dtype),
        "b_dec": jnp.zeros((n, d_in), dtype=dtype),
    }
    if cfg.activation == "jumprelu":
        # log-threshold parameterization keeps theta positive under Adam
        params["log_theta"] = jnp.full((d_hidden,), jnp.log(cfg.jumprelu_theta), dtype=jnp.float32)
    return params


def pre_acts(params: Params, x: jax.Array) -> jax.Array:
    """Encoder pre-activations: ``x @ W_enc + b_enc`` summed over sources.

    x: ``[..., n_sources, d_in]`` → ``[..., d_hidden]``. One einsum, contracted
    over both the source and feature axes (reference ``crosscoder.py:71-75``),
    with fp32 MXU accumulation.
    """
    h = jnp.einsum(
        "...nd,ndh->...h", x, params["W_enc"], preferred_element_type=jnp.float32
    )
    return (h + params["b_enc"].astype(jnp.float32)).astype(x.dtype)


def encode(params: Params, x: jax.Array, cfg: CrossCoderConfig, *, apply_activation: bool = True) -> jax.Array:
    """Latent activations ``[..., d_hidden]``.

    ``apply_activation=False`` returns raw pre-activations (the reference's
    ``apply_relu=False`` path, ``crosscoder.py:69-80``).
    """
    h = pre_acts(params, x)
    if not apply_activation:
        return h
    return act_ops.apply(h, cfg, params)


def calibrate_batchtopk_threshold(
    params: Params, cfg: CrossCoderConfig, batches
) -> float:
    """Mean per-batch BatchTopK threshold over representative batches —
    the fixed global threshold for EVAL (set it as
    ``cfg.batchtopk_threshold``; dispatch then uses
    :func:`crosscoder_tpu.ops.activations.batchtopk_fixed` so one
    example's activations never depend on the rest of its batch).

    ``batches``: iterable of ``[B, n_sources, d_in]`` activation batches
    (normalized exactly as training batches were).
    """
    import numpy as np

    @jax.jit
    def one(p, x):
        # cast like training does (fp32 masters -> enc_dtype): the order
        # statistic must come from the same bf16 pre-acts training saw.
        # params are a traced argument (not a closure) so the dictionary
        # weights are not baked into the executable as constants — same
        # trap documented at decoder.firing_rates / ce_eval.
        cp = cast_params(p, dtype_of(cfg.enc_dtype))
        hp = jax.nn.relu(pre_acts(cp, x.astype(dtype_of(cfg.enc_dtype))))
        return act_ops.batchtopk_threshold_of(hp, cfg.topk_k)

    vals = [float(jax.device_get(one(params, jnp.asarray(b)))) for b in batches]
    if not vals:
        raise ValueError("calibrate_batchtopk_threshold needs >= 1 batch")
    return float(np.mean(vals))


def decode(params: Params, f: jax.Array) -> jax.Array:
    """Reconstruction ``[..., n_sources, d_in]`` from latents ``[..., d_hidden]``
    (reference ``crosscoder.py:82-89``)."""
    y = jnp.einsum(
        "...h,hnd->...nd", f, params["W_dec"], preferred_element_type=jnp.float32
    )
    return (y + params["b_dec"].astype(jnp.float32)).astype(f.dtype)


def forward(params: Params, x: jax.Array, cfg: CrossCoderConfig) -> jax.Array:
    """encode → decode (reference ``crosscoder.py:91-94``)."""
    return decode(params, encode(params, x, cfg))


# apply-function cache keyed by the cfg's JSON identity. Consumers (CE eval,
# dashboards) close cfg into a function and pass that function as a STATIC
# jit argument with params/activations as array arguments; without this
# cache each call site would mint a fresh function object → a full retrace
# and recompile per eval/dashboard run, and the jit cache would retain
# every stale executable.
_APPLY_CACHE: dict[tuple[str, str], Any] = {}


def cached_apply(cfg: CrossCoderConfig, kind: str = "forward"):
    """A stable-identity ``apply(params, x)`` for this config.

    ``kind``: ``"forward"`` (encode→decode, the CE eval's reconstruction)
    or ``"encode"`` (latent activations, the dashboards' path).
    """
    import json

    if kind not in ("forward", "encode"):
        raise ValueError(f"kind must be forward|encode, got {kind!r}")
    key = (json.dumps(cfg.to_dict(), sort_keys=True, default=str), kind)
    fn = _APPLY_CACHE.get(key)
    if fn is None:
        if len(_APPLY_CACHE) > 32:
            # evict OLDEST only (dict preserves insertion order): clearing
            # everything would orphan functions still live as static jit
            # args and force a retrace of every active consumer
            _APPLY_CACHE.pop(next(iter(_APPLY_CACHE)))
        if kind == "forward":
            def fn(p: Params, x: jax.Array) -> jax.Array:
                return forward(p, x, cfg)
        else:
            def fn(p: Params, x: jax.Array) -> jax.Array:
                return encode(p, x, cfg)
        _APPLY_CACHE[key] = fn
    return fn


# ---------------------------------------------------------------------------
# sparse TopK decode (no reference counterpart — the reference's decode is
# always the dense [B,H]x[H,n,d] matmul, reference crosscoder.py:82-89,
# which at TopK(k=32) multiplies ~0.1% nonzeros).
#
# Measured guidance (TPU v5e, k 32, batch 4096, full train step —
# artifacts/BENCH_r03_local.json matrix): at dict 2^15 the DENSE decode
# wins (76.7 vs 95.0 ms/step) because at B·k/H ≈ 4 hits per latent every
# W_dec row is read anyway, the dense matmul is a compute-bound MXU op,
# and XLA's row gather runs well below HBM bandwidth. Against the plain
# dense path this gather wins at 2^17 (251.0 vs 278.3 ms/step) — but
# round-3's width-chunked Pallas TopK moved the goalposts: the
# kernel+dense-decode step is faster still at every dict (208.3 ms at
# 2^17), so cfg.sparse_decode now only pays on shapes the kernel's
# supported() gate rejects. Default stays False.


@jax.custom_vjp
def _sparse_decode_product(vals: jax.Array, idx: jax.Array, W_dec: jax.Array) -> jax.Array:
    """``Σ_j vals[b,j] · W_dec[idx[b,j]]`` → ``[B, n, d]`` fp32.

    Forward gathers only the k active decoder rows per example (bandwidth
    ~B·k·n·d instead of the dense matmul's B·H FLOP column). The backward
    computes ``dW_dec`` by scattering the k values into a dense ``[B, H]``
    one-hot-weighted matrix and running a dense matmul — on TPU the MXU
    matmul over mostly-zeros beats a ``[B,k,n,d]``-sized scatter-add with
    row collisions by a wide margin.
    """
    w = jnp.take(W_dec, idx, axis=0)                       # [B, k, n, d]
    return jnp.einsum("bk,bknd->bnd", vals, w, preferred_element_type=jnp.float32)


def _sparse_decode_fwd(vals, idx, W_dec):
    return _sparse_decode_product(vals, idx, W_dec), (vals, idx, W_dec)


def _sparse_decode_bwd(res, g):
    vals, idx, W_dec = res
    g = g.astype(jnp.float32)
    w = jnp.take(W_dec, idx, axis=0)                       # recomputed (residual would be B·k·n·d)
    d_vals = jnp.einsum("bnd,bknd->bk", g, w.astype(jnp.float32)).astype(vals.dtype)
    # dense-scatter trick for dW_dec: f_dense[b, idx[b,j]] = vals[b,j]
    B, k = vals.shape
    rows = jnp.arange(B)[:, None]
    f_dense = jnp.zeros((B, W_dec.shape[0]), dtype=vals.dtype)
    f_dense = f_dense.at[rows, idx].add(vals, mode="drop")
    dW_dec = jnp.einsum(
        "bh,bnd->hnd", f_dense, g, preferred_element_type=jnp.float32
    ).astype(W_dec.dtype)
    return d_vals, None, dW_dec


_sparse_decode_product.defvjp(_sparse_decode_fwd, _sparse_decode_bwd)


# ---------------------------------------------------------------------------
# factored TopK decode (Pallas tier, round-5): forward through the k active
# rows only, backward through the SAME dense matmuls as the dense path.
#
# Why this split (all numbers v5e, B=4096, k=32, artifacts/TOPK_PROBE_r05 +
# GATHER_PROBE_r05): the decode FORWARD is the only dense matmul sparsity
# can actually remove — jnp.take of the k active W_dec rows + a [B,k,n,d]
# einsum costs 5.7-16 ms vs the 20-33 ms dense matmul at dict >= 2^16. The
# BACKWARD stays dense on purpose: a factored df (gather 8-16 ms + the
# [B,k]->[B,H] scatter 6-20 ms) loses to the dense matmul+mask at every
# size, and XLA's own scatter-add gradient for a gathered W_dec costs
# 42-76 ms. Gradients are therefore numerically IDENTICAL to the dense
# path (same matmuls, same straight-through mask) while the forward saves
# ~27 ms at 2^17. (vals, idx) come from the sparsify drain kernel — every
# general extractor measured is slower: lax.top_k 25-63 ms, approx_max_k
# inexact per row (79-97%), XLA scatter-compaction touches all B*H pairs.
# No reference counterpart (the reference decode is always dense,
# reference crosscoder.py:82-89).


@functools.partial(jax.custom_vjp, nondiff_argnums=(2,))
def _factored_topk_forward(
    h: jax.Array, W_dec: jax.Array, k: int
) -> tuple[jax.Array, jax.Array, jax.Array]:
    """``(recon [B,n,d] f32 (no b_dec), vals [B,k], idx [B,k])`` from
    pre-acts ``h [B,H]``.

    Differentiable in ``h`` (straight-through mask) and ``W_dec`` (dense
    matmul), exactly as the dense TopK path. ``vals``/``idx`` carry NO
    gradient path — cotangents on them are ignored, which is only sound
    when nothing differentiable consumes them (the dispatch in get_losses
    guarantees l1_coeff == 0 on this path; metric-only uses are fine).
    """
    from crosscoder_tpu.ops import topk_pallas

    f = topk_pallas.topk(h, k)
    vals, idx = topk_pallas.sparsify(f, k)
    w = jnp.take(W_dec, idx, axis=0)                       # [B, k, n, d]
    recon = jnp.einsum("bk,bknd->bnd", vals, w, preferred_element_type=jnp.float32)
    return recon, vals, idx


def _factored_topk_fwd(h, W_dec, k):
    from crosscoder_tpu.ops import topk_pallas

    f = topk_pallas.topk(h, k)
    vals, idx = topk_pallas.sparsify(f, k)
    w = jnp.take(W_dec, idx, axis=0)                       # [B, k, n, d]
    recon = jnp.einsum("bk,bknd->bnd", vals, w, preferred_element_type=jnp.float32)
    # f is the residual: both backward matmuls consume the masked [B,H]
    # activations (dW_dec contraction + the straight-through mask on df)
    return (recon, vals, idx), (f, W_dec)


def _factored_topk_bwd(k, res, g):
    f, W_dec = res
    g_recon = g[0].astype(jnp.float32)                     # [B, n, d]
    # cotangents g[1], g[2] (vals, idx) are ignored — see docstring
    dW_dec = jnp.einsum(
        "bh,bnd->hnd", f.astype(jnp.float32), g_recon,
        preferred_element_type=jnp.float32,
    ).astype(W_dec.dtype)
    df = jnp.einsum(
        "bnd,hnd->bh", g_recon, W_dec.astype(jnp.float32),
        preferred_element_type=jnp.float32,
    )
    dh = jnp.where(f > 0, df, 0.0).astype(f.dtype)
    return dh, dW_dec


_factored_topk_forward.defvjp(_factored_topk_fwd, _factored_topk_bwd)


# ---------------------------------------------------------------------------
# sparse backward plane (cfg.sparse_bwd; ops/sparse_grad.py): the factored
# tier with the dense backward matmuls replaced by O(B·k) scatter-
# accumulates. The dense factored backward (_factored_topk_bwd above) runs
# dW_dec [B,H]x[B,nd] + df [B,nd]x[H,nd] — and the encoder VJP behind it
# runs dW_enc [B,nd]x[B,H] — three matmuls that each multiply ~99.9%
# structural zeros at TopK(k=32), dict 2^17. With (vals, idx) in hand the
# same gradients are B·k-pair scatter/gathers:
#
#   d_vals[b,j] = <g[b], W_dec[idx[b,j]]>          (gather + [B,k,nd] einsum)
#   dW_dec[idx[b,j]] += vals[b,j] · g[b]           (scatter_add_rows)
#   dW_enc[:, :, idx[b,j]] += d_vals[b,j] · x[b]   (scatter_add_rows, with a
#   db_enc[idx[b,j]] += d_vals[b,j]                 ones column riding along)
#
# accumulated in f32 with deterministic within-block ordering (the kernel
# sorts pairs by destination, stable). Gradients equal the dense backward's
# up to f32 summation order — asserted in tests/test_sparse_grad.py,
# including the duplicate-index (two rows activating the same latent) case.
#
# Two variants, same split as the factored forward pair above:
# - _sparse_topk_step: owns encode AND decode (x, W_enc, b_enc, W_dec), so
#   ALL THREE backward matmuls disappear. Used on bare steps (no AuxK this
#   step) — the throughput-defining variant. dx is computed exactly (a
#   k-row gather of W_enc) and DCE'd by XLA when only params are
#   differentiated, which is every training step.
# - _sparse_topk_from_h: (h, W_dec) only, used when another consumer needs
#   the pre-acts differentiably (the AuxK ranking/gather). dh is scattered
#   back to [B, H] (the one scatter this variant keeps) and dW_enc flows
#   through the ordinary encoder VJP.
#
# Soundness gate is the factored tier's (l1_coeff == 0: no gradient path
# through (vals, idx) cotangents).


@functools.partial(jax.custom_vjp, nondiff_argnums=(4,))
def _sparse_topk_step(
    x: jax.Array, W_enc: jax.Array, b_enc: jax.Array, W_dec: jax.Array,
    k: int,
) -> tuple[jax.Array, jax.Array, jax.Array]:
    """``(recon [B,n,d] f32 (no b_dec), vals [B,k], idx [B,k])`` from the
    batch ``x [B,n,d]`` — encode + TopK + factored decode in one
    custom-vjp scope so the backward never leaves factored form."""
    from crosscoder_tpu.ops import topk_pallas

    hf = jnp.einsum("bnd,ndh->bh", x, W_enc,
                    preferred_element_type=jnp.float32)
    h = (hf + b_enc.astype(jnp.float32)).astype(x.dtype)
    f = topk_pallas.topk(h, k)
    vals, idx = topk_pallas.sparsify(f, k)
    w = jnp.take(W_dec, idx, axis=0)                       # [B, k, n, d]
    recon = jnp.einsum("bk,bknd->bnd", vals, w,
                       preferred_element_type=jnp.float32)
    return recon, vals, idx


def _sparse_topk_step_fwd(x, W_enc, b_enc, W_dec, k):
    out = _sparse_topk_step(x, W_enc, b_enc, W_dec, k)
    _, vals, idx = out
    # residuals are FACTORED: (vals, idx) [B,k] replace the [B,H] masked
    # activations the dense backward keeps — ~H/k less residual memory.
    # (b_tok: zero-size dtype token — residual leaves must be arrays.)
    return out, (x, vals, idx, W_enc, W_dec, jnp.zeros((0,), b_enc.dtype))


def _sparse_topk_step_bwd(k, res, g):
    from crosscoder_tpu.ops import sparse_grad

    x, vals, idx, W_enc, W_dec, b_tok = res
    b_dtype = b_tok.dtype
    g_recon = g[0].astype(jnp.float32)                     # [B, n, d]
    # cotangents g[1], g[2] (vals, idx) are ignored — soundness gated on
    # l1_coeff == 0, exactly like _factored_topk_forward
    B = vals.shape[0]
    H, n, d = W_dec.shape
    nd = n * d
    g_flat = g_recon.reshape(B, nd)

    # d_vals through the k active decoder rows, straight-through masked on
    # the survivors (vals > 0; padded slots carry val 0 and drop out —
    # the same rule as the dense path's f > 0 mask)
    w = jnp.take(W_dec, idx, axis=0).astype(jnp.float32)   # [B, k, n, d]
    d_vals = jnp.einsum("bnd,bknd->bk", g_recon, w)
    d_vals = jnp.where(vals > 0, d_vals, 0.0)              # [B, k] f32

    # decoder gradient: B·k scatter-accumulate instead of [B,H]x[B,nd]
    dW_dec = sparse_grad.scatter_add_rows(
        vals.astype(jnp.float32), idx, g_flat, H
    ).reshape(H, n, d).astype(W_dec.dtype)

    # encoder gradients from the k-sparse dh: one scatter over the batch
    # rows, with a ones column appended (lane-padded to 128) so db_enc
    # rides the same accumulation instead of needing its own scatter
    x_flat = x.reshape(B, nd).astype(jnp.float32)
    ones_col = (jax.lax.broadcasted_iota(jnp.int32, (B, 128), 1) == 0
                ).astype(jnp.float32)
    x_aug = jnp.concatenate([x_flat, ones_col], axis=1)    # [B, nd + 128]
    enc_grads = sparse_grad.scatter_add_rows(d_vals, idx, x_aug, H)
    dW_enc = jnp.transpose(
        enc_grads[:, :nd].reshape(H, n, d), (1, 2, 0)
    ).astype(W_enc.dtype)
    db_enc = enc_grads[:, nd].astype(b_dtype)

    # dx exactly (k-row gather of W_enc); XLA DCEs this whole branch when
    # only params are differentiated — i.e. on every training step
    we = jnp.take(W_enc, idx.reshape(-1), axis=2).reshape(n, d, B, k)
    dx = jnp.einsum("bk,ndbk->bnd", d_vals, we.astype(jnp.float32)
                    ).astype(x.dtype)
    return dx, dW_enc, db_enc, dW_dec


_sparse_topk_step.defvjp(_sparse_topk_step_fwd, _sparse_topk_step_bwd)


# ---------------------------------------------------------------------------
# fused encoder→TopK tier (cfg.fused_encoder; ops/fused_encoder_topk.py):
# the _sparse_topk_step forward with the dense encode + TopK + sparsify
# chain replaced by ONE Pallas kernel that streams encoder tiles through
# VMEM and folds them into a running per-row top-k — the [B, H] pre-act
# matrix never exists in HBM. The BACKWARD is _sparse_topk_step's
# verbatim: its residuals are (x, vals, idx, W_enc, W_dec), none of which
# the fusion removes, so the two tiers share one bwd implementation and
# the (vals, idx) contract is pinned by construction. AuxK steps need the
# pre-acts as a differentiable residual for the aux ranking — the
# ``h``-residual escape hatch: they stay on _sparse_topk_from_h's dense
# encode (see get_losses).


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5))
def _fused_topk_step(
    x: jax.Array, W_enc: jax.Array, b_enc: jax.Array, W_dec: jax.Array,
    k: int, quant_block: int = 0,
) -> tuple[jax.Array, jax.Array, jax.Array]:
    """``(recon [B,n,d] f32 (no b_dec), vals [B,k], idx [B,k])`` with the
    encode+TopK+sparsify chain fused into one kernel (``quant_block`` > 0
    routes the in-kernel int8 block-scaled matmul — cfg.quant_encoder)."""
    from crosscoder_tpu.ops import fused_encoder_topk as fek

    B = x.shape[0]
    vals, idx = fek.fused_topk_encode(
        x.reshape(B, -1), W_enc.reshape(-1, W_enc.shape[-1]), b_enc, k,
        quant_block=quant_block,
    )
    w = jnp.take(W_dec, idx, axis=0)                       # [B, k, n, d]
    recon = jnp.einsum("bk,bknd->bnd", vals, w,
                       preferred_element_type=jnp.float32)
    return recon, vals, idx


def _fused_topk_step_fwd(x, W_enc, b_enc, W_dec, k, quant_block):
    out = _fused_topk_step(x, W_enc, b_enc, W_dec, k, quant_block)
    _, vals, idx = out
    # the _sparse_topk_step residual tuple exactly (see its fwd)
    return out, (x, vals, idx, W_enc, W_dec, jnp.zeros((0,), b_enc.dtype))


def _fused_topk_step_bwd(k, quant_block, res, g):
    # gradients are the sparse plane's verbatim: the kernel only changed
    # how (vals, idx) were PRODUCED, not what they mean
    return _sparse_topk_step_bwd(k, res, g)


_fused_topk_step.defvjp(_fused_topk_step_fwd, _fused_topk_step_bwd)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3,))
def _fused_batchtopk_encode(
    x: jax.Array, W_enc: jax.Array, b_enc: jax.Array, k: int
) -> jax.Array:
    """Masked BatchTopK activations ``f [B, H]`` with the encoder matmul
    and the global-threshold bisection fused over streamed tiles
    (ops/fused_encoder_topk.fused_batchtopk_encode_raw) — bit-identical
    to ``activations.batchtopk(pre_acts(params, x), k)``. The custom VJP
    reproduces the dense path's gradients exactly: straight-through on
    the survivors, then the ordinary encoder-einsum VJP."""
    from crosscoder_tpu.ops import fused_encoder_topk as fek

    B = x.shape[0]
    return fek.fused_batchtopk_encode_raw(
        x.reshape(B, -1), W_enc.reshape(-1, W_enc.shape[-1]), b_enc, k,
    )


def _fused_batchtopk_encode_fwd(x, W_enc, b_enc, k):
    f = _fused_batchtopk_encode(x, W_enc, b_enc, k)
    return f, (x, W_enc, f, jnp.zeros((0,), b_enc.dtype))


def _fused_batchtopk_encode_bwd(k, res, g):
    x, W_enc, f, b_tok = res
    # dense chain: f = hp·stop_grad(mask) → dh = g·mask (mask ⟺ f > 0);
    # h = (hf + b).astype(x.dtype) → dhf = dh in f32; then the einsum VJP
    dh = jnp.where(f > 0, g, 0).astype(jnp.float32)        # [B, H]
    db_enc = jnp.sum(dh, axis=0).astype(b_tok.dtype)
    dW_enc = jnp.einsum(
        "bnd,bh->ndh", x.astype(jnp.float32), dh,
        preferred_element_type=jnp.float32,
    ).astype(W_enc.dtype)
    dx = jnp.einsum(
        "bh,ndh->bnd", dh, W_enc.astype(jnp.float32),
        preferred_element_type=jnp.float32,
    ).astype(x.dtype)
    return dx, dW_enc, db_enc


_fused_batchtopk_encode.defvjp(_fused_batchtopk_encode_fwd,
                               _fused_batchtopk_encode_bwd)


_FUSED_DEMOTION_WARNED: set[str] = set()


def _warn_fused_demoted(reason: str) -> None:
    """``fused_encoder='on'`` fell back to the dense encode — the silent
    no-op class the dispatch layer exists to prevent, so say it once per
    (process, reason) on stderr. Config validation can't catch these:
    they depend on env/backend resolution ("auto" knobs) only known at
    trace time."""
    if reason in _FUSED_DEMOTION_WARNED:
        return
    _FUSED_DEMOTION_WARNED.add(reason)
    import sys

    print(
        f"[crosscoder_tpu] fused_encoder='on' demoted to the dense "
        f"encode: {reason}",
        file=sys.stderr, flush=True,
    )


def use_fused_encoder(cfg: CrossCoderConfig, batch: int | None = None) -> bool:
    """Dispatch for the fused encoder→TopK tier (``cfg.fused_encoder``).

    "off" never. For ``topk`` the fused forward hands (vals, idx)
    straight to the sparse backward plane, so it rides the
    ``_sparse_topk_step`` scope: the factored tier AND
    :func:`use_sparse_bwd` must be live (AuxK steps additionally fall
    back at the trace site — the ``h``-residual escape hatch). For
    ``batchtopk`` it needs only training mode (a calibrated fixed
    threshold is eval — the emit sweep alone, no bisection to fuse).
    "auto" additionally requires the kernel to be live (TPU +
    ``CROSSCODER_FUSED_TOPK_PALLAS=1`` / umbrella, or interpret mode)
    and a kernel-supported shape; "on" forces, with the ops layer's
    dense fallback covering unsupported shapes. An "on" that a
    prerequisite tier demotes anyway (e.g. ``sparse_bwd='auto'``
    resolving off) warns once on stderr instead of silently no-opping.
    """
    if cfg.fused_encoder == "off":
        return False
    forced = cfg.fused_encoder == "on"
    if cfg.activation == "topk":
        if not (use_factored_decode(cfg) and use_sparse_bwd(cfg, batch)):
            if forced:
                _warn_fused_demoted(
                    "activation='topk' needs the factored tier and the "
                    "sparse backward plane live (use_factored_decode/"
                    "use_sparse_bwd resolved off — check dict_size, "
                    "batch divisibility, and the sparse_grad kernel gate)"
                )
            return False
    elif cfg.activation == "batchtopk":
        if cfg.batchtopk_threshold > 0:
            if forced:
                _warn_fused_demoted(
                    "batchtopk_threshold > 0 is eval mode — a calibrated "
                    "fixed threshold has no bisection to fuse"
                )
            return False
    else:
        return False
    if cfg.fused_encoder == "on":
        return True
    from crosscoder_tpu.ops import fused_encoder_topk as fek

    if not fek.kernel_enabled():
        return False
    # the int8 path is topk-only (validated in config) — batchtopk's
    # support probe must not gate on quant geometry it will never use
    qb = (cfg.quant_block
          if cfg.quant_encoder and cfg.activation == "topk" else 0)
    return batch is None or fek.supported(
        batch, cfg.n_sources * cfg.d_in, cfg.dict_size, cfg.topk_k,
        dtype_of(cfg.enc_dtype), qb,
    )


@functools.partial(jax.custom_vjp, nondiff_argnums=(2,))
def _sparse_topk_from_h(
    h: jax.Array, W_dec: jax.Array, k: int
) -> tuple[jax.Array, jax.Array, jax.Array]:
    """The (h, W_dec)-scoped sparse-backward variant: same forward as
    ``_factored_topk_forward``, backward with the dense dW_dec/df matmuls
    replaced by the scatter/gather pair. ``dh`` is materialized [B, H]
    (one scatter) because ``h`` has other consumers on this path (the
    AuxK ranking) — the full-step variant above avoids even that."""
    from crosscoder_tpu.ops import topk_pallas

    f = topk_pallas.topk(h, k)
    vals, idx = topk_pallas.sparsify(f, k)
    w = jnp.take(W_dec, idx, axis=0)
    recon = jnp.einsum("bk,bknd->bnd", vals, w,
                       preferred_element_type=jnp.float32)
    return recon, vals, idx


def _sparse_topk_from_h_fwd(h, W_dec, k):
    out = _sparse_topk_from_h(h, W_dec, k)
    _, vals, idx = out
    # h_tok: zero-size dtype token (residual leaves must be arrays); the
    # dh shape is recoverable as (vals batch, W_dec rows)
    return out, (vals, idx, W_dec, jnp.zeros((0,), h.dtype))


def _sparse_topk_from_h_bwd(k, res, g):
    from crosscoder_tpu.ops import sparse_grad

    vals, idx, W_dec, h_tok = res
    h_shape = (vals.shape[0], W_dec.shape[0])
    h_dtype = h_tok.dtype
    g_recon = g[0].astype(jnp.float32)
    B = vals.shape[0]
    H, n, d = W_dec.shape
    w = jnp.take(W_dec, idx, axis=0).astype(jnp.float32)
    d_vals = jnp.einsum("bnd,bknd->bk", g_recon, w)
    d_vals = jnp.where(vals > 0, d_vals, 0.0)
    dW_dec = sparse_grad.scatter_add_rows(
        vals.astype(jnp.float32), idx, g_recon.reshape(B, n * d), H
    ).reshape(H, n, d).astype(W_dec.dtype)
    rows = jnp.arange(B)[:, None]
    dh = jnp.zeros(h_shape, h_dtype).at[rows, idx].add(
        d_vals.astype(h_dtype), mode="drop"
    )
    return dh, dW_dec


_sparse_topk_from_h.defvjp(_sparse_topk_from_h_fwd, _sparse_topk_from_h_bwd)


@jax.custom_vjp
def _sparse_aux_product(avals: jax.Array, aidx: jax.Array,
                        W_dec: jax.Array) -> jax.Array:
    """AuxK decode ``e_hat [B,n,d] f32`` with the SPARSE backward.

    Forward is byte-identical to the dense aux path (scatter the aux
    activations to [B, H], one MXU matmul — the measured-best forward at
    aux_k ≈ 8k, see the dense-decode note in get_losses); only the two
    backward matmuls are replaced: ``d_avals`` through the aux_k gathered
    rows, ``dW_dec`` through the scatter-accumulate plane.
    """
    B = avals.shape[0]
    H = W_dec.shape[0]
    rows = jnp.arange(B)[:, None]
    f_aux = jnp.zeros((B, H), avals.dtype).at[rows, aidx].add(avals)
    return jnp.einsum("bh,hnd->bnd", f_aux, W_dec,
                      preferred_element_type=jnp.float32)


def _sparse_aux_product_fwd(avals, aidx, W_dec):
    return _sparse_aux_product(avals, aidx, W_dec), (avals, aidx, W_dec)


def _sparse_aux_product_bwd(res, g):
    from crosscoder_tpu.ops import sparse_grad

    avals, aidx, W_dec = res
    gf = g.astype(jnp.float32)                             # [B, n, d]
    B = avals.shape[0]
    H, n, d = W_dec.shape
    w = jnp.take(W_dec, aidx, axis=0).astype(jnp.float32)  # [B, ak, n, d]
    d_avals = jnp.einsum("bnd,bknd->bk", gf, w).astype(avals.dtype)
    dW_dec = sparse_grad.scatter_add_rows(
        avals.astype(jnp.float32), aidx, gf.reshape(B, n * d), H
    ).reshape(H, n, d).astype(W_dec.dtype)
    return d_avals, None, dW_dec


_sparse_aux_product.defvjp(_sparse_aux_product_fwd, _sparse_aux_product_bwd)


def use_sparse_bwd(cfg: CrossCoderConfig, batch: int | None = None) -> bool:
    """Dispatch for the sparse backward plane (``cfg.sparse_bwd``).

    Applies on top of the factored tier (callers AND the factored gate
    must agree — ``get_losses`` computes ``factored and use_sparse_bwd``).
    "off" never; "on" whenever sound (forced — CPU parity tests and
    forced A/Bs; unsupported shapes fall back to the XLA scatter inside
    scatter_add_rows, still sparse math); "auto" additionally requires
    the Pallas scatter kernel to be live (interpret mode, or TPU with
    ``CROSSCODER_SPARSE_GRAD_PALLAS=1`` — the ops/quant.py hardware gate)
    and, when the batch size is known, kernel-supported shapes for both
    scatter calls — without the kernel, a sparse backward IS the measured
    42-76 ms XLA scatter the dense matmuls beat.
    Soundness: the factored tier's l1_coeff == 0 gate.
    """
    if cfg.activation != "topk" or cfg.sparse_decode:
        return False
    if cfg.sparse_bwd == "off" or cfg.l1_coeff != 0:
        return False
    if cfg.sparse_bwd == "on":
        return True
    from crosscoder_tpu.ops import sparse_grad

    if not sparse_grad.kernel_enabled():
        return False
    if batch is not None and not sparse_grad.decode_grad_supported(
        cfg.dict_size, cfg.topk_k, cfg.n_sources, cfg.d_in, batch
    ):
        return False
    return True


def use_sparse_aux(cfg: CrossCoderConfig, batch: int) -> bool:
    """Sparse backward for the AuxK aux term. Requires the sparse plane
    active ("on"/live-"auto") AND kernel-supported aux shapes — the
    B·aux_k pair list must be VMEM-resident (sparse_grad._MAX_PAIRS;
    aux_k ≈ 8k at batch 4096 is ~32× over the cap, and the XLA fallback
    would materialize a [B·aux_k, n·d] f32 update matrix, so the support
    gate is hard even under forced "on" — unsupported aux falls back to
    the dense aux VJP, which is the measured-best dense path anyway).
    "auto" additionally applies the traffic heuristic
    ``aux_k · 512 <= dict_size``: the sparse backward's pair-gather bytes
    beat the dense VJP matmuls only once the dictionary is ~500× the aux
    width (v5e flop:byte ratio ≈ 250, ×2 for the two matmuls replaced) —
    provisional until a hardware A/B lands."""
    if cfg.aux_k <= 0 or not use_sparse_bwd(cfg):
        return False
    from crosscoder_tpu.ops import sparse_grad

    k_aux = min(cfg.aux_k, cfg.dict_size)
    aux_ok = sparse_grad.supported(
        cfg.dict_size, cfg.n_sources * cfg.d_in, batch, batch * k_aux
    )
    if cfg.sparse_bwd == "on":
        return aux_ok
    return aux_ok and cfg.aux_k * 512 <= cfg.dict_size


def use_factored_decode(cfg: CrossCoderConfig) -> bool:
    """Dispatch for the factored TopK decode tier.

    ``cfg.factored_decode``: "off" never; "on" whenever sound+supported;
    "auto" additionally requires dict_size >= 2^17 — the XLA row gather
    costs ~17-20 ms flat (131k x 9 KB rows is instruction-rate-bound on
    v5e, ~74 GB/s effective), so it only beats the dense decode matmul
    once that matmul crosses ~30 ms (dict 2^17 at bench shapes; measured
    A/B: -8 ms at 2^17, +6 ms at 2^16).
    Soundness gate: l1_coeff must be 0 (see _factored_topk_forward).
    """
    if cfg.activation != "topk" or cfg.sparse_decode:
        return False
    mode = cfg.factored_decode
    if mode == "off" or cfg.l1_coeff != 0:
        return False
    from crosscoder_tpu.ops import activations as act_ops
    from crosscoder_tpu.ops import topk_pallas

    if not act_ops._default_use_pallas() and not topk_pallas._INTERPRET:
        return False
    probe = jax.ShapeDtypeStruct((1, cfg.dict_size), dtype_of(cfg.enc_dtype))
    if not topk_pallas.supported(probe, cfg.topk_k):
        return False
    if not topk_pallas.sparsify_supported(cfg.dict_size, cfg.topk_k):
        return False
    # sparse_bwd="on" forces the factored tier too (the sparse backward
    # plane extends it — the factored (vals, idx) ARE its inputs), so a
    # forced sparse backward at sub-2^17 dicts doesn't silently noop
    return (mode == "on" or cfg.dict_size >= 131072
            or cfg.sparse_bwd == "on")


def topk_vals_idx(params: Params, x: jax.Array, cfg: CrossCoderConfig) -> tuple[jax.Array, jax.Array]:
    """TopK encode in factored form: ``(vals [B,k], idx [B,k])``.

    Gradients flow to ``W_enc``/``b_enc`` through the ``take_along_axis``
    gather (its VJP is the scatter the dense TopK mask implements); ``idx``
    is treated as a constant of the backward pass, the standard
    straight-through treatment (same as ops.activations.topk).
    """
    h = pre_acts(params, x)
    hp = act_ops.relu(h)
    _, idx = jax.lax.top_k(hp, cfg.topk_k)
    vals = jnp.take_along_axis(hp, jax.lax.stop_gradient(idx), axis=-1)
    return vals, idx


def sparse_topk_forward(params: Params, x: jax.Array, cfg: CrossCoderConfig) -> tuple[jax.Array, jax.Array, jax.Array]:
    """TopK encode + sparse decode: ``(recon [B,n,d] fp32, vals, idx)``.

    Numerically the dense path's reconstruction restricted to its nonzero
    terms — equal up to fp32 summation order.
    """
    vals, idx = topk_vals_idx(params, x, cfg)
    recon = _sparse_decode_product(vals, idx, params["W_dec"])
    return recon + params["b_dec"].astype(jnp.float32), vals, idx


def get_losses(
    params: Params,
    x: jax.Array,
    cfg: CrossCoderConfig,
    with_metrics: bool = True,
    dead_mask: jax.Array | None = None,
    track_fired: bool = False,
) -> LossOutput:
    """Full loss surface for a batch ``x: [batch, n_sources, d_in]``.

    ``with_metrics=False`` skips the metric-only reductions (l0 and the
    explained variances — several extra full passes over the batch/latents,
    ~13% of a TPU train step) and returns zeros in their slots; the
    objective terms (l2, weighted l1) are always computed. The trainer uses
    this off log-steps; numerics of the objective are identical.

    Numerics follow reference ``crosscoder.py:96-130`` exactly, with the
    fp32 upcast for all loss reductions (reference ``crosscoder.py:104``):

    - ``l2``: per-row sum of squared error over (source, d_in), mean over batch
    - explained variance: ``1 − l2_row / (total_variance_row + 1e-8)``, where
      total variance is about the batch mean
    - ``l1``: ``mean_b Σ_f acts[b,f] · Σ_n ‖W_dec[f,n]‖`` — the decoder-norm
      weighted form (reference ``crosscoder.py:123-126``), NOT plain Σ|acts|
    - ``l0``: mean count of strictly-positive latents
    """
    x = x.astype(dtype_of(cfg.enc_dtype))
    factored = use_factored_decode(cfg)
    sparse = (cfg.sparse_decode and cfg.activation == "topk") or factored
    l0_penalty: jax.Array | float = 0.0
    h = None            # pre-acts, kept when a later consumer (the
                        # JumpReLU L0 penalty, the AuxK ranking) needs
                        # them — shared explicitly rather than trusting
                        # CSE to dedupe a second encode matmul
    aux_active = dead_mask is not None and cfg.aux_k > 0
    sparse_bwd = factored and use_sparse_bwd(cfg, x.shape[0])
    fused = use_fused_encoder(cfg, x.shape[0])
    if factored and sparse_bwd and not aux_active:
        # sparse backward plane, full-step scope: encode + TopK + factored
        # decode under ONE custom vjp (ops/sparse_grad.py) — none of the
        # three dense backward matmuls survives. Forward numerics are the
        # factored tier's exactly (same einsum/kernel/gather chain). The
        # fused tier (cfg.fused_encoder) swaps that forward for the
        # encoder→TopK megakernel — same (vals, idx) contract, same
        # backward, no [B, H] pre-act matrix in HBM; aux-active steps
        # fall through to the (h, W_dec) scope below (the h-residual
        # escape hatch — the aux ranking consumes the pre-acts).
        if fused:
            qb = cfg.quant_block if cfg.quant_encoder else 0
            recon_f32, vals, idx = _fused_topk_step(
                x, params["W_enc"], params["b_enc"], params["W_dec"],
                cfg.topk_k, qb,
            )
        else:
            recon_f32, vals, idx = _sparse_topk_step(
                x, params["W_enc"], params["b_enc"], params["W_dec"],
                cfg.topk_k,
            )
        recon = (recon_f32 + params["b_dec"].astype(jnp.float32)).astype(x.dtype)
        f = None
    elif factored:
        # Pallas factored tier: kernel mask → sparsify → k-row decode;
        # backward identical to the dense path (see _factored_topk_forward)
        # — or, on sparse-backward AuxK steps, the (h, W_dec)-scoped sparse
        # variant (h must stay an explicit residual for the aux ranking)
        h = pre_acts(params, x)
        tier = _sparse_topk_from_h if sparse_bwd else _factored_topk_forward
        recon_f32, vals, idx = tier(h, params["W_dec"], cfg.topk_k)
        recon = (recon_f32 + params["b_dec"].astype(jnp.float32)).astype(x.dtype)
        f = None
    elif sparse:
        # factored TopK path: decode touches only the k active rows; the
        # rounding of recon through the compute dtype matches the dense
        # decode's output cast so both paths see the same loss numerics
        recon_f32, vals, idx = sparse_topk_forward(params, x, cfg)
        recon = recon_f32.astype(x.dtype)
        f = None
    elif cfg.activation == "batchtopk" and fused and not aux_active:
        # fused BatchTopK: encoder matmul + global-threshold bisection +
        # emit over streamed VMEM tiles (the pre-acts are recomputed per
        # bisection pass instead of round-tripping [B, H] through HBM);
        # f is bit-identical to the dense chain, gradients are the dense
        # straight-through VJP. AuxK steps keep the dense encode (the
        # aux ranking needs h — same escape hatch as the topk tier).
        f = _fused_batchtopk_encode(
            x, params["W_enc"], params["b_enc"], cfg.topk_k
        )
        recon = decode(params, f)
    elif cfg.activation == "jumprelu" and cfg.l0_coeff > 0:
        h = pre_acts(params, x)
        f = act_ops.apply(h, cfg, params)
        recon = decode(params, f)
        l0_penalty = act_ops.jumprelu_l0(
            h, params["log_theta"], cfg.jumprelu_bandwidth
        )
    else:
        h = pre_acts(params, x)
        f = act_ops.apply(h, cfg, params)
        recon = decode(params, f)

    xf = x.astype(jnp.float32)
    rf = recon.astype(jnp.float32)
    err2 = jnp.square(rf - xf)                            # [B, n, d]
    l2_per_row = jnp.sum(err2, axis=(-2, -1))             # [B]
    l2_loss = jnp.mean(l2_per_row)

    # L1 is an objective term only when l1_coeff != 0 (TopK-style runs set it
    # to 0 and control sparsity structurally); off log-steps
    # (with_metrics=False) a zero-coeff L1 would be pure overhead — the
    # [H, n] decoder-norm reduce plus a full [B, H] weighted sweep, ~2-3 ms
    # of the bare TopK step at dict 2^15 — so it is gated exactly like the
    # other metric-only reductions and returns 0 in that slot.
    need_l1 = with_metrics or cfg.l1_coeff != 0
    if need_l1:
        dec_norms = jnp.linalg.norm(params["W_dec"].astype(jnp.float32), axis=-1)  # [H, n]
        total_dec_norm = jnp.sum(dec_norms, axis=-1)      # [H]
    if not need_l1:
        l1_loss = jnp.zeros((), jnp.float32)
    elif sparse:
        # identical to the dense weighted L1: inactive latents contribute 0
        w_active = jnp.take(total_dec_norm, idx)          # [B, k]
        l1_loss = jnp.mean(jnp.sum(vals.astype(jnp.float32) * w_active, axis=-1))
    else:
        ff = f.astype(jnp.float32)
        l1_loss = jnp.mean(jnp.sum(ff * total_dec_norm[None, :], axis=-1))

    # --- AuxK (cfg.aux_k > 0; Gao et al. 2024 "Scaling and evaluating
    # sparse autoencoders", the standard TopK-SAE dead-latent recipe; no
    # reference counterpart — the reference's dense ReLU never faces mass
    # latent death). Reconstruct the MAIN reconstruction's residual
    # e = stop_grad(x − x̂) with the top aux_k latents among those the
    # trainer marked dead, decoded through W_dec without b_dec; the loss is
    # normalized by the residual's own power so cfg.aux_k_coeff stays
    # dimensionless as the residual shrinks. Raw (un-ReLU'd) pre-acts are
    # ranked/decoded — a dead latent's pre-act is usually ≤ 0, and ReLU
    # would zero exactly the gradient path this loss exists to provide.
    # Objective-relevant, so computed in the with_metrics=False step too.
    aux_loss: jax.Array | float = 0.0
    fired = None
    if track_fired or (dead_mask is not None and cfg.aux_k > 0):
        # which latents fired this batch (the trainer's steps_since_fired
        # update). Tracked on EVERY step even when the aux loss itself is
        # amortized to every cfg.aux_every-th step — deadness must stay
        # current, or a revived latent would keep receiving aux gradient
        # for up to aux_every steps after coming back.
        d_hidden = params["W_dec"].shape[0]
        if sparse:
            hits = jnp.zeros((d_hidden,), jnp.int32).at[idx.reshape(-1)].add(
                (vals.reshape(-1) > 0).astype(jnp.int32), mode="drop"
            )
            fired = hits > 0
        else:
            fired = jnp.any(f > 0, axis=0)
    if dead_mask is not None and cfg.aux_k > 0:
        d_hidden = params["W_dec"].shape[0]
        k_aux = min(cfg.aux_k, d_hidden)
        # Selection runs in the COMPUTE dtype with approx_max_k (the TPU
        # PartialReduce instruction) — an exact fp32 top_k here cost more
        # than the whole rest of the step at dict 2^15 (measured 498 vs
        # 79 ms, bench matrix): it materialized [B, H] fp32 and paid the
        # k=256 sort. Which near-top dead latent gets the aux gradient is
        # heuristic anyway; values are re-GATHERED from the pre-acts so
        # the encoder's gradient path is exact (same straight-through
        # treatment as topk_vals_idx), and non-dead slots (when fewer
        # dead than aux_k exist) are zeroed by the mask gather.
        h_all = h if h is not None else pre_acts(params, x)
        neg = jnp.asarray(jnp.finfo(h_all.dtype).min, h_all.dtype)
        ranked = jnp.where(dead_mask[None, :], jax.lax.stop_gradient(h_all), neg)
        if cfg.aux_exact_rank:
            # engine-parity mode: the torch oracle ranks exactly, so the
            # jax side must select the same latents (cfg.aux_exact_rank)
            _, aidx = jax.lax.top_k(ranked, k_aux)
        else:
            _, aidx = jax.lax.approx_max_k(ranked, k_aux, recall_target=0.95)
        aidx = jax.lax.stop_gradient(aidx)
        avals = jnp.take_along_axis(h_all, aidx, axis=-1)
        avals = jnp.where(jnp.take(dead_mask, aidx), avals, 0)
        e = jax.lax.stop_gradient(xf - rf)                # [B, n, d] fp32
        # dense decode of the scattered aux activations: at aux_k ≈ 8k the
        # per-example row gather (_sparse_decode_product) materializes
        # [B, aux_k, n, d] — ~10 GB of HBM traffic at bench shapes
        # (measured 391 ms/step vs ~145 dense) — while B·aux_k/H ≈ 32
        # hits per dictionary row means every W_dec row is read anyway:
        # three MXU matmuls (fwd + the two VJPs) win outright, the same
        # trade the sparse_decode notes above document for the main path.
        if use_sparse_aux(cfg, x.shape[0]):
            # sparse backward reuse (cfg.sparse_bwd): identical dense
            # forward, backward through the O(B·aux_k) scatter/gather
            # plane instead of the two [B,H]-sized VJP matmuls
            e_hat = _sparse_aux_product(
                avals.astype(x.dtype), aidx, params["W_dec"]
            )
        else:
            f_aux = jnp.zeros((x.shape[0], d_hidden), x.dtype).at[
                jnp.arange(x.shape[0])[:, None], aidx
            ].add(avals.astype(x.dtype))
            e_hat = jnp.einsum(
                "bh,hnd->bnd", f_aux, params["W_dec"],
                preferred_element_type=jnp.float32,
            )
        num = jnp.mean(jnp.sum(jnp.square(e_hat - e), axis=(-2, -1)))
        den = jnp.mean(jnp.sum(jnp.square(e), axis=(-2, -1)))
        # no dead latents → e_hat ≡ 0 and the ratio is a gradient-free
        # constant ≈ 1; gate it to 0 so loss/metrics don't carry the ghost
        aux_loss = jnp.where(jnp.any(dead_mask), num / (den + 1e-8), 0.0)

    if not with_metrics:
        zero = jnp.zeros((), jnp.float32)
        return LossOutput(
            l2_loss=l2_loss,
            l1_loss=l1_loss,
            l0_loss=zero,
            explained_variance=jnp.zeros_like(l2_per_row),
            explained_variance_per_source=jnp.zeros(
                (x.shape[-2], x.shape[0]), jnp.float32
            ),
            l0_penalty=l0_penalty,
            aux_loss=aux_loss,
            fired=fired,
        )

    eps = 1e-8
    centered = xf - jnp.mean(xf, axis=0, keepdims=True)
    tot_var = jnp.sum(jnp.square(centered), axis=(-2, -1))  # [B]
    explained_variance = 1.0 - l2_per_row / (tot_var + eps)

    # per-source EV (reference computes _A and _B separately,
    # crosscoder.py:115-121); vectorized over the source axis here
    l2_per_source = jnp.sum(err2, axis=-1)                # [B, n]
    var_per_source = jnp.sum(jnp.square(centered), axis=-1)  # [B, n]
    ev_per_source = 1.0 - l2_per_source / (var_per_source + eps)  # [B, n]

    if sparse:
        l0_loss = jnp.mean(jnp.sum((vals > 0).astype(jnp.float32), axis=-1))
    else:
        l0_loss = jnp.mean(jnp.sum((f > 0).astype(jnp.float32), axis=-1))

    return LossOutput(
        l2_loss=l2_loss,
        l1_loss=l1_loss,
        l0_loss=l0_loss,
        explained_variance=explained_variance,
        explained_variance_per_source=jnp.transpose(ev_per_source),
        l0_penalty=l0_penalty,
        aux_loss=aux_loss,
        fired=fired,
    )


def cast_params(params: Params, dtype: jnp.dtype) -> Params:
    """Cast weight leaves to the compute dtype (``log_theta`` stays fp32 —
    its gradient path is the STE, not the MXU)."""
    return {
        k: (v if k == "log_theta" else v.astype(dtype)) for k, v in params.items()
    }


def training_loss(
    params: Params,
    x: jax.Array,
    l1_coeff: jax.Array | float,
    cfg: CrossCoderConfig,
    with_metrics: bool = True,
    l0_coeff: jax.Array | float | None = None,
    dead_mask: jax.Array | None = None,
    aux_coeff: jax.Array | float | None = None,
    track_fired: bool = False,
) -> tuple[jax.Array, LossOutput]:
    """Scalar training objective ``l2 + l1_coeff · l1`` (reference
    ``trainer.py:44``) plus the full loss surface as aux.

    Params may be fp32 masters; they are cast to ``cfg.enc_dtype`` here so
    the einsums hit the MXU in bf16 while gradients accumulate into fp32.
    """
    # The l1 metric/objective term is compiled out when with_metrics=False
    # AND cfg.l1_coeff == 0 (get_losses's need_l1 gate — a static decision).
    # The objective here multiplies the DYNAMIC ``l1_coeff`` argument, so a
    # direct caller passing a nonzero runtime coefficient against
    # cfg.l1_coeff == 0 would silently train l2 + coeff·0. Catch every
    # concretely-checkable disagreement; a traced coefficient can't be
    # inspected, but the production trainer derives it from cfg.l1_coeff's
    # schedule, so trace-time values always agree with the static gate.
    if not with_metrics and cfg.l1_coeff == 0:
        concrete: float | None = None
        if not isinstance(l1_coeff, jax.core.Tracer):
            # python numbers, numpy scalars (np.float32 is NOT a float
            # subclass), and concrete jax scalars all float(); anything
            # that can't is treated as unknowable, like a tracer
            try:
                concrete = float(l1_coeff)
            except (TypeError, ValueError):
                concrete = None
        if concrete is not None and concrete != 0.0:
            raise ValueError(
                f"training_loss got l1_coeff={concrete} but cfg.l1_coeff == 0 "
                "and with_metrics=False: the L1 term is compiled out on this "
                "path, so the sparsity penalty would be silently dropped. "
                "Set cfg.l1_coeff to the intended scale (the schedule-derived "
                "argument then agrees) or pass with_metrics=True."
            )
    losses = get_losses(
        cast_params(params, dtype_of(cfg.enc_dtype)), x, cfg, with_metrics,
        dead_mask=dead_mask, track_fired=track_fired,
    )
    # TopK-style runs control sparsity structurally and typically set
    # l1_coeff=0 in config; the objective shape is the same either way.
    # JumpReLU runs may add the paper's L0 objective via cfg.l0_coeff
    # (``l0_coeff`` overrides it — the trainer passes the warmed-up value).
    loss = losses.l2_loss + l1_coeff * losses.l1_loss
    if cfg.l0_coeff > 0:
        eff = cfg.l0_coeff if l0_coeff is None else l0_coeff
        loss = loss + eff * losses.l0_penalty
    if cfg.aux_k > 0 and dead_mask is not None:
        # AuxK term (``aux_coeff`` overrides cfg.aux_k_coeff — the trainer
        # passes the sparsity-warmup-scaled value, same ramp as l0_coeff)
        eff_aux = cfg.aux_k_coeff if aux_coeff is None else aux_coeff
        loss = loss + eff_aux * losses.aux_loss
    return loss, losses


def param_count(cfg: CrossCoderConfig) -> int:
    n, d, h = cfg.n_sources, cfg.d_in, cfg.dict_size
    count = 2 * n * d * h + h + n * d
    if cfg.activation == "jumprelu":
        count += h  # log_theta
    return count


def fold_scaling_factors(params: Params, factors: Any) -> Params:
    """Fold per-source activation-normalization factors into the weights.

    Mirrors the notebook's ``fold_activation_scaling_factor`` (reference
    ``nb:cell 27``): with per-source scale s (activations were trained on
    ``x·s``), an equivalent crosscoder over *raw* activations has
    ``W_enc[n] ·= s[n]``, ``W_dec[:, n] /= s[n]``, ``b_dec[n] /= s[n]``
    (``b_enc`` unchanged). After folding, analysis/evals can run on
    unnormalized model activations.
    """
    s = jnp.asarray(factors, dtype=jnp.float32)
    out = dict(params)
    out["W_enc"] = (params["W_enc"].astype(jnp.float32) * s[:, None, None]).astype(params["W_enc"].dtype)
    out["W_dec"] = (params["W_dec"].astype(jnp.float32) / s[None, :, None]).astype(params["W_dec"].dtype)
    out["b_dec"] = (params["b_dec"].astype(jnp.float32) / s[:, None]).astype(params["b_dec"].dtype)
    return out
