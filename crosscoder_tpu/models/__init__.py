"""Model zoo: the crosscoder itself and the JAX Gemma-2 harvest runtime."""

from crosscoder_tpu.models import crosscoder, lm  # noqa: F401

__all__ = ["crosscoder", "lm"]
