"""Unit tests for the bounded in-flight pipeline driver — the shared
machinery under buffer refresh, norm calibration, dashboard harvest, and
the CE eval — plus the zero-bubble refill engine's concurrency primitives
(LaunchSequencer, QuantumDispatcher) in crosscoder_tpu/utils/pipeline.py."""

import threading
import time

import pytest

from crosscoder_tpu.utils import pipeline


def test_fifo_order_and_completeness():
    out = []
    pipeline.drive(iter(range(10)), out.append, depth=3)
    assert out == list(range(10))


def test_depth_bounds_in_flight():
    """At most `depth` items are produced-but-undrained at any moment."""
    live = 0
    peak = 0

    def produced():
        nonlocal live, peak
        for i in range(20):
            live += 1
            peak = max(peak, live)
            yield i

    def drain(_):
        nonlocal live
        live -= 1

    pipeline.drive(produced(), drain, depth=3)
    assert live == 0
    assert peak == 3


def test_drain_lag():
    """Item i is drained only after item i+depth-1 was produced (the lag
    that lets device work overlap host work)."""
    events = []
    pipeline.drive(
        (events.append(("p", i)) or i for i in range(6)),
        lambda i: events.append(("d", i)),
        depth=2,
    )
    assert events.index(("d", 0)) > events.index(("p", 1))
    assert events.index(("d", 4)) > events.index(("p", 5))


@pytest.mark.parametrize("depth", [1, 2, 5])
def test_serial_and_deep(depth):
    out = []
    pipeline.drive(iter("abc"), out.append, depth=depth)
    assert out == list("abc")


def test_empty_stream():
    pipeline.drive(iter(()), lambda _: pytest.fail("drain on empty stream"))


def test_producer_exception_propagates():
    def produced():
        yield 1
        raise RuntimeError("boom")

    drained = []
    with pytest.raises(RuntimeError, match="boom"):
        pipeline.drive(produced(), drained.append, depth=1)
    assert drained == [1]   # FIFO items before the failure were drained


# ---------------------------------------------------------------------------
# LaunchSequencer — ticketed program-launch ordering (multi-process prefetch)


def test_sequencer_executes_in_reservation_order():
    """Threads entering their turns in REVERSE order still execute in
    reservation order — the SPMD launch-order guarantee."""
    seq = pipeline.LaunchSequencer()
    tickets = [seq.reserve() for _ in range(3)]
    order = []

    def run(ticket, delay):
        time.sleep(delay)
        with seq.turn(ticket):
            order.append(ticket)

    threads = [
        threading.Thread(target=run, args=(t, d))
        for t, d in zip(tickets, (0.06, 0.03, 0.0))   # last ticket tries first
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert order == tickets


def test_sequencer_skip_unblocks_later_turns():
    seq = pipeline.LaunchSequencer()
    a, b = seq.reserve(), seq.reserve()
    seq.skip(a)                 # a reservation that bailed (failed submit)
    ran = []
    with seq.turn(b):
        ran.append(b)
    assert ran == [b]


def test_sequencer_releases_on_exception():
    """A launch that raises inside its turn must still release the slot —
    a wedged head ticket would deadlock every later launch."""
    seq = pipeline.LaunchSequencer()
    a, b = seq.reserve(), seq.reserve()
    with pytest.raises(RuntimeError, match="launch failed"):
        with seq.turn(a):
            raise RuntimeError("launch failed")
    done = []
    t = threading.Thread(target=lambda: seq.turn(b).__enter__() or done.append(b))
    t.start()
    t.join(timeout=5)
    assert done == [b]


def test_sequencer_out_of_order_release():
    """Tickets released out of order (b skips before a runs) advance the
    head past BOTH once a releases."""
    seq = pipeline.LaunchSequencer()
    a, b, c = seq.reserve(), seq.reserve(), seq.reserve()
    seq.skip(b)
    seq.skip(a)
    with seq.turn(c):
        pass                    # would hang if the head stuck at b


def test_sequencer_invalidate_releases_stale_tickets():
    """The stale-epoch ticket hazard (elastic shrink/grow): a ticket
    reserved before a re-mesh and never released must not block the
    quiesce drain behind a turn that can never come — ``invalidate``
    lets every blocked AND future turn pass straight through."""
    seq = pipeline.LaunchSequencer()
    seq.reserve()                       # a — orphaned by the epoch change
    b = seq.reserve()
    started, done = threading.Event(), []

    def blocked_turn():
        started.set()
        with seq.turn(b):               # blocks: a never releases
            done.append(b)

    t = threading.Thread(target=blocked_turn)
    t.start()
    assert started.wait(timeout=5)
    time.sleep(0.05)
    assert done == []                   # genuinely wedged behind a
    seq.invalidate()
    t.join(timeout=5)
    assert done == [b]
    # post-invalidate reservations pass through without any release
    c = seq.reserve()
    with seq.turn(c):
        done.append(c)
    assert done == [b, c]


# ---------------------------------------------------------------------------
# QuantumDispatcher — the refill engine's offloaded dispatch thread


def test_dispatcher_spends_all_credit():
    got = []
    d = pipeline.QuantumDispatcher(got.append)
    for credit in (3, 2, 5):
        d.submit(credit)
    d.drain()
    assert sum(got) == 10
    d.close()


def test_dispatcher_drain_reraises_pump_error():
    """A harvest failure on the dispatcher thread surfaces on the caller's
    thread at the next quiesce point, not as a silently dead daemon."""
    def pump(credit):
        raise RuntimeError("pump boom")

    d = pipeline.QuantumDispatcher(pump)
    d.submit(1)
    with pytest.raises(RuntimeError, match="pump boom"):
        d.drain()
    d.drain()                   # the error was consumed, not sticky
    d.close()


def test_dispatcher_close_idempotent_and_rejects_submit():
    d = pipeline.QuantumDispatcher(lambda credit: None)
    d.submit(2)
    d.close()
    d.close()                   # second close is a no-op
    with pytest.raises(RuntimeError, match="closed"):
        d.submit(1)


def test_dispatcher_zero_credit_is_noop():
    calls = []
    d = pipeline.QuantumDispatcher(calls.append)
    d.submit(0)
    d.submit(-3)
    d.drain()
    assert calls == []
    d.close()


def test_dispatcher_round_robin_bounds_slow_channel():
    """Fan-out fairness (train/fleet.py): a slow consumer channel with a
    deep backlog cannot starve the shared refill pump — round-robin
    servicing in chunks of QUANTUM credits gets the refill channel a turn
    after at most QUANTUM foreign credits, long before the slow backlog
    drains."""
    events: list[tuple[str, int]] = []
    slow_started = threading.Event()
    refill_posted = threading.Event()

    def refill_pump(credit):
        events.append(("refill", credit))

    def slow_pump(credit):
        events.append(("slow", credit))
        slow_started.set()
        refill_posted.wait(timeout=5)       # a genuinely slow consumer

    d = pipeline.QuantumDispatcher(refill_pump)
    d.add_channel("slow", slow_pump)
    d.submit(40, channel="slow")            # deep backlog, posted first
    assert slow_started.wait(timeout=5)
    d.submit(4)                             # refill credit arrives late
    refill_posted.set()
    d.drain()
    first_refill = next(i for i, (ch, _) in enumerate(events)
                        if ch == "refill")
    slow_before = sum(c for ch, c in events[:first_refill] if ch == "slow")
    # bound: the chunk in flight when refill credit landed + at most one
    # more turn of the rotation
    assert slow_before <= 2 * pipeline.QuantumDispatcher.QUANTUM, events
    assert sum(c for ch, c in events if ch == "slow") == 40
    assert sum(c for ch, c in events if ch == "refill") == 4
    d.close()


def test_dispatcher_single_channel_keeps_grab_all():
    """With only the primary channel registered, the pre-fleet semantics
    hold exactly: ALL accumulated credit is spent in one pump call."""
    calls = []
    release = threading.Event()
    first = threading.Event()

    def pump(credit):
        calls.append(credit)
        first.set()
        release.wait(timeout=5)

    d = pipeline.QuantumDispatcher(pump)
    d.submit(3)
    assert first.wait(timeout=5)
    for c in (2, 5, 1):                     # accumulate while pump busy
        d.submit(c)
    release.set()
    d.drain()
    assert calls == [3, 8]                  # one grab-all, no quantum split
    d.close()


def test_dispatcher_channel_validation():
    d = pipeline.QuantumDispatcher(lambda credit: None)
    with pytest.raises(ValueError, match="unknown channel"):
        d.submit(1, channel="ghost")
    d.add_channel("t", lambda credit: None)
    with pytest.raises(ValueError, match="already registered"):
        d.add_channel("t", lambda credit: None)
    d.close()
    with pytest.raises(RuntimeError, match="closed"):
        d.add_channel("late", lambda credit: None)
