"""Unit tests for the bounded in-flight pipeline driver — the shared
machinery under buffer refresh, norm calibration, dashboard harvest, and
the CE eval (crosscoder_tpu/utils/pipeline.py)."""

import pytest

from crosscoder_tpu.utils import pipeline


def test_fifo_order_and_completeness():
    out = []
    pipeline.drive(iter(range(10)), out.append, depth=3)
    assert out == list(range(10))


def test_depth_bounds_in_flight():
    """At most `depth` items are produced-but-undrained at any moment."""
    live = 0
    peak = 0

    def produced():
        nonlocal live, peak
        for i in range(20):
            live += 1
            peak = max(peak, live)
            yield i

    def drain(_):
        nonlocal live
        live -= 1

    pipeline.drive(produced(), drain, depth=3)
    assert live == 0
    assert peak == 3


def test_drain_lag():
    """Item i is drained only after item i+depth-1 was produced (the lag
    that lets device work overlap host work)."""
    events = []
    pipeline.drive(
        (events.append(("p", i)) or i for i in range(6)),
        lambda i: events.append(("d", i)),
        depth=2,
    )
    assert events.index(("d", 0)) > events.index(("p", 1))
    assert events.index(("d", 4)) > events.index(("p", 5))


@pytest.mark.parametrize("depth", [1, 2, 5])
def test_serial_and_deep(depth):
    out = []
    pipeline.drive(iter("abc"), out.append, depth=depth)
    assert out == list("abc")


def test_empty_stream():
    pipeline.drive(iter(()), lambda _: pytest.fail("drain on empty stream"))


def test_producer_exception_propagates():
    def produced():
        yield 1
        raise RuntimeError("boom")

    drained = []
    with pytest.raises(RuntimeError, match="boom"):
        pipeline.drive(produced(), drained.append, depth=1)
    assert drained == [1]   # FIFO items before the failure were drained
