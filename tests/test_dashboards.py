"""Tests for the plot helpers (reference utils.py:45-147) and the
sae_vis-equivalent feature dashboards (reference nb:cells 33-42)."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from crosscoder_tpu.analysis.dashboards import FeatureVisConfig, FeatureVisData
from crosscoder_tpu.analysis.plots import (
    svg_histogram,
    tokens_to_html,
)
from crosscoder_tpu.config import CrossCoderConfig
from crosscoder_tpu.models import crosscoder as cc
from crosscoder_tpu.models import lm

HP = "blocks.2.hook_resid_pre"


def test_tokens_to_html_escapes_and_colors():
    html = tokens_to_html(["<b>", "safe", "nl\n"], [0.0, 1.0, 0.5])
    assert "&lt;b&gt;" in html                     # escaped
    assert "↵" in html                             # visible newline
    assert 'title="1.000"' in html                 # hover value
    assert html.count("<span") == 3


def test_tokens_to_html_id_tooltips():
    """token_ids enriches hover tooltips with the id (sae_vis per-token
    hover detail)."""
    html = tokens_to_html(["a", "b"], [0.5, 1.0], token_ids=[17, 42])
    assert "id 17" in html and "id 42" in html
    assert "act 1.000" in html


def test_svg_histogram_counts():
    svg = svg_histogram([0.1] * 5 + [0.9] * 3, bins=2, width=100, height=50)
    assert svg.count("<rect") == 2
    assert ": 5</title>" in svg and ": 3</title>" in svg


@pytest.fixture(scope="module")
def dash_setup():
    lm_cfg = lm.LMConfig.tiny()
    params = [lm.init_params(jax.random.key(i), lm_cfg) for i in range(2)]
    cfg = CrossCoderConfig(d_in=32, dict_size=64, batch_size=16, enc_dtype="fp32")
    cc_params = cc.init_params(jax.random.key(9), cfg)
    rng = np.random.default_rng(11)
    tokens = rng.integers(0, 257, size=(12, 24), dtype=np.int64)
    return lm_cfg, params, cfg, cc_params, tokens


def test_feature_vis_data(dash_setup):
    lm_cfg, params, cfg, cc_params, tokens = dash_setup
    vis_cfg = FeatureVisConfig(hook_point=HP, features=(0, 5, 63),
                               minibatch_size_tokens=4, top_k_sequences=3)
    data = FeatureVisData.create(cc_params, cfg, lm_cfg, params, tokens, vis_cfg)
    assert [f.feature for f in data.features] == [0, 5, 63]
    for fd in data.features:
        assert 0.0 <= fd.frac_active <= 1.0
        assert 0.0 <= fd.relative_norm <= 1.0
        assert len(fd.top_seqs) <= 3
        for seq in fd.top_seqs:
            assert len(seq["tokens"]) == len(seq["values"])
            # peak token is the displayed window's argmax
            assert seq["values"][seq["peak"]] == max(seq["values"])


def test_feature_acts_match_direct_encode(dash_setup):
    """Dashboard latent activations == direct harvest→encode path."""
    lm_cfg, params, cfg, cc_params, tokens = dash_setup
    vis_cfg = FeatureVisConfig(hook_point=HP, features=(5,),
                               minibatch_size_tokens=12)
    data = FeatureVisData.create(cc_params, cfg, lm_cfg, params, tokens, vis_cfg)
    caches = [lm.run_with_cache(p, jnp.asarray(tokens), lm_cfg, [HP])[HP] for p in params]
    x = jnp.stack(caches, axis=2)[:, 1:].astype(jnp.float32)
    f = np.asarray(cc.encode(cc_params, x, cfg))[..., 5]
    assert data.features[0].max_act == pytest.approx(float(f.max()), rel=1e-5)
    assert data.features[0].frac_active == pytest.approx(float((f > 0).mean()), abs=1e-9)


def test_interval_groups(dash_setup):
    """sae_vis-parity interval groups (nb:cells 36-42; round-3 VERDICT R14):
    sequences sampled from equal value-bands of (0, max_act], disjoint from
    the top-k group, each entry's peak inside its band."""
    lm_cfg, params, cfg, cc_params, tokens = dash_setup
    vis_cfg = FeatureVisConfig(hook_point=HP, features=(0, 5),
                               top_k_sequences=2, n_interval_groups=3,
                               seqs_per_group=2)
    data = FeatureVisData.create(cc_params, cfg, lm_cfg, params, tokens, vis_cfg)
    for fd in data.features:
        if fd.max_act <= 0:
            continue
        assert len(fd.interval_groups) <= 3
        for grp in fd.interval_groups:
            assert grp["lo"] < grp["hi"] <= fd.max_act + 1e-6
            assert 1 <= len(grp["seqs"]) <= 2
            for seq in grp["seqs"]:
                peak_val = max(seq["values"])
                assert grp["lo"] < peak_val + 1e-6
                assert peak_val <= grp["hi"] + 1e-6

    # off switch
    vis_off = FeatureVisConfig(hook_point=HP, features=(0,), n_interval_groups=0)
    d2 = FeatureVisData.create(cc_params, cfg, lm_cfg, params, tokens, vis_off)
    assert d2.features[0].interval_groups == []


def test_interval_groups_in_html(dash_setup, tmp_path):
    lm_cfg, params, cfg, cc_params, tokens = dash_setup
    vis_cfg = FeatureVisConfig(hook_point=HP, features=(0, 5), n_interval_groups=3)
    data = FeatureVisData.create(cc_params, cfg, lm_cfg, params, tokens, vis_cfg)
    doc = data.save_feature_centric_vis(tmp_path / "g.html").read_text()
    assert "top activations" in doc
    if any(fd.interval_groups for fd in data.features):
        assert "interval " in doc


def test_save_feature_centric_vis(dash_setup, tmp_path):
    lm_cfg, params, cfg, cc_params, tokens = dash_setup
    vis_cfg = FeatureVisConfig(hook_point=HP, features=(0, 1))
    data = FeatureVisData.create(cc_params, cfg, lm_cfg, params, tokens, vis_cfg)
    out = data.save_feature_centric_vis(tmp_path / "vis.html")
    doc = out.read_text()
    assert doc.startswith("<!doctype html>")
    assert "feature 0" in doc and "feature 1" in doc
    assert HP in doc
    # custom tokenizer hook
    out2 = data.save_feature_centric_vis(tmp_path / "vis2.html", decode_fn=lambda t: f"T{t}")
    assert "T" + str(int(tokens[0, 1])) in out2.read_text() or "T" in out2.read_text()


def test_analysis_script_end_to_end(tmp_path):
    """scripts/analysis.py on a saved checkpoint prints the 3-cluster
    summary (reference analysis.py flow)."""
    import sys
    sys.path.insert(0, "scripts")
    try:
        import analysis as analysis_script
    finally:
        sys.path.pop(0)
    from crosscoder_tpu.checkpoint.ckpt import Checkpointer
    from crosscoder_tpu.train.state import init_train_state, make_optimizer
    from crosscoder_tpu.train import schedules

    cfg = CrossCoderConfig(d_in=16, dict_size=64, checkpoint_dir=str(tmp_path))
    tx = make_optimizer(cfg, schedules.lr_schedule(cfg))
    state = init_train_state(jax.random.key(0), cfg, tx)
    ckpt = Checkpointer(cfg=cfg)
    ckpt.save(state, cfg)
    vdir = Checkpointer.latest_version_dir(tmp_path)
    summary = analysis_script.main(["--version-dir", str(vdir), "--out", str(tmp_path / "o")])
    assert summary["d_hidden"] == 64
    total = summary["cluster_A_only"] + summary["cluster_shared"] + summary["cluster_B_only"]
    assert total == 64
    assert (tmp_path / "o" / "relative_norm_hist.json").exists()


def test_logit_lens_tables(dash_setup):
    """The fork's per-latent logit tables (nb:cells 33-42): top promoted /
    suppressed output tokens per source, verified against a direct numpy
    computation of direction·(1+w_final)·embed^T."""
    lm_cfg, params, cfg, cc_params, tokens = dash_setup
    vis_cfg = FeatureVisConfig(hook_point=HP, features=(3, 7), logit_lens_k=5)
    data = FeatureVisData.create(cc_params, cfg, lm_cfg, params, tokens, vis_cfg)
    for fd in data.features:
        assert len(fd.logit_lens) == 2               # one table per source
        for tab in fd.logit_lens:
            m = tab["source"]                        # n_hooks == 1
            dirs = np.asarray(cc_params["W_dec"], np.float32)[fd.feature, m]
            w = np.asarray(params[m]["final_norm"], np.float32)
            emb = np.asarray(params[m]["embed"], np.float32)
            logits = (dirs * (1.0 + w)) @ emb.T
            want_top = set(np.argsort(-logits)[:5].tolist())
            got_top = {t for t, _ in tab["promoted"]}
            assert got_top == want_top
            want_bot = set(np.argsort(logits)[:5].tolist())
            got_bot = {t for t, _ in tab["suppressed"]}
            assert got_bot == want_bot
            # promoted values descend, suppressed ascend
            pv = [v for _, v in tab["promoted"]]
            sv = [v for _, v in tab["suppressed"]]
            assert pv == sorted(pv, reverse=True) and sv == sorted(sv)


def test_logit_lens_in_html(dash_setup, tmp_path):
    lm_cfg, params, cfg, cc_params, tokens = dash_setup
    vis_cfg = FeatureVisConfig(hook_point=HP, features=(0,), logit_lens_k=3)
    data = FeatureVisData.create(cc_params, cfg, lm_cfg, params, tokens, vis_cfg)
    doc = data.save_feature_centric_vis(tmp_path / "v.html").read_text()
    assert "promoted:" in doc and "suppressed:" in doc
    # off switch
    vis_cfg2 = FeatureVisConfig(hook_point=HP, features=(0,), include_logit_lens=False)
    d2 = FeatureVisData.create(cc_params, cfg, lm_cfg, params, tokens, vis_cfg2)
    assert d2.features[0].logit_lens == []
    assert "promoted:" not in d2.save_feature_centric_vis(tmp_path / "v2.html").read_text()


def test_tokenizer_wired_dashboards(dash_setup, tmp_path):
    """A local HF tokenizer.json renders REAL text in the feature pages
    (VERDICT round-2 weak #7: pages showed ⟨id⟩ placeholders only); no
    tokenizer → placeholders, unchanged."""
    tokenizers = pytest.importorskip("tokenizers")
    from tokenizers.models import WordLevel

    lm_cfg, params, cfg, cc_params, tokens = dash_setup
    # tiny word-level tokenizer covering the fixture's 257-token vocab
    vocab = {f"word{i}": i for i in range(257)}
    tok = tokenizers.Tokenizer(WordLevel(vocab, unk_token="word0"))
    tok_path = tmp_path / "tokenizer.json"
    tok.save(str(tok_path))

    vis_cfg = FeatureVisConfig(hook_point=HP, features=(3, 7))
    data = FeatureVisData.create(cc_params, cfg, lm_cfg, params, tokens, vis_cfg)
    out = data.save_feature_centric_vis(tmp_path / "dash.html", tokenizer=tok_path)
    doc = out.read_text()
    assert "word" in doc and "⟨" not in doc

    # directory form resolves tokenizer.json inside it
    out2 = data.save_feature_centric_vis(tmp_path / "dash2.html", tokenizer=tmp_path)
    assert "word" in out2.read_text()

    # without a tokenizer: placeholder ids, as before
    out3 = data.save_feature_centric_vis(tmp_path / "dash3.html")
    assert "⟨" in out3.read_text()
