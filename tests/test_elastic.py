"""Elastic multihost membership (cfg.elastic; resilience/elastic.py).

Fast tests cover the membership layer's single-process degenerations, the
chaos grammar's preemption faults, and config validation. The slow test is
the real thing: the 2-process preemption drill
(crosscoder_tpu/resilience/elastic_drill.py) — chaos kills process 1
mid-run with ``os._exit``, process 0 must detect the loss, shrink to its
local devices, restore-with-respec from the newest verified save, and
finish with a post-remesh loss trajectory BITWISE equal to a clean
single-process restart from the same checkpoint.
"""

import numpy as np
import pytest

import jax

from crosscoder_tpu.config import CrossCoderConfig
from crosscoder_tpu.parallel import multihost
from crosscoder_tpu.resilience.chaos import Chaos
from crosscoder_tpu.resilience.elastic import ElasticController, PeerLoss


def _cfg(**kw):
    base = dict(d_in=32, dict_size=64, n_models=2, batch_size=16,
                num_tokens=16 * 50, log_backend="null")
    base.update(kw)
    return CrossCoderConfig(**base)


# ---------------------------------------------------------------------------
# chaos grammar: the two host-loss faults


def test_chaos_parses_preempt_and_die():
    c = Chaos.parse("preempt@3,die@5,nan@1")
    assert c.preempt_serves == (3,)
    assert c.die_serves == (5,)
    assert c.nan_serves == (1,)


def test_chaos_preempt_sends_sigterm():
    import signal

    got = []
    old = signal.signal(signal.SIGTERM, lambda *a: got.append(True))
    try:
        Chaos.parse("preempt@0").on_serve(0)
    finally:
        signal.signal(signal.SIGTERM, old)
    assert got == [True]


def test_chaos_preempt_fires_once():
    import signal

    got = []
    old = signal.signal(signal.SIGTERM, lambda *a: got.append(True))
    try:
        c = Chaos.parse("preempt@2")
        for serve in (0, 1, 2, 2, 3):
            c.on_serve(serve)
    finally:
        signal.signal(signal.SIGTERM, old)
    assert got == [True]


# ---------------------------------------------------------------------------
# config plumbing


def test_elastic_config_fields():
    cfg = _cfg(elastic="on", elastic_heartbeat_s=2.0, elastic_grace_s=7.0)
    assert cfg.elastic == "on"
    assert cfg.elastic_heartbeat_s == 2.0
    assert cfg.elastic_grace_s == 7.0
    assert _cfg().elastic == "off"          # default: zero-cost off


def test_elastic_config_validation():
    with pytest.raises(ValueError, match="elastic"):
        _cfg(elastic="maybe")
    with pytest.raises(ValueError, match="seq_shards"):
        _cfg(elastic="on", seq_shards=2, model_batch_size=4)


# ---------------------------------------------------------------------------
# membership layer: single-process degenerations (the multi-process truths
# are proven by the drill below and tests/test_multihost_ckpt.py)


def test_membership_none_outside_elastic_runtime():
    assert multihost.membership() is None
    assert not multihost.peer_loss_flagged()
    # a probe outside any elastic world is vacuously healthy
    assert multihost.probe_liveness("p0", timeout_s=0.1)


def test_controller_inactive_single_process():
    ctl = ElasticController(_cfg(elastic="on"))
    assert not ctl.active()
    assert ctl.epoch() == 0
    assert not ctl.should_probe(0)
    # an ordinary software error is never a peer loss without a membership
    assert not ctl.confirm_peer_loss(RuntimeError("boom"))
    with pytest.raises(PeerLoss, match="no elastic membership"):
        ctl.shrink()


def test_survivor_mesh_preserves_tp_width():
    ctl = ElasticController(_cfg(elastic="on", model_axis_size=4))
    mesh = ctl.survivor_mesh()
    assert mesh.shape["model"] == 4
    assert mesh.shape["data"] == jax.device_count() // 4


def test_trainer_elastic_off_has_no_controller():
    from crosscoder_tpu.train.trainer import Trainer

    tr = Trainer(_cfg())
    assert tr._elastic is None
    tr.close()


def test_put_global_matches_device_put():
    """The collective-free placement helper must be a drop-in for
    device_put on the single-process meshes every other test uses."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    from crosscoder_tpu.parallel import mesh as mesh_lib

    mesh = mesh_lib.make_mesh(-1, 1)
    x = np.arange(32, dtype=np.float32).reshape(8, 4)
    sh = NamedSharding(mesh, P("data", None))
    a = multihost.put_global(x, sh)
    b = jax.device_put(x, sh)
    assert a.sharding.is_equivalent_to(b.sharding, a.ndim)
    np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


# ---------------------------------------------------------------------------
# buffer reshard: the data-plane leg of the elastic recovery


@pytest.mark.slow
def test_buffer_reshard_stream_determinism():
    """Reshard a mesh-sharded HBM buffer (data 2 × model 4 → 1 × 8 batch
    layout) mid-stream: the served sequence after ``reshard(refill=True)``
    must equal a fresh buffer on the NEW sharding restored from the same
    stream snapshot (provenance rebuild, determinism A2)."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    from crosscoder_tpu.data.buffer import make_buffer
    from crosscoder_tpu.models import lm
    from crosscoder_tpu.parallel import mesh as mesh_lib

    lm_cfg = lm.LMConfig.tiny()
    params = [lm.init_params(jax.random.key(0), lm_cfg),
              lm.init_params(jax.random.key(1), lm_cfg)]
    rng = np.random.default_rng(7)
    tokens = rng.integers(0, 257, size=(256, 17), dtype=np.int64)
    cfg = CrossCoderConfig(
        batch_size=32, buffer_mult=32, seq_len=17, d_in=32, n_models=2,
        model_batch_size=4, norm_calib_batches=2, seed=3,
        hook_point="blocks.2.hook_resid_pre", buffer_device="hbm",
    )
    wide = NamedSharding(mesh_lib.make_mesh(2, 4), P("data", None))
    narrow = NamedSharding(mesh_lib.make_mesh(1, 8), P("data", None))

    b = make_buffer(cfg, lm_cfg, params, tokens, batch_sharding=wide)
    for _ in range(5):
        b.next()
    snap = b.state_dict()

    b.prepare_reshard()             # parks LM params to host numpy
    b.reshard(narrow, refill=True)  # re-allocs the store, replays the snap

    ref = make_buffer(cfg, lm_cfg, params, tokens, batch_sharding=narrow,
                      lazy=True)
    ref.load_state_dict(snap)
    for step in range(6):
        np.testing.assert_array_equal(
            np.asarray(b.next(), np.float32),
            np.asarray(ref.next(), np.float32), err_msg=f"step {step}")


# ---------------------------------------------------------------------------
# the acceptance drill: 2 REAL processes, one dies, the survivor re-meshes


@pytest.mark.slow
def test_preemption_drill_bitwise_recovery(tmp_path):
    from crosscoder_tpu.resilience.elastic_drill import run_drill

    report = run_drill(workdir=str(tmp_path), keep_logs=True)
    assert report["bitwise_equal"], {
        "post": report["post_losses"], "restart": report["restart_losses"]}
    assert report["post_losses"], "no post-remesh steps ran"
    assert report["remesh_ms"] > 0
    surv = report["survivor"]
    assert surv["counters"].get("resilience/remeshes") == 1
    assert surv["counters"].get("resilience/remesh_ms", 0) >= 1
    assert surv["final_step"] == report["steps"]
    # the survivor resumed from the newest save BEFORE the death
    assert report["resume_step"] == surv["remesh"]["step"]
    assert surv["remesh"]["epoch"] == 1
