"""Elastic multihost membership (cfg.elastic; resilience/elastic.py).

Fast tests cover the membership layer's single-process degenerations, the
chaos grammar (preemption, return, flaky, slow), probe hysteresis, the
rendezvous board, the fleet policy, and config validation. The slow tests
are the real thing: the 2-process preemption drill, the full autoscale
(grow/shrink/grow) cycle, and the chaos-stability drill
(crosscoder_tpu/resilience/elastic_drill.py) — multi-process over real
CPU subprocesses, with bitwise loss-trajectory equality as the
determinism contract.
"""

import numpy as np
import pytest

import jax

from crosscoder_tpu.config import CrossCoderConfig
from crosscoder_tpu.parallel import multihost
from crosscoder_tpu.resilience.chaos import Chaos
from crosscoder_tpu.resilience.elastic import ElasticController, PeerLoss


def _cfg(**kw):
    base = dict(d_in=32, dict_size=64, n_models=2, batch_size=16,
                num_tokens=16 * 50, log_backend="null")
    base.update(kw)
    return CrossCoderConfig(**base)


# ---------------------------------------------------------------------------
# chaos grammar: the two host-loss faults


def test_chaos_parses_preempt_and_die():
    c = Chaos.parse("preempt@3,die@5,nan@1")
    assert c.preempt_serves == (3,)
    assert c.die_serves == (5,)
    assert c.nan_serves == (1,)


def test_chaos_preempt_sends_sigterm():
    import signal

    got = []
    old = signal.signal(signal.SIGTERM, lambda *a: got.append(True))
    try:
        Chaos.parse("preempt@0").on_serve(0)
    finally:
        signal.signal(signal.SIGTERM, old)
    assert got == [True]


def test_chaos_preempt_fires_once():
    import signal

    got = []
    old = signal.signal(signal.SIGTERM, lambda *a: got.append(True))
    try:
        c = Chaos.parse("preempt@2")
        for serve in (0, 1, 2, 2, 3):
            c.on_serve(serve)
    finally:
        signal.signal(signal.SIGTERM, old)
    assert got == [True]


def test_chaos_parses_autoscale_tokens():
    c = Chaos.parse("return@4,flaky@2:0.4,slow@5:1500,seed=3")
    assert c.return_serves == (4,)
    assert c.flaky_probes == {2: 0.4}
    assert c.slow_probes == {5: 1500.0}
    assert c.seed == 3
    # defaults: flaky p=0.5, slow 1000 ms
    d = Chaos.parse("flaky@1,slow@2")
    assert d.flaky_probes == {1: 0.5}
    assert d.slow_probes == {2: 1000.0}


def test_chaos_render_round_trips_autoscale_tokens():
    c = Chaos.parse("return@4,flaky@2:0.4,slow@5:1500,seed=3")
    c2 = Chaos.parse(c.render())
    assert c2.return_serves == c.return_serves
    assert c2.flaky_probes == c.flaky_probes
    assert c2.slow_probes == c.slow_probes
    assert c2.seed == c.seed


def test_chaos_take_return_fires_once():
    c = Chaos.parse("return@2")
    assert [c.take_return(s) for s in (0, 1, 2, 2, 3)] == [
        False, False, True, False, False]


def test_chaos_on_probe_flaky_and_slow():
    c = Chaos.parse("flaky@2:1.0,slow@0:500")
    assert c.on_probe(0) == 0.5          # slow: returned in SECONDS
    assert c.on_probe(0) is None         # slow fires once
    assert c.on_probe(1) is None         # before the flaky window
    assert c.on_probe(2) == "skip"       # p=1.0 always skips
    assert c.on_probe(3) == "skip"       # the window extends rightward
    assert Chaos.parse("flaky@2:0.0").on_probe(5) is None   # p=0 never


def test_chaos_probe_validation():
    with pytest.raises(ValueError, match="flaky"):
        Chaos.parse("flaky@2:1.5")
    with pytest.raises(ValueError, match="slow"):
        Chaos.parse("slow@2:0")


def test_stability_chaos_plan_pinned():
    """The stability drill's seeded flaky stream must keep its shape: at
    least one skip, NEVER a run of skips at or past the drill's
    suspect_probes threshold (that would flip the drill from 'absorbed'
    to 'declared loss'), and the straggler present. Pinning the stream
    here means an rng change breaks a fast test, not a 2-process drill."""
    from crosscoder_tpu.resilience.elastic_drill import _STABILITY

    c = Chaos.parse(_STABILITY["chaos"])
    behaviors = [c.on_probe(p) for p in range(_STABILITY["steps"])]
    skips = [b == "skip" for b in behaviors]
    assert any(skips), behaviors
    assert any(isinstance(b, float) for b in behaviors), behaviors
    run = best = 0
    for s in skips:
        run = run + 1 if s else 0
        best = max(best, run)
    assert best < _STABILITY["suspect_probes"], behaviors


# ---------------------------------------------------------------------------
# config plumbing


def test_elastic_config_fields():
    cfg = _cfg(elastic="on", elastic_heartbeat_s=2.0, elastic_grace_s=7.0)
    assert cfg.elastic == "on"
    assert cfg.elastic_heartbeat_s == 2.0
    assert cfg.elastic_grace_s == 7.0
    assert _cfg().elastic == "off"          # default: zero-cost off


def test_elastic_config_validation():
    with pytest.raises(ValueError, match="elastic"):
        _cfg(elastic="maybe")
    with pytest.raises(ValueError, match="seq_shards"):
        _cfg(elastic="on", seq_shards=2, model_batch_size=4)


# ---------------------------------------------------------------------------
# membership layer: single-process degenerations (the multi-process truths
# are proven by the drill below and tests/test_multihost_ckpt.py)


def test_membership_none_outside_elastic_runtime():
    assert multihost.membership() is None
    assert not multihost.peer_loss_flagged()
    # a probe outside any elastic world is vacuously healthy
    assert multihost.probe_liveness("p0", timeout_s=0.1)


def test_controller_inactive_single_process():
    ctl = ElasticController(_cfg(elastic="on"))
    assert not ctl.active()
    assert ctl.epoch() == 0
    assert not ctl.should_probe(0)
    # an ordinary software error is never a peer loss without a membership
    assert not ctl.confirm_peer_loss(RuntimeError("boom"))
    with pytest.raises(PeerLoss, match="no elastic membership"):
        ctl.shrink()


def test_survivor_mesh_preserves_tp_width():
    ctl = ElasticController(_cfg(elastic="on", model_axis_size=4))
    mesh = ctl.survivor_mesh()
    assert mesh.shape["model"] == 4
    assert mesh.shape["data"] == jax.device_count() // 4


def test_trainer_elastic_off_has_no_controller():
    from crosscoder_tpu.train.trainer import Trainer

    tr = Trainer(_cfg())
    assert tr._elastic is None
    tr.close()


def test_put_global_matches_device_put():
    """The collective-free placement helper must be a drop-in for
    device_put on the single-process meshes every other test uses."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    from crosscoder_tpu.parallel import mesh as mesh_lib

    mesh = mesh_lib.make_mesh(-1, 1)
    x = np.arange(32, dtype=np.float32).reshape(8, 4)
    sh = NamedSharding(mesh, P("data", None))
    a = multihost.put_global(x, sh)
    b = jax.device_put(x, sh)
    assert a.sharding.is_equivalent_to(b.sharding, a.ndim)
    np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


# ---------------------------------------------------------------------------
# probe hysteresis (flaky heartbeats must cost grace windows, not remeshes)


def _fast_cfg(**kw):
    base = dict(elastic="on", elastic_heartbeat_s=0.01,
                elastic_grace_s=0.01)
    base.update(kw)
    return _cfg(**base)


def test_probe_hysteresis_absorbs_below_threshold(monkeypatch):
    from crosscoder_tpu.resilience import elastic as el
    from crosscoder_tpu.utils.logging import ResilienceCounters

    cleared = []
    monkeypatch.setattr(el.multihost, "probe_liveness",
                        lambda *a, **k: False)
    monkeypatch.setattr(el.multihost, "clear_peer_loss",
                        lambda: cleared.append(1))
    counters = ResilienceCounters()
    ctl = ElasticController(_fast_cfg(elastic_suspect_probes=2),
                            counters=counters)
    assert ctl.probe(0) is True      # first miss: SUSPICION, absorbed
    assert cleared == [1]            # the latched flag is cleared too
    assert ctl.probe(1) is False     # second consecutive miss: declared
    snap = counters.snapshot()
    assert snap["resilience/elastic_suspects"] == 2
    assert snap["resilience/elastic_probes"] == 2


def test_probe_hysteresis_resets_on_success(monkeypatch):
    from crosscoder_tpu.resilience import elastic as el

    seq = iter([False, True, False, True])
    monkeypatch.setattr(el.multihost, "probe_liveness",
                        lambda *a, **k: next(seq))
    monkeypatch.setattr(el.multihost, "clear_peer_loss", lambda: None)
    ctl = ElasticController(_fast_cfg(elastic_suspect_probes=2))
    # miss-hit-miss-hit: the streak never reaches 2, no loss declared
    assert all(ctl.probe(i) for i in range(4))


def test_probe_flaky_chaos_skips_barrier_in_phase(monkeypatch):
    """A flaky host SKIPS the barrier but sits out the same grace window
    its peers spend timing out — the probe phases stay aligned, so one
    flake cannot cascade into staggered mutual timeouts."""
    import time as _time

    from crosscoder_tpu.resilience import elastic as el

    called = []
    monkeypatch.setattr(el.multihost, "probe_liveness",
                        lambda *a, **k: called.append(1) or True)
    ctl = ElasticController(_fast_cfg(elastic_grace_s=0.05),
                            chaos=Chaos.parse("flaky@0:1.0"))
    t0 = _time.perf_counter()
    assert ctl.probe(0) is True
    assert not called                    # the barrier was never entered
    assert _time.perf_counter() - t0 >= 0.05   # but the grace was paid


def test_probe_counts_slow_peer(monkeypatch):
    """A straggler peer (chaos slow@S:ms on the other host) shows up
    HERE as a successful barrier whose wall time exceeded the heartbeat:
    counted, never suspected."""
    import time as _time

    from crosscoder_tpu.resilience import elastic as el
    from crosscoder_tpu.utils.logging import ResilienceCounters

    monkeypatch.setattr(el.multihost, "probe_liveness",
                        lambda *a, **k: _time.sleep(0.03) or True)
    counters = ResilienceCounters()
    ctl = ElasticController(
        _fast_cfg(elastic_heartbeat_s=0.01, elastic_grace_s=0.2),
        counters=counters)
    assert ctl.probe(0) is True          # late but within grace: healthy
    snap = counters.snapshot()
    assert snap.get("resilience/elastic_slow_probes", 0) == 1
    assert "resilience/elastic_suspects" not in snap


# ---------------------------------------------------------------------------
# rendezvous board + debounce (the scale-up courtship)


def _grow_cfg(tmp_path, **kw):
    base = dict(elastic="on", elastic_grow="on",
                checkpoint_dir=str(tmp_path), elastic_grow_debounce=2,
                elastic_dwell_steps=2)
    base.update(kw)
    return _cfg(**base)


def test_rendezvous_board_round_trip(tmp_path):
    from crosscoder_tpu.resilience.elastic import RendezvousBoard

    board = RendezvousBoard(tmp_path / "elastic_board")
    assert board.read_grant() is None
    assert board.poll_announces() == []
    assert board.read_admit() is None
    board.post_grant({"serve": 7})
    assert board.read_grant() == {"serve": 7}
    board.announce("c1", 4, seq=0)
    board.announce("c2", 4, seq=3)
    assert [r["id"] for r in board.poll_announces()] == ["c1", "c2"]
    board.retract("c1")
    assert [r["id"] for r in board.poll_announces()] == ["c2"]
    board.post_admit({"epoch": 2, "assignments": {"c2": 1}})
    board.post_admit({"epoch": 1, "assignments": {}})
    assert board.read_admit()["epoch"] == 2      # newest admit wins
    board.clear_admit(2)
    assert board.read_admit()["epoch"] == 1


def test_announce_until_admitted_beats_and_times_out(tmp_path):
    from crosscoder_tpu.resilience.elastic import RendezvousBoard

    board = RendezvousBoard(tmp_path / "elastic_board")
    with pytest.raises(TimeoutError, match="not admitted"):
        board.announce_until_admitted("c1", 4, timeout_s=0.3, beat_s=0.05)
    # the courtship retracted its announce on the way out
    assert board.poll_announces() == []


def test_announce_until_admitted_returns_record(tmp_path):
    from crosscoder_tpu.resilience.elastic import RendezvousBoard

    board = RendezvousBoard(tmp_path / "elastic_board")
    board.post_admit({"epoch": 2, "assignments": {"c1": 1}})
    admit = board.announce_until_admitted("c1", 4, timeout_s=5.0,
                                          beat_s=0.05)
    assert admit["assignments"]["c1"] == 1


def test_poll_candidates_debounce_and_staleness(tmp_path):
    import time as _time

    ctl = ElasticController(_grow_cfg(tmp_path, elastic_grace_s=5.0))
    board = ctl._board
    board.announce("c1", 4, seq=0)
    assert ctl._poll_candidates() == []          # first sighting: streak 1
    assert ctl._poll_candidates() == []          # between beats: holds, not stable
    board.announce("c1", 4, seq=1)
    stable = ctl._poll_candidates()              # observed advance: streak 2
    assert [c["id"] for c in stable] == ["c1"]
    # a crashed candidate (seq stalled past the grace window) restarts
    # its courtship from scratch
    seq, streak, _ = ctl._cand_freshness["c1"]
    ctl._cand_freshness["c1"] = (seq, streak, _time.monotonic() - 10.0)
    assert ctl._poll_candidates() == []
    # and a vanished announce drops out entirely
    board.retract("c1")
    ctl._poll_candidates()
    assert "c1" not in ctl._cand_freshness


def test_grow_ready_gates(tmp_path):
    """grow_ready is inert without a board, without a shrunk single-
    process membership, and within the dwell window."""
    ctl_off = ElasticController(_cfg(elastic="on"))
    assert ctl_off._board is None
    assert not ctl_off.grow_ready(0)
    ctl = ElasticController(_grow_cfg(tmp_path))
    # no elastic membership at all in-process → never grow-ready
    assert not ctl.grow_ready(0)


def test_grow_without_world_raises(tmp_path):
    from crosscoder_tpu.resilience.elastic import GrowAborted

    ctl = ElasticController(_grow_cfg(tmp_path))
    with pytest.raises(GrowAborted, match="shrunk single-process"):
        ctl.grow(0, save_version=0, version_dir=str(tmp_path), save_step=0)


def test_open_rejoin_window_posts_grant(tmp_path):
    ctl = ElasticController(_grow_cfg(tmp_path))
    ctl.open_rejoin_window(11)
    assert ctl._board.read_grant() == {"serve": 11}
    # inert (no board) when the grow plane is off
    ElasticController(_cfg(elastic="on")).open_rejoin_window(3)


# ---------------------------------------------------------------------------
# fleet policy (resilience/fleet.py)


def test_fleet_fixed_policy_preserves_tp_width():
    from crosscoder_tpu.resilience.fleet import FleetPolicy

    pol = FleetPolicy(_cfg(model_axis_size=4))
    ch = pol.choose(8)
    assert (ch.n_data, ch.n_model) == (2, 4)
    ch = pol.choose(16)
    assert (ch.n_data, ch.n_model) == (4, 4)
    with pytest.raises(ValueError, match="not divisible"):
        pol.choose(6)


def test_fleet_candidate_shapes():
    from crosscoder_tpu.resilience.fleet import FleetPolicy

    shapes = FleetPolicy(_cfg()).candidate_shapes(8)   # dict_size=64
    assert (8, 1) in shapes and (2, 4) in shapes and (1, 8) in shapes
    # quant_grads pins pure data parallelism, same as config validation
    dp_only = FleetPolicy(_cfg(quant_grads=True)).candidate_shapes(8)
    assert dp_only == [(8, 1)]


@pytest.mark.slow
def test_fleet_score_policy_ranks():
    """The score policy prices every split with the PR 2/PR 5 cost
    planes (one compile per TP width) and returns cheapest-first."""
    from crosscoder_tpu.resilience.fleet import FleetPolicy

    pol = FleetPolicy(_cfg(elastic_policy="score"))
    ranked = pol.rank(jax.device_count())
    assert ranked, "score policy produced no candidates"
    scores = [c.score_ms for c in ranked]
    assert scores == sorted(scores)
    assert all(c.detail["policy"] == "score" for c in ranked)
    choice = pol.choose(jax.device_count())
    assert (choice.n_data, choice.n_model) == \
        (ranked[0].n_data, ranked[0].n_model)


def test_elastic_grow_config_validation(tmp_path):
    with pytest.raises(ValueError, match="requires elastic='on'"):
        _cfg(elastic_grow="on", checkpoint_dir=str(tmp_path))
    with pytest.raises(ValueError, match="elastic_policy"):
        _cfg(elastic_policy="best")
    with pytest.raises(ValueError, match="elastic_grow_debounce"):
        _grow_cfg(tmp_path, elastic_grow_debounce=0)
    with pytest.raises(ValueError, match="elastic_suspect_probes"):
        _cfg(elastic="on", elastic_suspect_probes=0)
    cfg = _grow_cfg(tmp_path)
    assert cfg.elastic_grow == "on"
    assert _cfg().elastic_grow == "off"     # default: zero-cost off


# ---------------------------------------------------------------------------
# buffer reshard: the data-plane leg of the elastic recovery


@pytest.mark.slow
def test_buffer_reshard_stream_determinism():
    """Reshard a mesh-sharded HBM buffer (data 2 × model 4 → 1 × 8 batch
    layout) mid-stream: the served sequence after ``reshard(refill=True)``
    must equal a fresh buffer on the NEW sharding restored from the same
    stream snapshot (provenance rebuild, determinism A2)."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    from crosscoder_tpu.data.buffer import make_buffer
    from crosscoder_tpu.models import lm
    from crosscoder_tpu.parallel import mesh as mesh_lib

    lm_cfg = lm.LMConfig.tiny()
    params = [lm.init_params(jax.random.key(0), lm_cfg),
              lm.init_params(jax.random.key(1), lm_cfg)]
    rng = np.random.default_rng(7)
    tokens = rng.integers(0, 257, size=(256, 17), dtype=np.int64)
    cfg = CrossCoderConfig(
        batch_size=32, buffer_mult=32, seq_len=17, d_in=32, n_models=2,
        model_batch_size=4, norm_calib_batches=2, seed=3,
        hook_point="blocks.2.hook_resid_pre", buffer_device="hbm",
    )
    wide = NamedSharding(mesh_lib.make_mesh(2, 4), P("data", None))
    narrow = NamedSharding(mesh_lib.make_mesh(1, 8), P("data", None))

    b = make_buffer(cfg, lm_cfg, params, tokens, batch_sharding=wide)
    for _ in range(5):
        b.next()
    snap = b.state_dict()

    b.prepare_reshard()             # parks LM params to host numpy
    b.reshard(narrow, refill=True)  # re-allocs the store, replays the snap

    ref = make_buffer(cfg, lm_cfg, params, tokens, batch_sharding=narrow,
                      lazy=True)
    ref.load_state_dict(snap)
    for step in range(6):
        np.testing.assert_array_equal(
            np.asarray(b.next(), np.float32),
            np.asarray(ref.next(), np.float32), err_msg=f"step {step}")


# ---------------------------------------------------------------------------
# the acceptance drill: 2 REAL processes, one dies, the survivor re-meshes


@pytest.mark.slow
def test_preemption_drill_bitwise_recovery(tmp_path):
    from crosscoder_tpu.resilience.elastic_drill import run_drill

    report = run_drill(workdir=str(tmp_path), keep_logs=True)
    assert report["bitwise_equal"], {
        "post": report["post_losses"], "restart": report["restart_losses"]}
    assert report["post_losses"], "no post-remesh steps ran"
    assert report["remesh_ms"] > 0
    surv = report["survivor"]
    assert surv["counters"].get("resilience/remeshes") == 1
    assert surv["counters"].get("resilience/remesh_ms", 0) >= 1
    assert surv["final_step"] == report["steps"]
    # the survivor resumed from the newest save BEFORE the death
    assert report["resume_step"] == surv["remesh"]["step"]
    assert surv["remesh"]["epoch"] == 1


@pytest.mark.slow
def test_autoscale_drill_bitwise_cycle(tmp_path):
    """The full grow/shrink/grow cycle (ISSUE 16 acceptance drill): die@S
    shrinks the pair to one host, return@S grants capacity back, the
    parked rejoiner is admitted at a step boundary, and the grown world's
    post-grow trajectory is bitwise-equal to a clean restart at the wide
    shape — on all members (survivor AND joiner)."""
    from crosscoder_tpu.resilience.elastic_drill import run_autoscale_drill

    report = run_autoscale_drill(workdir=str(tmp_path), keep_logs=True)
    assert report["bitwise_equal"], {
        "post": report["post_losses"], "clean": report["clean_losses"]}
    assert report["joiner_equal"], {
        "post": report["post_losses"], "joiner": report["joiner_losses"]}
    assert report["remesh_ms"] > 0 and report["grow_ms"] > 0
    surv, join = report["survivor"], report["joiner"]
    # one shrink + one grow: exactly two remeshes, one of them a grow
    assert surv["counters"].get("resilience/remeshes") == 2
    assert surv["counters"].get("resilience/grows") == 1
    assert surv["counters"].get("resilience/grow_aborts") is None
    # grow = die epoch (1) + 1, back to the wide data width
    assert surv["grow"]["epoch"] == 2
    assert surv["grow"]["n_data"] == 2
    # both members finish the whole run — no lost steps, no restart
    assert surv["final_step"] == report["steps"]
    assert join["final_step"] == report["steps"]
    # hydration restored the grow-boundary save on every member
    assert report["resume_step"] == surv["grow"]["step"]


@pytest.mark.slow
def test_stability_drill_zero_remeshes(tmp_path):
    """Sub-threshold chaos (flaky + slow probes) must cost grace windows,
    not remeshes: the pair finishes together while the counters prove the
    faults actually fired (the ISSUE 16 'no spurious remesh' criterion)."""
    from crosscoder_tpu.resilience.elastic_drill import run_stability_drill

    report = run_stability_drill(workdir=str(tmp_path), keep_logs=True)
    assert report["stable"], report
    assert report["remeshes"] == 0
    assert report["suspects"] >= 1        # a flake was absorbed...
    assert report["skipped_probes"] >= 1  # ...after the barrier skip fired
    assert report["slow_probes"] >= 1     # and the straggler was counted
