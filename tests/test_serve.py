"""The serving path (cfg.serve; serve/engine.py + serve/step.py +
serve/replica.py; docs/SERVING.md): bitwise parity of served results vs
the offline padded oracle (mixed lengths, bucket padding, the extend
path), the deadline/backpressure/shed admission semantics, the
zero-compiles-after-warmup SLO, and the replica drain hand-off. All CPU,
tier-1; the tiny serving stack comes from serve/smoke.py so the test and
the smoke drive literally the same engine."""

import numpy as np
import pytest

from crosscoder_tpu.data.paging import ContinuousBatcher
from crosscoder_tpu.serve import InferenceEngine, Shed, batch_buckets, bucket_of
from crosscoder_tpu.serve.replica import ReplicaBoard, ServeReplica
from crosscoder_tpu.serve.smoke import build_engine, oracle, serve_batch

SEQ = 16


class Clock:
    """Injected engine clock: tests advance time, nothing sleeps."""

    def __init__(self):
        self.t = 0.0

    def __call__(self):
        return self.t


@pytest.fixture()
def stack():
    return build_engine(serve_max_batch=8)


def _docs(rng, lm_cfg, lengths):
    return [rng.integers(1, lm_cfg.vocab_size, size=int(ln),
                         dtype=np.int32) for ln in lengths]


def _padded(docs, seq_len):
    tokens = np.zeros((len(docs), seq_len), np.int64)
    for d, doc in enumerate(docs):
        tokens[d, : doc.shape[0]] = doc
    return tokens, np.asarray([d.shape[0] for d in docs])


# ---------------------------------------------------------------------------
# parity vs the offline padded oracle


def test_served_bitwise_parity_mixed_lengths(stack):
    """Full bucket of mixed lengths (incl. single-token and max-length):
    served (vals, idx, diff) are BITWISE the padded-path oracle's."""
    eng, cfg, lm_cfg, lm_params, cc_params = stack
    rng = np.random.default_rng(0)
    docs = _docs(rng, lm_cfg, [1, SEQ, 7, 3, 9, 5, SEQ, 2])
    res = serve_batch(eng, docs)
    tokens, lengths = _padded(docs, SEQ)
    vals, idx, diff = oracle(eng, cfg, lm_cfg, lm_params, cc_params,
                             tokens, lengths)
    for i, r in enumerate(res):
        np.testing.assert_array_equal(r.vals, vals[i], err_msg=f"doc {i}")
        np.testing.assert_array_equal(r.idx, idx[i], err_msg=f"doc {i}")
        np.testing.assert_array_equal(r.diff, diff[i], err_msg=f"doc {i}")
        assert r.idx.dtype == np.int32 and r.vals.shape == (cfg.topk_k,)


def test_bucket_padding_invisible(stack):
    """A partial batch rides a padded bucket (3 requests → bucket 4 with
    one dummy row); each request's result is bitwise what the request
    gets served alone — pad rows never leak into real rows."""
    eng, cfg, lm_cfg, _, _ = stack
    rng = np.random.default_rng(1)
    docs = _docs(rng, lm_cfg, [5, SEQ, 2])
    together = serve_batch(eng, docs)
    assert [r.bucket for r in together] == [4, 4, 4]
    for doc, r in zip(docs, together):
        solo = serve_batch(eng, [doc])[0]
        assert solo.bucket == 1
        np.testing.assert_array_equal(r.vals, solo.vals)
        np.testing.assert_array_equal(r.idx, solo.idx)
        np.testing.assert_array_equal(r.diff, solo.diff)


def test_extend_parity_and_page_prefix(stack):
    """The incremental path: a keep-resident request extended with
    follow-up tokens (a) keeps its prefix pages and only takes delta
    pages, (b) serves bitwise what re-prefilling the concatenation from
    scratch serves."""
    eng, cfg, lm_cfg, _, _ = stack
    rng = np.random.default_rng(2)
    full = rng.integers(1, lm_cfg.vocab_size, size=SEQ, dtype=np.int32)
    rid = eng.submit(full[: SEQ // 2], keep=True)
    pages_before = eng.pages_of(rid)
    eng.step(force=True)
    eng.extend(rid, full[SEQ // 2:])
    pages_after = eng.pages_of(rid)
    assert pages_after[: len(pages_before)] == pages_before  # prefix kept
    assert len(pages_after) > len(pages_before)              # delta granted
    ext = eng.step(force=True)[0]
    assert ext.extended and ext.request_id == rid
    eng.release(rid)
    fresh = serve_batch(eng, [full])[0]
    np.testing.assert_array_equal(ext.vals, fresh.vals)
    np.testing.assert_array_equal(ext.idx, fresh.idx)
    np.testing.assert_array_equal(ext.diff, fresh.diff)


def test_extend_requires_live_request(stack):
    eng, _, lm_cfg, _, _ = stack
    rng = np.random.default_rng(3)
    rid = eng.submit(_docs(rng, lm_cfg, [4])[0])     # keep=False
    eng.step(force=True)
    with pytest.raises(KeyError, match="not live"):
        eng.extend(rid, np.ones(2, np.int32))


# ---------------------------------------------------------------------------
# admission: deadlines, backpressure, shed


def test_bucket_helpers():
    assert batch_buckets(8) == (1, 2, 4, 8)
    assert bucket_of(1, 8) == 1 and bucket_of(3, 8) == 4
    assert bucket_of(8, 8) == 8 and bucket_of(9, 8) == 8


def test_batcher_deadline():
    cb = ContinuousBatcher(seq_len=8, n_rows=2, max_wait_s=0.05)
    assert cb.oldest_wait(1.0) == 0.0 and not cb.due(1.0)
    assert cb.admit(np.ones(3, np.int32), now=1.0)
    assert cb.oldest_wait(1.03) == pytest.approx(0.03)
    assert not cb.due(1.03)
    assert cb.due(1.06)
    cb.flush()
    assert not cb.due(99.0) and cb.oldest_wait(99.0) == 0.0


def test_step_flushes_on_deadline_not_before():
    """Deadline-aware micro-batching with an injected clock: a partial
    batch holds until the oldest request waited serve_max_wait_ms, then
    flushes without needing force or batch-full."""
    clk = Clock()
    eng, _, lm_cfg, _, _ = build_engine(serve_max_batch=8, clock=clk)
    rng = np.random.default_rng(4)
    eng.submit(_docs(rng, lm_cfg, [4])[0])
    clk.t = 0.001
    assert eng.step() == []                  # 1ms: batch open, not due
    clk.t = 0.0021
    res = eng.step()                         # past the 2ms smoke deadline
    assert len(res) == 1 and res[0].bucket == 1
    assert res[0].queue_wait_ms >= 2.0


def test_queue_overflow_sheds():
    eng, cfg, lm_cfg, _, _ = build_engine(
        serve_max_batch=1, serve_queue=2, batch_size=32)
    rng = np.random.default_rng(5)
    a, b, c = _docs(rng, lm_cfg, [3, 4, 5])
    eng.submit(a)
    eng.submit(b)
    with pytest.raises(Shed, match="queue full"):
        eng.submit(c)
    assert eng.stats()["serve/shed_total"] == 1
    assert eng.n_queued == 2                 # the admitted two survive


def test_stale_requests_evicted_with_counter():
    """cfg.serve_shed_ms: queued requests past the deadline are evicted
    (429-style) with serve/shed_total counted and was_shed() queryable;
    fresh requests are untouched."""
    clk = Clock()
    eng, _, lm_cfg, _, _ = build_engine(
        serve_max_batch=8, serve_shed_ms=50.0, clock=clk)
    rng = np.random.default_rng(6)
    stale = eng.submit(_docs(rng, lm_cfg, [4])[0])
    clk.t = 0.2                              # 200ms > 50ms deadline
    fresh = eng.submit(_docs(rng, lm_cfg, [4])[0])
    res = eng.step(force=True)
    assert [r.request_id for r in res] == [fresh]
    assert eng.was_shed(stale) and not eng.was_shed(fresh)
    assert eng.stats()["serve/shed_total"] == 1
    assert eng.stats()["serve/requests_total"] == 1


def test_page_pool_exhaustion_sheds():
    """Keep-resident sequences hold pages; when the pool can't cover a
    new request the submit sheds instead of stalling."""
    eng, cfg, lm_cfg, _, _ = build_engine(serve_max_batch=1, serve_queue=1)
    rng = np.random.default_rng(7)
    held = []
    with pytest.raises(Shed, match="page pool"):
        for _ in range(cfg.serve_queue + cfg.serve_max_batch + 1):
            held.append(eng.submit(_docs(rng, lm_cfg, [SEQ])[0], keep=True))
            eng.step(force=True)             # serve it; pages stay held
    assert eng.stats()["serve/shed_total"] == 1
    eng.release(held[0])                     # freed pages admit again
    eng.submit(_docs(rng, lm_cfg, [SEQ])[0])


def test_engine_requires_serve_on():
    from crosscoder_tpu.config import CrossCoderConfig

    cfg = CrossCoderConfig(d_in=32, dict_size=64, batch_size=8,
                           enc_dtype="fp32")
    with pytest.raises(ValueError, match="serve"):
        InferenceEngine(cfg, None, [], {})


# ---------------------------------------------------------------------------
# the zero-compile SLO


def test_zero_compiles_after_warmup():
    """warmup() builds the whole bucket ladder; arbitrary traffic after
    it (partial buckets, mixed lengths, extends) must never compile."""
    eng, cfg, lm_cfg, _, _ = build_engine(serve_max_batch=4)
    # NB not asserted > 0: the AOT memo is process-wide, so a sibling
    # test may legitimately have prewarmed every bucket already
    assert eng.warmup() == eng.compiles
    rng = np.random.default_rng(8)
    for n in (1, 3, 4, 2):
        serve_batch(eng, _docs(rng, lm_cfg, rng.integers(1, SEQ + 1, n)))
    rid = eng.submit(_docs(rng, lm_cfg, [5])[0], keep=True)
    eng.step(force=True)
    eng.extend(rid, np.ones(3, np.int32))
    eng.step(force=True)
    eng.release(rid)
    assert eng.compiles_after_warmup == 0
    assert eng.stats()["serve_compiles_after_warmup"] == 0


# ---------------------------------------------------------------------------
# replica drain hand-off


def test_replica_drain_and_adopt(tmp_path):
    """Preemption smoke: replica A spools its queued requests to the
    board; peer B's next heartbeat claims and re-submits them through its
    own admission path. Exactly-once: a second heartbeat adopts nothing."""
    board = ReplicaBoard(tmp_path / "serve_board")
    eng_a, _, lm_cfg, _, _ = build_engine(serve_max_batch=8)
    eng_b, _, _, _, _ = build_engine(serve_max_batch=8)
    rep_a = ServeReplica("a", eng_a, board)
    rep_b = ServeReplica("b", eng_b, board)
    rep_a.heartbeat()
    rep_b.heartbeat()
    assert {p["id"] for p in board.peers()} == {"a", "b"}

    rng = np.random.default_rng(9)
    docs = _docs(rng, lm_cfg, [3, SEQ, 6])
    for d in docs:
        eng_a.submit(d)
    assert rep_a.preempt() == 3              # SIGTERM body: drain + spool
    assert eng_a.n_queued == 0
    assert board.peers(exclude="b") == []    # A left the board

    assert rep_b.heartbeat() == 3            # B adopts the spool
    assert rep_b.heartbeat() == 0            # exactly once
    assert eng_b.n_queued == 3
    assert eng_b.stats()["serve/adopted_total"] == 3
    assert eng_a.stats()["serve/drained_total"] == 3
    res = eng_b.step(force=True)             # adopted requests serve
    assert len(res) == 3


def test_replica_never_adopts_own_spool(tmp_path):
    board = ReplicaBoard(tmp_path / "serve_board")
    eng, _, lm_cfg, _, _ = build_engine(serve_max_batch=8)
    rep = ServeReplica("solo", eng, board)
    rng = np.random.default_rng(10)
    eng.submit(_docs(rng, lm_cfg, [4])[0])
    rep.preempt()
    assert rep.heartbeat() == 0              # own drain record is skipped
