"""Child process for the 2-process multi-host checkpoint test.

Run as: python _multihost_ckpt_child.py <proc_id> <port> <ckpt_dir>
Each of the 2 processes owns 4 virtual CPU devices (8-device global mesh,
data 2 × model 4); crosscoder params shard the dict axis over 'model' and
replicate over 'data', which spans both processes — so every state leaf is
NOT fully addressable and save must take the process_allgather path
(VERDICT round-2 weak #3: a blind np.asarray crashes exactly here).
"""

import json
import os
import sys

proc_id = int(sys.argv[1])
port = sys.argv[2]
workdir = sys.argv[3]

os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
from crosscoder_tpu.parallel import multihost  # noqa: E402

multihost.initialize(
    coordinator_address=f"localhost:{port}", num_processes=2, process_id=proc_id
)
assert jax.device_count() == 8, jax.device_count()
assert jax.local_device_count() == 4

import numpy as np  # noqa: E402

from crosscoder_tpu.checkpoint.ckpt import Checkpointer  # noqa: E402
from crosscoder_tpu.config import CrossCoderConfig  # noqa: E402
from crosscoder_tpu.parallel import mesh as mesh_lib  # noqa: E402
from crosscoder_tpu.train.trainer import Trainer  # noqa: E402

cfg = CrossCoderConfig(
    d_in=32, dict_size=64, n_models=2, batch_size=16,
    num_tokens=16 * 50, enc_dtype="fp32",
    data_axis_size=2, model_axis_size=4,
    log_backend="null", checkpoint_dir=workdir, prefetch=False,
)
mesh = mesh_lib.mesh_from_cfg(cfg)
tr = Trainer(cfg, mesh=mesh, checkpointer=Checkpointer(workdir))
# every param leaf must span both processes (else the test proves nothing)
for k, v in tr.state.params.items():
    assert not v.is_fully_addressable, k

losses = [float(jax.device_get(tr.step()["loss"])) for _ in range(3)]
tr.save()
pre = {k: Checkpointer._fetch_global(v) for k, v in tr.state.params.items()}
tr.close()

# fresh trainer; restore; params must round-trip; training must continue
tr2 = Trainer(cfg, mesh=mesh, checkpointer=Checkpointer(workdir))
tr2.restore(version_dir=os.path.join(workdir, "version_0"))
post = {k: Checkpointer._fetch_global(v) for k, v in tr2.state.params.items()}
for k in pre:
    assert np.array_equal(pre[k].astype(np.float32), post[k].astype(np.float32)), k
assert int(tr2.state.step) == 3
resumed = float(jax.device_get(tr2.step()["loss"]))
assert np.isfinite(resumed)
assert int(tr2.state.step) == 4
# the full train() loop: multi-process stop sync + the collective final
# save in `finally` must complete on BOTH processes (clean exit)
tr2.train(num_steps=6)
assert int(tr2.state.step) == 6
tr2.close()

print(json.dumps({"proc": proc_id, "losses": losses, "resumed_loss": resumed,
                  "ok": True}))
