"""Chaos-driven tests of the resilience subsystem (docs/resilience.md):
verified checkpoint restore with fallback, keep-last-k retention, the
divergence guard + rollback, the data-pipeline watchdog, and the
fast-path zero-cost guarantee. All CPU-only, tier-1."""

import json

import jax
import numpy as np
import pytest

from crosscoder_tpu.checkpoint import Checkpointer
from crosscoder_tpu.config import CrossCoderConfig
from crosscoder_tpu.resilience.chaos import Chaos, ChaosFault
from crosscoder_tpu.resilience.watchdog import Watchdog, WatchdogTimeout
from crosscoder_tpu.train.trainer import Trainer
from crosscoder_tpu.utils.logging import ResilienceCounters


def tiny_cfg(tmp_path, steps=20, **kw):
    base = dict(
        d_in=16,
        dict_size=64,
        batch_size=64,
        num_tokens=64 * steps,
        enc_dtype="fp32",
        lr=1e-3,
        l1_coeff=0.1,
        log_backend="null",
        checkpoint_dir=str(tmp_path),
    )
    base.update(kw)
    return CrossCoderConfig(**base)


# ---------------------------------------------------------------------------
# chaos spec


def test_chaos_spec_parse():
    c = Chaos.parse("nan@5,inf@7,stall@3:1.5,fail@4,stall-harvest@2,"
                    "fail-harvest@9,corrupt-save@1:state,mode=flipbyte,seed=7")
    assert c.nan_serves == (5,) and c.inf_serves == (7,)
    assert c.stall_serves == {3: 1.5} and c.fail_serves == (4,)
    assert c.stall_harvests[2] > 0 and c.fail_harvests == (9,)
    assert c.corrupt_saves == {1: "state"}
    assert c.corrupt_mode == "flipbyte" and c.seed == 7
    assert Chaos.parse("") is None and Chaos.parse(None) is None
    assert Chaos.parse("corrupt-save@0").corrupt_saves == {0: "weights"}
    with pytest.raises(ValueError, match="kind"):
        Chaos.parse("explode@3")
    with pytest.raises(ValueError, match="artifact kind"):
        Chaos.parse("corrupt-save@0:nonsense")


def test_chaos_faults_fire_exactly_once():
    c = Chaos.parse("nan@2,fail@3")
    b = np.ones((4, 2, 8), np.float32)
    assert np.isnan(c.poison_batch(b, 2)[0]).all()
    assert np.isfinite(c.poison_batch(b, 2)).all()   # second pass: clean
    with pytest.raises(ChaosFault):
        c.on_serve(3)
    c.on_serve(3)                                     # fired: now a no-op


# ---------------------------------------------------------------------------
# verified restore


def test_checksums_recorded_and_verified(tmp_path):
    cfg = tiny_cfg(tmp_path)
    tr = Trainer(cfg, checkpointer=Checkpointer(cfg=cfg))
    tr.step()
    tr.save()
    vdir = tmp_path / "version_0"
    meta = json.loads((vdir / "0_meta.json").read_text())
    sums = meta["checksums"]
    assert set(sums) == {"0.npz", "0_cfg.json", "0_train_state.npz"}
    assert Checkpointer.verify_save(vdir, 0)
    # bit-rot one artifact: verification must catch it
    data = bytearray((vdir / "0_train_state.npz").read_bytes())
    data[len(data) // 2] ^= 0xFF
    (vdir / "0_train_state.npz").write_bytes(bytes(data))
    assert not Checkpointer.verify_save(vdir, 0)


def test_corrupt_newest_save_falls_back(tmp_path):
    """Truncate the newest save's weights artifact: restore must skip it
    (counted) and land on the previous intact save."""
    cfg = tiny_cfg(tmp_path)
    ck = Checkpointer(cfg=cfg)
    tr = Trainer(cfg, checkpointer=ck)
    for _ in range(3):
        tr.step()
    tr.save()                 # save 0 at step 3
    for _ in range(2):
        tr.step()
    tr.save()                 # save 1 at step 5
    vdir = tmp_path / "version_0"
    blob = (vdir / "1.npz").read_bytes()
    (vdir / "1.npz").write_bytes(blob[: len(blob) // 2])

    counters = ResilienceCounters()
    ck2 = Checkpointer(base_dir=tmp_path, counters=counters)
    tr2 = Trainer(cfg, checkpointer=ck2)
    meta = tr2.restore()
    assert meta["step"] == 3          # fell back past the corrupt save 1
    assert counters.get("corrupt_artifact_skips") == 1
    tr2.close()


def test_chaos_corrupt_save_hook(tmp_path):
    """The chaos layer corrupts a save as it lands (via the checkpointer's
    own writer hook); restore falls back to the intact predecessor."""
    cfg = tiny_cfg(tmp_path)
    chaos = Chaos.parse("corrupt-save@1:state")
    ck = Checkpointer(cfg=cfg, chaos=chaos)
    tr = Trainer(cfg, checkpointer=ck, chaos=chaos)
    tr.step()
    tr.save()                 # save 0: intact
    tr.step()
    tr.save()                 # save 1: train_state truncated by chaos
    assert not Checkpointer.verify_save(tmp_path / "version_0", 1)
    tr2 = Trainer(cfg, checkpointer=Checkpointer(base_dir=tmp_path))
    assert tr2.restore()["step"] == 1
    tr2.close()


def test_explicit_save_verifies_loudly(tmp_path):
    cfg = tiny_cfg(tmp_path)
    ck = Checkpointer(cfg=cfg)
    tr = Trainer(cfg, checkpointer=ck)
    tr.step()
    tr.save()
    vdir = tmp_path / "version_0"
    blob = (vdir / "0.npz").read_bytes()
    (vdir / "0.npz").write_bytes(blob[: len(blob) // 2])
    tr2 = Trainer(cfg, checkpointer=Checkpointer(base_dir=tmp_path))
    with pytest.raises(ValueError, match="checksum"):
        tr2.restore(version_dir=vdir, save=0)
    tr2.close()


def test_keep_last_k_retention(tmp_path):
    cfg = tiny_cfg(tmp_path, keep_saves=2)
    ck = Checkpointer(cfg=cfg)
    tr = Trainer(cfg, checkpointer=ck)
    for _ in range(4):
        tr.step()
        tr.save()
    vdir = tmp_path / "version_0"
    assert Checkpointer.complete_saves(vdir) == [2, 3]
    # pruned saves leave no artifacts behind
    for v in (0, 1):
        assert not list(vdir.glob(f"{v}_*")) and not (vdir / f"{v}.npz").exists()
    tr2 = Trainer(cfg, checkpointer=Checkpointer(base_dir=tmp_path))
    assert tr2.restore()["step"] == 4
    tr2.close()


def test_discard_saves_after_branch_truncation(tmp_path):
    cfg = tiny_cfg(tmp_path)
    ck = Checkpointer(cfg=cfg)
    tr = Trainer(cfg, checkpointer=ck)
    for _ in range(3):
        tr.step()
        tr.save()
    vdir = tmp_path / "version_0"
    ck.discard_saves_after(vdir, 0)
    assert Checkpointer.complete_saves(vdir) == [0]
    assert not (vdir / "2.npz").exists()


# ---------------------------------------------------------------------------
# divergence guard + rollback


def test_nan_step_rolls_back_and_converges(tmp_path):
    """Inject one NaN batch: the guard detects at the next log step, rolls
    back to the last intact save, skips the poisoned window, and the run
    still reaches its target step with finite, decreased loss."""
    cfg = tiny_cfg(tmp_path, steps=30, log_every=3, save_every=5,
                   guard_loss=True, max_rollbacks=3)
    chaos = Chaos.parse("nan@11")
    tr = Trainer(cfg, checkpointer=Checkpointer(cfg=cfg), chaos=chaos)
    out = tr.train()
    assert tr.step_counter == 30
    assert np.isfinite(out["loss"])
    assert tr.resilience.get("rollbacks") == 1
    assert tr.resilience.get("skipped_batches") >= 1
    # params finite after recovery
    assert all(np.isfinite(np.asarray(v)).all()
               for v in jax.device_get(tr.state.params).values())


def test_rollback_during_active_profiler_trace(tmp_path):
    """Divergence inside the profiling window (steps start+10..start+14):
    the rollback must close the active trace before the new stretch
    re-enters start_trace, or recovery dies on 'session already active'."""
    cfg = tiny_cfg(tmp_path, steps=30, log_every=3, save_every=5,
                   guard_loss=True, max_rollbacks=3,
                   profile_dir=str(tmp_path / "trace"))
    chaos = Chaos.parse("nan@11")   # NaN at step 11 -> detected at log 12,
    tr = Trainer(cfg, checkpointer=Checkpointer(cfg=cfg), chaos=chaos)
    out = tr.train()                # while the step-10..14 trace is live
    assert tr.step_counter == 30
    assert np.isfinite(out["loss"])
    assert tr.resilience.get("rollbacks") == 1


def test_rollback_budget_exhaustion_aborts(tmp_path):
    """Faults outrunning max_rollbacks must abort loudly, not loop."""
    cfg = tiny_cfg(tmp_path, steps=40, log_every=2, save_every=4,
                   guard_loss=True, max_rollbacks=1)
    # two distinct NaN serves, far enough apart that the second lands
    # after the first rollback's skipped window
    chaos = Chaos.parse("nan@9,nan@25")
    tr = Trainer(cfg, checkpointer=Checkpointer(cfg=cfg), chaos=chaos)
    with pytest.raises(RuntimeError, match="rollback budget"):
        tr.train()
    assert tr.resilience.get("rollbacks") == 1


def test_loss_spike_detection_unit():
    cfg = CrossCoderConfig(d_in=8, dict_size=16, guard_loss=True,
                           loss_spike_factor=5.0, enc_dtype="fp32")
    tr = Trainer(cfg)
    assert not tr._loss_diverged(10.0)    # establishes the reference
    assert not tr._loss_diverged(12.0)    # mild rise: healthy
    assert tr._loss_diverged(float("nan"))
    assert tr._loss_diverged(float("inf"))
    assert tr._loss_diverged(12.0 * 6)    # > factor x last healthy
    assert not tr._loss_diverged(12.0)    # reference unchanged by spikes


def test_guard_config_validation():
    with pytest.raises(ValueError, match="keep_saves"):
        CrossCoderConfig(guard_loss=True, keep_saves=1)
    with pytest.raises(ValueError, match="loss_spike_factor"):
        CrossCoderConfig(loss_spike_factor=1.0)
    with pytest.raises(ValueError, match="harvest_timeout_s"):
        CrossCoderConfig(harvest_timeout_s=-1.0)


# ---------------------------------------------------------------------------
# watchdog


def test_watchdog_exception_backoff_retry():
    counters = ResilienceCounters()
    w = Watchdog(5.0, retries=2, backoff_s=0.01, counters=counters)
    calls = [0]

    def flaky():
        calls[0] += 1
        if calls[0] < 3:
            raise RuntimeError("transient")
        return "ok"

    assert w.call(flaky) == "ok"
    assert counters.get("harvest_retries") == 2
    with pytest.raises(RuntimeError, match="always"):
        w.call(lambda: (_ for _ in ()).throw(RuntimeError("always")))


def test_watchdog_stall_escalates_then_aborts():
    counters = ResilienceCounters()
    w = Watchdog(0.05, retries=1, backoff_s=0.01, counters=counters)
    import time

    # a stall shorter than the escalation budget: detected, then survives
    assert w.call(lambda: (time.sleep(0.08), "late")[1]) == "late"
    assert counters.get("harvest_timeouts") >= 1
    # a stall that never clears: aborts loudly instead of hanging
    with pytest.raises(WatchdogTimeout):
        w.call(lambda: time.sleep(30))


def test_stalled_serve_recovers_through_watchdog(tmp_path):
    """A chaos-stalled serve under a short watchdog timeout: the stall is
    detected (counted) and the run completes normally."""
    cfg = tiny_cfg(tmp_path, steps=8, harvest_timeout_s=0.1,
                   harvest_retries=4, harvest_backoff_s=0.05)
    chaos = Chaos.parse("stall@3:0.25")
    tr = Trainer(cfg, chaos=chaos)
    out = tr.train()
    assert tr.step_counter == 8
    assert np.isfinite(out["loss"])
    assert tr.resilience.get("harvest_timeouts") >= 1


def test_failed_serve_retried_through_watchdog(tmp_path):
    cfg = tiny_cfg(tmp_path, steps=8, harvest_timeout_s=5.0,
                   harvest_retries=2, harvest_backoff_s=0.01)
    chaos = Chaos.parse("fail@2")
    tr = Trainer(cfg, chaos=chaos)
    out = tr.train()
    assert tr.step_counter == 8
    assert np.isfinite(out["loss"])
    assert tr.resilience.get("harvest_retries") == 1


# ---------------------------------------------------------------------------
# fast path: resilience off must add nothing


def test_fast_path_device_transfer_count(monkeypatch):
    """With every resilience feature at its default (off), the host loop
    performs EXACTLY the transfers it always did: one loss fetch per log
    step plus the final metrics fetch — the divergence guard piggybacks on
    the log fetch and contributes zero additional host syncs."""
    steps, log_every = 7, 3
    cfg = CrossCoderConfig(d_in=16, dict_size=64, batch_size=64,
                           num_tokens=64 * steps, enc_dtype="fp32",
                           log_every=log_every, log_backend="null")
    assert not cfg.guard_loss and cfg.harvest_timeout_s == 0 and not cfg.chaos
    tr = Trainer(cfg)
    fetches = []
    real = jax.device_get
    monkeypatch.setattr(jax, "device_get",
                        lambda x: (fetches.append(1), real(x))[1])
    out = tr.train()
    assert np.isfinite(out["loss"])
    n_log_steps = sum(1 for i in range(steps) if i % log_every == 0)
    assert len(fetches) == n_log_steps + 1, (len(fetches), n_log_steps)


def test_jitted_step_is_independent_of_resilience_config():
    """The compiled train step must not change when resilience features
    are enabled — detection/recovery live entirely in the host loop. The
    lowered HLO with guard+watchdog config on is byte-identical to the
    default's."""
    from crosscoder_tpu.parallel import mesh as mesh_lib
    from crosscoder_tpu.train import schedules
    from crosscoder_tpu.train.state import init_train_state, make_optimizer
    from crosscoder_tpu.train.trainer import make_train_step
    import jax.numpy as jnp

    texts = []
    for extra in ({}, dict(guard_loss=True, loss_spike_factor=4.0,
                           max_rollbacks=5, harvest_timeout_s=2.0,
                           keep_saves=3)):
        cfg = CrossCoderConfig(d_in=8, dict_size=32, batch_size=32,
                               enc_dtype="fp32", **extra)
        mesh = mesh_lib.make_mesh(devices=jax.devices()[:1])
        tx = make_optimizer(cfg, schedules.lr_schedule(cfg))
        state = jax.eval_shape(lambda k: init_train_state(k, cfg, tx),
                               jax.random.key(0))
        shardings = mesh_lib.state_shardings(mesh, state, cfg.shard_sources)
        step = make_train_step(cfg, mesh, tx, shardings)
        state_sh = jax.tree_util.tree_map(
            lambda s, sh: jax.ShapeDtypeStruct(s.shape, s.dtype, sharding=sh),
            state, shardings,
        )
        batch = jax.ShapeDtypeStruct(
            (cfg.batch_size, cfg.n_sources, cfg.d_in), jnp.float32,
            sharding=mesh_lib.batch_sharding(mesh),
        )
        scale = jax.ShapeDtypeStruct(
            (cfg.n_sources,), jnp.float32,
            sharding=jax.sharding.NamedSharding(
                mesh, jax.sharding.PartitionSpec()
            ),
        )
        texts.append(step.lower(state_sh, batch, scale).as_text())
    assert texts[0] == texts[1]


# ---------------------------------------------------------------------------
# the full loop: every fault class in one short run


def test_integration_survives_corruption_nan_and_stall(tmp_path):
    """Acceptance: with fault injection enabled, one short run survives
    (a) truncation of the newest checkpoint artifact, (b) one injected
    NaN step, and (c) one stalled harvest — reaching its target step with
    finite loss and resilience/* counters reflecting each recovery."""
    cfg = tiny_cfg(tmp_path, steps=30, log_every=3, save_every=5,
                   guard_loss=True, max_rollbacks=3, keep_saves=3,
                   harvest_timeout_s=0.15, harvest_retries=4,
                   harvest_backoff_s=0.05)
    # save 2 lands at step 10 and is corrupted as it lands; the NaN batch
    # at serve 11 diverges the loss right after — rollback must skip the
    # corrupt newest save and land on the intact save 1 (step 5); the
    # serve-3 stall exercises the watchdog on the way
    chaos = Chaos.parse("stall@3:0.35,nan@11,corrupt-save@2:state")
    ck = Checkpointer(cfg=cfg, chaos=chaos)
    tr = Trainer(cfg, checkpointer=ck, chaos=chaos)
    out = tr.train()

    assert tr.step_counter == 30
    assert np.isfinite(out["loss"])
    snap = tr.resilience.snapshot()
    assert snap.get("resilience/rollbacks", 0) >= 1, snap
    assert snap.get("resilience/harvest_timeouts", 0) >= 1, snap
    assert snap.get("resilience/corrupt_artifact_skips", 0) >= 1, snap
    assert snap.get("resilience/skipped_batches", 0) >= 1, snap
    # params finite, and the run is resumable from what's on disk
    assert all(np.isfinite(np.asarray(v)).all()
               for v in jax.device_get(tr.state.params).values())
    tr2 = Trainer(cfg, checkpointer=Checkpointer(base_dir=tmp_path))
    assert tr2.restore()["step"] > 0
    tr2.close()
