"""Parity tests: ring attention and the sequence-parallel Gemma forward
must match the dense single-device path exactly (the point of SURVEY
component N5 — long-context harvest without approximation)."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from crosscoder_tpu.parallel import shard_map_compat as shard_map
from jax.sharding import Mesh, PartitionSpec as P

from crosscoder_tpu.models import lm
from crosscoder_tpu.parallel.ring_attention import ring_attention


def _mesh():
    return Mesh(np.array(jax.devices()), ("data",))


def _dense_reference(q, k, v, scale, softcap, sliding_window, is_local):
    """Unsharded oracle with the same GQA/softcap/mask semantics."""
    B, S, H, hd = q.shape
    KV = k.shape[2]
    g = H // KV
    qg = q.reshape(B, S, KV, g, hd).astype(jnp.float32) * scale
    logits = jnp.einsum("bqkgh,bskh->bkgqs", qg.astype(q.dtype), k,
                        preferred_element_type=jnp.float32)
    if softcap:
        logits = softcap * jnp.tanh(logits / softcap)
    pos = jnp.arange(S)
    causal = pos[:, None] >= pos[None, :]
    window = pos[:, None] - pos[None, :] < sliding_window
    mask = (causal & window) if is_local else causal
    logits = jnp.where(mask[None, None, None], logits, -1e30)
    p = jax.nn.softmax(logits, axis=-1)
    out = jnp.einsum("bkgqs,bskh->bkgqh", p.astype(v.dtype), v,
                     preferred_element_type=jnp.float32)
    return jnp.transpose(out, (0, 3, 1, 2, 4)).reshape(B, S, H, hd)


@pytest.mark.parametrize("is_local", [False, True])
def test_ring_attention_matches_dense(is_local):
    mesh = _mesh()
    n = 8
    B, S, H, KV, hd = 2, 64, 4, 2, 8
    ks = jax.random.split(jax.random.key(0), 3)
    q = jax.random.normal(ks[0], (B, S, H, hd))
    k = jax.random.normal(ks[1], (B, S, KV, hd))
    v = jax.random.normal(ks[2], (B, S, KV, hd))
    scale, softcap, window = 0.35, 50.0, 16

    ring = shard_map(
        lambda q, k, v: ring_attention(
            q, k, v, axis_name="data", n_shards=n, scale=scale,
            softcap=softcap, sliding_window=window, is_local=is_local,
        ),
        mesh=mesh,
        in_specs=(P(None, "data"), P(None, "data"), P(None, "data")),
        out_specs=P(None, "data"),
        check_vma=False,
    )
    got = np.asarray(jax.jit(ring)(q, k, v))
    want = np.asarray(_dense_reference(q, k, v, scale, softcap, window, is_local))
    np.testing.assert_allclose(got, want, rtol=2e-5, atol=2e-5)


def _lowered_text(n_shards: int) -> str:
    """StableHLO for a ring over ``n_shards`` devices with a FIXED
    per-device block shape (so any size growth is graph structure, not
    tensor constants)."""
    mesh = Mesh(np.array(jax.devices()[:n_shards]), ("data",))
    B, H, KV, hd = 1, 2, 1, 4
    S = 8 * n_shards                      # 8 positions per shard
    ks = jax.random.split(jax.random.key(2), 3)
    q = jax.random.normal(ks[0], (B, S, H, hd))
    k = jax.random.normal(ks[1], (B, S, KV, hd))
    v = jax.random.normal(ks[2], (B, S, KV, hd))
    ring = shard_map(
        lambda q, k, v: ring_attention(
            q, k, v, axis_name="data", n_shards=n_shards, scale=0.5,
            softcap=30.0, sliding_window=8, is_local=False,
        ),
        mesh=mesh,
        in_specs=(P(None, "data"), P(None, "data"), P(None, "data")),
        out_specs=P(None, "data"),
        check_vma=False,
    )
    return jax.jit(ring).lower(q, k, v).as_text()


def test_ring_graph_size_flat_in_shard_count():
    """The lax.scan ring keeps the traced graph O(1) in n_shards (round-3
    VERDICT weak #4: the Python unroll grew it linearly — a pod-scale
    32-64-way sequence shard would have paid compile time and graph size
    for every extra device)."""
    t4, t8 = _lowered_text(4), _lowered_text(8)
    # the K/V ppermute pair appears once, inside the scan body, regardless
    # of shard count (the unrolled version had 2*(n-1) collective_permutes)
    assert t8.count("collective_permute") == t4.count("collective_permute")
    assert t8.count("collective_permute") <= 4
    # total graph size stays flat (same ops, different ring length)
    assert len(t8) < 1.25 * len(t4), (len(t4), len(t8))


def test_ring_attention_single_shard_degenerates():
    """n_shards=1 is plain blockwise attention — sanity for the accumulator."""
    mesh = Mesh(np.array(jax.devices()[:1]), ("data",))
    B, S, H, KV, hd = 1, 16, 2, 1, 4
    ks = jax.random.split(jax.random.key(1), 3)
    q = jax.random.normal(ks[0], (B, S, H, hd))
    k = jax.random.normal(ks[1], (B, S, KV, hd))
    v = jax.random.normal(ks[2], (B, S, KV, hd))
    ring = shard_map(
        lambda q, k, v: ring_attention(
            q, k, v, axis_name="data", n_shards=1, scale=0.5),
        mesh=mesh, in_specs=(P(), P(), P()), out_specs=P(), check_vma=False,
    )
    got = np.asarray(ring(q, k, v))
    want = np.asarray(_dense_reference(q, k, v, 0.5, 0.0, 0, False))
    np.testing.assert_allclose(got, want, rtol=2e-5, atol=2e-5)


@pytest.fixture(scope="module")
def tiny():
    cfg = lm.LMConfig.tiny()          # sliding_window=8 < S: both masks live
    params = lm.init_params(jax.random.key(0), cfg)
    rng = np.random.default_rng(3)
    tokens = jnp.asarray(rng.integers(0, cfg.vocab_size, size=(2, 64)))
    return cfg, params, tokens


def test_seq_parallel_forward_matches_dense(tiny):
    """Full Gemma-2 stack, 8-way sequence sharding: logits and captured
    residual streams equal the dense forward."""
    cfg, params, tokens = tiny
    hooks = ["blocks.1.hook_resid_pre", "blocks.3.hook_resid_pre"]
    dense_logits, dense_cache = lm.forward(params, tokens, cfg, capture=hooks)
    sp_logits, sp_cache = lm.forward_seq_parallel(
        params, tokens, cfg, _mesh(), capture=hooks, return_logits=True
    )
    np.testing.assert_allclose(
        np.asarray(sp_logits), np.asarray(dense_logits), rtol=5e-4, atol=5e-4
    )
    for hp in hooks:
        np.testing.assert_allclose(
            np.asarray(sp_cache[hp]), np.asarray(dense_cache[hp]),
            rtol=5e-4, atol=5e-4, err_msg=hp,
        )


def test_seq_parallel_sublayer_hooks_match_dense(tiny):
    """attn_out/mlp_out capture through the ring path equals the dense
    forward's (the sublayer sites ride the same capture machinery)."""
    cfg, params, tokens = tiny
    hooks = ["blocks.1.hook_attn_out", "blocks.2.hook_mlp_out"]
    _, dense = lm.forward(params, tokens, cfg, capture=hooks, return_logits=False)
    _, sp = lm.forward_seq_parallel(params, tokens, cfg, _mesh(), capture=hooks)
    for hp in hooks:
        np.testing.assert_allclose(
            np.asarray(sp[hp]), np.asarray(dense[hp]),
            rtol=5e-4, atol=5e-4, err_msg=hp,
        )


def test_seq_parallel_capture_only(tiny):
    """Harvest mode (return_logits=False) skips the unembedding and returns
    just the cache, sharded over the sequence axis."""
    cfg, params, tokens = tiny
    hp = "blocks.2.hook_resid_pre"
    logits, cache = lm.forward_seq_parallel(params, tokens, cfg, _mesh(), capture=[hp])
    assert logits is None
    assert cache[hp].shape == (2, 64, cfg.d_model)


def test_seq_parallel_rejects_indivisible(tiny):
    cfg, params, tokens = tiny
    with pytest.raises(ValueError):
        lm.forward_seq_parallel(params, tokens[:, :60], cfg, _mesh())


def test_multihost_single_process_noop():
    """initialize() must be a safe no-op off-pod; primary is process 0."""
    from crosscoder_tpu.parallel import multihost

    assert multihost.initialize() is False
    assert multihost.is_primary()
    info = multihost.process_info()
    assert info["process_count"] == 1 and info["global_devices"] == 8
