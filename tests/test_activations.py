"""Tests for the sparse activation family (TopK / BatchTopK / JumpReLU) —
TPU-native additions with no reference counterpart (reference has dense ReLU
only, crosscoder.py:76-77)."""

import jax
import jax.numpy as jnp
import numpy as np

from crosscoder_tpu.config import CrossCoderConfig
from crosscoder_tpu.models import crosscoder as cc
from crosscoder_tpu.ops import activations as act


def test_topk_keeps_k_largest():
    h = jnp.asarray(np.random.default_rng(0).normal(size=(16, 64)).astype(np.float32))
    out = act.topk(h, 8, use_pallas=False)
    n_active = np.asarray((out > 0).sum(axis=-1))
    assert (n_active <= 8).all()
    # surviving values are unchanged
    hp = np.maximum(np.asarray(h), 0)
    mask = np.asarray(out) > 0
    np.testing.assert_allclose(np.asarray(out)[mask], hp[mask])
    # each row's kept entries are its largest positives
    for r in range(16):
        kept = set(np.flatnonzero(mask[r]))
        expect = set(np.argsort(-hp[r])[: len(kept)])
        assert kept == expect


def test_topk_gradient_flows_only_through_survivors():
    h = jnp.asarray([[3.0, 1.0, 2.0, -1.0]])
    g = jax.grad(lambda x: act.topk(x, 2, use_pallas=False).sum())(h)
    np.testing.assert_allclose(np.asarray(g), [[1.0, 0.0, 1.0, 0.0]])


def test_batchtopk_global_budget():
    h = jnp.asarray(np.random.default_rng(1).normal(size=(8, 32)).astype(np.float32))
    out = act.batchtopk(h, 4)
    assert int((out > 0).sum()) <= 4 * 8


def _batchtopk_sort_oracle(h: np.ndarray, k: int) -> np.ndarray:
    """The flatten-and-sort definition batchtopk replaces: threshold = the
    (k·batch)-th largest ReLU'd value, all ties at the threshold kept."""
    hp = np.maximum(h.astype(np.float32), 0)
    kk = min(k * int(np.prod(hp.shape[:-1])), hp.size)
    thresh = np.sort(hp.reshape(-1))[::-1][kk - 1]
    return (hp * ((hp >= thresh) & (hp > 0))).astype(h.dtype)


def test_batchtopk_matches_sort_oracle():
    rng = np.random.default_rng(2)
    for dtype in (np.float32, jnp.bfloat16):
        h = rng.normal(size=(16, 96)).astype(np.float32)
        # force ties at what will be the threshold region
        h[h > 0.9] = 1.0
        h = jnp.asarray(h).astype(dtype)
        out = np.asarray(act.batchtopk(h, 3), np.float32)
        expect = np.asarray(_batchtopk_sort_oracle(np.asarray(h, np.float32), 3))
        np.testing.assert_array_equal(out, expect)


def test_batchtopk_all_zero_and_full_budget():
    z = jnp.zeros((4, 16))
    assert int((act.batchtopk(z, 2) > 0).sum()) == 0
    # budget >= total size keeps every positive entry
    h = jnp.asarray(np.random.default_rng(3).normal(size=(4, 8)).astype(np.float32))
    out = np.asarray(act.batchtopk(h, 8))
    np.testing.assert_array_equal(out, np.maximum(np.asarray(h), 0))


def test_batchtopk_production_shape():
    """VERDICT round-1 weak #5: the old flatten-and-sort became a 134M-element
    device sort at [4096, 2^15]; the bisection path must handle that shape."""
    h = jax.random.normal(jax.random.key(0), (4096, 2**15), dtype=jnp.bfloat16)
    out = jax.jit(act.batchtopk, static_argnums=1)(h, 32)
    out_np = np.asarray(out, np.float32)
    hp = np.maximum(np.asarray(h, np.float32), 0)
    n_active = int((out_np > 0).sum())
    # at least the budget is kept (bf16 ties at the threshold can exceed it —
    # the same ties-all-kept semantics the sort-based definition has)
    assert n_active >= 32 * 4096
    # exact threshold semantics: every dropped positive is strictly below
    # every kept value
    assert hp[out_np == 0].max() < out_np[out_np > 0].min()
    # grad path compiles and is masked like the forward
    g = jax.jit(jax.grad(lambda x: act.batchtopk(x, 32).astype(jnp.float32).sum()))(h)
    assert bool(((np.asarray(g, np.float32) != 0) == (out_np > 0)).all())


def test_jumprelu_forward_and_theta_grad():
    log_theta = jnp.log(jnp.asarray([0.5, 0.5, 0.5]))
    h = jnp.asarray([[0.2, 0.6, 1.5]])
    out = act.jumprelu(h, log_theta, 0.3)
    np.testing.assert_allclose(np.asarray(out), [[0.0, 0.6, 1.5]])
    # h-grad passes through active units only
    gh = jax.grad(lambda x: act.jumprelu(x, log_theta, 0.3).sum())(h)
    np.testing.assert_allclose(np.asarray(gh), [[0.0, 1.0, 1.0]])
    # theta-grad is nonzero only near the threshold (|h−θ| ≤ bandwidth/2):
    # h=0.6 with θ=0.5, bw=0.3 → inside window; others outside
    gt = jax.grad(lambda lt: act.jumprelu(h, lt, 0.3).sum(), argnums=0)(log_theta)
    assert float(gt[0]) == 0.0
    assert float(gt[1]) != 0.0
    assert float(gt[2]) == 0.0


def test_jumprelu_via_config_dispatch():
    cfg = CrossCoderConfig(d_in=8, dict_size=16, enc_dtype="fp32", activation="jumprelu")
    p = cc.init_params(jax.random.key(0), cfg)
    assert "log_theta" in p
    x = jax.random.normal(jax.random.key(1), (4, 2, 8))
    out = cc.get_losses(p, x, cfg)
    assert np.isfinite(float(out.l2_loss))


def test_topk_via_config_dispatch():
    cfg = CrossCoderConfig(d_in=8, dict_size=16, enc_dtype="fp32", activation="topk", topk_k=4)
    p = cc.init_params(jax.random.key(0), cfg)
    x = jax.random.normal(jax.random.key(1), (4, 2, 8))
    f = cc.encode(p, x, cfg)
    assert int((f > 0).sum(axis=-1).max()) <= 4


def test_batchtopk_fixed_threshold_eval_mode():
    """cfg.batchtopk_threshold > 0 switches batchtopk to a FIXED global
    threshold: one example's activations no longer depend on its batch,
    and the calibrated threshold reproduces the per-batch behavior on the
    calibration distribution."""
    from crosscoder_tpu.config import CrossCoderConfig
    from crosscoder_tpu.models import crosscoder as cc

    cfg = CrossCoderConfig(d_in=16, dict_size=64, n_models=2, batch_size=32,
                           activation="batchtopk", topk_k=4, enc_dtype="fp32")
    params = cc.init_params(jax.random.key(0), cfg)
    batches = [
        np.asarray(jax.random.normal(jax.random.key(i), (32, 2, 16)))
        for i in range(4)
    ]
    thr = cc.calibrate_batchtopk_threshold(params, cfg, batches)
    assert thr > 0

    cfg_eval = cfg.replace(batchtopk_threshold=thr)
    # batch-independence: a row encoded alone == the same row in a batch
    full = cc.encode(params, jnp.asarray(batches[0]), cfg_eval)
    solo = cc.encode(params, jnp.asarray(batches[0][:1]), cfg_eval)
    # matmul tiling differs with batch size -> fp32 noise; the SUPPORT
    # must match exactly, values to reduction tolerance
    np.testing.assert_array_equal(np.asarray(full[:1]) > 0, np.asarray(solo) > 0)
    np.testing.assert_allclose(np.asarray(full[:1]), np.asarray(solo),
                               rtol=1e-5, atol=1e-6)
    # (per-batch mode would drop/keep different entries for the solo row)
    full_b = cc.encode(params, jnp.asarray(batches[0]), cfg)
    solo_b = cc.encode(params, jnp.asarray(batches[0][:1]), cfg)
    assert not np.array_equal(np.asarray(full_b[:1]), np.asarray(solo_b))

    # calibrated threshold ~ reproduces per-batch L0 on calibration data
    l0_eval = float((np.asarray(full) > 0).sum(-1).mean())
    l0_batch = float((np.asarray(full_b) > 0).sum(-1).mean())
    assert abs(l0_eval - l0_batch) / max(l0_batch, 1) < 0.5


def test_jumprelu_l0_penalty_gradient():
    """The rectangle-kernel STE: d/d log_theta of the L0 penalty is
    −(1/ε)·mean_b rect·θ per feature; h gets no gradient."""
    from crosscoder_tpu.ops.activations import jumprelu_l0

    bandwidth = 0.5
    h = jnp.asarray([[0.1, 0.9, 2.0], [0.15, 1.1, -0.3]], jnp.float32)
    log_theta = jnp.log(jnp.asarray([0.2, 1.0, 0.05], jnp.float32))

    val, grads = jax.value_and_grad(
        lambda lt, x: jumprelu_l0(x, lt, bandwidth), argnums=(0, 1)
    )(log_theta, h)
    # forward: mean over batch of counts above theta
    counts = (np.asarray(h) > np.exp(np.asarray(log_theta))).sum(-1)
    assert float(val) == counts.mean()
    # manual rectangle gradient
    theta = np.exp(np.asarray(log_theta))
    rect = (np.abs(np.asarray(h) - theta) <= bandwidth / 2).astype(np.float32)
    want_glt = -(1.0 / bandwidth) * rect.mean(0) * theta
    np.testing.assert_allclose(np.asarray(grads[0]), want_glt, rtol=1e-6)
    np.testing.assert_array_equal(np.asarray(grads[1]), np.zeros_like(h))


def test_jumprelu_l0_coeff_trains_sparsity():
    """cfg.l0_coeff > 0 drives L0 down over training where l0_coeff=0
    does not (the paper's sparsity objective, wired through
    training_loss)."""
    from crosscoder_tpu.config import CrossCoderConfig
    from crosscoder_tpu.models import crosscoder as cc
    import optax

    def run(l0_coeff):
        cfg = CrossCoderConfig(
            d_in=16, dict_size=128, n_models=2, batch_size=64,
            activation="jumprelu", jumprelu_theta=0.01,
            jumprelu_bandwidth=0.05, l1_coeff=0.0, l0_coeff=l0_coeff,
            enc_dtype="fp32",
        )
        params = cc.init_params(jax.random.key(0), cfg)
        tx = optax.adam(1e-2)
        opt = tx.init(params)
        x = jax.random.normal(jax.random.key(1), (64, 2, 16))

        @jax.jit
        def step(params, opt):
            (loss, aux), g = jax.value_and_grad(
                lambda p: cc.training_loss(p, x, 0.0, cfg), has_aux=True
            )(params)
            upd, opt = tx.update(g, opt, params)
            return optax.apply_updates(params, upd), opt, aux

        for _ in range(400):
            params, opt, aux = step(params, opt)
        return float(aux.l0_loss)

    l0_with = run(5e-2)
    l0_without = run(0.0)
    # measured: ~49 vs ~66 active latents after 400 steps
    assert l0_with < 0.85 * l0_without, (l0_with, l0_without)
