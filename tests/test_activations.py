"""Tests for the sparse activation family (TopK / BatchTopK / JumpReLU) —
TPU-native additions with no reference counterpart (reference has dense ReLU
only, crosscoder.py:76-77)."""

import jax
import jax.numpy as jnp
import numpy as np

from crosscoder_tpu.config import CrossCoderConfig
from crosscoder_tpu.models import crosscoder as cc
from crosscoder_tpu.ops import activations as act


def test_topk_keeps_k_largest():
    h = jnp.asarray(np.random.default_rng(0).normal(size=(16, 64)).astype(np.float32))
    out = act.topk(h, 8, use_pallas=False)
    n_active = np.asarray((out > 0).sum(axis=-1))
    assert (n_active <= 8).all()
    # surviving values are unchanged
    hp = np.maximum(np.asarray(h), 0)
    mask = np.asarray(out) > 0
    np.testing.assert_allclose(np.asarray(out)[mask], hp[mask])
    # each row's kept entries are its largest positives
    for r in range(16):
        kept = set(np.flatnonzero(mask[r]))
        expect = set(np.argsort(-hp[r])[: len(kept)])
        assert kept == expect


def test_topk_gradient_flows_only_through_survivors():
    h = jnp.asarray([[3.0, 1.0, 2.0, -1.0]])
    g = jax.grad(lambda x: act.topk(x, 2, use_pallas=False).sum())(h)
    np.testing.assert_allclose(np.asarray(g), [[1.0, 0.0, 1.0, 0.0]])


def test_batchtopk_global_budget():
    h = jnp.asarray(np.random.default_rng(1).normal(size=(8, 32)).astype(np.float32))
    out = act.batchtopk(h, 4)
    assert int((out > 0).sum()) <= 4 * 8


def test_jumprelu_forward_and_theta_grad():
    log_theta = jnp.log(jnp.asarray([0.5, 0.5, 0.5]))
    h = jnp.asarray([[0.2, 0.6, 1.5]])
    out = act.jumprelu(h, log_theta, 0.3)
    np.testing.assert_allclose(np.asarray(out), [[0.0, 0.6, 1.5]])
    # h-grad passes through active units only
    gh = jax.grad(lambda x: act.jumprelu(x, log_theta, 0.3).sum())(h)
    np.testing.assert_allclose(np.asarray(gh), [[0.0, 1.0, 1.0]])
    # theta-grad is nonzero only near the threshold (|h−θ| ≤ bandwidth/2):
    # h=0.6 with θ=0.5, bw=0.3 → inside window; others outside
    gt = jax.grad(lambda lt: act.jumprelu(h, lt, 0.3).sum(), argnums=0)(log_theta)
    assert float(gt[0]) == 0.0
    assert float(gt[1]) != 0.0
    assert float(gt[2]) == 0.0


def test_jumprelu_via_config_dispatch():
    cfg = CrossCoderConfig(d_in=8, dict_size=16, enc_dtype="fp32", activation="jumprelu")
    p = cc.init_params(jax.random.key(0), cfg)
    assert "log_theta" in p
    x = jax.random.normal(jax.random.key(1), (4, 2, 8))
    out = cc.get_losses(p, x, cfg)
    assert np.isfinite(float(out.l2_loss))


def test_topk_via_config_dispatch():
    cfg = CrossCoderConfig(d_in=8, dict_size=16, enc_dtype="fp32", activation="topk", topk_k=4)
    p = cc.init_params(jax.random.key(0), cfg)
    x = jax.random.normal(jax.random.key(1), (4, 2, 8))
    f = cc.encode(p, x, cfg)
    assert int((f > 0).sum(axis=-1).max()) <= 4
