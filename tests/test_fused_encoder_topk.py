"""Fused encoder→TopK megakernel (cfg.fused_encoder;
ops/fused_encoder_topk.py, docs/SCALING.md "Fused encoder→TopK"):
interpret-mode CPU parity against the dense oracle chain — bit-identical
(vals, idx) including threshold ties, sign-bit-set NaN patterns (the
PR 1 clamp case), duplicate-max rows, and non-tile-divisible dictionary
tails — gradient parity through the ``_fused_topk_step`` /
``_fused_batchtopk_encode`` custom VJPs, the int8 block-scaled matmul
path's quality bounds, dispatch gates, config validation, and the
zero-cost-off step-HLO identity. All CPU, tier-1; registered in
scripts/kernels.sh (the ``fused`` stanza).

Data discipline: the bit-exactness tests use integer-valued operands so
the kernel's per-tile MXU dots and the oracle's one-shot einsum are
EXACTLY equal (f32-exact sums), making "bit-identical" a deterministic
claim rather than an association-order coin flip; the float tests use
tolerances sized to f32 association noise.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from crosscoder_tpu.config import CrossCoderConfig
from crosscoder_tpu.models import crosscoder as cc
from crosscoder_tpu.ops import activations as act_ops
from crosscoder_tpu.ops import fused_encoder_topk as fek
from crosscoder_tpu.ops import sparse_grad, topk_pallas


@pytest.fixture(autouse=True)
def _interpret_kernels():
    """Route every Pallas path through the interpreter (the CPU stand-in
    for the TPU kernels, same as test_topk_pallas / test_sparse_grad)."""
    fek.set_interpret(True)
    topk_pallas.set_interpret(True)
    sparse_grad.set_interpret(True)
    yield
    fek.set_interpret(False)
    topk_pallas.set_interpret(False)
    sparse_grad.set_interpret(False)


def _int_operands(rng, B, nd, H, dtype, b_scale=2):
    x2 = jnp.asarray(rng.integers(-3, 4, size=(B, nd)), dtype)
    W2 = jnp.asarray(rng.integers(-2, 3, size=(nd, H)), dtype)
    b = jnp.asarray(rng.integers(-b_scale, b_scale + 1, size=(H,)),
                    jnp.float32)
    return x2, W2, b


def _oracle_chain(x2, W2, b, k):
    """The exact forward the fused kernel replaces: dense pre-acts →
    dense TopK scatter → the sparsify drain contract."""
    hf = jnp.dot(x2, W2, preferred_element_type=jnp.float32)
    h = (hf + b).astype(x2.dtype)
    f = act_ops._topk_dense(h, k)
    vals, idx = topk_pallas.sparsify(f, k)
    return h, vals, idx


def _assert_bitexact(got, want, what):
    g = np.asarray(got[0], np.float32), np.asarray(got[1])
    w = np.asarray(want[0], np.float32), np.asarray(want[1])
    np.testing.assert_array_equal(g[0], w[0], err_msg=f"{what}: vals")
    np.testing.assert_array_equal(g[1], w[1], err_msg=f"{what}: idx")


# ---------------------------------------------------------------------------
# TopK kernel vs the dense oracle chain


@pytest.mark.parametrize("dtype", [jnp.float32, jnp.bfloat16])
@pytest.mark.parametrize("B,nd,H,k", [
    (48, 256, 1024, 8),       # chunk-divisible width
    (48, 256, 1000, 8),       # non-tile-divisible dictionary tail
    (33, 128, 640, 16),       # odd batch (row-block padding) + small tail
    (16, 128, 200, 32),       # width barely above k, single padded chunk
])
def test_fused_topk_bitexact(dtype, B, nd, H, k):
    rng = np.random.default_rng(0)
    x2, W2, b = _int_operands(rng, B, nd, H, dtype)
    assert fek.supported(B, nd, H, k, dtype)
    got = fek.fused_topk_encode(x2, W2, b, k)
    _, *want = _oracle_chain(x2, W2, b, k)
    _assert_bitexact(got, want, f"{dtype.__name__} [{B},{nd}]x{H} k={k}")


def test_fused_topk_threshold_ties_break_by_lowest_index():
    """Duplicate W columns manufacture exact value ties at and across the
    k-th position; selection must keep the lowest global indices, the
    lax.top_k contract the whole tier chain pins."""
    rng = np.random.default_rng(1)
    B, nd, H, k = 32, 128, 512, 8
    W = rng.integers(-2, 3, size=(nd, H)).astype(np.float32)
    for dup in (100, 200, 300, 511):          # 5-way tie incl. last column
        W[:, dup] = W[:, 7]
    x2 = jnp.asarray(rng.integers(-3, 4, size=(B, nd)), jnp.bfloat16)
    W2 = jnp.asarray(W, jnp.bfloat16)
    b = jnp.zeros((H,), jnp.float32)
    got = fek.fused_topk_encode(x2, W2, b, k)
    _, *want = _oracle_chain(x2, W2, b, k)
    _assert_bitexact(got, want, "threshold ties")


def test_fused_topk_duplicate_max_rows_and_few_positives():
    """All-equal rows (every entry ties at the max) and rows with fewer
    than k positive pre-acts (output must pad with (0.0, 0), never
    recruit zeros or pad columns)."""
    B, nd, H, k = 32, 128, 512, 8
    rng = np.random.default_rng(2)
    x2 = jnp.zeros((B, nd), jnp.bfloat16)          # h == b_enc everywhere
    W2 = jnp.asarray(rng.integers(-2, 3, size=(nd, H)), jnp.bfloat16)
    ball = jnp.full((H,), 2.0, jnp.float32)        # H-way duplicate max
    got = fek.fused_topk_encode(x2, W2, ball, k)
    _, *want = _oracle_chain(x2, W2, ball, k)
    _assert_bitexact(got, want, "duplicate-max rows")
    np.testing.assert_array_equal(np.asarray(got[1]), np.arange(k)[None, :]
                                  .repeat(B, 0))   # lowest indices win

    bfew = np.zeros((H,), np.float32)
    bfew[3], bfew[700 % H] = 5.0, 2.0              # exactly two positives
    got = fek.fused_topk_encode(x2, W2, jnp.asarray(bfew), k)
    vals, idx = np.asarray(got[0], np.float32), np.asarray(got[1])
    np.testing.assert_array_equal(idx[:, :2], [[3, 700 % H]] * B)
    np.testing.assert_array_equal(vals[:, 2:], 0.0)
    np.testing.assert_array_equal(idx[:, 2:], 0)


@pytest.mark.parametrize("payload", [0x7FFF, 0xFFFF])
def test_fused_topk_nan_patterns(payload):
    """The PR 1 composite-key clamp case: a NaN pre-act — including the
    SIGN-BIT-SET payload 0xFFFF that pre-fix silently corrupted the
    composite kernel's row — must rank as a near-max sentinel (occupying
    one top-k slot, exactly as the masked-TopK → sparsify chain gives it
    a slot then drops it at the ``> 0`` drain) and leave every other row
    bit-exact."""
    B, nd, H, k = 16, 128, 512, 8
    rng = np.random.default_rng(3)
    x2 = jnp.zeros((B, nd), jnp.bfloat16)
    W2 = jnp.asarray(rng.integers(-2, 3, size=(nd, H)), jnp.bfloat16)
    bn = np.zeros((H,), np.float32)
    bn[1:2 * k + 1] = np.arange(2 * k, 0, -1)      # 2k positives: 2k..1
    b_clean = jnp.asarray(bn)
    nan_val = jax.lax.bitcast_convert_type(
        jnp.uint16(payload), jnp.bfloat16)
    assert bool(jnp.isnan(nan_val))
    # NaN lands in column 0 of every row via the bias
    bn_nan = bn.copy()
    bn_nan[0] = np.float32(np.asarray(nan_val, np.float32))
    got_v, got_i = fek.fused_topk_encode(x2, W2, jnp.asarray(bn_nan), k)
    got_v = np.asarray(got_v, np.float32)
    got_i = np.asarray(got_i)
    # the NaN burned one slot: exactly k-1 finite survivors, and they are
    # the k-1 LARGEST finite entries (columns 1..k-1), ascending index
    np.testing.assert_array_equal(got_i[:, :k - 1],
                                  np.arange(1, k)[None, :].repeat(B, 0))
    np.testing.assert_array_equal(got_v[:, :k - 1],
                                  bn[1:k][None, :].repeat(B, 0))
    np.testing.assert_array_equal(got_v[:, k - 1:], 0.0)
    # a clean run on the same operands stays bit-exact vs the oracle
    got = fek.fused_topk_encode(x2, W2, b_clean, k)
    _, *want = _oracle_chain(x2, W2, b_clean, k)
    _assert_bitexact(got, want, "clean rows beside the NaN case")


def test_fused_topk_unsupported_shape_falls_back_to_oracle():
    """nd not lane-aligned → the dense-encode fallback, still the exact
    oracle contract (the 'dense fallback on unsupported shapes' leg)."""
    rng = np.random.default_rng(4)
    B, nd, H, k = 16, 192, 512, 8                  # 192 % 128 != 0
    x2, W2, b = _int_operands(rng, B, nd, H, jnp.float32)
    assert not fek.supported(B, nd, H, k, jnp.float32)
    got = fek.fused_topk_encode(x2, W2, b, k)
    _, *want = _oracle_chain(x2, W2, b, k)
    _assert_bitexact(got, want, "fallback")


def test_supported_gates():
    f32 = jnp.float32
    assert fek.supported(32, 256, 1024, 8, f32)
    assert fek.supported(32, 256, 1000, 8, f32)       # tails are fine
    assert not fek.supported(32, 100, 1024, 8, f32)   # contraction align
    assert not fek.supported(32, 256, 1024, 0, f32)   # k bounds
    assert not fek.supported(32, 256, 1024, 200, f32)
    assert not fek.supported(32, 256, 4, 8, f32)      # width < k
    assert not fek.supported(32, 256, 1024, 8, jnp.int8)
    # quant layout: block must be lane-aligned and divide nd
    assert fek.supported(32, 256, 1024, 8, f32, quant_block=128)
    assert not fek.supported(32, 256, 1024, 8, f32, quant_block=96)
    assert not fek.supported(32, 384, 1024, 8, f32, quant_block=256)


# ---------------------------------------------------------------------------
# int8 block-scaled in-kernel matmul (cfg.quant_encoder)


def test_fused_topk_int8_quality_bounds():
    """The --quant-encoder quality gate's test-sized stand-in: selection
    agreement and value error of the int8 block-scaled matmul vs the
    exact fused path stay inside the bench gate's bounds on
    Gaussian-activation-shaped data."""
    rng = np.random.default_rng(5)
    B, nd, H, k = 64, 512, 2048, 16
    x2 = jnp.asarray(rng.standard_normal((B, nd)), jnp.bfloat16)
    W2 = jnp.asarray(rng.standard_normal((nd, H)) * 0.05, jnp.bfloat16)
    b = jnp.asarray(rng.standard_normal(H) * 0.01, jnp.float32)
    ev, ei = fek.fused_topk_encode(x2, W2, b, k)
    qv, qi = fek.fused_topk_encode(x2, W2, b, k, quant_block=128)
    ev, qv = np.asarray(ev, np.float32), np.asarray(qv, np.float32)
    ei, qi = np.asarray(ei), np.asarray(qi)
    overlap = np.mean([
        len(set(qi[r][qv[r] > 0]) & set(ei[r][ev[r] > 0]))
        / max((ev[r] > 0).sum(), 1)
        for r in range(B)
    ])
    assert overlap >= 0.9, f"selection agreement collapsed: {overlap}"
    rel = np.abs(qv.sum(1) - ev.sum(1)) / np.maximum(ev.sum(1), 1e-6)
    assert float(rel.mean()) < 5e-3, f"value error too large: {rel.mean()}"


# ---------------------------------------------------------------------------
# BatchTopK: fused bisection+emit vs the dense oracle


@pytest.mark.parametrize("dtype", [jnp.float32, jnp.bfloat16])
def test_fused_batchtopk_bitexact_incl_ties(dtype):
    rng = np.random.default_rng(6)
    B, nd, H, k = 48, 128, 1000, 8                 # tail width too
    W = rng.integers(-2, 3, size=(nd, H)).astype(np.float32)
    W[:, 500] = W[:, 9]                            # exact global-threshold tie
    x2 = jnp.asarray(rng.integers(-3, 4, size=(B, nd)), dtype)
    W2 = jnp.asarray(W, dtype)
    b = jnp.asarray(rng.integers(-2, 3, size=(H,)), jnp.float32)
    got = fek.fused_batchtopk_encode_raw(x2, W2, b, k)
    hf = jnp.dot(x2, W2, preferred_element_type=jnp.float32)
    h = (hf + b).astype(dtype)
    want = act_ops.batchtopk(h, k, use_pallas=False)
    np.testing.assert_array_equal(np.asarray(got, np.float32),
                                  np.asarray(want, np.float32))


def test_fused_batchtopk_padded_rows_never_enter_the_statistic():
    """Batch padding resurrection guard: with a POSITIVE bias, zero-pad
    rows would grow positive pre-acts; the kernel must mask them out of
    the global (k·B)-th order statistic (B=33 forces row padding)."""
    rng = np.random.default_rng(7)
    B, nd, H, k = 33, 128, 512, 4
    x2, W2, _ = _int_operands(rng, B, nd, H, jnp.float32)
    b = jnp.full((H,), 3.0, jnp.float32)           # everything positive
    got = fek.fused_batchtopk_encode_raw(x2, W2, b, k)
    hf = jnp.dot(x2, W2, preferred_element_type=jnp.float32)
    h = (hf + b).astype(jnp.float32)
    want = act_ops.batchtopk(h, k, use_pallas=False)
    np.testing.assert_array_equal(np.asarray(got), np.asarray(want))


# ---------------------------------------------------------------------------
# model tier: gradients + dispatch


def _cfg(**kw):
    base = dict(d_in=128, n_models=2, dict_size=1024, activation="topk",
                topk_k=8, l1_coeff=0.0, batch_size=32, enc_dtype="fp32",
                master_dtype="fp32", factored_decode="on", sparse_bwd="on",
                fused_encoder="on")
    base.update(kw)
    return CrossCoderConfig(**base)


def _loss_and_grads(cfg, x):
    params = cc.init_params(jax.random.key(0), cfg)

    def loss(p):
        return cc.training_loss(p, x, 0.0, cfg, with_metrics=False)[0]

    return jax.value_and_grad(loss)(params)


@pytest.mark.parametrize("activation", ["topk", "batchtopk"])
def test_grad_parity_fused_vs_dense(activation):
    """The fused tier changes how the forward is COMPUTED, not what it
    means: loss bit-equal (integer operands → exact matmuls), gradients
    within f32 association noise of the unfused tier's."""
    kw = {} if activation == "topk" else dict(
        activation="batchtopk", factored_decode="auto", sparse_bwd="auto")
    rng = np.random.default_rng(8)
    x = jnp.asarray(rng.integers(-3, 4, size=(32, 2, 128)), jnp.float32)
    l_f, g_f = _loss_and_grads(_cfg(**kw), x)
    l_d, g_d = _loss_and_grads(_cfg(fused_encoder="off", **kw), x)
    assert float(l_f) == float(l_d)
    for name in g_d:
        a = np.asarray(g_d[name], np.float32)
        b = np.asarray(g_f[name], np.float32)
        scale = max(float(np.abs(a).max()), 1e-6)
        np.testing.assert_allclose(b, a, atol=2e-5 * scale, rtol=0,
                                   err_msg=f"grad mismatch on {name}")


def test_auxk_step_keeps_the_dense_encode():
    """The h-residual escape hatch: an aux-active step needs the
    pre-acts differentiably for the AuxK ranking, so the fused tier must
    stand down there — and the step must still match the unfused AuxK
    step's loss/grads."""
    kw = dict(aux_k=16, aux_dead_steps=1)
    rng = np.random.default_rng(9)
    x = jnp.asarray(rng.integers(-3, 4, size=(32, 2, 128)), jnp.float32)
    dead = jnp.ones((1024,), bool)

    def run(cfg):
        params = cc.init_params(jax.random.key(0), cfg)

        def loss(p):
            return cc.training_loss(p, x, 0.0, cfg, with_metrics=False,
                                    dead_mask=dead, aux_coeff=1.0)[0]

        return jax.value_and_grad(loss)(params)

    l_f, g_f = run(_cfg(**kw))
    l_d, g_d = run(_cfg(fused_encoder="off", **kw))
    assert float(l_f) == float(l_d)
    for name in g_d:
        a = np.asarray(g_d[name], np.float32)
        b = np.asarray(g_f[name], np.float32)
        np.testing.assert_array_equal(b, a, err_msg=name)


def test_use_fused_encoder_dispatch():
    assert cc.use_fused_encoder(_cfg(), batch=32)
    assert not cc.use_fused_encoder(_cfg(fused_encoder="off"), batch=32)
    # auto: live here because the fixture set interpret mode
    assert cc.use_fused_encoder(_cfg(fused_encoder="auto"), batch=32)
    fek.set_interpret(False)
    assert not cc.use_fused_encoder(_cfg(fused_encoder="auto"), batch=32)
    fek.set_interpret(True)
    # topk rides the sparse-backward scope: a dead plane kills the tier
    assert not cc.use_fused_encoder(
        _cfg(fused_encoder="auto", sparse_bwd="off"), batch=32)
    # auto rejects kernel-unsupported shapes (contraction misalignment)
    assert not cc.use_fused_encoder(
        _cfg(fused_encoder="auto", d_in=100), batch=32)
    # batchtopk: training mode only (a calibrated threshold is eval)
    assert cc.use_fused_encoder(
        _cfg(activation="batchtopk", factored_decode="auto",
             sparse_bwd="auto"), batch=32)
    assert not cc.use_fused_encoder(
        _cfg(activation="batchtopk", factored_decode="auto",
             sparse_bwd="auto", batchtopk_threshold=0.5), batch=32)
    # relu has nothing to fuse
    assert not cc.use_fused_encoder(
        _cfg(activation="relu", factored_decode="auto", sparse_bwd="auto",
             fused_encoder="auto"), batch=32)


def test_config_validation():
    with pytest.raises(ValueError, match="did you mean 'auto'"):
        _cfg(fused_encoder="atuo")
    with pytest.raises(ValueError, match="activation='topk' or 'batchtopk'"):
        _cfg(activation="relu", factored_decode="auto", sparse_bwd="auto")
    with pytest.raises(ValueError, match="sparse_bwd"):
        _cfg(sparse_bwd="off")
    with pytest.raises(ValueError, match="l1_coeff=0"):
        _cfg(l1_coeff=1.0, sparse_bwd="auto", factored_decode="auto")
    with pytest.raises(ValueError, match="quant_encoder requires"):
        _cfg(fused_encoder="off", quant_encoder=True)
    with pytest.raises(ValueError, match="must be a multiple of 128"):
        _cfg(quant_encoder=True, quant_block=96)
    with pytest.raises(ValueError, match="quant_encoder requires activation"):
        _cfg(activation="batchtopk", factored_decode="auto",
             sparse_bwd="auto", quant_encoder=True, quant_block=128)
    # a valid quant layout passes (nd = 256, block 128)
    assert _cfg(quant_encoder=True, quant_block=128).quant_encoder


def test_quant_encoder_step_runs_and_tracks_exact():
    """cfg.quant_encoder end-to-end through training_loss: runs, finite,
    and the loss stays near the exact fused tier's (the in-kernel int8
    matmul only perturbs selection at quantization-noise scale)."""
    rng = np.random.default_rng(10)
    x = jnp.asarray(rng.standard_normal((32, 2, 128)), jnp.float32)
    l_q, g_q = _loss_and_grads(_cfg(quant_encoder=True, quant_block=128), x)
    l_e, _ = _loss_and_grads(_cfg(), x)
    assert np.isfinite(float(l_q))
    assert abs(float(l_q) - float(l_e)) / max(abs(float(l_e)), 1e-6) < 0.05
    assert all(np.all(np.isfinite(np.asarray(g, np.float32)))
               for g in g_q.values())


# ---------------------------------------------------------------------------
# zero-cost off


# the contract engine's public step-lowering harness (the same one
# scripts/analyze.py sweeps the knob lattice with) — the local copy this
# file used to carry is retired
from crosscoder_tpu.analysis.contracts.hlo_rules import \
    lower_step_text as _lower_step_text  # noqa: E402


@pytest.mark.parametrize("activation", ["topk", "batchtopk"])
def test_step_hlo_identical_with_fused_off(activation):
    """fused_encoder="off" and a dead "auto" (no kernel — the seed's
    effective path) trace the byte-identical step: the knob's presence
    costs nothing (the acceptance criterion's step-HLO identity across
    the new knobs)."""
    fek.set_interpret(False)
    topk_pallas.set_interpret(False)
    sparse_grad.set_interpret(False)
    texts = []
    for mode in ("off", "auto"):
        cfg = CrossCoderConfig(
            d_in=128, dict_size=256, batch_size=32, enc_dtype="fp32",
            activation=activation, topk_k=8, l1_coeff=0.0,
            fused_encoder=mode,
        )
        texts.append(_lower_step_text(cfg))
    assert texts[0] == texts[1]
