"""Pallas TopK kernel vs. the dense ``lax.top_k`` oracle.

The kernel's contract is bit-identical top-k selection (ties broken by
lowest index, matching ``activations._topk_dense``); tests run the Pallas
interpreter on CPU. No reference counterpart — the reference has dense ReLU
only (reference crosscoder.py:76-77).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from crosscoder_tpu.ops import activations as act
from crosscoder_tpu.ops import topk_pallas


def _dense(h, k):
    return act._topk_dense(h, k)


@pytest.mark.parametrize("dtype", [jnp.float32, jnp.bfloat16])
@pytest.mark.parametrize("shape,k", [((8, 256), 4), ((24, 512), 32), ((3, 384), 7)])
def test_matches_dense_oracle(shape, k, dtype):
    h = jax.random.normal(jax.random.key(0), shape, dtype=dtype) * 2.0
    out = topk_pallas.topk(h, k, interpret=True)
    ref = _dense(h, k)
    np.testing.assert_array_equal(np.asarray(out, np.float32), np.asarray(ref, np.float32))


def test_ties_broken_by_lowest_index():
    # bf16-style quantized values force many exact ties at the k-th value
    h = jnp.asarray(
        np.random.default_rng(3).integers(0, 4, size=(16, 256)).astype(np.float32)
    )
    out = topk_pallas.topk(h, 8, interpret=True)
    ref = _dense(h, 8)
    np.testing.assert_array_equal(np.asarray(out), np.asarray(ref))


def test_rows_with_few_positives():
    h = -jnp.abs(jax.random.normal(jax.random.key(1), (8, 256)))
    h = h.at[0, 3].set(1.0)  # row 0 has a single positive; others none
    out = topk_pallas.topk(h, 4, interpret=True)
    assert float(out[0, 3]) == 1.0
    assert int((out > 0).sum()) == 1


def test_leading_dims_and_padding():
    # 5 rows (not a multiple of the block) across a leading batch dim
    h = jax.random.normal(jax.random.key(2), (5, 3, 256))
    out = topk_pallas.topk(h, 3, interpret=True)
    ref = _dense(h, 3)
    np.testing.assert_array_equal(np.asarray(out), np.asarray(ref))


def test_gradient_matches_dense():
    h = jax.random.normal(jax.random.key(4), (8, 256))
    g_pallas = jax.grad(lambda x: topk_pallas.topk(x, 5, True).sum())(h)
    g_dense = jax.grad(lambda x: _dense(x, 5).sum())(h)
    np.testing.assert_array_equal(np.asarray(g_pallas), np.asarray(g_dense))


def test_supported_gate():
    assert topk_pallas.supported(jnp.zeros((4, 512)), 32)
    assert not topk_pallas.supported(jnp.zeros((4, 100)), 8)      # unaligned
    assert not topk_pallas.supported(jnp.zeros((4, 512)), 512)    # k == width
    assert not topk_pallas.supported(jnp.zeros((4, 512), jnp.int32), 8)


def test_gradient_parity_at_exact_zero_survivors():
    """Rows with < k strictly-positive entries select exact-0.0 survivors;
    neither path may pass gradient through them (relu subgradient at 0 is 0)."""
    h = jnp.zeros((2, 256))
    h = h.at[0, 7].set(3.0)
    g_pallas = jax.grad(lambda x: topk_pallas.topk(x, 4, True).sum())(h)
    g_dense = jax.grad(lambda x: _dense(x, 4).sum())(h)
    np.testing.assert_array_equal(np.asarray(g_pallas), np.asarray(g_dense))
    assert int((np.asarray(g_dense) != 0).sum()) == 1  # only the 3.0 entry


def test_composite_wide_width_oracle():
    """The slim composite leg at width_bits=16 (bf16 2^16 — the width the
    round-5 kernel exists for) and at a %128-but-not-%4096 width, against
    the dense oracle in interpreter mode; plus the NaN int32-overflow
    guard (a 0x7FFF-payload NaN at key position (bits<<16 | col) would
    wrap ``hi = max+1`` without the clamp)."""
    import numpy as np

    from crosscoder_tpu.ops import topk_pallas as tp

    for width in (2**16, 36992):
        h = jax.random.normal(jax.random.key(0), (8, width), jnp.bfloat16)
        assert tp._composite_supported(h, 8)
        out = tp.topk(h, 8, True)
        ref = act._topk_dense(h, 8)
        assert bool(jnp.all(out == ref)), width

    # NaN with the MAXIMAL payload (bf16 pattern 0x7FFF) in column 0 — the
    # exact key that would overflow hi = max+1 without the clamp: clean
    # rows must stay bit-exact; the NaN row must still keep >= k-1 of the
    # true finite top-k (ordering among NaN payloads is outside the
    # oracle contract)
    h = jax.random.normal(jax.random.key(1), (8, 2**16), jnp.bfloat16)
    worst_nan = jax.lax.bitcast_convert_type(
        jnp.uint16(0x7FFF), jnp.bfloat16
    )
    assert bool(jnp.isnan(worst_nan))
    h = h.at[0, 0].set(worst_nan)
    out = np.asarray(tp.topk(h, 8, True)).astype(np.float32)
    ref = np.asarray(act._topk_dense(h, 8)).astype(np.float32)
    for r in range(1, 8):
        assert np.array_equal(out[r], ref[r]), r
    kept = np.count_nonzero(out[0] != 0) + np.isnan(out[0]).sum()
    assert kept >= 7, kept

    # SIGN-BIT-SET (negative-payload) NaN, bf16 pattern 0xFFFF: if the
    # backend's maximum(x, 0) propagates it sign-intact, the shifted
    # pattern lands in [0x8000, 0xFFFF] — pre-fix, the int32 clamp folded
    # it to a FINITE ~1.7e38 that outranked every genuine activation and
    # corrupted the row silently; the sign-aware guard must keep it a NaN
    # (or, if the backend canonicalizes the sign away, an ordinary
    # positive NaN) — either way the row behaves like the 0x7FFF case:
    # clean rows bit-exact, the NaN row keeps >= k-1 of the finite top-k
    # and NEVER contains a fabricated huge finite value.
    h = jax.random.normal(jax.random.key(2), (8, 2**16), jnp.bfloat16)
    neg_nan = jax.lax.bitcast_convert_type(jnp.uint16(0xFFFF), jnp.bfloat16)
    assert bool(jnp.isnan(neg_nan))
    h = h.at[0, 0].set(neg_nan)
    out = np.asarray(tp.topk(h, 8, True)).astype(np.float32)
    ref = np.asarray(act._topk_dense(h, 8)).astype(np.float32)
    for r in range(1, 8):
        assert np.array_equal(out[r], ref[r]), r
    finite0 = out[0][np.isfinite(out[0])]
    assert finite0.max(initial=0.0) < 1e30, "sign-set NaN leaked as finite"
    kept = np.count_nonzero(out[0] != 0) + np.isnan(out[0]).sum()
    assert kept >= 7, kept


def test_supported_covers_wide_dicts():
    """supported() is True at every BASELINE dict size: bf16 2^15/2^16 via
    the slim composite single-block, bf16 2^17 and f32 2^16+ via the
    width-chunked variant (round-3; VERDICT round-2 weak #1) instead of
    falling back to dense."""
    import jax

    from crosscoder_tpu.ops import topk_pallas as tp

    for width in (2**15, 2**16, 2**17):
        for dtype in (jnp.bfloat16, jnp.float32):
            assert tp.supported(jax.ShapeDtypeStruct((4096, width), dtype), 32)
    # but widths that fit neither a single block nor the chunk grid still
    # fall back (chunked needs width % _CHUNK_WIDTH == 0)
    odd = jax.ShapeDtypeStruct((4096, 2**16 + 128), jnp.bfloat16)
    assert not tp.supported(odd, 32)


@pytest.mark.parametrize("dtype", [jnp.float32, jnp.bfloat16])
def test_chunked_matches_dense_oracle(dtype):
    h = jax.random.normal(jax.random.key(0), (24, 1024), dtype=dtype) * 2.0
    out = topk_pallas._topk_chunked_impl(h, 32, interpret=True, chunk_width=256)
    ref = _dense(h, 32)
    np.testing.assert_array_equal(
        np.asarray(out, np.float32), np.asarray(ref, np.float32)
    )


@pytest.mark.parametrize("dtype", [jnp.float32, jnp.bfloat16])
def test_chunked_cross_chunk_ties(dtype):
    # quantized values force many exact ties at the k-th value, spread
    # across chunks — the emit pass must keep lowest GLOBAL index first
    h0 = np.random.default_rng(3).integers(0, 4, size=(16, 1024)).astype(np.float32)
    h = jnp.asarray(h0, dtype=dtype)
    out = topk_pallas._topk_chunked_impl(h, 8, interpret=True, chunk_width=128)
    ref = _dense(h, 8)
    np.testing.assert_array_equal(
        np.asarray(out, np.float32), np.asarray(ref, np.float32)
    )


def test_chunked_row_padding_and_few_positives():
    # 130 rows pads the row-block grid; a row with < k positives keeps
    # exact-0.0 survivors whose positions never affect the output
    h = -jnp.abs(jax.random.normal(jax.random.key(1), (130, 512)))
    h = h.at[0, 3].set(1.0)
    out = topk_pallas._topk_chunked_impl(h, 4, interpret=True, chunk_width=128)
    assert float(out[0, 3]) == 1.0
    assert int((np.asarray(out) > 0).sum()) == 1


def test_chunked_gradient_matches_dense():
    h = jax.random.normal(jax.random.key(4), (8, 1024))
    # route through the public entry (custom_vjp) at a width that forces
    # the chunked path in interpret mode
    import crosscoder_tpu.ops.topk_pallas as tp

    orig = tp._VMEM_BUDGET_BYTES
    tp._VMEM_BUDGET_BYTES = 0          # force every width onto the chunked path
    tp._CHUNK_WIDTH_SAVED = tp._CHUNK_WIDTH
    tp._CHUNK_WIDTH = 256
    try:
        g_pallas = jax.grad(lambda x: tp.topk(x, 5, True).sum())(h)
    finally:
        tp._VMEM_BUDGET_BYTES = orig
        tp._CHUNK_WIDTH = tp._CHUNK_WIDTH_SAVED
    g_dense = jax.grad(lambda x: _dense(x, 5).sum())(h)
    np.testing.assert_array_equal(np.asarray(g_pallas), np.asarray(g_dense))
