"""Tests for the persistent AOT executable tier
(crosscoder_tpu/utils/compile_cache.py, docs/SCALING.md "Persistent
compile cache"): hit/miss/eviction lifecycle, every fall-back gate
(corrupt entry, fingerprint mismatch, strict verify), cross-process
claim dedup with two REAL processes, warm-vs-cold bitwise training
parity, zero-cost-off HLO identity, and the bounded thread-safe memo."""

import json
import pickle
import subprocess
import sys
import threading

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from crosscoder_tpu.utils import compile_cache


@pytest.fixture(autouse=True)
def _reset_compile_cache():
    """Snapshot + restore every module-global table so tests can flip
    the disk tier and clear the memo without leaking into each other."""
    with compile_cache._LOCK:
        saved = (dict(compile_cache._AOT_CACHE),
                 dict(compile_cache._COST_CACHE),
                 dict(compile_cache._COST_PENDING),
                 dict(compile_cache._COLLECTIVES),
                 compile_cache._DISK, compile_cache._VERIFY)
        compile_cache._AOT_CACHE.clear()
        compile_cache._COST_CACHE.clear()
        compile_cache._COST_PENDING.clear()
        compile_cache._COLLECTIVES.clear()
        compile_cache._DISK = None
        compile_cache._VERIFY = "off"
    yield
    with compile_cache._LOCK:
        compile_cache._AOT_CACHE.clear()
        compile_cache._AOT_CACHE.update(saved[0])
        compile_cache._COST_CACHE.clear()
        compile_cache._COST_CACHE.update(saved[1])
        compile_cache._COST_PENDING.clear()
        compile_cache._COST_PENDING.update(saved[2])
        compile_cache._COLLECTIVES.clear()
        compile_cache._COLLECTIVES.update(saved[3])
        compile_cache._DISK = saved[4]
        compile_cache._VERIFY = saved[5]


def _tiny_exe(i: int = 0):
    """A real compiled executable (serializable) plus its lower()."""
    x = jnp.arange(4.0)
    lowered = jax.jit(lambda v: v * 2.0 + i).lower(x)
    return lowered.compile(), lowered


def _clear_memo():
    with compile_cache._LOCK:
        compile_cache._AOT_CACHE.clear()
        compile_cache._COST_PENDING.clear()
        compile_cache._COST_CACHE.clear()


# ---------------------------------------------------------------------------
# lifecycle: miss -> store -> hit -> evict


def test_disk_roundtrip_and_cost_sidecar(tmp_path):
    disk = compile_cache.configure(cache_dir=str(tmp_path / "cc"))
    assert disk is not None and compile_cache.disk_enabled()
    key = ("t_roundtrip", 4, "f32")
    builds = []

    def build():
        exe, _ = _tiny_exe(1)
        builds.append(1)
        return exe

    exe1 = compile_cache.aot_get(key, build)
    assert builds == [1]
    assert compile_cache.disk_entry_count() == 1
    # second process simulated: cold memo, same disk
    _clear_memo()
    loads = []
    exe2 = compile_cache.aot_get(key, build, on_load=loads.append)
    assert builds == [1]                       # no recompile
    assert loads == [key]
    np.testing.assert_array_equal(np.asarray(exe2(jnp.arange(4.0))),
                                  np.asarray(exe1(jnp.arange(4.0))))
    stats = compile_cache.disk_stats()
    assert stats["disk_hit"] == 1 and stats["disk_miss"] == 1
    # the cost sidecar answers without any executable in the process
    _clear_memo()
    cost = compile_cache.cost_of(key)
    assert cost is not None and set(cost) == {"flops", "bytes_accessed"}


def test_eviction_respects_byte_cap(tmp_path):
    exe, _ = _tiny_exe()
    from jax.experimental.serialize_executable import serialize
    one = len(pickle.dumps({"format": compile_cache.DISK_FORMAT,
                            "payload": serialize(exe)[0]}))
    disk = compile_cache.configure(cache_dir=str(tmp_path / "cc"),
                                   max_bytes=int(2.5 * one))
    digests = []
    for i in range(4):
        exe_i, low = _tiny_exe(i)
        d = compile_cache.disk_key(("t_evict", i))
        disk.store(d, exe_i, variant=f"v{i}", lower=lambda lw=low: lw)
        digests.append(d)
    total = sum(p.stat().st_size for p in disk.root.glob("*.exec"))
    assert total <= int(2.5 * one)
    assert not disk.has(digests[0])            # oldest went first
    assert disk.has(digests[-1])               # newest survives
    assert disk.stats["evictions"] >= 1
    # manifest never names an evicted entry's bytes as live
    m = disk.manifest()
    assert digests[0] not in m["entries"]


# ---------------------------------------------------------------------------
# fall-back gates: the cache may be slower, never wrong or fatal


def test_corrupt_entry_falls_back_to_live_build(tmp_path):
    disk = compile_cache.configure(cache_dir=str(tmp_path / "cc"))
    key = ("t_corrupt",)
    compile_cache.aot_get(key, lambda: _tiny_exe(2)[0])
    [path] = list(disk.root.glob("*.exec"))
    path.write_bytes(b"\x00garbage" * 16)
    _clear_memo()
    builds = []
    exe = compile_cache.aot_get(key, lambda: (builds.append(1),
                                              _tiny_exe(2)[0])[1])
    assert builds == [1]                       # rebuilt live, no crash
    np.testing.assert_array_equal(np.asarray(exe(jnp.arange(4.0))),
                                  np.arange(4.0) * 2.0 + 2)
    # the rebuild re-stored a healthy entry: a third cold lookup loads
    # from disk without building
    _clear_memo()
    compile_cache.aot_get(key, lambda: (builds.append(1),
                                        _tiny_exe(2)[0])[1])
    assert builds == [1]


def test_fingerprint_mismatch_falls_back(tmp_path):
    disk = compile_cache.configure(cache_dir=str(tmp_path / "cc"))
    key = ("t_fpr",)
    compile_cache.aot_get(key, lambda: _tiny_exe(3)[0])
    [path] = list(disk.root.glob("*.exec"))
    rec = pickle.loads(path.read_bytes())
    rec["fingerprint"] = "jax=0.0.0,jaxlib=0.0.0,backend=other,device=x"
    path.write_bytes(pickle.dumps(rec))
    _clear_memo()
    builds = []
    compile_cache.aot_get(key, lambda: (builds.append(1),
                                        _tiny_exe(3)[0])[1])
    assert builds == [1]                       # stale entry never loads
    assert compile_cache.disk_stats()["disk_miss"] >= 1


def test_strict_verify_rejects_tampered_hlo(tmp_path):
    disk = compile_cache.configure(cache_dir=str(tmp_path / "cc"),
                                   verify="strict")
    exe, low = _tiny_exe(4)
    d = compile_cache.disk_key(("t_strict",))
    disk.store(d, exe, lower=lambda: low)
    [path] = list(disk.root.glob("*.exec"))
    rec = pickle.loads(path.read_bytes())
    rec["hlo_sha"] = "0" * 64                  # stored program lies
    path.write_bytes(pickle.dumps(rec))
    assert disk.load(d, lower=lambda: low, verify="strict") is None
    assert not path.exists()                   # rejected AND discarded
    # an honest entry passes strict verify
    disk.store(d, exe, lower=lambda: low)
    assert disk.load(d, lower=lambda: low, verify="strict") is not None


# ---------------------------------------------------------------------------
# cross-process claim dedup (two REAL processes)

_RACE_SCRIPT = r"""
import sys, time
from crosscoder_tpu.utils import compile_cache

compile_cache.configure(cache_dir=sys.argv[1])
builds = []

def build():
    import jax, jax.numpy as jnp
    time.sleep(1.0)        # widen the race window: both processes inside
    builds.append(1)
    return jax.jit(lambda v: v * 3.0).lower(jnp.arange(8.0)).compile()

exe = compile_cache.aot_get(("race_key", 8), build)
assert float(exe(__import__("jax.numpy", fromlist=["x"]).arange(8.0))[1]) == 3.0
print(len(builds))
"""


def test_cross_process_claim_dedup(tmp_path):
    """Two cold processes racing the same key: the claim-by-rename
    leader builds ONCE; the loser blocks on the claim and deserializes
    the winner's entry. Total builds across both processes == 1."""
    cc = str(tmp_path / "cc")
    script = tmp_path / "race.py"
    script.write_text(_RACE_SCRIPT)
    import os
    import pathlib
    repo_root = str(pathlib.Path(__file__).resolve().parents[1])
    env = dict(os.environ)
    env["PYTHONPATH"] = repo_root + os.pathsep + env.get("PYTHONPATH", "")
    procs = [subprocess.Popen([sys.executable, str(script), cc],
                              stdout=subprocess.PIPE, text=True,
                              cwd=repo_root, env=env)
             for _ in range(2)]
    outs = [p.communicate(timeout=300)[0] for p in procs]
    assert all(p.returncode == 0 for p in procs)
    total_builds = sum(int(o.strip().splitlines()[-1]) for o in outs)
    assert total_builds == 1, f"dedup failed: {total_builds} builds"
    assert compile_cache.configure(cache_dir=cc) is not None
    assert compile_cache.disk_entry_count() == 1


# ---------------------------------------------------------------------------
# warm-vs-cold training parity (the cache is invisible to numerics)


def _run_losses(tmp_path, n=3):
    from crosscoder_tpu.config import CrossCoderConfig
    from crosscoder_tpu.train.trainer import Trainer

    cfg = CrossCoderConfig(
        d_in=16, dict_size=64, batch_size=32, num_tokens=32 * 200,
        enc_dtype="fp32", lr=2e-3, l1_coeff=0.02, log_backend="null",
        compile_cache_dir=str(tmp_path / "cc"))
    tr = Trainer(cfg)
    return [float(tr.step()["loss"]) for _ in range(n)]


def test_warm_start_bitwise_equals_cold(tmp_path):
    cold = _run_losses(tmp_path)
    hits_before = compile_cache.disk_stats()["disk_hit"]
    _clear_memo()                              # force the disk path
    warm = _run_losses(tmp_path)
    assert warm == cold                        # bitwise, not approx
    assert compile_cache.disk_stats()["disk_hit"] > hits_before


# ---------------------------------------------------------------------------
# zero-cost off


def test_knob_off_step_hlo_identity(tmp_path):
    """With compile_cache_* set the step program lowers byte-identically
    to the bare baseline — the knob is pure host-side plumbing."""
    from crosscoder_tpu.analysis.contracts.hlo_rules import lower_step_text

    base = lower_step_text(_step_cfg(), n_devices=1)
    on = lower_step_text(
        _step_cfg(compile_cache_dir=str(tmp_path / "cc"),
                  compile_cache_max_bytes=1 << 20,
                  compile_cache_verify="strict"), n_devices=1)
    assert base == on


def _step_cfg(**kw):
    from crosscoder_tpu.config import CrossCoderConfig

    base = dict(d_in=16, dict_size=64, batch_size=32,
                enc_dtype="fp32", log_backend="null")
    base.update(kw)
    return CrossCoderConfig(**base)


def test_disk_tier_off_by_default():
    compile_cache.configure(_step_cfg())
    assert not compile_cache.disk_enabled()
    assert compile_cache.disk_entry_count() == 0
    assert compile_cache.disk_stats() == {"disk_hit": 0, "disk_miss": 0,
                                          "evictions": 0}


# ---------------------------------------------------------------------------
# the in-process memo: bounded, thread-safe, one build per key


def test_aot_memo_hammer_one_build_per_key():
    """8 threads hammering the same 32 keys (well under the cap):
    concurrent misses coalesce onto ONE build each, every caller gets
    the same executable object."""
    n_keys, n_threads = 32, 8
    builds = {k: 0 for k in range(n_keys)}
    build_lock = threading.Lock()
    barrier = threading.Barrier(n_threads)

    def get(k):
        def build():
            with build_lock:
                builds[k] += 1
            return ("exe", k)
        return compile_cache.aot_get(("hammer", k), build)

    errors = []

    def worker(seed):
        try:
            barrier.wait()
            for j in range(n_keys):
                k = (j + seed) % n_keys
                exe = get(k)
                assert exe == ("exe", k)
        except Exception as e:  # noqa: BLE001 — surfaced below
            errors.append(e)

    threads = [threading.Thread(target=worker, args=(s,))
               for s in range(n_threads)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert not errors
    assert all(builds[k] == 1 for k in range(n_keys)), builds


def test_aot_memo_is_bounded_and_costs_survive_eviction(monkeypatch):
    monkeypatch.setattr(compile_cache, "_AOT_CACHE_CAP", 8)
    exe, _ = _tiny_exe()
    for k in range(32):
        compile_cache.aot_get(("bounded", k), lambda: exe)
    assert len(compile_cache._AOT_CACHE) <= 8  # LRU stayed bounded
    # a pending cost analysis settled before its executable was dropped
    assert compile_cache.cost_of(("bounded", 0)) is not None


def test_config_validation(tmp_path):
    from crosscoder_tpu.config import CrossCoderConfig

    with pytest.raises(ValueError, match="compile_cache_verify"):
        _step_cfg(compile_cache_verify="strictest")
    with pytest.raises(ValueError, match="compile_cache_max_bytes"):
        _step_cfg(compile_cache_dir=str(tmp_path / "cc"),
                  compile_cache_max_bytes=0)
    cfg = _step_cfg(compile_cache_dir=str(tmp_path / "deep" / "cc"))
    assert (tmp_path / "deep" / "cc").is_dir()  # dir-creatable check ran
    assert cfg.compile_cache_verify == "off"
