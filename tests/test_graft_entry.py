"""The driver contract: entry() compiles; dryrun_multichip runs a real
sharded train step on the 8-virtual-device CPU mesh."""

import sys
from pathlib import Path

import jax

sys.path.insert(0, str(Path(__file__).resolve().parents[1]))

import __graft_entry__ as ge


def test_entry_returns_jittable():
    fn, args = ge.entry()
    lowered = jax.jit(fn).lower(*args)  # compile-check without running the big matmul
    assert lowered is not None


def test_dryrun_multichip_8():
    ge.dryrun_multichip(8)


def test_dryrun_multichip_2():
    ge.dryrun_multichip(2)
