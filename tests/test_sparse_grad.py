"""Sparse backward compute plane (cfg.sparse_bwd; ops/sparse_grad.py,
docs/SCALING.md "Sparse backward plane"): scatter-accumulate kernel vs
XLA-scatter oracle (interpret mode on CPU), end-to-end gradient parity of
the sparse custom VJPs against the dense factored backward — including
the duplicate-index accumulation case and a non-chunk-divisible tail
width — plus the dispatch gates, config validation, and the zero-cost
guarantees (step-HLO identity with sparse_bwd="off", no XLA scatter on
the supported "on" path). All CPU, tier-1."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from crosscoder_tpu.config import CrossCoderConfig
from crosscoder_tpu.models import crosscoder as cc
from crosscoder_tpu.ops import sparse_grad, topk_pallas
from crosscoder_tpu.parallel import mesh as mesh_lib


@pytest.fixture(autouse=True)
def _interpret_kernels():
    """Every test in this file exercises the Pallas path through the
    interpreter (the CPU stand-in for the TPU kernel, same as
    test_topk_pallas / test_quant)."""
    topk_pallas.set_interpret(True)
    sparse_grad.set_interpret(True)
    yield
    topk_pallas.set_interpret(False)
    sparse_grad.set_interpret(False)


def _np_scatter_oracle(coeff, idx, rows, n_out):
    out = np.zeros((n_out, rows.shape[-1]), np.float32)
    B, k = coeff.shape
    for b in range(B):
        for j in range(k):
            d = int(idx[b, j])
            if 0 <= d < n_out:
                out[d] += float(coeff[b, j]) * rows[b].astype(np.float32)
    return out


# ---------------------------------------------------------------------------
# scatter_add_rows: kernel vs oracle


@pytest.mark.parametrize("dtype", [jnp.float32, jnp.bfloat16])
@pytest.mark.parametrize("n_out,m,B,k", [
    (512, 128, 16, 4),
    (256, 256, 32, 8),
    (1920, 128, 8, 4),      # 1920 % 256 != 0: shrunk row block (240)
])
def test_scatter_kernel_matches_xla_and_numpy(n_out, m, B, k, dtype):
    rng = np.random.default_rng(0)
    coeff = rng.standard_normal((B, k)).astype(np.float32)
    idx = rng.integers(0, n_out, size=(B, k)).astype(np.int32)
    rows = rng.standard_normal((B, m)).astype(np.float32)
    rows_j = jnp.asarray(rows, dtype)
    assert sparse_grad.supported(n_out, m, B, B * k)
    got_k = sparse_grad.scatter_add_rows(
        jnp.asarray(coeff), jnp.asarray(idx), rows_j, n_out, use_pallas=True)
    got_x = sparse_grad.scatter_add_rows(
        jnp.asarray(coeff), jnp.asarray(idx), rows_j, n_out, use_pallas=False)
    oracle = _np_scatter_oracle(coeff, idx, np.asarray(rows_j, np.float32),
                                n_out)
    np.testing.assert_allclose(np.asarray(got_k), oracle, atol=1e-5, rtol=1e-5)
    np.testing.assert_allclose(np.asarray(got_x), oracle, atol=1e-5, rtol=1e-5)


def test_scatter_duplicate_destinations_accumulate():
    """The scatter-add race case: many pairs landing on the SAME output
    row must sum them all (the kernel serializes duplicates via the
    dst-sorted pair walk; determinism is its construction, correctness
    is this assert)."""
    B, k, n_out, m = 24, 8, 256, 128
    rng = np.random.default_rng(1)
    coeff = rng.standard_normal((B, k)).astype(np.float32)
    idx = np.full((B, k), 7, np.int32)          # every pair hits row 7
    idx[:, 1] = 200                              # and a second shared row
    rows = rng.standard_normal((B, m)).astype(np.float32)
    got = sparse_grad.scatter_add_rows(
        jnp.asarray(coeff), jnp.asarray(idx), jnp.asarray(rows), n_out,
        use_pallas=True)
    oracle = _np_scatter_oracle(coeff, idx, rows, n_out)
    np.testing.assert_allclose(np.asarray(got), oracle, atol=1e-4, rtol=1e-5)
    assert float(np.abs(oracle[7]).max()) > 0    # the row really is contested


def test_scatter_out_of_range_dropped_not_wrapped():
    """Negative / >= n_out destinations are dropped (scatter mode="drop"
    semantics) on BOTH implementations — numpy-style wrapping of a -1
    would corrupt the last dictionary row's gradient."""
    B, k, n_out, m = 8, 4, 256, 128
    rng = np.random.default_rng(2)
    coeff = rng.standard_normal((B, k)).astype(np.float32)
    idx = rng.integers(0, n_out, size=(B, k)).astype(np.int32)
    idx[0, 0] = -1
    idx[1, 0] = n_out
    rows = rng.standard_normal((B, m)).astype(np.float32)
    oracle = _np_scatter_oracle(coeff, idx, rows, n_out)
    for use_pallas in (True, False):
        got = sparse_grad.scatter_add_rows(
            jnp.asarray(coeff), jnp.asarray(idx), jnp.asarray(rows), n_out,
            use_pallas=use_pallas)
        np.testing.assert_allclose(np.asarray(got), oracle, atol=1e-5,
                                   rtol=1e-5)


def test_supported_gates():
    ok = dict(n_out=512, m=256, n_rows=32, n_pairs=256)
    assert sparse_grad.supported(**ok)
    assert not sparse_grad.supported(512, 100, 32, 256)    # m not lane-aligned
    assert not sparse_grad.supported(512, 64, 32, 256)     # m < 128
    assert not sparse_grad.supported(28, 256, 32, 256)     # no row block divides
    assert not sparse_grad.supported(512, 256, 32, 0)      # empty pair list
    assert not sparse_grad.supported(                      # pair-list VMEM cap
        512, 256, 32, sparse_grad._MAX_PAIRS + 1)
    # decode gate = both scatter calls (nd and the bias-augmented nd+128)
    assert sparse_grad.decode_grad_supported(1024, 8, 2, 128, 32)
    assert not sparse_grad.decode_grad_supported(1024, 8, 2, 100, 32)


# ---------------------------------------------------------------------------
# end-to-end gradient parity: sparse VJPs vs the dense factored backward


def _cfg(**kw):
    base = dict(d_in=128, n_models=2, dict_size=1024, activation="topk",
                topk_k=8, l1_coeff=0.0, batch_size=32, enc_dtype="fp32",
                master_dtype="fp32", factored_decode="on")
    base.update(kw)
    return CrossCoderConfig(**base)


def _grads(cfg, x, dead_mask=None):
    params = cc.init_params(jax.random.key(0), cfg)

    def loss(p):
        kw = {}
        if dead_mask is not None:
            kw["dead_mask"] = dead_mask
            kw["aux_coeff"] = 1.0
        return cc.training_loss(p, x, 0.0, cfg, with_metrics=False, **kw)[0]

    return jax.value_and_grad(loss)(params)


def _assert_grad_parity(cfg_kw, x, dead_mask=None, tol=2e-5):
    l_off, g_off = _grads(_cfg(sparse_bwd="off", **cfg_kw), x, dead_mask)
    l_on, g_on = _grads(_cfg(sparse_bwd="on", **cfg_kw), x, dead_mask)
    assert float(l_off) == pytest.approx(float(l_on), rel=1e-6)
    for name in g_off:
        a = np.asarray(g_off[name], np.float32)
        b = np.asarray(g_on[name], np.float32)
        scale = max(float(np.abs(a).max()), 1e-6)
        np.testing.assert_allclose(b, a, atol=tol * scale, rtol=0,
                                   err_msg=f"grad mismatch on {name}")


@pytest.mark.parametrize("dict_size", [512, 1024, 1920])
def test_grad_parity_bare_step(dict_size):
    """The full-step sparse variant (encode+TopK+decode in one custom vjp)
    against the dense factored backward, across dict widths including the
    non-chunk-divisible 1920 (row block shrinks to 240)."""
    rng = np.random.default_rng(3)
    x = jnp.asarray(rng.standard_normal((32, 2, 128)), jnp.float32)
    _assert_grad_parity(dict(dict_size=dict_size), x)


def test_grad_parity_duplicate_latent_batch():
    """Two identical examples activate the SAME k latents — every sparse
    pair is a duplicate destination, the scatter-accumulate race case."""
    rng = np.random.default_rng(4)
    row = rng.standard_normal((1, 2, 128))
    x = jnp.asarray(np.repeat(row, 32, axis=0), jnp.float32)
    _assert_grad_parity(dict(dict_size=512), x)


def test_grad_parity_auxk_step():
    """AuxK-on step: the main tier runs the (h, W_dec)-scoped sparse
    variant (h stays a residual for the aux ranking) and the aux term
    reuses the scatter plane (_sparse_aux_product) — both against the
    dense pair."""
    cfg_kw = dict(dict_size=512, aux_k=16, aux_dead_steps=1)
    rng = np.random.default_rng(5)
    x = jnp.asarray(rng.standard_normal((32, 2, 128)), jnp.float32)
    dead = jnp.ones((512,), bool)        # everything dead: aux path fully live
    # f32 einsum-vs-scatter association differs more once the aux residual
    # couples the two losses; still well inside f32-accumulation agreement
    _assert_grad_parity(cfg_kw, x, dead_mask=dead, tol=2e-4)


def test_sparse_step_forward_matches_factored_tier():
    """sparse_bwd changes the BACKWARD only: the forward loss/recon of the
    full-step variant must match the factored tier's to f32 association
    noise."""
    cfg_off = _cfg(sparse_bwd="off")
    cfg_on = _cfg(sparse_bwd="on")
    rng = np.random.default_rng(6)
    x = jnp.asarray(rng.standard_normal((32, 2, 128)), jnp.float32)
    params = cc.init_params(jax.random.key(0), cfg_off)
    a = cc.get_losses(params, x, cfg_off)
    b = cc.get_losses(params, x, cfg_on)
    np.testing.assert_allclose(float(a.l2_loss), float(b.l2_loss),
                               rtol=1e-6)
    np.testing.assert_allclose(np.asarray(a.explained_variance),
                               np.asarray(b.explained_variance), atol=1e-5)


# ---------------------------------------------------------------------------
# dispatch gates + config validation


def test_use_sparse_bwd_dispatch():
    assert cc.use_sparse_bwd(_cfg(sparse_bwd="on"))
    assert not cc.use_sparse_bwd(_cfg(sparse_bwd="off"))
    # auto: live here because the fixture set interpret mode (the CPU
    # stand-in for TPU + CROSSCODER_SPARSE_GRAD_PALLAS=1)
    assert cc.use_sparse_bwd(_cfg(sparse_bwd="auto"), batch=32)
    sparse_grad.set_interpret(False)
    assert not cc.use_sparse_bwd(_cfg(sparse_bwd="auto"), batch=32)
    sparse_grad.set_interpret(True)
    # auto rejects kernel-unsupported shapes (d_in breaks lane alignment)
    assert not cc.use_sparse_bwd(
        _cfg(sparse_bwd="auto", d_in=100), batch=32)
    # non-topk / l1 never route sparse (validated for "on", gated for auto)
    assert not cc.use_sparse_bwd(
        _cfg(sparse_bwd="auto", activation="relu", l1_coeff=2.0,
             factored_decode="auto"))


def test_sparse_bwd_on_forces_factored_tier():
    """A forced sparse backward at a sub-crossover dict must not silently
    noop: "on" flips the factored-tier auto gate too."""
    cfg = _cfg(sparse_bwd="on", factored_decode="auto", dict_size=1024)
    assert cc.use_factored_decode(cfg)
    cfg_off = _cfg(sparse_bwd="off", factored_decode="auto", dict_size=1024)
    assert not cc.use_factored_decode(cfg_off)


def test_use_sparse_aux_gates():
    # aux reuse needs the plane active AND (in auto) the width heuristic
    assert cc.use_sparse_aux(_cfg(sparse_bwd="on", aux_k=16), batch=32)
    assert not cc.use_sparse_aux(_cfg(sparse_bwd="off", aux_k=16), batch=32)
    assert not cc.use_sparse_aux(_cfg(sparse_bwd="on", aux_k=0), batch=32)
    # auto: aux_k·512 > dict_size fails the traffic heuristic at this width
    assert not cc.use_sparse_aux(
        _cfg(sparse_bwd="auto", aux_k=16, dict_size=1024), batch=32)
    # the pair cap is HARD, forced "on" included: B·aux_k over
    # sparse_grad._MAX_PAIRS would route the aux VJP to the XLA fallback
    # that materializes a [B·aux_k, n·d] f32 update matrix — the bench
    # recipe shape (4096·256 = 1M pairs) must fall back to the dense aux
    big = sparse_grad._MAX_PAIRS // 32 + 32      # batch 32 → pairs > cap
    assert not cc.use_sparse_aux(
        _cfg(sparse_bwd="on", aux_k=big, dict_size=1 << 17), batch=32)


def test_config_rejects_bad_sparse_bwd():
    with pytest.raises(ValueError, match="did you mean 'auto'"):
        _cfg(sparse_bwd="atuo")
    with pytest.raises(ValueError, match="sparse_bwd='on' requires"):
        _cfg(sparse_bwd="on", activation="relu", l1_coeff=0.0,
             factored_decode="auto")
    with pytest.raises(ValueError, match="l1_coeff=0"):
        _cfg(sparse_bwd="on", l1_coeff=1.0)
    with pytest.raises(ValueError, match="sparse_decode"):
        _cfg(sparse_bwd="on", sparse_decode=True)


# ---------------------------------------------------------------------------
# zero-cost guarantees


def _lower_step_text(cfg):
    from jax.sharding import NamedSharding, PartitionSpec as P

    from crosscoder_tpu.train import schedules
    from crosscoder_tpu.train.state import init_train_state, make_optimizer
    from crosscoder_tpu.train.trainer import make_train_step

    mesh = mesh_lib.make_mesh(devices=jax.devices()[:1])
    tx = make_optimizer(cfg, schedules.lr_schedule(cfg))
    state = jax.eval_shape(lambda k: init_train_state(k, cfg, tx),
                           jax.random.key(0))
    shardings = mesh_lib.state_shardings(mesh, state, cfg.shard_sources)
    step = make_train_step(cfg, mesh, tx, shardings)
    state_sh = jax.tree_util.tree_map(
        lambda s, sh: jax.ShapeDtypeStruct(s.shape, s.dtype, sharding=sh),
        state, shardings,
    )
    batch = jax.ShapeDtypeStruct(
        (cfg.batch_size, cfg.n_sources, cfg.d_in), jnp.float32,
        sharding=mesh_lib.batch_sharding(mesh),
    )
    scale = jax.ShapeDtypeStruct(
        (cfg.n_sources,), jnp.float32, sharding=NamedSharding(mesh, P()),
    )
    return step.lower(state_sh, batch, scale).as_text()


def test_step_hlo_identical_with_sparse_bwd_off():
    """sparse_bwd="off" (and a dead "auto" — no kernel, the seed's
    effective path) must trace the byte-identical step the pre-PR graph
    traced: the knob's presence costs nothing."""
    sparse_grad.set_interpret(False)     # "auto" must be DEAD for this test
    topk_pallas.set_interpret(False)
    texts = []
    for mode in ("off", "auto"):
        cfg = CrossCoderConfig(
            d_in=128, dict_size=256, batch_size=32, enc_dtype="fp32",
            activation="topk", topk_k=8, l1_coeff=0.0, sparse_bwd=mode,
        )
        texts.append(_lower_step_text(cfg))
    assert texts[0] == texts[1]


def test_sparse_on_path_has_no_xla_scatter():
    """The whole point: on supported shapes the "on" bare-step gradient
    contains NO XLA scatter op — every gradient lands through the Pallas
    scatter-accumulate (interpret-lowered here) or a matmul. The dense
    baseline's same lowering is scatter-free too (it's all matmuls), so
    also assert the sparse path didn't smuggle one in via sorting/searching
    machinery. Mirrors test_quant's no-s8 assert."""
    cfg = _cfg(sparse_bwd="on")
    params = cc.init_params(jax.random.key(0), cfg)
    x = jax.ShapeDtypeStruct((32, cfg.n_sources, cfg.d_in), jnp.float32)

    def loss(p, xb):
        return cc.training_loss(p, xb, 0.0, cfg, with_metrics=False)[0]

    text = jax.jit(jax.grad(loss)).lower(params, x).as_text()
    assert "scatter" not in text
