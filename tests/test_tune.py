"""Autotuner tests: lattice validity, deterministic ranking, the rigged
two-candidate race, the contracts gate's rejection accounting, artifact
schema validation, the ``--tuned`` round-trip through train/main.py, the
per-topology remesh lifecycle, and scripts/tune_report.py's exit codes.

Stage-1 pricing normally compiles one step per distinct step signature;
these tests monkeypatch :func:`crosscoder_tpu.tune.lattice._step_cost`
with a constant so the search logic is exercised without a compiler in
the loop (the real compile path is covered by the tier-1 tune smoke,
``python -m crosscoder_tpu.tune.smoke``, and the bench ``tune`` leg).
"""

import importlib.util
import json
from pathlib import Path

import pytest

from crosscoder_tpu.config import CrossCoderConfig
from crosscoder_tpu.obs.registry import MetricsRegistry
from crosscoder_tpu.tune import artifact as tune_artifact
from crosscoder_tpu.tune import autotune, lattice
from crosscoder_tpu.tune.artifact import (TunedArtifact, apply_tuned,
                                          config_hash, load_tuned, on_remesh,
                                          topology_key)
from crosscoder_tpu.tune.lattice import (Candidate, default_axes,
                                         enumerate_lattice, rank_candidates)

_SCRIPTS = Path(__file__).parent.parent / "scripts"


def _load_script(name):
    spec = importlib.util.spec_from_file_location(name, _SCRIPTS / f"{name}.py")
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def tiny_cfg(**kw):
    base = dict(d_in=8, dict_size=32, batch_size=32, enc_dtype="fp32",
                log_backend="null")
    base.update(kw)
    return CrossCoderConfig(**base)


_FLAT_COST = {"flops": 1e9, "bytes_accessed": 1e8, "wire_bytes": 0.0}


@pytest.fixture
def flat_step_cost(monkeypatch):
    """Constant device terms: pricing differences come only from the
    data-plane model, and no compiler runs."""
    monkeypatch.setattr(lattice, "_step_cost",
                        lambda cand, n_devices: dict(_FLAT_COST))


# ---------------------------------------------------------------------------
# lattice enumeration
# ---------------------------------------------------------------------------


def test_lattice_prunes_exactly_the_config_invalid_points():
    """The lattice is filtered by config.py's OWN validation: refill_frac
    above 0.5 and a zero dispatch batch both raise in __post_init__, so
    those products are pruned; everything else survives as a validated
    config whose attributes equal the knob assignment."""
    cfg = tiny_cfg()
    axes = {
        "refill_frac": (0.25, 0.5, 0.75),       # 0.75 > serve trigger: invalid
        "refill_dispatch_batch": (0, 4),        # 0 quanta/dispatch: invalid
        "prefetch": (False, True),
    }
    cands, pruned = enumerate_lattice(cfg, axes)
    assert len(cands) == 4                      # 2 valid fracs x 1 batch x 2
    assert pruned == 8
    for c in cands:
        # the validated config really carries the knob assignment…
        for k, v in c.knobs.items():
            assert getattr(c.cfg, k) == v
        # …and satisfies the constraints the pruned points violated
        assert 0.0 < c.cfg.refill_frac <= 0.5
        assert c.cfg.refill_dispatch_batch >= 1
    # every surviving point is unique and carries the shared base signature
    assert len({json.dumps(c.knobs, sort_keys=True) for c in cands}) == 4
    assert len({c.base_sig for c in cands}) == 1


def test_lattice_empty_when_everything_invalid():
    cands, pruned = enumerate_lattice(tiny_cfg(), {"refill_frac": (0.9,)})
    assert cands == [] and pruned == 1


def test_default_axes_shapes():
    cfg = tiny_cfg(seq_len=64)
    for objective in lattice.OBJECTIVES:
        axes = default_axes(cfg, objective)
        assert len(axes) >= 3
        assert all(len(v) >= 1 for v in axes.values())
    # serve page_size axis only offers divisors of seq_len
    for p in default_axes(cfg, "serve")["page_size"]:
        assert cfg.seq_len % p == 0
    with pytest.raises(ValueError):
        default_axes(cfg, "nope")


# ---------------------------------------------------------------------------
# stage-1 ranking
# ---------------------------------------------------------------------------


def test_ranking_deterministic_under_fixed_seed(flat_step_cost):
    """Same seed, same order — including across exact score ties (with
    refill_overlap='off' the dispatch-batch knob cannot move the price,
    so those candidates tie and the seeded hash must break them
    identically every run)."""
    cfg = tiny_cfg(refill_overlap="off")
    axes = {"refill_dispatch_batch": (2, 4, 8, 16),
            "prefetch": (False, True)}

    def order(seed):
        cands, _ = enumerate_lattice(cfg, axes)
        ranked = rank_candidates(cands, "train", 1, seed)
        return [json.dumps(c.knobs, sort_keys=True) for c in ranked]

    assert order(seed=0) == order(seed=0)
    assert order(seed=7) == order(seed=7)
    # ranking is a permutation of the lattice, scores best-first
    cands, _ = enumerate_lattice(cfg, axes)
    ranked = rank_candidates(cands, "train", 1, 0)
    assert len(ranked) == 8
    scores = [c.score for c in ranked]
    assert scores == sorted(scores, reverse=True)
    # prefetch=True hides the gather, so it never ranks below its
    # prefetch=False twin
    best = ranked[0]
    assert best.knobs["prefetch"] is True


def test_pricing_fills_predictions(flat_step_cost):
    cands, _ = enumerate_lattice(tiny_cfg(), {"prefetch": (False, True)})
    ranked = rank_candidates(cands, "train", 1, 0)
    for c in ranked:
        assert c.predicted["score"] == c.score > 0
        assert {"device_ms", "wire_ms", "step_total_ms",
                "harvest_ms"} <= set(c.predicted)


# ---------------------------------------------------------------------------
# the tune driver (stage 2 rigged through the injectable seams)
# ---------------------------------------------------------------------------


def _pass_gate(cfg, knobs=None):
    return True, []


def test_rigged_race_picks_the_planted_winner(flat_step_cost, tmp_path):
    """Stage 2 overrules stage 1: the measured window plants the win on a
    knob assignment the cost model ranks LAST (prefetch=False scores
    worse analytically), and tune() must pin exactly that assignment."""
    cfg = tiny_cfg()
    planted = {"prefetch": False, "refill_frac": 0.25}

    def measure(mcfg, *, steps, warmup, n_devices):
        won = (mcfg.prefetch, mcfg.refill_frac) == (False, 0.25)
        s = 1e6 if won else 10.0
        return {"score": s, "acts_per_sec_chip": s, "step_ms": 1.0,
                "bubble_frac": 0.0}

    out = tmp_path / "TUNED.json"
    reg = MetricsRegistry()
    art = autotune.tune(
        cfg, "train",
        axes={"prefetch": (False, True), "refill_frac": (0.25, 0.5)},
        top_k=4, out_path=str(out), registry=reg,
        measure=measure, gate=_pass_gate)
    assert art.knobs == planted
    assert art.measured["score"] == 1e6
    assert reg.get_count("tune/candidates") == 4
    assert reg.get_count("tune/calibrated") == 4
    assert reg.get_count("tune/emitted") == 1
    # the pinned file round-trips to the same knobs
    assert load_tuned(out).knobs == planted
    # the audit trail carries every calibrated candidate
    assert len(art.search["candidates"]) == 4
    assert all(r["gate"] == "pass" for r in art.search["candidates"])


def test_contract_violator_is_discarded_and_counted(flat_step_cost):
    """A candidate the contracts gate rejects never ships: it is dropped
    from the race, counted under tune/rejected_contract, and recorded in
    the artifact's audit trail with its findings."""
    cfg = tiny_cfg()

    def gate(gcfg, knobs=None):
        if gcfg.prefetch:            # reject the analytically-better half
            return False, ["hlo-knob-off-identity: seeded violation"]
        return True, []

    def measure(mcfg, *, steps, warmup, n_devices):
        return {"score": 100.0}

    reg = MetricsRegistry()
    art = autotune.tune(cfg, "train", axes={"prefetch": (False, True)},
                        top_k=2, registry=reg, measure=measure, gate=gate)
    assert art.knobs == {"prefetch": False}
    assert reg.get_count("tune/rejected_contract") == 1
    assert art.gate["rejected"] == 1 and art.gate["checked"] == 2
    rejected = [r for r in art.search["candidates"]
                if r["gate"] == "rejected"]
    assert len(rejected) == 1
    assert rejected[0]["knobs"] == {"prefetch": True}
    assert "seeded violation" in rejected[0]["findings"][0]


def test_all_candidates_rejected_refuses_to_emit(flat_step_cost):
    with pytest.raises(ValueError, match="rejected by the contracts gate"):
        autotune.tune(
            tiny_cfg(), "train", axes={"prefetch": (False, True)},
            gate=lambda cfg, knobs=None: (False, ["no"]),
            measure=lambda cfg, **kw: {"score": 1.0})


def test_empty_lattice_refuses_to_emit(flat_step_cost):
    with pytest.raises(ValueError, match="config validation"):
        autotune.tune(tiny_cfg(), "train", axes={"refill_frac": (0.9,)})


def test_default_knobs_always_calibrated(flat_step_cost):
    """top_k=1 still measures the base config's own knob assignment, so
    the winner can never measure worse than the user's defaults."""
    cfg = tiny_cfg()            # defaults: prefetch=True, refill_frac=0.5
    seen = []

    def measure(mcfg, *, steps, warmup, n_devices):
        seen.append((mcfg.prefetch, mcfg.refill_frac))
        return {"score": 50.0}

    autotune.tune(cfg, "train",
                  axes={"prefetch": (False, True),
                        "refill_frac": (0.25, 0.5)},
                  top_k=1, measure=measure, gate=_pass_gate)
    assert (True, 0.5) in seen          # the default-knob candidate


# ---------------------------------------------------------------------------
# artifact schema
# ---------------------------------------------------------------------------


def _valid_art(**kw):
    base = dict(objective="train", knobs={"prefetch": False},
                mesh={"n_devices": 1, "n_model": 1})
    base.update(kw)
    return TunedArtifact(**base)


def test_artifact_round_trip_and_topology(tmp_path):
    art = _valid_art(mesh={"n_devices": 8, "n_model": 2},
                     measured={"score": 3.5})
    assert art.topology == "d8m2" == topology_key(8, 2)
    p = art.save(tmp_path / "TUNED.json")
    got = load_tuned(p)
    assert got.knobs == art.knobs
    assert got.measured == art.measured
    assert got.topology == "d8m2"


@pytest.mark.parametrize("breakage", [
    lambda d: d.pop("knobs"),                       # missing key
    lambda d: d.update(knobs=[]),                   # ill-typed key
    lambda d: d.update(knobs={}),                   # empty knob set
    lambda d: d.update(version=99),                 # wrong schema version
])
def test_artifact_validation_rejects(tmp_path, breakage):
    d = _valid_art().to_dict()
    breakage(d)
    p = tmp_path / "TUNED.json"
    p.write_text(json.dumps(d, default=str))
    with pytest.raises(ValueError):
        load_tuned(p)


@pytest.mark.parametrize("payload", ["", "not json {", "[1, 2]"])
def test_load_tuned_rejects_non_artifacts(tmp_path, payload):
    p = tmp_path / "TUNED.json"
    p.write_text(payload)
    with pytest.raises(ValueError):
        load_tuned(p)
    with pytest.raises(ValueError):
        load_tuned(tmp_path / "no_such_file.json")


def test_apply_tuned(tmp_path):
    cfg = tiny_cfg()
    p = _valid_art(knobs={"prefetch": False, "refill_frac": 0.25}).save(
        tmp_path / "TUNED.json")
    got = apply_tuned(cfg, p)
    assert got.prefetch is False and got.refill_frac == 0.25
    assert got.tuned == str(p)
    # config_hash ignores the artifact path (no self-reference)
    assert config_hash(got) == config_hash(
        cfg.replace(prefetch=False, refill_frac=0.25))
    # identity with nothing pinned
    assert apply_tuned(cfg) is cfg
    # unknown knob names are a schema violation, not an extras passenger
    _valid_art(knobs={"no_such_knob": 1}).save(tmp_path / "BAD.json")
    with pytest.raises(ValueError, match="unknown knob"):
        apply_tuned(cfg, tmp_path / "BAD.json")
    # a stale artifact whose knobs no longer validate fails loudly
    _valid_art(knobs={"refill_frac": 0.9}).save(tmp_path / "STALE.json")
    with pytest.raises(ValueError, match="refill_frac"):
        apply_tuned(cfg, tmp_path / "STALE.json")


# ---------------------------------------------------------------------------
# re-tune on remesh
# ---------------------------------------------------------------------------


def test_on_remesh_lifecycle(tmp_path):
    # off: nothing pinned
    cfg = tiny_cfg()
    assert on_remesh(cfg, 2) == (cfg, "off")

    pinned = _valid_art(knobs={"refill_frac": 0.5},
                        mesh={"n_devices": 1, "n_model": 1})
    p = pinned.save(tmp_path / "TUNED.json")
    cfg = apply_tuned(tiny_cfg(), p)

    # current: the pinned artifact was searched at this very topology
    got, status = on_remesh(cfg, 1)
    assert status == "current" and got.refill_frac == 0.5

    # stale: new shape, no cached sibling — knobs stand but are flagged
    got, status = on_remesh(cfg, 4)
    assert status == "stale" and got.refill_frac == 0.5

    # cache_hit: a TUNED.d4m1.json sibling re-pins the searched knobs
    _valid_art(knobs={"refill_frac": 0.25},
               mesh={"n_devices": 4, "n_model": 1}).save(
        tune_artifact.cache_path(tmp_path, "d4m1"))
    got, status = on_remesh(cfg, 4)
    assert status == "cache_hit" and got.refill_frac == 0.25

    # a torn cache entry is a miss (stale), never a crash
    tune_artifact.cache_path(tmp_path, "d2m1").write_text("torn{")
    got, status = on_remesh(cfg, 2)
    assert status == "stale" and got.refill_frac == 0.5


def test_fleet_policy_prefers_tuned_shape(tmp_path):
    """A per-topology artifact outranks the score policy: the searched
    TP width is returned verbatim with policy='tuned' provenance."""
    from crosscoder_tpu.resilience.fleet import FleetPolicy

    p = _valid_art(mesh={"n_devices": 4, "n_model": 2}).save(
        tune_artifact.cache_path(tmp_path, "d4m2"))
    cfg = tiny_cfg(elastic_policy="fixed", tuned=str(tmp_path / "nope.json"))
    choice = FleetPolicy(cfg).choose(4)
    assert (choice.n_data, choice.n_model) == (2, 2)
    assert choice.detail["policy"] == "tuned"
    assert choice.detail["artifact"] == str(p)
    # no artifact for this device count: falls through to the base policy
    fallback = FleetPolicy(cfg).choose(8)
    assert fallback.detail.get("policy") != "tuned"


# ---------------------------------------------------------------------------
# --tuned through the real CLI entry point
# ---------------------------------------------------------------------------


def _argv(tmp_path, tag, extra=()):
    return [
        "--data-source", "synthetic",
        "--batch-size", "64",
        "--buffer-mult", "4",
        "--num-tokens", "1920",             # 30 steps
        "--d-in", "16",
        "--dict-size", "256",
        "--seq-len", "17",
        "--log-backend", "jsonl",
        "--log-every", "10",
        "--save-every", "10000",
        "--checkpoint-dir", str(tmp_path / f"ckpt_{tag}"),
        *extra,
    ]


@pytest.mark.slow
def test_tuned_flag_round_trips_bitwise_through_main(tmp_path):
    """`--tuned TUNED.json` must resolve to the SAME config — and the
    same loss trajectory, bit for bit — as hand-passing the artifact's
    knobs as explicit CLI flags."""
    from crosscoder_tpu.train.main import main

    p = _valid_art(knobs={"refill_frac": 0.25, "prefetch": False}).save(
        tmp_path / "TUNED.json")
    t_tuned = main(_argv(tmp_path, "tuned", ["--tuned", str(p)]))
    t_hand = main(_argv(tmp_path, "hand", ["--refill-frac", "0.25",
                                           "--prefetch", "false"]))

    da, db = t_tuned.cfg.to_dict(), t_hand.cfg.to_dict()
    for d in (da, db):
        d.pop("tuned"), d.pop("checkpoint_dir")
    assert da == db
    assert t_tuned.cfg.tuned == str(p)

    rows_a = [json.loads(ln) for ln in
              (tmp_path / "ckpt_tuned" / "metrics.jsonl")
              .read_text().splitlines()]
    rows_b = [json.loads(ln) for ln in
              (tmp_path / "ckpt_hand" / "metrics.jsonl")
              .read_text().splitlines()]
    assert [r["loss"] for r in rows_a] == [r["loss"] for r in rows_b]
    assert len(rows_a) >= 2


def test_from_cli_tuned_resolution_order(tmp_path):
    """TUNED knobs land between --config-json and explicit flags: an
    explicit flag wins over the artifact, the artifact over the json."""
    p = _valid_art(knobs={"refill_frac": 0.25, "prefetch": False}).save(
        tmp_path / "TUNED.json")
    cj = tmp_path / "cfg.json"
    cj.write_text(json.dumps({"refill_frac": 0.5, "d_in": 16}))
    cfg = CrossCoderConfig.from_cli([
        "--config-json", str(cj), "--tuned", str(p),
        "--prefetch", "true",
    ])
    assert cfg.refill_frac == 0.25          # artifact beat config-json
    assert cfg.prefetch is True             # explicit flag beat artifact
    assert cfg.d_in == 16                   # untouched json field survives
    # --tuned "" clears a json-pinned artifact path
    cj.write_text(json.dumps({"tuned": str(p)}))
    cfg = CrossCoderConfig.from_cli(["--config-json", str(cj),
                                     "--tuned", ""])
    assert cfg.tuned == "" and cfg.refill_frac == 0.5


# ---------------------------------------------------------------------------
# scripts/tune_report.py
# ---------------------------------------------------------------------------


def test_tune_report_renders_valid_artifact(tmp_path, capsys):
    art = _valid_art(
        predicted={"score": 123.4}, measured={"score": 117.0},
        gate={"rule_set": "analysis.contracts.hlo_rules",
              "checked": 3, "rejected": 1},
        search={"axes": {"prefetch": [False, True]}, "n_candidates": 2,
                "n_pruned_invalid": 0, "n_priced": 2, "top_k": 2,
                "seed": 0, "calibration_steps": 6,
                "candidates": [
                    {"knobs": {"prefetch": False}, "gate": "pass",
                     "predicted_score": 123.4, "measured_score": 117.0},
                    {"knobs": {"prefetch": True}, "gate": "rejected"},
                ]})
    p = art.save(tmp_path / "TUNED.json")
    mod = _load_script("tune_report")
    assert mod.main([str(p)]) == 0
    out = capsys.readouterr().out
    assert "prefetch" in out and "rejected" in out
    assert "d1m1" in out
    # --json re-emits the validated artifact
    assert mod.main([str(p), "--json"]) == 0
    out = capsys.readouterr().out
    assert json.loads(out)["knobs"] == {"prefetch": False}


@pytest.mark.parametrize("payload", [
    "", "not json", json.dumps({"version": 1}),
    json.dumps({**_valid_art().to_dict(), "knobs": {}}, default=str),
])
def test_tune_report_rejects_malformed(tmp_path, payload):
    p = tmp_path / "TUNED.json"
    p.write_text(payload)
    mod = _load_script("tune_report")
    assert mod.main([str(p)]) == 2


# ---------------------------------------------------------------------------
# the real contracts gate (one compile-backed spot check)
# ---------------------------------------------------------------------------


def test_contracts_gate_passes_clean_data_plane_candidate():
    """End-to-end gate over a real lowering: a data-plane knob assignment
    must pass every HLO rule INCLUDING the tune-specific step-projection
    identity (the stage-1 cost-sharing assumption)."""
    from crosscoder_tpu.tune.calibrate import contracts_gate

    cfg = tiny_cfg(refill_frac=0.25, prefetch=False)
    ok, findings = contracts_gate(
        cfg, knobs={"refill_frac": 0.25, "prefetch": False})
    assert ok, [str(f) for f in findings]
