"""Native host-kernel tests: the C++ data-plane ops must be byte-identical
to their NumPy fallbacks (SURVEY.md §4 parity-test strategy applied to the
framework's own native tier — the reference has no native code to mirror,
SURVEY.md §2 'native-code statement').
"""

import numpy as np
import jax.numpy as jnp
import pytest

from crosscoder_tpu import native

BF16 = np.dtype(jnp.bfloat16.dtype)


def _random_store(rng, n=257, n_sources=3, d_in=19):
    # include denormals/inf/nan bit patterns: kernels move raw bits and the
    # upcast is a pure shift, so special values must survive exactly
    bits = rng.integers(0, 2**16, size=(n, n_sources, d_in), dtype=np.uint16)
    return bits.view(BF16)


def test_native_builds():
    assert native.available(), native.build_error()


def test_gather_rows_matches_numpy():
    rng = np.random.default_rng(0)
    store = _random_store(rng)
    idx = rng.integers(0, store.shape[0], size=64)
    out = native.gather_rows(store, idx)
    assert out.dtype == store.dtype and out.shape == (64,) + store.shape[1:]
    assert np.array_equal(out.view(np.uint16), store[idx].view(np.uint16))


def test_gather_scale_f32_matches_numpy():
    rng = np.random.default_rng(1)
    store = _random_store(rng)
    # keep scales finite/normal; inf*0-style NaN propagation must also match
    scale = rng.uniform(0.1, 2.0, size=store.shape[1]).astype(np.float32)
    idx = rng.integers(0, store.shape[0], size=128)
    out = native.gather_scale_f32(store, idx, scale)
    with np.errstate(over="ignore", invalid="ignore"):  # inf/nan rows on purpose
        ref = store[idx].astype(np.float32) * scale[None, :, None]
    assert out.dtype == np.float32
    # bit-level equality, NaNs included
    assert np.array_equal(out.view(np.uint32), ref.view(np.uint32))


def test_scatter_rows_matches_numpy():
    rng = np.random.default_rng(2)
    store_a = _random_store(rng)
    store_b = store_a.copy()
    pos = rng.permutation(store_a.shape[0])[:50]
    rows = _random_store(rng, n=50, n_sources=store_a.shape[1], d_in=store_a.shape[2])
    store_a[pos] = rows
    native.scatter_rows(store_b, pos, rows)
    assert np.array_equal(store_a.view(np.uint16), store_b.view(np.uint16))


def test_gather_rejects_non_contiguous():
    rng = np.random.default_rng(3)
    store = _random_store(rng)[:, ::2, :]  # non-contiguous view
    if not native.available():
        pytest.skip("numpy fallback accepts anything")
    with pytest.raises(ValueError, match="contiguous"):
        native.gather_rows(store, np.array([0, 1]))


def test_gather_rejects_wrong_scale_shape():
    if not native.available():
        pytest.skip("native only")
    rng = np.random.default_rng(4)
    store = _random_store(rng)
    with pytest.raises(ValueError, match="scale"):
        native.gather_scale_f32(store, np.array([0]), np.ones(store.shape[1] + 1, np.float32))


def test_native_bounds_check_raises_indexerror():
    """Out-of-range indices must raise (exactly like NumPy), never touch
    memory; in-range negatives wrap exactly like NumPy."""
    rng = np.random.default_rng(5)
    store = _random_store(rng)
    n = store.shape[0]
    with pytest.raises(IndexError):
        native.gather_rows(store, np.array([n]))
    with pytest.raises(IndexError):
        native.gather_scale_f32(store, np.array([-(n + 1)]), np.ones(store.shape[1], np.float32))
    with pytest.raises(IndexError):
        native.scatter_rows(store, np.array([n + 3]), store[:1].copy())
    # NumPy-style wrap of in-range negatives
    out = native.gather_rows(store, np.array([-1, -n]))
    assert np.array_equal(out.view(np.uint16), store[[-1, -n]].view(np.uint16))


def test_gather_scale_rejects_float16():
    if not native.available():
        pytest.skip("native only")
    store = np.zeros((8, 2, 4), np.float16)
    with pytest.raises(ValueError, match="bfloat16"):
        native.gather_scale_f32(store, np.array([0]), np.ones(2, np.float32))
