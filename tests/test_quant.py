"""Block-scaled int8 data plane (cfg.quant_buffer / cfg.quant_grads;
ops/quant.py, parallel/quant_ar.py, docs/SCALING.md "Quantized data
plane"): numeric oracles, buffer-storage parity across all three store
placements, the HBM budget assertion, the quantized gradient all-reduce's
trajectory + modeled-bytes acceptance, and the zero-cost-off guarantees
(step-HLO identity, no extra transfers). All CPU, tier-1."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from crosscoder_tpu.config import CrossCoderConfig
from crosscoder_tpu.data import buffer as buffer_mod
from crosscoder_tpu.data.buffer import make_buffer
from crosscoder_tpu.models import lm
from crosscoder_tpu.ops import quant
from crosscoder_tpu.parallel import mesh as mesh_lib
from crosscoder_tpu.parallel import quant_ar

SEQ = 17
HP = "blocks.2.hook_resid_pre"


@pytest.fixture(scope="module")
def lm_pair():
    cfg = lm.LMConfig.tiny()
    pa = lm.init_params(jax.random.key(0), cfg)
    pb = lm.init_params(jax.random.key(1), cfg)
    return cfg, [pa, pb]


@pytest.fixture(scope="module")
def tokens():
    rng = np.random.default_rng(7)
    return rng.integers(0, 257, size=(256, SEQ), dtype=np.int64)


def make_cfg(**kw):
    base = dict(
        batch_size=32, buffer_mult=32, seq_len=SEQ, d_in=32, n_models=2,
        model_batch_size=4, norm_calib_batches=2, hook_point=HP, seed=3,
        quant_block=16,
    )
    base.update(kw)
    return CrossCoderConfig(**base)


# ---------------------------------------------------------------------------
# quantize/dequantize numerics


def test_quantize_matches_numpy_oracle():
    rng = np.random.default_rng(0)
    x = rng.normal(size=(33, 3, 128)).astype(np.float32) * 7.0
    x[0, 0, :64] = 0.0                                 # an all-zero block
    q_np, s_np = quant.quantize_np(x, 64)
    q_j, s_j = jax.device_get(quant.quantize_blocks(jnp.asarray(x), 64))
    np.testing.assert_array_equal(np.asarray(q_j), q_np)
    np.testing.assert_allclose(np.asarray(s_j), s_np, rtol=1e-7)
    # zero blocks roundtrip to exact zeros
    deq = quant.dequantize_np(q_np, s_np, np.float32)
    assert (deq[0, 0, :64] == 0).all()
    # jnp and numpy dequant agree
    deq_j = jax.device_get(quant.dequantize_blocks(
        jnp.asarray(q_np), jnp.asarray(s_np), jnp.float32))
    np.testing.assert_allclose(np.asarray(deq_j), deq, rtol=1e-6)


def test_roundtrip_error_bounded():
    """Symmetric per-block int8: elementwise error <= scale/2, i.e. each
    value is within (block max)/254 of its original."""
    rng = np.random.default_rng(1)
    x = rng.normal(size=(64, 2, 256)).astype(np.float32)
    q, s = quant.quantize_np(x, 32)
    deq = quant.dequantize_np(q, s, np.float32)
    bound = np.repeat(s, 32, axis=-1) / 2 + 1e-7
    assert (np.abs(deq - x) <= bound).all()
    rel_mse = np.sum((deq - x) ** 2) / np.sum(x ** 2)
    assert rel_mse < 4e-4                              # the bench gate bound


def test_pallas_interpret_matches_xla():
    """The fused Pallas rowwise quantize kernel (interpret mode on CPU)
    must agree with the XLA lowering bit-for-bit."""
    rng = np.random.default_rng(2)
    x = jnp.asarray(rng.normal(size=(64, 512)).astype(np.float32))
    assert quant.rows_supported(64, 512, 128)
    q_ref, s_ref = jax.device_get(quant.quantize_blocks(x, 128))
    quant.set_interpret(True)
    try:
        q_k, s_k = jax.device_get(quant.quantize_rows(x, 128))
    finally:
        quant.set_interpret(False)
    np.testing.assert_array_equal(np.asarray(q_k), np.asarray(q_ref))
    np.testing.assert_allclose(np.asarray(s_k), np.asarray(s_ref), rtol=1e-7)


def test_rows_supported_gates():
    assert not quant.rows_supported(64, 512, 100)      # block not lane-aligned
    assert not quant.rows_supported(63, 512, 128)      # rows not 32-aligned
    assert not quant.rows_supported(64, 500, 128)      # width % block
    # grid floors at rows_blk=256: a 320-row input would leave rows
    # 256-319 unwritten — the gate must reject it (kernel falls back)
    assert not quant.rows_supported(320, 512, 128)
    assert quant.rows_supported(512, 512, 128)


def test_quantize_rows_partial_tail_falls_back_correct():
    """Regression: n_rows > 256 and not a multiple of 256 must NOT go
    through the Pallas kernel (whose grid floors and never writes the
    tail tile) — quantize_rows falls back to XLA and stays exact."""
    rng = np.random.default_rng(9)
    x = jnp.asarray(rng.normal(size=(320, 512)).astype(np.float32))
    q_ref, s_ref = jax.device_get(quant.quantize_blocks(x, 128))
    quant.set_interpret(True)
    try:
        q_k, s_k = jax.device_get(quant.quantize_rows(x, 128))
    finally:
        quant.set_interpret(False)
    np.testing.assert_array_equal(np.asarray(q_k), np.asarray(q_ref))
    np.testing.assert_array_equal(np.asarray(s_k), np.asarray(s_ref))


# ---------------------------------------------------------------------------
# config validation (satellite)


def test_config_rejects_bad_quant_block():
    with pytest.raises(ValueError, match="must be >= 1"):
        make_cfg(quant_block=0)
    with pytest.raises(ValueError, match="must divide"):
        make_cfg(quant_buffer=True, quant_block=7)
    # off: any positive block is allowed (gradient blocks pad internally)
    make_cfg(quant_block=7)


def test_config_rejects_bad_refill_frac():
    with pytest.raises(ValueError, match=r"\(0, 1\]"):
        make_cfg(refill_frac=0.0)
    with pytest.raises(ValueError, match=r"\(0, 1\]"):
        make_cfg(refill_frac=-0.25)
    with pytest.raises(ValueError, match=r"\(0, 1\]"):
        make_cfg(refill_frac=1.5)
    with pytest.raises(ValueError, match="0.5"):
        make_cfg(refill_frac=0.75)                     # in (0,1] but unsafe
    make_cfg(refill_frac=0.5)
    make_cfg(refill_frac=0.25)


def test_config_rejects_quant_grads_beyond_pure_dp():
    with pytest.raises(ValueError, match="pure data parallelism"):
        make_cfg(quant_grads=True, model_axis_size=2)
    with pytest.raises(ValueError, match="batchtopk"):
        make_cfg(quant_grads=True, activation="batchtopk")
    make_cfg(quant_grads=True)


# ---------------------------------------------------------------------------
# quantized replay stores: parity across placements + the HBM budget


def test_host_quant_buffer_tracks_bf16_store(lm_pair, tokens):
    lm_cfg, params = lm_pair
    b_bf = make_buffer(make_cfg(), lm_cfg, params, tokens)
    b_q = make_buffer(make_cfg(quant_buffer=True), lm_cfg, params, tokens)
    assert type(b_q) is buffer_mod.QuantPairedActivationBuffer
    for _ in range(8):
        r_bf = np.asarray(b_bf.next_raw(), np.float32)
        r_q = np.asarray(b_q.next_raw(), np.float32)
        assert r_q.shape == r_bf.shape and r_q.dtype == r_bf.dtype
        # same serve stream (same seed → same perm/pointer), values within
        # the per-block quantization bound
        denom = np.abs(r_bf).max()
        assert np.abs(r_q - r_bf).max() / denom < 0.01
    # next() applies the same norm factors
    n_bf = b_bf.next()
    n_q = b_q.next()
    assert np.abs(n_q - n_bf).max() / np.abs(n_bf).max() < 0.01


def test_device_and_mesh_quant_stores_serve_bitidentical(lm_pair, tokens):
    """Quantization is deterministic, so all three placements must serve
    the SAME bytes from the same harvest chunks — not merely close."""
    lm_cfg, params = lm_pair
    b_host = make_buffer(make_cfg(quant_buffer=True), lm_cfg, params, tokens)
    b_dev = make_buffer(
        make_cfg(quant_buffer=True, buffer_device="hbm"), lm_cfg, params, tokens
    )
    mesh = mesh_lib.make_mesh(4, 1, devices=jax.devices()[:4])
    b_mesh = make_buffer(
        make_cfg(quant_buffer=True, buffer_device="hbm"), lm_cfg, params,
        tokens, batch_sharding=NamedSharding(mesh, P("data", None)),
    )
    assert type(b_dev) is buffer_mod.QuantDevicePairedActivationBuffer
    assert type(b_mesh) is buffer_mod.QuantMeshPairedActivationBuffer
    # enough serves to cross a refill cycle (trigger at buffer//2 - batch)
    for _ in range(18):
        r_h = np.asarray(b_host.next_raw())
        r_d = np.asarray(jax.device_get(b_dev.next_raw()))
        r_m = np.asarray(jax.device_get(b_mesh.next_raw()))
        np.testing.assert_array_equal(r_d, r_h)
        np.testing.assert_array_equal(r_m, r_h)


def test_quant_store_hbm_budget(lm_pair, tokens):
    """Acceptance: device-store HBM bytes <= 0.55x the bf16 baseline at
    the production geometry (d_in 2304, block 256 → (1 + 4/256)/2 ≈
    0.508). Allocated lazily (no fill) so the real Gemma-width store is
    built and measured without a Gemma-width harvest."""
    lm_cfg, params = lm_pair
    kw = dict(d_in=2304, quant_block=256, buffer_device="hbm")
    b_bf = make_buffer(make_cfg(**kw), lm_cfg, params, tokens, lazy=True)
    b_q = make_buffer(make_cfg(quant_buffer=True, **kw), lm_cfg, params,
                      tokens, lazy=True)
    ratio = b_q.store_nbytes() / b_bf.store_nbytes()
    assert ratio <= 0.55, ratio
    # the analytic accounting agrees
    analytic = quant.store_bytes((4096, 2, 2304), 256) / (2 * 4096 * 2 * 2304)
    assert abs(ratio - analytic) < 1e-6


def test_quant_buffer_resume_roundtrip(lm_pair, tokens):
    """state_dict/load_state_dict semantics are inherited: a restored
    quantized buffer re-fills from the checkpoint stream position and
    serves the same rows as a restored bf16 buffer (within quantization)."""
    lm_cfg, params = lm_pair
    b_q = make_buffer(make_cfg(quant_buffer=True), lm_cfg, params, tokens)
    for _ in range(5):
        b_q.next_raw()
    snap = b_q.state_dict()
    b_q2 = make_buffer(make_cfg(quant_buffer=True), lm_cfg, params, tokens,
                       lazy=True)
    b_q2.load_state_dict(snap)
    expect = np.asarray(b_q2._store[b_q2._perm[:32]]).copy()
    np.testing.assert_array_equal(np.asarray(b_q2.next_raw()), expect)


# ---------------------------------------------------------------------------
# quantized gradient all-reduce


def _dp_mesh(n=4):
    return mesh_lib.make_mesh(n, 1, devices=jax.devices()[:n])


def test_quantized_pmean_matches_exact_mean():
    """One exchange of the real quant_ar collective vs the exact mean on a
    4-device mesh; error bounded by two rounds of per-block quantization."""
    n_dev, block = 4, 32
    rng = np.random.default_rng(5)
    g = rng.normal(size=(n_dev, 7, 33)).astype(np.float32)   # odd sizes pad
    L = quant_ar.padded_len(7 * 33, n_dev, block)
    ef0 = np.zeros((n_dev, L), np.float32)
    mesh = _dp_mesh(n_dev)
    fn = quant_ar.quantized_pmean_fn(mesh, block)
    out, ef1 = fn(jnp.asarray(g), jnp.asarray(ef0))
    out = np.asarray(jax.device_get(out))
    exact = g.mean(axis=0)
    # every device holds the same reduced value
    for d in range(n_dev):
        np.testing.assert_array_equal(out[d], out[0])
    rel = np.abs(out[0] - exact).max() / np.abs(exact).max()
    assert rel < 0.02, rel
    # error feedback residuals are nonzero (there WAS quantization error)
    assert np.abs(np.asarray(jax.device_get(ef1))).max() > 0


def test_error_feedback_unbiases_the_running_mean():
    """EF acceptance: re-reducing the SAME gradient with carried residuals
    makes the running mean converge to the exact mean — the compression
    error cancels instead of accumulating as bias."""
    n_dev, block = 4, 32
    rng = np.random.default_rng(6)
    g = rng.normal(size=(n_dev, 256)).astype(np.float32)
    L = quant_ar.padded_len(256, n_dev, block)
    mesh = _dp_mesh(n_dev)
    fn = quant_ar.quantized_pmean_fn(mesh, block)
    exact = g.mean(axis=0)
    ef = jnp.zeros((n_dev, L), jnp.float32)
    acc = np.zeros_like(exact)
    one_shot = None
    steps = 16
    for i in range(steps):
        out, ef = fn(jnp.asarray(g), ef)
        got = np.asarray(jax.device_get(out))[0]
        if one_shot is None:
            one_shot = np.abs(got - exact).max()
        acc += got
    running = np.abs(acc / steps - exact).max()
    assert running < one_shot / 4, (running, one_shot)


def test_quant_grads_trainer_tracks_exact_trajectory():
    """Acceptance (_traj_parity-style): a CPU-mesh run with quant_grads
    stays loss-finite and within a bounded divergence of the exact-psum
    trajectory on the identical stream."""
    from crosscoder_tpu.data.synthetic import SyntheticActivationSource

    mesh = _dp_mesh(4)

    def run(qg):
        cfg = CrossCoderConfig(
            d_in=32, dict_size=64, batch_size=64, num_tokens=64 * 40,
            enc_dtype="fp32", lr=1e-3, l1_coeff=0.1, log_backend="null",
            data_axis_size=4, model_axis_size=1, quant_grads=qg,
            quant_block=32, prefetch=False,
        )
        from crosscoder_tpu.train.trainer import Trainer

        tr = Trainer(cfg, SyntheticActivationSource(cfg), mesh=mesh)
        if qg:
            assert "quant_ef" in tr.state.aux
        out = []
        for _ in range(20):
            out.append(float(jax.device_get(tr.step()["loss"])))
        tr.close()
        return np.asarray(out)

    lq, lb = run(True), run(False)
    assert np.isfinite(lq).all()
    rel = np.abs(lq - lb) / np.maximum(np.abs(lb), 1e-9)
    assert rel.max() < 5e-3, rel.max()


def test_quant_grads_comm_model_halves_grad_sync_bytes():
    """Acceptance: the compiled-HLO model shows ~2x fewer collective
    OUTPUT bytes and <=0.5x modeled wire bytes for the DP grad sync."""
    from crosscoder_tpu.parallel import comm_model

    profs = comm_model.profile_width(
        4, dict_size=2**10, batch_size=256, programs=("train", "train_quant")
    )
    base = next(p for p in profs if p.program == "train_dp")
    q = next(p for p in profs if p.program == "train_dp_quant")
    assert q.bytes_by_op["all-to-all"] > 0          # the int8 exchange exists
    assert q.bytes_by_op["all-gather"] > 0
    ratio = q.total_bytes / base.total_bytes
    assert ratio < 0.6, ratio
    wire_ratio = comm_model.wire_bytes(q) / comm_model.wire_bytes(base)
    assert wire_ratio < 0.5, wire_ratio


def test_quant_grads_checkpoint_roundtrip(tmp_path):
    """quant_ef residuals live in TrainState.aux and must survive
    save→restore (same-width mesh), so a resumed quant run keeps its
    error-feedback state instead of re-biasing from zero."""
    from crosscoder_tpu.checkpoint import Checkpointer
    from crosscoder_tpu.data.synthetic import SyntheticActivationSource
    from crosscoder_tpu.train.trainer import Trainer

    mesh = _dp_mesh(4)
    cfg = CrossCoderConfig(
        d_in=32, dict_size=64, batch_size=64, num_tokens=64 * 40,
        enc_dtype="fp32", lr=1e-3, l1_coeff=0.1, log_backend="null",
        data_axis_size=4, model_axis_size=1, quant_grads=True,
        quant_block=32, prefetch=False, checkpoint_dir=str(tmp_path),
    )
    tr = Trainer(cfg, SyntheticActivationSource(cfg), mesh=mesh,
                 checkpointer=Checkpointer(cfg=cfg))
    for _ in range(3):
        tr.step()
    ef_before = {k: np.asarray(jax.device_get(v))
                 for k, v in tr.state.aux["quant_ef"].items()}
    assert any(np.abs(v).max() > 0 for v in ef_before.values())
    tr.save()
    tr.close()

    tr2 = Trainer(cfg, SyntheticActivationSource(cfg), mesh=mesh,
                  checkpointer=Checkpointer(cfg=cfg))
    tr2.restore()
    for k, v in ef_before.items():
        np.testing.assert_array_equal(
            np.asarray(jax.device_get(tr2.state.aux["quant_ef"][k])), v
        )
    assert tr2.step_counter == 3
    tr2.close()


# ---------------------------------------------------------------------------
# zero-cost when off (mirrors test_resilience.py's fast-path tests)


def test_step_hlo_independent_of_quant_config():
    """The compiled train step must not change when quant knobs are
    present-but-off (quant_buffer is a data-plane flag; quant_block is
    inert without a consumer): byte-identical HLO, and no int8 anywhere
    in the off-path program. Lowering rides the contract engine's public
    harness (the same one scripts/analyze.py sweeps the knob lattice
    with) — one definition of "the step program" repo-wide."""
    from crosscoder_tpu.analysis.contracts.hlo_rules import lower_step_text

    texts = []
    for extra in ({}, dict(quant_buffer=True, quant_block=8)):
        cfg = CrossCoderConfig(d_in=8, dict_size=32, batch_size=32,
                               enc_dtype="fp32", **extra)
        texts.append(lower_step_text(cfg))
    assert texts[0] == texts[1]
    assert "s8[" not in texts[0]


def test_quant_off_selects_untouched_classes_and_adds_no_transfers(
    lm_pair, tokens, monkeypatch
):
    """With quant off, make_buffer returns the pre-quantization classes
    (no quantized state allocated anywhere) and the serve path performs
    ZERO extra host↔device transfers: the device store serves without a
    single device_get, the host store fetches exactly one chunk per
    drained harvest chunk."""
    lm_cfg, params = lm_pair
    b_dev = make_buffer(make_cfg(buffer_device="hbm"), lm_cfg, params, tokens)
    b_host = make_buffer(make_cfg(), lm_cfg, params, tokens)
    assert type(b_dev) is buffer_mod.DevicePairedActivationBuffer
    assert type(b_host) is buffer_mod.PairedActivationBuffer
    for b in (b_dev, b_host):
        assert not hasattr(b, "_store_q") and not hasattr(b, "_store_scale")

    fetches = []
    real_get = jax.device_get
    monkeypatch.setattr(jax, "device_get",
                        lambda x: (fetches.append(1), real_get(x))[1])
    drains = []
    real_drain = buffer_mod.PairedActivationBuffer._drain_one
    monkeypatch.setattr(
        buffer_mod.PairedActivationBuffer, "_drain_one",
        lambda self: (drains.append(1), real_drain(self))[1],
    )
    for _ in range(6):
        b_dev.next_raw()                    # device store: zero device_get
    assert fetches == []
    for _ in range(6):
        b_host.next_raw()                   # host store: one fetch per drain
    assert len(fetches) == len(drains), (len(fetches), len(drains))
