"""Collective-byte regression: the scaling properties docs/SCALING.md
rests on must hold in the compiled HLO at every width (VERDICT round-4
weak #5: nothing predicted whether 8 chips deliver ~8x).

Asserted invariants (the O(params + batch/n) communication law):

- pure DP: per-step collective volume is the gradient psum — CONSTANT in
  n and bounded by ~4 bytes/param (f32 reduction of the grads+metrics),
  with no weight-sized all-gather;
- DP x TP: sharding the dict axis REDUCES psum volume (each shard reduces
  its own slice);
- SP harvest: ring-attention collective-permute volume is bounded by the
  K/V blocks (independent of the dictionary entirely).
"""

import jax
import pytest

from crosscoder_tpu.models import lm
from crosscoder_tpu.parallel import comm_model


needs8 = pytest.mark.skipif(jax.device_count() < 8, reason="needs 8 devices")

DICT, DIN, BATCH = 2**12, 128, 256


def _one(programs, n, **kw):
    profs = comm_model.profile_width(
        n, dict_size=DICT, d_in=DIN, batch_size=BATCH, programs=programs, **kw
    )
    assert len(profs) == 1
    return profs[0]


@needs8
def test_dp_psum_constant_in_width():
    sizes = {}
    for n in (2, 4, 8):
        p = _one(("train",), n)
        assert p.bytes_by_op["all-gather"] == 0, "weight-sized gather crept in"
        sizes[n] = p.bytes_by_op["all-reduce"]
    # the gradient psum is the whole story and does not grow with width
    assert sizes[2] == sizes[4] == sizes[8], sizes
    # bounded by ~4 bytes/param (f32 grads) + small metric slack
    n_params = 2 * 2 * DIN * DICT + DICT + 2 * DIN
    assert sizes[8] <= 4 * n_params * 1.05, (sizes[8], n_params)
    assert sizes[8] >= 2 * n_params, "psum suspiciously small — DCE'd step?"


@needs8
def test_tp_shards_the_psum():
    dp = _one(("train",), 8)
    tp = _one(("train_tp",), 8, model_axis=2)
    assert tp.bytes_by_op["all-reduce"] < dp.bytes_by_op["all-reduce"], (
        tp.bytes_by_op, dp.bytes_by_op,
    )


@needs8
def test_sp_harvest_permute_bounded_by_kv():
    cfg = lm.LMConfig.tiny()
    p = _one(("sp_harvest",), 8, lm_cfg=cfg, seq_len=64)
    permute = p.bytes_by_op["collective-permute"]
    assert permute > 0, "ring attention emitted no collective-permute"
    # ring attention rotates K and V blocks: per scan-layer-step 2 blocks of
    # [B_local, S/n, kv_heads * head_dim]; bound the TOTAL volume by the
    # full K+V for the whole (batch x seq x layers) extent — byte counts
    # above that would mean the ring moves more than the entire KV cache
    b, s = 8, 64
    kv_total = 2 * b * s * cfg.n_kv_heads * cfg.head_dim * 4 * cfg.n_layers
    assert permute <= kv_total * 8, (permute, kv_total)


def test_shape_parser():
    hlo = """
  %ar = f32[4096,2304]{1,0} all-reduce(%x), replica_groups={}
  %ag.1 = bf16[16,8]{1,0} all-gather(%y), dimensions={0}
  %cp-start = (f32[8,2]{1,0}, f32[8,2]{1,0}) collective-permute-start(%z)
  %cp-done = f32[8,2]{1,0} collective-permute-done(%cp-start)
  %notacoll = f32[2,2]{1,0} add(%a, %b)
"""
    out = comm_model.collective_bytes(hlo)
    assert out["all-reduce"] == 4096 * 2304 * 4
    assert out["all-gather"] == 16 * 8 * 2
    # -start tuple is (operand_alias, result): only the RESULT half counts
    # (summing the whole tuple overcounted async permutes ~2x), and the
    # -done completion stays skipped
    assert out["collective-permute"] == 8 * 2 * 4
    assert out["count"] == 3


def test_async_start_result_half_only():
    """Async all-gather: the -start tuple's operand and result DIFFER in
    size — the result element (the gathered output), not the operand and
    not the tuple sum, is what must be tallied."""
    hlo = """
  %ag-start = (f32[8,2]{1,0}, f32[64,2]{1,0}) all-gather-start(%x), dimensions={0}
  %ag-done = f32[64,2]{1,0} all-gather-done(%ag-start)
"""
    out = comm_model.collective_bytes(hlo)
    assert out["all-gather"] == 64 * 2 * 4
    assert out["count"] == 1


def test_variadic_all_reduce_start_counts_every_result():
    """A combined variadic all-reduce-start's tuple holds ONLY results (no
    operand alias, unlike permute/all-gather) — every element must count,
    or combined gradient psums are undercounted."""
    hlo = """
  %ar-start = (f32[1024]{0}, f32[2048]{0}) all-reduce-start(%a, %b), replica_groups={}
  %ar-done = (f32[1024]{0}, f32[2048]{0}) all-reduce-done(%ar-start)
"""
    out = comm_model.collective_bytes(hlo)
    assert out["all-reduce"] == (1024 + 2048) * 4
    assert out["count"] == 1
