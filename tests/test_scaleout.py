"""BASELINE.json scale-out configs 3-5 as integration smokes (scaled-down
shapes, full production code paths, 8-virtual-device mesh):

- config 3: Gemma-2-9B geometry (d_model 3584) — bigger d_in through the
  sharded train step and the 9B LMConfig mapping;
- config 4: 3-way crosscoder (n_models=3 stack) through harvest → train;
- config 5: multi-layer crosscoder (3 hook points jointly) with the
  layer-axis (source-axis) sharding mode.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from crosscoder_tpu.config import CrossCoderConfig
from crosscoder_tpu.data.buffer import make_buffer
from crosscoder_tpu.models import lm
from crosscoder_tpu.parallel import mesh as mesh_lib
from crosscoder_tpu.train.trainer import Trainer


def test_config3_gemma9b_geometry():
    """d_model 3584 (Gemma-2-9B residual width): the 9B LMConfig maps the
    right shapes and the DP×TP step trains at that d_in."""
    lm9 = lm.config_for("gemma-2-9b")
    assert lm9.d_model == 3584 and lm9.n_layers == 42
    assert lm.config_for("gemma-2-9b-it") == lm9

    cfg = CrossCoderConfig(
        d_in=3584, dict_size=4096, n_models=2, batch_size=64,
        enc_dtype="bf16", data_axis_size=4, model_axis_size=2,
        num_tokens=64 * 10, log_backend="null", prefetch=False,
    )
    mesh = mesh_lib.mesh_from_cfg(cfg)
    trainer = Trainer(cfg, mesh=mesh)          # synthetic source at 3584
    m = trainer.step()
    assert np.isfinite(float(jax.device_get(m["loss"])))
    # dict axis is genuinely TP-sharded at this width
    assert trainer.state.params["W_enc"].sharding.spec[2] == "model"
    trainer.close()


@pytest.fixture(scope="module")
def lm_trio():
    cfg = lm.LMConfig.tiny()
    return cfg, [lm.init_params(jax.random.key(i), cfg) for i in range(3)]


def test_config4_three_way_stack_end_to_end(lm_trio):
    """n_models=3 (base/IT/code-tuned analogue): harvest all three models'
    streams and train the 3-source crosscoder on the mesh."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    lm_cfg, trio = lm_trio
    rng = np.random.default_rng(0)
    toks = rng.integers(0, 257, size=(128, 17), dtype=np.int64)
    cfg = CrossCoderConfig(
        d_in=lm_cfg.d_model, dict_size=128, n_models=3, batch_size=32,
        buffer_mult=32, seq_len=17, model_batch_size=8, norm_calib_batches=1,
        hook_point="blocks.2.hook_resid_pre", num_tokens=32 * 6,
        enc_dtype="fp32", log_backend="null", prefetch=False,
    )
    mesh = mesh_lib.mesh_from_cfg(cfg)
    buf = make_buffer(cfg, lm_cfg, trio, toks,
                      batch_sharding=NamedSharding(mesh, P("data", None)))
    assert buf._store.shape[1] == 3
    trainer = Trainer(cfg, buf, mesh=mesh)
    losses = [float(jax.device_get(trainer.step()["loss"])) for _ in range(3)]
    assert all(np.isfinite(losses))
    trainer.close()


def test_config5_multilayer_with_source_axis_shard(lm_trio):
    """Layers {1,2,3} jointly (the {6,13,20} analogue): n_sources = 2×3 = 6,
    sharded over the model axis (cfg.shard_sources — the 'layer-axis shard'
    BASELINE names), trained from a real multi-hook harvest."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    lm_cfg, trio = lm_trio
    rng = np.random.default_rng(1)
    toks = rng.integers(0, 257, size=(128, 17), dtype=np.int64)
    hooks = tuple(f"blocks.{i}.hook_resid_pre" for i in (1, 2, 3))
    cfg = CrossCoderConfig(
        d_in=lm_cfg.d_model, dict_size=128, n_models=2, hook_points=hooks,
        batch_size=32, buffer_mult=32, seq_len=17, model_batch_size=8,
        norm_calib_batches=1, num_tokens=32 * 6, enc_dtype="fp32",
        data_axis_size=4, model_axis_size=2, shard_sources=True,
        log_backend="null", prefetch=False,
    )
    assert cfg.n_sources == 6
    mesh = mesh_lib.mesh_from_cfg(cfg)
    buf = make_buffer(cfg, lm_cfg, trio[:2], toks,
                      batch_sharding=NamedSharding(mesh, P("data", None)))
    trainer = Trainer(cfg, buf, mesh=mesh)
    m = trainer.step()
    assert np.isfinite(float(jax.device_get(m["loss"])))
    # the source axis is the sharded one
    assert trainer.state.params["W_enc"].sharding.spec[0] == "model"
    trainer.close()


# ---------------------------------------------------------------------------
# tensor-parallel harvest (round-3: models too big for one chip's HBM)


def test_tp_sharded_forward_matches_dense():
    """lm.shard_params_tp places weights in the Megatron layout over the
    'model' axis; forward/capture must match the replicated forward to
    fp32 reduction-order tolerance (GSPMD inserts the psums)."""
    from jax.sharding import Mesh

    lm_cfg = lm.LMConfig.tiny()
    params = lm.init_params(jax.random.key(0), lm_cfg)
    mesh = Mesh(np.array(jax.devices()).reshape(4, 2), ("data", "model"))
    tp = lm.shard_params_tp(params, mesh)
    toks = jnp.asarray(
        np.random.default_rng(3).integers(0, 257, (8, 24), dtype=np.int64)
    )
    logits, cache = lm.forward(params, toks, lm_cfg,
                               capture=("blocks.2.hook_resid_pre",))
    lt, ct = lm.forward(tp, toks, lm_cfg, capture=("blocks.2.hook_resid_pre",))
    np.testing.assert_allclose(np.asarray(logits), np.asarray(lt),
                               rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(
        np.asarray(cache["blocks.2.hook_resid_pre"]),
        np.asarray(ct["blocks.2.hook_resid_pre"]), rtol=1e-4, atol=1e-5,
    )


def test_tp_harvest_through_buffer_and_trainer():
    """The production pipeline with TENSOR-PARALLEL harvest params: the
    buffer's harvest dispatch takes the TP layout as-is (no code changes),
    and the served stream matches the replicated-params buffer."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    lm_cfg = lm.LMConfig.tiny()
    pair = [lm.init_params(jax.random.key(i), lm_cfg) for i in (0, 1)]
    rng = np.random.default_rng(5)
    toks = rng.integers(0, 257, size=(64, 17), dtype=np.int64)
    cfg = CrossCoderConfig(
        d_in=lm_cfg.d_model, dict_size=64, n_models=2, batch_size=16,
        buffer_mult=32, seq_len=17, model_batch_size=8, norm_calib_batches=1,
        hook_point="blocks.2.hook_resid_pre", num_tokens=16 * 6,
        enc_dtype="fp32", data_axis_size=4, model_axis_size=2,
        log_backend="null", prefetch=False,
    )
    mesh = mesh_lib.mesh_from_cfg(cfg)
    sh = NamedSharding(mesh, P("data", None))
    dense = make_buffer(cfg, lm_cfg, pair, toks, batch_sharding=sh)
    tp_pair = [lm.shard_params_tp(p, mesh) for p in pair]
    tp_buf = make_buffer(cfg, lm_cfg, tp_pair, toks, batch_sharding=sh)
    np.testing.assert_allclose(tp_buf.normalisation_factor,
                               dense.normalisation_factor, rtol=1e-5)
    for _ in range(4):
        # the TP forward's ~1e-6 fp32 deltas occasionally cross a bf16
        # store-rounding boundary: allow 1-ulp (~0.8%) bf16 differences
        np.testing.assert_allclose(tp_buf.next(), dense.next(),
                                   rtol=1e-2, atol=1e-2)
    trainer = Trainer(cfg, tp_buf, mesh=mesh)
    m = trainer.step()
    assert np.isfinite(float(jax.device_get(m["loss"])))
    trainer.close()


def test_from_torch_state_dict_places_into_tp_shards():
    """Loading HF-format weights with shardings= places every leaf directly
    in its tensor-parallel layout (peak per-device memory = shard size),
    value-identical to the unsharded conversion."""
    from jax.sharding import Mesh

    lm_cfg = lm.LMConfig.tiny()
    rng = np.random.default_rng(9)
    D, F = lm_cfg.d_model, lm_cfg.d_ff
    qd = lm_cfg.n_heads * lm_cfg.head_dim
    kd = lm_cfg.n_kv_heads * lm_cfg.head_dim
    sd = {"model.embed_tokens.weight": rng.normal(size=(lm_cfg.vocab_size, D)).astype(np.float32),
          "model.norm.weight": rng.normal(size=(D,)).astype(np.float32)}
    for i in range(lm_cfg.n_layers):
        p = f"model.layers.{i}."
        for name, shape in (
            ("input_layernorm.weight", (D,)),
            ("post_attention_layernorm.weight", (D,)),
            ("pre_feedforward_layernorm.weight", (D,)),
            ("post_feedforward_layernorm.weight", (D,)),
            ("self_attn.q_proj.weight", (qd, D)),
            ("self_attn.k_proj.weight", (kd, D)),
            ("self_attn.v_proj.weight", (kd, D)),
            ("self_attn.o_proj.weight", (D, qd)),
            ("mlp.gate_proj.weight", (F, D)),
            ("mlp.up_proj.weight", (F, D)),
            ("mlp.down_proj.weight", (D, F)),
        ):
            sd[p + name] = rng.normal(size=shape).astype(np.float32)

    mesh = Mesh(np.array(jax.devices()).reshape(4, 2), ("data", "model"))
    shardings = lm.tp_shardings(mesh)
    tp = lm.from_torch_state_dict(sd, lm_cfg, shardings=shardings)
    plain = lm.from_torch_state_dict(sd, lm_cfg)
    assert tp["layers"]["wq"].sharding.spec == shardings["layers"]["wq"].spec
    assert tp["embed"].sharding.spec == shardings["embed"].spec
    for path in (("embed",), ("layers", "wq"), ("layers", "wo"),
                 ("layers", "w_down"), ("final_norm",)):
        a, b = tp, plain
        for k in path:
            a, b = a[k], b[k]
        np.testing.assert_array_equal(np.asarray(a, np.float32),
                                      np.asarray(b, np.float32))


def test_analysis_paths_take_tp_params():
    """CE-recovered eval and dashboards run with TENSOR-PARALLEL subject
    params unchanged (the 9B analysis story), matching replicated-params
    results to fp32 tolerance."""
    from jax.sharding import Mesh

    from crosscoder_tpu.analysis.ce_eval import get_ce_recovered_metrics
    from crosscoder_tpu.analysis.dashboards import FeatureVisConfig, FeatureVisData
    from crosscoder_tpu.models import crosscoder as cc

    lm_cfg = lm.LMConfig.tiny()
    pair = [lm.init_params(jax.random.key(i), lm_cfg) for i in (0, 1)]
    mesh = Mesh(np.array(jax.devices()).reshape(4, 2), ("data", "model"))
    tp_pair = [lm.shard_params_tp(p, mesh) for p in pair]
    rng = np.random.default_rng(11)
    toks = rng.integers(0, 257, size=(8, 24), dtype=np.int64)
    ccfg = CrossCoderConfig(d_in=lm_cfg.d_model, dict_size=64, batch_size=16,
                            enc_dtype="fp32",
                            hook_point="blocks.2.hook_resid_pre")
    cc_params = cc.init_params(jax.random.key(3), ccfg)

    from crosscoder_tpu.analysis.ce_eval import crosscoder_reconstruct_fn

    rec = crosscoder_reconstruct_fn(cc_params, ccfg)
    dense = get_ce_recovered_metrics(toks, lm_cfg, pair,
                                     "blocks.2.hook_resid_pre", rec, chunk=4)
    tp = get_ce_recovered_metrics(toks, lm_cfg, tp_pair,
                                  "blocks.2.hook_resid_pre", rec, chunk=4)
    for k in dense:
        np.testing.assert_allclose(tp[k], dense[k], rtol=1e-3, atol=1e-4)

    vis_cfg = FeatureVisConfig(hook_point="blocks.2.hook_resid_pre",
                               features=(3, 5), minibatch_size_tokens=4)
    d1 = FeatureVisData.create(cc_params, ccfg, lm_cfg, pair, toks, vis_cfg)
    d2 = FeatureVisData.create(cc_params, ccfg, lm_cfg, tp_pair, toks, vis_cfg)
    for f1, f2 in zip(d1.features, d2.features):
        np.testing.assert_allclose(f2.max_act, f1.max_act, rtol=1e-3, atol=1e-5)


def test_tp_forward_never_allgathers_weights():
    """The TP layout's memory claim depends on GSPMD keeping weights
    sharded through the forward — annotations alone don't guarantee it.
    Assert the compiled HLO contains no weight-sized all-gather (the
    collectives it does insert are activation-sized psums/gathers)."""
    from jax.sharding import Mesh

    lm_cfg = lm.LMConfig.tiny().replace(d_ff=256)   # weights unmistakable
    params = lm.init_params(jax.random.key(0), lm_cfg)
    mesh = Mesh(np.array(jax.devices()).reshape(4, 2), ("data", "model"))
    tp = lm.shard_params_tp(params, mesh)
    toks = jnp.asarray(
        np.random.default_rng(0).integers(0, 257, (8, 24), dtype=np.int64)
    )
    fn = jax.jit(lambda p, t: lm.forward(p, t, lm_cfg,
                                         capture=("blocks.2.hook_resid_pre",)))
    hlo = fn.lower(tp, toks).compile().as_text()
    gathers = [l for l in hlo.splitlines() if "all-gather" in l]
    # derive every FULL (unsharded) weight shape from the config — the
    # layer-stacked leading dim keeps these from colliding with
    # activation shapes like [B,S,d_model]
    L, D, F = lm_cfg.n_layers, lm_cfg.d_model, lm_cfg.d_ff
    qd = lm_cfg.n_heads * lm_cfg.head_dim
    kd = lm_cfg.n_kv_heads * lm_cfg.head_dim
    weight_shapes = [
        f"{L},{D},{F}", f"{L},{F},{D}",            # w_gate/w_up, w_down
        f"{L},{D},{qd}", f"{L},{qd},{D}",          # wq, wo
        f"{L},{D},{kd}",                            # wk/wv
        f"{lm_cfg.vocab_size},{D}",                 # embed
    ]
    offenders = [l for l in gathers if any(w in l for w in weight_shapes)]
    assert not offenders, offenders
    # the assertion must not be vacuous: GSPMD does insert activation
    # collectives in this program
    assert gathers, "expected activation-sized all-gathers in the TP HLO"
