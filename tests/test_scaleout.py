"""BASELINE.json scale-out configs 3-5 as integration smokes (scaled-down
shapes, full production code paths, 8-virtual-device mesh):

- config 3: Gemma-2-9B geometry (d_model 3584) — bigger d_in through the
  sharded train step and the 9B LMConfig mapping;
- config 4: 3-way crosscoder (n_models=3 stack) through harvest → train;
- config 5: multi-layer crosscoder (3 hook points jointly) with the
  layer-axis (source-axis) sharding mode.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from crosscoder_tpu.config import CrossCoderConfig
from crosscoder_tpu.data.buffer import make_buffer
from crosscoder_tpu.models import lm
from crosscoder_tpu.parallel import mesh as mesh_lib
from crosscoder_tpu.train.trainer import Trainer


def test_config3_gemma9b_geometry():
    """d_model 3584 (Gemma-2-9B residual width): the 9B LMConfig maps the
    right shapes and the DP×TP step trains at that d_in."""
    lm9 = lm.config_for("gemma-2-9b")
    assert lm9.d_model == 3584 and lm9.n_layers == 42
    assert lm.config_for("gemma-2-9b-it") == lm9

    cfg = CrossCoderConfig(
        d_in=3584, dict_size=4096, n_models=2, batch_size=64,
        enc_dtype="bf16", data_axis_size=4, model_axis_size=2,
        num_tokens=64 * 10, log_backend="null", prefetch=False,
    )
    mesh = mesh_lib.mesh_from_cfg(cfg)
    trainer = Trainer(cfg, mesh=mesh)          # synthetic source at 3584
    m = trainer.step()
    assert np.isfinite(float(jax.device_get(m["loss"])))
    # dict axis is genuinely TP-sharded at this width
    assert trainer.state.params["W_enc"].sharding.spec[2] == "model"
    trainer.close()


@pytest.fixture(scope="module")
def lm_trio():
    cfg = lm.LMConfig.tiny()
    return cfg, [lm.init_params(jax.random.key(i), cfg) for i in range(3)]


def test_config4_three_way_stack_end_to_end(lm_trio):
    """n_models=3 (base/IT/code-tuned analogue): harvest all three models'
    streams and train the 3-source crosscoder on the mesh."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    lm_cfg, trio = lm_trio
    rng = np.random.default_rng(0)
    toks = rng.integers(0, 257, size=(128, 17), dtype=np.int64)
    cfg = CrossCoderConfig(
        d_in=lm_cfg.d_model, dict_size=128, n_models=3, batch_size=32,
        buffer_mult=32, seq_len=17, model_batch_size=8, norm_calib_batches=1,
        hook_point="blocks.2.hook_resid_pre", num_tokens=32 * 6,
        enc_dtype="fp32", log_backend="null", prefetch=False,
    )
    mesh = mesh_lib.mesh_from_cfg(cfg)
    buf = make_buffer(cfg, lm_cfg, trio, toks,
                      batch_sharding=NamedSharding(mesh, P("data", None)))
    assert buf._store.shape[1] == 3
    trainer = Trainer(cfg, buf, mesh=mesh)
    losses = [float(jax.device_get(trainer.step()["loss"])) for _ in range(3)]
    assert all(np.isfinite(losses))
    trainer.close()


def test_config5_multilayer_with_source_axis_shard(lm_trio):
    """Layers {1,2,3} jointly (the {6,13,20} analogue): n_sources = 2×3 = 6,
    sharded over the model axis (cfg.shard_sources — the 'layer-axis shard'
    BASELINE names), trained from a real multi-hook harvest."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    lm_cfg, trio = lm_trio
    rng = np.random.default_rng(1)
    toks = rng.integers(0, 257, size=(128, 17), dtype=np.int64)
    hooks = tuple(f"blocks.{i}.hook_resid_pre" for i in (1, 2, 3))
    cfg = CrossCoderConfig(
        d_in=lm_cfg.d_model, dict_size=128, n_models=2, hook_points=hooks,
        batch_size=32, buffer_mult=32, seq_len=17, model_batch_size=8,
        norm_calib_batches=1, num_tokens=32 * 6, enc_dtype="fp32",
        data_axis_size=4, model_axis_size=2, shard_sources=True,
        log_backend="null", prefetch=False,
    )
    assert cfg.n_sources == 6
    mesh = mesh_lib.mesh_from_cfg(cfg)
    buf = make_buffer(cfg, lm_cfg, trio[:2], toks,
                      batch_sharding=NamedSharding(mesh, P("data", None)))
    trainer = Trainer(cfg, buf, mesh=mesh)
    m = trainer.step()
    assert np.isfinite(float(jax.device_get(m["loss"])))
    # the source axis is the sharded one
    assert trainer.state.params["W_enc"].sharding.spec[0] == "model"
    trainer.close()
