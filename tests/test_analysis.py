"""Tests for decoder-space analysis (reference analysis.py) and the
CE-recovered splicing eval (reference nb:cells 27-30), using constructed
decoders with known geometry and the tiny fake-LM with exact reconstruction
oracles."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from crosscoder_tpu.analysis import (
    cosine_sims,
    get_ce_recovered_metrics,
    relative_norms,
    relative_norm_histogram,
    shared_latent_mask,
)
from crosscoder_tpu.analysis.ce_eval import crosscoder_reconstruct_fn
from crosscoder_tpu.config import CrossCoderConfig
from crosscoder_tpu.models import crosscoder as cc
from crosscoder_tpu.models import lm


@pytest.fixture
def known_params():
    """W_dec [4 latents, 2 sources, 3 dims] with hand-built geometry:
    latent 0: A-only; latent 1: B-only; latent 2: shared, identical rows;
    latent 3: shared norms, opposite directions."""
    w = np.zeros((4, 2, 3), np.float32)
    w[0, 0] = [2, 0, 0]
    w[1, 1] = [0, 3, 0]
    w[2, 0] = [1, 1, 0]; w[2, 1] = [1, 1, 0]
    w[3, 0] = [0, 0, 5]; w[3, 1] = [0, 0, -5]
    return {"W_dec": jnp.asarray(w)}


def test_relative_norms_clusters(known_params):
    r = np.asarray(relative_norms(known_params))
    np.testing.assert_allclose(r, [0.0, 1.0, 0.5, 0.5], atol=1e-6)
    # reference analysis.py:12 measures source 1's share; flipping the pair
    # mirrors it
    r_flip = np.asarray(relative_norms(known_params, pair=(1, 0)))
    np.testing.assert_allclose(r_flip, 1 - r, atol=1e-6)


def test_shared_mask_band(known_params):
    mask = np.asarray(shared_latent_mask(known_params))
    np.testing.assert_array_equal(mask, [False, False, True, True])


def test_cosine_sims(known_params):
    sims = np.asarray(cosine_sims(known_params))
    assert sims[2] == pytest.approx(1.0, abs=1e-6)
    assert sims[3] == pytest.approx(-1.0, abs=1e-6)


def test_histogram_data(known_params):
    counts, edges = relative_norm_histogram(known_params, bins=200)
    assert counts.shape == (200,) and edges.shape == (201,)
    assert int(counts.sum()) == 4
    assert int(counts[100]) == 2          # the two r=0.5 latents


@pytest.fixture(scope="module")
def eval_setup():
    lm_cfg = lm.LMConfig.tiny()
    pa = lm.init_params(jax.random.key(0), lm_cfg)
    pb = lm.init_params(jax.random.key(1), lm_cfg)
    rng = np.random.default_rng(5)
    tokens = rng.integers(0, 257, size=(8, 24), dtype=np.int64)
    return lm_cfg, [pa, pb], tokens


HP = "blocks.2.hook_resid_pre"


def test_ce_recovered_identity_is_one(eval_setup):
    """Perfect reconstruction ⇒ spliced forward == clean forward ⇒
    ce_recovered = 1 for both models (the nb:cell 29 fixed point)."""
    lm_cfg, params, tokens = eval_setup
    m = get_ce_recovered_metrics(tokens, lm_cfg, params, HP, lambda x: x)
    for tag in "AB":
        assert m[f"ce_recovered_{tag}"] == pytest.approx(1.0, abs=1e-3)
        assert m[f"ce_spliced_{tag}"] == pytest.approx(m[f"ce_clean_{tag}"], abs=1e-3)
        assert m[f"ce_zero_abl_{tag}"] != pytest.approx(m[f"ce_clean_{tag}"], abs=1e-4)


def test_ce_recovered_zero_reconstruction(eval_setup):
    """All-zero reconstruction: recovered is well below the identity oracle's
    1.0 and the reported components satisfy the nb:cell 29 formula exactly.
    (An *untrained* LM can have zero-abl CE ≈ uniform < clean CE, so the
    real-model expectation 'recovered ≈ 0' is not an invariant here.)"""
    lm_cfg, params, tokens = eval_setup
    m = get_ce_recovered_metrics(tokens, lm_cfg, params, HP, jnp.zeros_like)
    for tag in "AB":
        clean, zero, spliced = (
            m[f"ce_clean_{tag}"], m[f"ce_zero_abl_{tag}"], m[f"ce_spliced_{tag}"]
        )
        assert m[f"ce_recovered_{tag}"] == pytest.approx(
            1.0 - (spliced - clean) / (zero - clean), abs=1e-9
        )
        assert m[f"ce_diff_{tag}"] == pytest.approx(spliced - clean, abs=1e-9)
        assert abs(m[f"ce_recovered_{tag}"] - 1.0) > 0.01
        assert spliced != pytest.approx(clean, abs=1e-4)


def test_ce_recovered_with_crosscoder(eval_setup):
    """The real path: a random crosscoder through crosscoder_reconstruct_fn
    yields finite metrics strictly between the oracles."""
    lm_cfg, params, tokens = eval_setup
    cfg = CrossCoderConfig(d_in=lm_cfg.d_model, dict_size=128, batch_size=32,
                           enc_dtype="fp32")
    cc_params = cc.init_params(jax.random.key(2), cfg)
    m = get_ce_recovered_metrics(
        tokens, lm_cfg, params, HP, crosscoder_reconstruct_fn(cc_params, cfg)
    )
    for tag in "AB":
        assert np.isfinite(m[f"ce_recovered_{tag}"])
        # a random crosscoder is not the identity: its splice visibly moves CE
        assert m[f"ce_spliced_{tag}"] != pytest.approx(m[f"ce_clean_{tag}"], abs=1e-3)


def test_ce_eval_ragged_tail_counts_all_sequences(eval_setup):
    """A token count not divisible by the chunk still evaluates every
    sequence (seq-weighted means): 8 seqs at chunk=3 == chunk=4."""
    lm_cfg, params, tokens = eval_setup
    a = get_ce_recovered_metrics(tokens, lm_cfg, params, HP, lambda x: x, chunk=3)
    b = get_ce_recovered_metrics(tokens, lm_cfg, params, HP, lambda x: x, chunk=4)
    for tag in "AB":
        assert a[f"ce_clean_{tag}"] == pytest.approx(b[f"ce_clean_{tag}"], abs=1e-4)
    with pytest.raises(ValueError):
        get_ce_recovered_metrics(tokens[:0], lm_cfg, params, HP, lambda x: x)


def test_eval_ce_script_demo_smoke(tmp_path):
    """scripts/eval_ce.py --demo end-to-end with tiny budgets: every stage
    (LM pair training, harvest, crosscoder training, fold, splice eval,
    oracles) runs and emits the full metric surface. Budgets are too small
    for the quality gate itself — that's asserted by the default-budget run
    recorded in artifacts/ce_gate_demo.json."""
    import json
    import subprocess
    import sys
    from pathlib import Path

    out = tmp_path / "gate.json"
    # subprocess, not in-process main(): --demo sets jax_platforms=cpu,
    # a process-global backend choice that must not leak into (or be
    # silently no-op'd by) this test session's already-initialized backend
    script = Path(__file__).parent.parent / "scripts" / "eval_ce.py"
    proc = subprocess.run(
        [sys.executable, str(script), "--demo", "--demo-lm-steps", "30",
         "--demo-cc-steps", "20", "--n-seqs", "8", "--out", str(out)],
        capture_output=True, text=True, timeout=300,
        cwd=Path(__file__).parent.parent,
    )
    assert proc.returncode == 0, proc.stderr[-2000:]
    m = json.loads(out.read_text())
    for tag in "AB":
        for k in ("ce_clean", "ce_zero_abl", "ce_spliced", "ce_recovered"):
            assert np.isfinite(m[f"{k}_{tag}"])
    assert abs(m["oracle_identity_recovered"]["A"] - 1) < 1e-3
    assert "gate_pass" in m
    # tiny budgets are NOT the recorded-expectation run: the demo band is
    # reported as informational but must not gate here
    assert m["band_checked"] is False
    assert set(m["distance_from_expected"]) == {"A", "B"}
    assert m["expected_recovered"] == {"A": 1.0076, "B": 0.9864}


def test_replicate_script_demo_smoke(tmp_path):
    """scripts/replicate.py --demo with tiny budgets: all four stages run
    and the report/dashboards artifacts land. Quality gates are asserted by
    the default-budget run (artifacts/replicate_demo)."""
    import json
    import subprocess
    import sys
    from pathlib import Path

    script = Path(__file__).parent.parent / "scripts" / "replicate.py"
    out = tmp_path / "rep"
    proc = subprocess.run(
        [sys.executable, str(script), "--demo", "--demo-lm-steps", "30",
         "--demo-cc-steps", "20", "--n-seqs", "8", "--out", str(out)],
        capture_output=True, text=True, timeout=300,
        cwd=Path(__file__).parent.parent,
    )
    assert proc.returncode == 0, proc.stderr[-2000:]
    report = json.loads((out / "replicate_report.json").read_text())
    assert report["decoder"]["d_hidden"] == 1024
    assert "ce_recovered_A" in report["ce"]
    assert (out / "dashboards.html").exists()
    assert "checks" in report and "all_pass" in report["checks"]


def test_firing_rates_and_dead_fraction():
    """firing_rates counts strictly-positive latent activations per row;
    a latent whose encoder row is strongly negative never fires and shows
    up in dead_latent_fraction."""
    from crosscoder_tpu.analysis.decoder import dead_latent_fraction, firing_rates
    from crosscoder_tpu.config import CrossCoderConfig
    from crosscoder_tpu.models import crosscoder as cc

    cfg = CrossCoderConfig(d_in=8, dict_size=32, n_models=2, batch_size=16,
                           enc_dtype="fp32")
    params = dict(cc.init_params(jax.random.key(0), cfg))
    # kill latent 5: large negative bias guarantees pre-act < 0 everywhere
    params["b_enc"] = params["b_enc"].at[5].set(-1e6)
    batches = [np.asarray(jax.random.normal(jax.random.key(i), (16, 2, 8)))
               for i in range(3)]
    rates = firing_rates(params, cfg, batches)
    assert rates.shape == (32,)
    assert rates[5] == 0.0
    assert 0.0 <= rates.min() and rates.max() <= 1.0
    # oracle: direct encode over the concatenated batches
    f = np.asarray(cc.encode(params, jnp.asarray(np.concatenate(batches)), cfg))
    np.testing.assert_allclose(rates, (f > 0).mean(0), atol=1e-12)
    assert dead_latent_fraction(rates) >= 1 / 32
