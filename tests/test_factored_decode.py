"""Factored TopK decode (cfg.factored_decode, the Pallas tier): the
forward through the k active rows + dense-matmul backward must reproduce
the dense TopK path's losses AND parameter gradients exactly (the
backward IS the dense backward; the forward is the same sum restricted to
its nonzero terms). Runs the kernels in Pallas interpreter mode on CPU.

No reference counterpart — the reference decode is always dense
(reference crosscoder.py:82-89); this is the TPU build's native tier.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from crosscoder_tpu.config import CrossCoderConfig
from crosscoder_tpu.models import crosscoder as cc
from crosscoder_tpu.ops import topk_pallas


@pytest.fixture(autouse=True)
def _interpret():
    topk_pallas.set_interpret(True)
    yield
    topk_pallas.set_interpret(False)


def _cfgs(**kw):
    base = dict(d_in=24, dict_size=256, batch_size=64, enc_dtype="fp32",
                activation="topk", topk_k=8, l1_coeff=0.0, log_backend="null")
    base.update(kw)
    dense = CrossCoderConfig(**base, factored_decode="off")
    return dense, dense.replace(factored_decode="on")


def _data(cfg, seed=0):
    rng = np.random.default_rng(seed)
    x = rng.normal(size=(cfg.batch_size, cfg.n_sources, cfg.d_in)).astype(np.float32)
    return cc.init_params(jax.random.key(1), cfg), jnp.asarray(x)


def test_dispatch_gates():
    dense, fact = _cfgs()
    assert not cc.use_factored_decode(dense)
    assert cc.use_factored_decode(fact)            # "on" + interpret forced
    # auto requires dict >= 2^17 (gather-vs-matmul crossover)
    assert not cc.use_factored_decode(fact.replace(factored_decode="auto"))
    # nonzero L1 objective is unsound on this path (no grad through vals)
    with pytest.raises(ValueError, match="factored_decode"):
        fact.replace(l1_coeff=0.5)
    # and auto silently falls back rather than erroring
    assert not cc.use_factored_decode(
        dense.replace(l1_coeff=0.5, factored_decode="auto")
    )


def test_losses_match_dense():
    dense_cfg, fact_cfg = _cfgs()
    params, x = _data(dense_cfg)
    ld = cc.get_losses(params, x, dense_cfg)
    lf = cc.get_losses(params, x, fact_cfg)
    np.testing.assert_allclose(float(ld.l2_loss), float(lf.l2_loss), rtol=1e-5)
    np.testing.assert_allclose(float(ld.l1_loss), float(lf.l1_loss), rtol=1e-5)
    assert float(ld.l0_loss) == float(lf.l0_loss)
    np.testing.assert_allclose(
        np.asarray(ld.explained_variance),
        np.asarray(lf.explained_variance), rtol=1e-4,
    )


def test_grads_match_dense_exactly():
    """The factored backward runs the SAME dense matmuls + mask as the
    dense path, so parameter gradients agree to fp tolerance (not just
    statistically)."""
    dense_cfg, fact_cfg = _cfgs()
    params, x = _data(dense_cfg, seed=3)

    def grad_of(cfg):
        def fn(p):
            loss, _ = cc.training_loss(p, x, 0.0, cfg, with_metrics=False)
            return loss
        return jax.grad(fn)(params)

    gd, gf = grad_of(dense_cfg), grad_of(fact_cfg)
    for k in gd:
        np.testing.assert_allclose(
            np.asarray(gd[k]), np.asarray(gf[k]), rtol=2e-5, atol=1e-7,
            err_msg=f"grad mismatch on {k}",
        )


def test_auxk_composes_with_factored():
    """AuxK's ranking consumes the pre-acts the factored path already
    computed; the aux loss must match the dense path's."""
    dense_cfg, fact_cfg = _cfgs(aux_k=16, aux_k_coeff=0.5)
    params, x = _data(dense_cfg, seed=5)
    dead = np.zeros(dense_cfg.dict_size, bool)
    dead[::3] = True
    dead = jnp.asarray(dead)
    ld = cc.get_losses(params, x, dense_cfg, dead_mask=dead, track_fired=True)
    lf = cc.get_losses(params, x, fact_cfg, dead_mask=dead, track_fired=True)
    np.testing.assert_allclose(float(ld.aux_loss), float(lf.aux_loss), rtol=1e-4)
    np.testing.assert_array_equal(np.asarray(ld.fired), np.asarray(lf.fired))


def test_sparsify_matches_mask():
    h = jax.random.normal(jax.random.key(0), (96, 512), jnp.float32)
    f = np.asarray(jax.jit(lambda x: topk_pallas.topk(x, 8, True))(h))
    vals, idx = topk_pallas.sparsify(jnp.asarray(f), 8, interpret=True)
    v, i = np.asarray(vals), np.asarray(idx)
    for r in range(f.shape[0]):
        nz = np.nonzero(f[r])[0]
        assert list(i[r][v[r] != 0]) == list(nz)
        assert np.array_equal(v[r][v[r] != 0], f[r][nz])
        assert np.all(v[r][len(nz):] == 0)


def test_sparsify_wide_single_chunk_fits_vmem():
    """Width 8064 (<= 8192 but not %2048): the single-chunk leg must shrink
    its row block so the f32 scratch + input block stay inside the module's
    VMEM budget — 256 rows at 8 B/element is 16.5 MB, which Mosaic refuses
    to compile; the pre-fix geometry passed sparsify_supported and then
    died at compile time for direct callers."""
    width = 8064
    assert topk_pallas.sparsify_supported(width, 8)
    for itemsize in (4, 2):
        rows = topk_pallas._sparsify_rows(width, 4096, itemsize)
        assert rows % 32 == 0 and rows >= 32
        working_set = rows * width * (4 + itemsize)
        assert working_set <= topk_pallas._VMEM_BUDGET_BYTES, (rows, working_set)
    # and the shrunk geometry still produces correct output (interpret mode)
    h = jax.random.normal(jax.random.key(3), (64, width), jnp.float32)
    f = np.asarray(jax.jit(lambda x: topk_pallas.topk(x, 8, True))(h))
    vals, idx = topk_pallas.sparsify(jnp.asarray(f), 8, interpret=True)
    v, i = np.asarray(vals), np.asarray(idx)
    for r in range(f.shape[0]):
        nz = np.nonzero(f[r])[0]
        assert list(i[r][v[r] != 0]) == list(nz)
        assert np.array_equal(v[r][v[r] != 0], f[r][nz])
