"""Tests for the persistent-compile-cache helper
(crosscoder_tpu/utils/compile_cache.py)."""

import jax
import pytest


def test_compile_cache_enable(tmp_path, monkeypatch):
    """compile_cache.enable(): explicit dir, env override, env-empty disable;
    process-global jax config restored whatever happens."""
    from crosscoder_tpu.utils import compile_cache

    prev_dir = jax.config.jax_compilation_cache_dir
    prev_min = jax.config.jax_persistent_cache_min_compile_time_secs
    try:
        d = compile_cache.enable(str(tmp_path / "cc"))
        assert d == str(tmp_path / "cc")
        monkeypatch.setenv("JAX_COMPILE_CACHE", str(tmp_path / "env"))
        assert compile_cache.enable() == str(tmp_path / "env")
        monkeypatch.setenv("JAX_COMPILE_CACHE", "")
        assert compile_cache.enable() is None
        monkeypatch.delenv("JAX_COMPILE_CACHE")
        # default lands inside the repo
        assert compile_cache.enable().endswith(".jax_cache")
    finally:
        jax.config.update("jax_compilation_cache_dir", prev_dir)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", prev_min)
