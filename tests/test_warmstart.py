"""JumpReLU θ warm-start (train/warmstart.py): the transplant must carry
the trained leaves, set log_theta to the calibrated threshold, produce an
immediate effective L0 near k, and train under the JumpReLU objective."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from crosscoder_tpu.config import CrossCoderConfig
from crosscoder_tpu.data.synthetic import SyntheticActivationSource
from crosscoder_tpu.models import crosscoder as cc
from crosscoder_tpu.train.trainer import Trainer
from crosscoder_tpu.train.warmstart import jumprelu_warmstart_params

K = 8


def _cfg(**kw):
    base = dict(d_in=16, dict_size=256, batch_size=64, num_tokens=64 * 200,
                enc_dtype="fp32", log_backend="null", seed=5)
    base.update(kw)
    return CrossCoderConfig(**base)


def test_warmstart_transplant_and_l0():
    cfg1 = _cfg(activation="batchtopk", topk_k=K, l1_coeff=0.0)
    tr = Trainer(cfg1, buffer=SyntheticActivationSource(cfg1))
    for _ in range(30):
        tr.step()
    src = SyntheticActivationSource(cfg1)
    batches = [src.next() for _ in range(3)]
    cfg2 = _cfg(activation="jumprelu", l1_coeff=0.0, l0_coeff=1.0,
                jumprelu_bandwidth=0.03)
    p1 = jax.device_get(tr.state.params)
    p2 = jumprelu_warmstart_params(tr.state.params, cfg1, cfg2, batches)
    tr.close()

    # carried leaves identical; log_theta at a single calibrated value
    for k in ("W_enc", "W_dec", "b_enc", "b_dec"):
        np.testing.assert_array_equal(np.asarray(p1[k]), np.asarray(p2[k]))
    theta = np.exp(np.asarray(p2["log_theta"]))
    assert theta.shape == (cfg2.dict_size,)
    assert np.allclose(theta, theta[0]) and theta[0] > 0

    # immediate effective L0 is in the k regime, not the dense regime
    x = jnp.asarray(batches[0])
    f = cc.encode(p2, x.astype(jnp.float32), cfg2)
    l0 = float(jnp.mean(jnp.sum((f > 0).astype(jnp.float32), axis=-1)))
    assert K / 4 <= l0 <= 4 * K, l0

    # and the jumprelu trainer runs from the transplant
    tr2 = Trainer(cfg2, buffer=SyntheticActivationSource(cfg2))
    tr2.state = tr2.state._replace(
        params=jax.device_put(
            {k: jnp.asarray(v) for k, v in p2.items()},
            jax.tree_util.tree_map(lambda s: s, tr2._state_shardings.params),
        )
    )
    losses = [float(np.asarray(jax.device_get(tr2.step()["loss"])))
              for _ in range(10)]
    assert all(np.isfinite(losses))
    tr2.close()


def test_warmstart_validation():
    cfg1 = _cfg(activation="batchtopk", topk_k=K, l1_coeff=0.0)
    params = cc.init_params(jax.random.key(0), cfg1)
    src = SyntheticActivationSource(cfg1)
    batches = [src.next()]
    with pytest.raises(ValueError, match="jumprelu"):
        jumprelu_warmstart_params(params, cfg1, cfg1, batches)
    cfg_relu = _cfg(activation="relu")
    cfg2 = _cfg(activation="jumprelu", l1_coeff=0.0)
    with pytest.raises(ValueError, match="topk|batchtopk"):
        jumprelu_warmstart_params(params, cfg_relu, cfg2, batches)
