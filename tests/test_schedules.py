"""Schedule parity: jnp schedules vs the reference formulas, including the
actual lr trace a torch LambdaLR would produce (SURVEY.md §4)."""

import numpy as np
import torch

from crosscoder_tpu.config import CrossCoderConfig
from crosscoder_tpu.train import schedules

from torch_oracle import oracle_l1_coeff, oracle_lr_lambda


def cfg_with_steps(total_steps: int, **kw) -> CrossCoderConfig:
    return CrossCoderConfig(num_tokens=total_steps * 64, batch_size=64, **kw)


def test_lr_schedule_matches_reference_formula():
    cfg = cfg_with_steps(1000, lr=5e-5)
    f = schedules.lr_schedule(cfg)
    for step in [0, 1, 399, 799, 800, 900, 999, 1000]:
        expect = cfg.lr * oracle_lr_lambda(step, 1000)
        np.testing.assert_allclose(float(f(step)), expect, rtol=3e-6)


def test_l1_schedule_matches_reference_formula():
    cfg = cfg_with_steps(1000, l1_coeff=2.0)
    f = schedules.l1_coeff_schedule(cfg)
    for step in [0, 1, 25, 49, 50, 51, 500, 999]:
        expect = oracle_l1_coeff(step, 1000, 2.0)
        np.testing.assert_allclose(float(f(step)), expect, rtol=3e-6)


def test_lr_trace_matches_torch_lambdalr():
    """The lr actually used on optimizer step i must match torch's LambdaLR
    driven exactly as the reference drives it (scheduler.step() after each
    optimizer step, trainer.py:47-48)."""
    total = 50
    cfg = cfg_with_steps(total, lr=1e-3)
    f = schedules.lr_schedule(cfg)

    p = torch.nn.Parameter(torch.zeros(1))
    opt = torch.optim.Adam([p], lr=cfg.lr)
    sched = torch.optim.lr_scheduler.LambdaLR(
        opt, lambda step: 1.0 if step < 0.8 * total else 1.0 - (step - 0.8 * total) / (0.2 * total)
    )
    for i in range(total):
        torch_lr = opt.param_groups[0]["lr"]  # lr applied at step i
        np.testing.assert_allclose(float(f(i)), torch_lr, rtol=3e-6, err_msg=f"step {i}")
        opt.step()
        sched.step()


def test_schedules_accept_traced_arrays():
    import jax
    import jax.numpy as jnp

    cfg = cfg_with_steps(100)
    f = schedules.lr_schedule(cfg)
    g = schedules.l1_coeff_schedule(cfg)
    out = jax.jit(lambda s: (f(s), g(s)))(jnp.asarray(90, jnp.int32))
    assert np.isfinite(float(out[0])) and np.isfinite(float(out[1]))
