"""BatchTopK through the chunked Pallas global-threshold kernels
(ops/topk_pallas.batchtopk / batchtopk_fixed, interpret mode on CPU):
bit-identical masks vs the dense oracle (activations.batchtopk with the
kernel forced off) — including ties at the threshold, which BatchTopK
keeps in full — plus the straight-through gradient, the supported-shape
gate, and the activations-layer dispatch (kernel when live+supported,
dense fallback otherwise). All CPU, tier-1."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from crosscoder_tpu.ops import activations as act
from crosscoder_tpu.ops import topk_pallas


@pytest.fixture(autouse=True)
def _interpret_kernels():
    """Run every Pallas dispatch through the interpreter (the CPU
    stand-in for the TPU kernel, same as test_topk_pallas / test_quant);
    also flips batchtopk_kernel_enabled() on for the dispatch tests."""
    topk_pallas.set_interpret(True)
    yield
    topk_pallas.set_interpret(False)


def _dense(h, k):
    return np.asarray(act.batchtopk(h, k, use_pallas=False))


# width cases: chunk-divisible multi-chunk (2 x _CHUNK_WIDTH), a single
# non-chunk-divisible VMEM-sized chunk, and the lane-aligned minimum;
# batch cases include a non-multiple-of-32 row count (the geometry's
# zero-padded tail rows must stay invisible to the global count)
@pytest.mark.parametrize("dtype", [jnp.float32, jnp.bfloat16])
@pytest.mark.parametrize("B,width,k", [
    (16, 8192, 4),     # 2 chunks of _CHUNK_WIDTH
    (5, 640, 3),       # single chunk, width % _CHUNK_WIDTH != 0, row pad
    (33, 256, 2),      # minimum width, row pad
])
def test_batchtopk_matches_dense_oracle(B, width, k, dtype):
    h = jax.random.normal(jax.random.key(B * width + k), (B, width), dtype)
    out = topk_pallas.batchtopk(h, k, True)
    assert out.dtype == h.dtype
    np.testing.assert_array_equal(np.asarray(out), _dense(h, k))


def test_batchtopk_keeps_all_ties_at_threshold():
    # plant more copies of the threshold value than the budget has room
    # for: BatchTopK's contract keeps every tie (mask is >=, no tie quota)
    h = np.full((4, 256), -1.0, np.float32)
    h[0, :7] = 2.0          # 7 entries above ...
    h[1, :6] = 1.0          # ... 6 tied AT the k*B=8-th largest
    out = np.asarray(topk_pallas.batchtopk(jnp.asarray(h), 2, True))
    assert int((out > 0).sum()) == 13
    np.testing.assert_array_equal(out, _dense(jnp.asarray(h), 2))


def test_batchtopk_all_zero_and_full_budget():
    z = jnp.zeros((4, 256), jnp.float32)
    assert int((np.asarray(topk_pallas.batchtopk(z, 3, True)) > 0).sum()) == 0
    # budget >= positive count: every positive entry survives
    h = jax.random.normal(jax.random.key(0), (4, 256), jnp.float32)
    out = np.asarray(topk_pallas.batchtopk(h, 256, True))
    np.testing.assert_array_equal(out > 0, np.asarray(h) > 0)
    np.testing.assert_array_equal(out, _dense(h, 256))


@pytest.mark.parametrize("dtype", [jnp.float32, jnp.bfloat16])
def test_batchtopk_fixed_matches_dense(dtype):
    h = jax.random.normal(jax.random.key(7), (6, 640), dtype)
    # <= 0 thresholds degenerate to the hp > 0 mask in the dense path; the
    # kernel must clamp the sign-set pattern rather than unsigned-compare it
    for threshold in (0.5, 1.25, 0.0, -0.5, -0.0):
        out = topk_pallas.batchtopk_fixed(h, threshold, True)
        expect = np.asarray(act.batchtopk_fixed(h, threshold,
                                                use_pallas=False))
        np.testing.assert_array_equal(np.asarray(out), expect)


def test_batchtopk_gradient_matches_dense():
    # straight-through on the survivors, exactly the dense mask's
    # hp * stop_grad(mask) gradient
    h = jax.random.normal(jax.random.key(3), (8, 512), jnp.float32)
    g_pallas = jax.grad(lambda x: topk_pallas.batchtopk(x, 4, True).sum())(h)
    g_dense = jax.grad(
        lambda x: act.batchtopk(x, 4, use_pallas=False).sum()
    )(h)
    np.testing.assert_array_equal(np.asarray(g_pallas), np.asarray(g_dense))
    gf_pallas = jax.grad(
        lambda x: topk_pallas.batchtopk_fixed(x, 0.5, True).sum()
    )(h)
    gf_dense = jax.grad(
        lambda x: act.batchtopk_fixed(x, 0.5, use_pallas=False).sum()
    )(h)
    np.testing.assert_array_equal(np.asarray(gf_pallas), np.asarray(gf_dense))


def test_batchtopk_supported_gates():
    ok = jnp.zeros((4, 8192), jnp.bfloat16)
    assert topk_pallas.batchtopk_supported(ok, 32)
    assert topk_pallas.batchtopk_supported(jnp.zeros((4, 640)), 4)
    assert not topk_pallas.batchtopk_supported(jnp.zeros((4, 100)), 4)   # lanes
    assert not topk_pallas.batchtopk_supported(jnp.zeros((4, 128)), 4)   # < 256
    assert not topk_pallas.batchtopk_supported(jnp.zeros((256,)), 4)     # ndim
    assert not topk_pallas.batchtopk_supported(ok, 0)                    # k
    assert not topk_pallas.batchtopk_supported(
        jnp.zeros((4, 256), jnp.int32), 4)                               # dtype
    # width neither chunk-divisible nor a single VMEM-sized chunk
    assert not topk_pallas.batchtopk_supported(jnp.zeros((4, 8192 + 128)), 4)


def test_activations_dispatch_routes_to_kernel(monkeypatch):
    # interpret mode makes batchtopk_kernel_enabled() true; a supported
    # shape with use_pallas=True must take the kernel path
    assert topk_pallas.batchtopk_kernel_enabled()
    calls = []
    real = topk_pallas.batchtopk
    monkeypatch.setattr(topk_pallas, "batchtopk",
                        lambda h, k, interpret=False:
                        calls.append("kernel") or real(h, k, interpret))
    h = jax.random.normal(jax.random.key(1), (4, 512), jnp.float32)
    out = act.batchtopk(h, 4, use_pallas=True)
    assert calls == ["kernel"]
    np.testing.assert_array_equal(np.asarray(out), _dense(h, 4))


def test_activations_dispatch_dense_fallback_unsupported(monkeypatch):
    # unsupported width (not lane-aligned) silently falls back dense —
    # the kernel must never be entered
    def _boom(*a, **kw):
        raise AssertionError("kernel entered on unsupported shape")

    monkeypatch.setattr(topk_pallas, "batchtopk", _boom)
    h = jax.random.normal(jax.random.key(2), (4, 100), jnp.float32)
    out = act.batchtopk(h, 4, use_pallas=True)
    np.testing.assert_array_equal(np.asarray(out), _dense(h, 4))


def test_kernel_gated_off_without_optin(monkeypatch):
    # off interpret mode + CPU backend: the hardware gate holds even if
    # the env var is set (the quant.py precedent — TPU-only opt-in)
    topk_pallas.set_interpret(False)
    monkeypatch.setenv("CROSSCODER_BATCHTOPK_PALLAS", "1")
    assert not topk_pallas.batchtopk_kernel_enabled()
