"""Dead-latent resampling (cfg.resample_every; train/resample.py).

Verifies the full Bricken-et-al. surgery against a hand-forced dead set:
decoder rows re-initialized to dec_init_norm residual directions, encoder
columns aligned + downscaled, b_enc zeroed, Adam moments zeroed, tracker
reset — and that ALIVE latents and their moments are untouched. Also runs
under the TP mesh so the where-select surgery is proven sharding-safe.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from crosscoder_tpu.config import CrossCoderConfig
from crosscoder_tpu.data.synthetic import SyntheticActivationSource
from crosscoder_tpu.parallel import mesh as mesh_lib
from crosscoder_tpu.train.trainer import Trainer


def _cfg(**kw):
    base = dict(
        d_in=16, dict_size=64, batch_size=32, num_tokens=32 * 200,
        activation="topk", topk_k=4, l1_coeff=0.0, enc_dtype="fp32",
        resample_every=3, resample_dead_steps=5, log_backend="null", seed=3,
    )
    base.update(kw)
    return CrossCoderConfig(**base)


def _force_dead(tr, idx):
    ssf = np.zeros(tr.cfg.dict_size, np.int32)
    ssf[idx] = 1000
    tr.state = tr.state._replace(aux={"steps_since_fired": jnp.asarray(ssf)})


def _adam_moment_rows(state, key, axis):
    """Collect the Adam mu/nu leaves for one param across the opt chain."""
    rows = []

    def visit(path, leaf):
        names = [getattr(e, "key", None) for e in path]
        if key in names and hasattr(leaf, "ndim") and leaf.ndim >= 1:
            rows.append(np.asarray(leaf))
        return leaf

    jax.tree_util.tree_map_with_path(visit, state.opt_state)
    return rows


def test_resample_replaces_dead_rows():
    cfg = _cfg()
    tr = Trainer(cfg, buffer=SyntheticActivationSource(cfg))
    # a couple of real steps so Adam moments are nonzero
    for _ in range(3):
        tr.step()
    dead_idx = np.asarray([1, 7, 40])
    _force_dead(tr, dead_idx)
    before = jax.device_get(tr.state.params)
    tr._host_step = cfg.resample_every          # land on the boundary
    m = tr.step()
    assert int(np.asarray(m["resampled"])) == len(dead_idx)
    after = jax.device_get(tr.state.params)

    alive = np.setdiff1d(np.arange(cfg.dict_size), dead_idx)
    # dead decoder rows replaced, at dec_init_norm per (latent, source);
    # compare PRE-step-update state indirectly: rows must have moved far
    # from their trained values and the tracker must have reset
    assert not np.allclose(after["W_dec"][dead_idx], before["W_dec"][dead_idx])
    # alive rows only moved by one optimizer step (small)
    assert np.allclose(after["W_dec"][alive], before["W_dec"][alive], atol=5e-2)
    ssf = np.asarray(jax.device_get(tr.state.aux["steps_since_fired"]))
    assert (ssf[dead_idx] <= 1).all()           # reset (then one step passed)


def test_resample_norms_and_moments():
    cfg = _cfg()
    tr = Trainer(cfg, buffer=SyntheticActivationSource(cfg))
    for _ in range(3):
        tr.step()
    dead_idx = np.asarray([2, 3, 50])
    _force_dead(tr, dead_idx)

    # call the resample fn directly so the post-surgery state is inspectable
    from crosscoder_tpu.train.resample import make_resample_fn

    fn = make_resample_fn(cfg, tr.mesh, tr._state_shardings)
    batch, scale = tr._produce_batch()
    state, n = fn(tr.state, batch, scale, jax.random.key(0))
    assert int(np.asarray(n)) == len(dead_idx)
    p = jax.device_get(state.params)

    dec_norms = np.linalg.norm(p["W_dec"][dead_idx], axis=-1)  # [3, n]
    np.testing.assert_allclose(dec_norms, cfg.dec_init_norm, rtol=1e-4)
    assert (p["b_enc"][dead_idx] == 0).all()

    enc_cols = p["W_enc"][:, :, dead_idx]
    enc_norm = np.sqrt((enc_cols ** 2).sum(axis=(0, 1)))
    alive = np.setdiff1d(np.arange(cfg.dict_size), dead_idx)
    alive_norms = np.sqrt((p["W_enc"][:, :, alive] ** 2).sum(axis=(0, 1)))
    np.testing.assert_allclose(enc_norm, 0.2 * alive_norms.mean(), rtol=1e-3)

    # Adam moments of the dead slices zeroed; alive slices untouched
    for arr in _adam_moment_rows(state, "W_dec", 0):
        assert (arr[dead_idx] == 0).all()
        assert np.abs(arr[alive]).max() > 0
    for arr in _adam_moment_rows(state, "W_enc", 2):
        assert (arr[..., dead_idx] == 0).all()
    ssf = np.asarray(jax.device_get(state.aux["steps_since_fired"]))
    assert (ssf[dead_idx] == 0).all()
    tr.close()


@pytest.mark.skipif(jax.device_count() < 8, reason="needs 8 devices")
def test_resample_under_tp_mesh():
    cfg = _cfg(dict_size=128, data_axis_size=4, model_axis_size=2)
    mesh = mesh_lib.make_mesh(4, 2)
    tr = Trainer(cfg, buffer=SyntheticActivationSource(cfg), mesh=mesh)
    for _ in range(2):
        tr.step()
    _force_dead(tr, np.asarray([0, 65]))
    tr.state = jax.device_put(tr.state, tr._state_shardings)
    tr._host_step = cfg.resample_every
    m = tr.step()
    assert int(np.asarray(m["resampled"])) == 2
    assert np.isfinite(float(np.asarray(m["loss"])))
    tr.close()


def test_resample_composes_with_auxk():
    cfg = _cfg(aux_k=8, aux_dead_steps=5, resample_dead_steps=0)
    assert cfg.resample_threshold_steps == 5
    tr = Trainer(cfg, buffer=SyntheticActivationSource(cfg))
    for _ in range(7):
        m = tr.step()
    assert "dead_frac" in m
    tr.close()
