"""Restore-with-respec (Checkpointer.restore(n_data=...)) at fixed
membership: a checkpoint written under one mesh layout restores onto a
different one. The TrainState is layout-free on disk, so the only
mesh-shaped piece is the quant_grads error-feedback residual
(``aux["quant_ef"]``, leading dim = data-axis width): respec drops it,
re-creates it, or resets it to the template zero-init when the widths
disagree — everything else round-trips exactly. This is the in-process
half of the elastic recovery story (tests/test_elastic.py runs the
2-process drill); it also covers deliberate topology changes between runs
(TP-only ↔ DP×TP).
"""

import numpy as np
import pytest

import jax

from crosscoder_tpu.checkpoint.ckpt import Checkpointer
from crosscoder_tpu.config import CrossCoderConfig
from crosscoder_tpu.parallel import mesh as mesh_lib
from crosscoder_tpu.train.trainer import Trainer

pytestmark = pytest.mark.skipif(
    jax.device_count() < 8, reason="needs the 8-virtual-device CPU harness")


def _cfg(workdir, **kw):
    base = dict(d_in=32, dict_size=64, n_models=2, batch_size=16,
                num_tokens=16 * 100, enc_dtype="fp32", log_backend="null",
                checkpoint_dir=str(workdir), prefetch=False,
                quant_grads=True, quant_block=32)
    base.update(kw)
    return CrossCoderConfig(**base)


def _ef_widths(state):
    aux = state.aux or {}
    if "quant_ef" not in aux:
        return None
    return {int(np.asarray(l).shape[0])
            for l in jax.tree_util.tree_leaves(aux["quant_ef"])}


class _Tape:
    def __init__(self):
        self.rows = []

    def log(self, scalars, step):
        if "loss" in scalars:
            self.rows.append((step, float(scalars["loss"]).hex()))

    def close(self):
        pass


def test_tp_to_dptp_round_trip(tmp_path):
    """Save under TP-only (1×8, no quant_ef) → restore onto DP×TP (2×4):
    quant_ef is created fresh at the new width; params/opt/step round-trip
    exactly. Then back: the 2-wide quant_ef is dropped on the way to 1×8."""
    cfg = _cfg(tmp_path)
    tp = mesh_lib.make_mesh(1, 8)
    dptp = mesh_lib.make_mesh(2, 4)

    a = Trainer(cfg, mesh=tp, checkpointer=Checkpointer(base_dir=tmp_path))
    assert _ef_widths(a.state) is None          # n_data=1: no residuals
    for _ in range(2):
        a.step()
    a.save()
    want = {k: np.asarray(Checkpointer._fetch_global(v), np.float32)
            for k, v in a.state.params.items()}
    a.close()

    b = Trainer(cfg, mesh=dptp, checkpointer=Checkpointer(base_dir=tmp_path))
    meta = b.restore()
    assert int(meta["step"]) == 2
    assert _ef_widths(b.state) == {2}           # respec created them
    for k in want:
        np.testing.assert_array_equal(
            np.asarray(Checkpointer._fetch_global(b.state.params[k]),
                       np.float32), want[k], err_msg=k)
    assert np.isfinite(float(jax.device_get(b.step()["loss"])))
    b.save()
    b.close()

    c = Trainer(cfg, mesh=tp, checkpointer=Checkpointer(base_dir=tmp_path))
    meta = c.restore()
    assert int(meta["step"]) == 3
    assert _ef_widths(c.state) is None          # respec dropped them
    assert np.isfinite(float(jax.device_get(c.step()["loss"])))
    c.close()


def test_mismatched_ef_width_resets(tmp_path):
    """A 2-wide quant_ef checkpoint restored onto a 4-wide mesh: the
    residuals cannot be re-laid-out (they are per-device error feedback),
    so respec resets them to the template zero-init at the NEW width."""
    cfg = _cfg(tmp_path)
    a = Trainer(cfg, mesh=mesh_lib.make_mesh(2, 4),
                checkpointer=Checkpointer(base_dir=tmp_path))
    a.step()
    a.save()
    a.close()

    b = Trainer(cfg, mesh=mesh_lib.make_mesh(4, 2),
                checkpointer=Checkpointer(base_dir=tmp_path))
    b.restore()
    assert _ef_widths(b.state) == {4}
    for leaf in jax.tree_util.tree_leaves((b.state.aux or {})["quant_ef"]):
        np.testing.assert_array_equal(np.asarray(leaf), 0)
    assert np.isfinite(float(jax.device_get(b.step()["loss"])))
    b.close()


def test_same_mesh_restores_are_bitwise_deterministic(tmp_path):
    """Two independent restores of one checkpoint onto the SAME mesh must
    replay bitwise-identical loss trajectories (synthetic stream + CPU
    float ops are run-to-run exact) — the determinism contract the elastic
    drill leans on for its survivor-vs-clean-restart comparison."""
    cfg = _cfg(tmp_path, save_every=1000, log_every=1)
    mesh = mesh_lib.make_mesh(2, 4)
    a = Trainer(cfg, mesh=mesh, checkpointer=Checkpointer(base_dir=tmp_path))
    for _ in range(3):
        a.step()
    a.save()
    a.close()

    tapes = []
    for _ in range(2):
        tape = _Tape()
        t = Trainer(cfg, mesh=mesh, logger=tape,
                    checkpointer=Checkpointer(base_dir=tmp_path))
        # pin the exact save: the first replay's own end-of-train save must
        # not become the second replay's (newer) restore point
        t.restore(version_dir=tmp_path / "version_0", save=0)
        t.train(num_steps=6)
        t.close()
        tapes.append(tape.rows)
    assert tapes[0] == tapes[1]
    assert len(tapes[0]) == 3                   # steps 3..5 replayed once


def test_grow_narrow_to_wide_restore(tmp_path):
    """The elastic scale-UP direction: a save written by the shrunk
    narrow world (1×4 over half the devices) restores onto the grown
    wide mesh (2×4) — params/opt/step exact, quant_ef created fresh at
    the new data width (there was nothing to carry: width 1 keeps no
    residuals)."""
    cfg = _cfg(tmp_path)
    narrow = mesh_lib.make_mesh(1, 4, devices=jax.devices()[:4])
    wide = mesh_lib.make_mesh(2, 4)

    a = Trainer(cfg, mesh=narrow,
                checkpointer=Checkpointer(base_dir=tmp_path))
    assert _ef_widths(a.state) is None
    for _ in range(2):
        a.step()
    a.save()
    want = {k: np.asarray(Checkpointer._fetch_global(v), np.float32)
            for k, v in a.state.params.items()}
    a.close()

    b = Trainer(cfg, mesh=wide, checkpointer=Checkpointer(base_dir=tmp_path))
    meta = b.restore()
    assert int(meta["step"]) == 2
    assert _ef_widths(b.state) == {2}           # grown width, zero-init
    for leaf in jax.tree_util.tree_leaves((b.state.aux or {})["quant_ef"]):
        np.testing.assert_array_equal(np.asarray(leaf), 0)
    for k in want:
        np.testing.assert_array_equal(
            np.asarray(Checkpointer._fetch_global(b.state.params[k]),
                       np.float32), want[k], err_msg=k)
    assert np.isfinite(float(jax.device_get(b.step()["loss"])))
    b.close()


def test_grow_cycle_wide_narrow_wide(tmp_path):
    """The full autoscale cycle at fixed process count: wide (2×4) →
    shrink to the narrow survivor (1×4, quant_ef dropped) → grow back to
    wide (quant_ef re-created). Each hop round-trips the params exactly
    and steps to a finite loss — the in-process mirror of the 2-process
    grow/shrink/grow drill."""
    cfg = _cfg(tmp_path)
    wide = mesh_lib.make_mesh(2, 4)
    narrow = mesh_lib.make_mesh(1, 4, devices=jax.devices()[:4])

    a = Trainer(cfg, mesh=wide, checkpointer=Checkpointer(base_dir=tmp_path))
    for _ in range(2):
        a.step()
    a.save()
    a.close()

    b = Trainer(cfg, mesh=narrow,
                checkpointer=Checkpointer(base_dir=tmp_path))
    meta = b.restore()
    assert int(meta["step"]) == 2
    assert _ef_widths(b.state) is None          # respec dropped them
    assert np.isfinite(float(jax.device_get(b.step()["loss"])))
    b.save()
    want = {k: np.asarray(Checkpointer._fetch_global(v), np.float32)
            for k, v in b.state.params.items()}
    b.close()

    c = Trainer(cfg, mesh=wide, checkpointer=Checkpointer(base_dir=tmp_path))
    meta = c.restore()
    assert int(meta["step"]) == 3
    assert _ef_widths(c.state) == {2}           # re-specced for the grow
    for k in want:
        np.testing.assert_array_equal(
            np.asarray(Checkpointer._fetch_global(c.state.params[k]),
                       np.float32), want[k], err_msg=k)
    assert np.isfinite(float(jax.device_get(c.step()["loss"])))
    c.close()


@pytest.mark.slow
def test_buffer_stream_bitwise_across_grow_reshard():
    """The data-plane leg of scale-UP, through a full shrink-then-grow
    cycle (prepare_reshard/reshard are per-cycle re-entrant): after the
    buffer reshards BACK to the wide batch layout, its served sequence
    must be bitwise-equal to a fresh wide buffer restored from the same
    stream snapshot — the stream position, not the store bytes, is the
    state."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    from crosscoder_tpu.data.buffer import make_buffer
    from crosscoder_tpu.models import lm

    lm_cfg = lm.LMConfig.tiny()
    params = [lm.init_params(jax.random.key(0), lm_cfg),
              lm.init_params(jax.random.key(1), lm_cfg)]
    rng = np.random.default_rng(11)
    tokens = rng.integers(0, 257, size=(256, 17), dtype=np.int64)
    cfg = CrossCoderConfig(
        batch_size=32, buffer_mult=32, seq_len=17, d_in=32, n_models=2,
        model_batch_size=4, norm_calib_batches=2, seed=3,
        hook_point="blocks.2.hook_resid_pre", buffer_device="hbm",
    )
    wide = NamedSharding(mesh_lib.make_mesh(2, 4), P("data", None))
    narrow = NamedSharding(
        mesh_lib.make_mesh(1, 4, devices=jax.devices()[:4]),
        P("data", None))

    b = make_buffer(cfg, lm_cfg, params, tokens, batch_sharding=wide)
    for _ in range(3):
        b.next()
    b.prepare_reshard()                 # the shrink leg...
    b.reshard(narrow, refill=True)
    for _ in range(2):
        b.next()
    snap = b.state_dict()

    b.prepare_reshard()                 # ...and the GROW leg back
    b.reshard(wide, refill=True)

    ref = make_buffer(cfg, lm_cfg, params, tokens, batch_sharding=wide,
                      lazy=True)
    ref.load_state_dict(snap)
    for step in range(6):
        np.testing.assert_array_equal(
            np.asarray(b.next(), np.float32),
            np.asarray(ref.next(), np.float32), err_msg=f"step {step}")


def test_foreign_extra_ef_is_tolerated_positionally_strict(tmp_path):
    """The positional (legacy leaf_i) layout keeps the strict count
    contract — respec only relaxes PATH-KEYED checkpoints, so old-format
    saves cannot silently mis-pair leaves."""
    cfg = _cfg(tmp_path)
    a = Trainer(cfg, mesh=mesh_lib.make_mesh(2, 4),
                checkpointer=Checkpointer(base_dir=tmp_path))
    a.step()
    a.save()
    a.close()

    vdir = tmp_path / "version_0"
    import numpy as _np
    with _np.load(vdir / "0_train_state.npz") as z:
        keys = list(z.keys())
    assert any("quant_ef" in k for k in keys), keys
    assert not all(k.startswith("leaf_") for k in keys)
