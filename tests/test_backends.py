"""Backend-boundary (N7) and scale-out-config tests.

The torch backend re-executes the reference's eager step semantics; running
both engines on the IDENTICAL config and data stream and comparing training
trajectories is the strongest whole-step parity statement we can make
(BASELINE.json: "same reconstruction+sparsity loss"). Scale-out tests cover
BASELINE configs 4-5 (3-way diff, multi-layer) and the TP mesh on the
8-virtual-device CPU backend.
"""

import numpy as np
import pytest

import jax

from crosscoder_tpu.config import CrossCoderConfig
from crosscoder_tpu.data.synthetic import SyntheticActivationSource
from crosscoder_tpu.parallel import mesh as mesh_lib
from crosscoder_tpu.train.torch_backend import make_trainer

pytest.importorskip("torch")


def _cfg(**kw):
    base = dict(
        d_in=16, dict_size=128, batch_size=64, buffer_mult=4,
        num_tokens=64 * 40, lr=1e-3, enc_dtype="fp32", log_backend="null",
        seed=11,
    )
    base.update(kw)
    return CrossCoderConfig(**base)


def test_backend_boundary_selects_engine():
    cfg = _cfg()
    assert type(make_trainer(cfg, "jax")).__name__ == "Trainer"
    assert type(make_trainer(cfg, "torch")).__name__ == "TorchTrainer"
    with pytest.raises(ValueError):
        make_trainer(cfg, "tensorflow")


def test_torch_jax_training_trajectory_parity():
    """Same config, same data stream, 38 of 40 total steps on each engine —
    crossing the lr-decay start at step 32 so schedule parity is exercised
    in the decay region too. Losses track step-for-step (fp32; init differs
    only through each framework's normal sampler)."""
    cfg = _cfg()
    assert cfg.total_steps == 40
    tj = make_trainer(cfg, "jax", buffer=SyntheticActivationSource(cfg))
    tt = make_trainer(cfg, "torch", buffer=SyntheticActivationSource(cfg))
    mj = [
        {k: float(np.asarray(v)) for k, v in jax.device_get(tj.step()).items()
         if k != "explained_variance_per_source"}
        for _ in range(38)
    ]
    mt = [tt.step() for _ in range(38)]
    for a, b in zip(mj, mt):
        assert a["lr"] == pytest.approx(b["lr"], rel=1e-6, abs=1e-12)
        assert a["l1_coeff"] == pytest.approx(b["l1_coeff"], rel=1e-6)
    assert mj[-1]["lr"] < mj[0]["lr"]          # decay region actually reached
    with pytest.raises(NotImplementedError):   # torch backend guards configs
        make_trainer(_cfg(activation="jumprelu"), "torch")
    # after the first few steps both engines should be on the same loss path
    ja = np.array([m["loss"] for m in mj[5:]])
    to = np.array([m["loss"] for m in mt[5:]])
    assert np.allclose(ja, to, rtol=0.05), (ja[-3:], to[-3:])
    assert ja[-1] < ja[0] and to[-1] < to[0]


def _identical_init(tj, tt):
    """Copy the jax init into the torch tensors in-place so trajectory
    divergence measures numerics drift, not sampler noise."""
    import torch

    jp = jax.device_get(tj.state.params)
    with torch.no_grad():
        for k, v in tt.params.items():
            v.copy_(torch.from_numpy(np.array(jp[k], np.float32, copy=True)))


@pytest.mark.parametrize(
    "kw",
    [
        dict(activation="topk", topk_k=8, l1_coeff=0.0),
        dict(activation="topk", topk_k=8, l1_coeff=0.0, aux_k=16,
             aux_dead_steps=5, aux_exact_rank=True),
    ],
    ids=["topk", "topk_auxk"],
)
def test_torch_jax_sparse_tier_trajectory_parity(kw):
    """VERDICT round-4 weak #6: the sparse tier the benchmarks headline had
    no independent-engine check. Same config, identical init, identical
    stream, both engines through the TopK straight-through step (and the
    AuxK arm with a forced-dead warm-in and EXACT ranking on both sides so
    the same latents receive aux gradient)."""
    cfg = _cfg(**kw)
    tj = make_trainer(cfg, "jax", buffer=SyntheticActivationSource(cfg))
    tt = make_trainer(cfg, "torch", buffer=SyntheticActivationSource(cfg))
    _identical_init(tj, tt)
    mj = [float(np.asarray(jax.device_get(tj.step()["loss"]))) for _ in range(30)]
    mt = [tt.step()["loss"] for _ in range(30)]
    tj.close()
    rel = np.abs(np.array(mj) - np.array(mt)) / np.maximum(np.abs(mt), 1e-9)
    assert rel.max() < 0.01, (rel.max(), mj[-3:], mt[-3:])
    if cfg.aux_k > 0:
        # the aux path must actually have engaged: after 30 steps at
        # dict 128 >> active latents, some latent must have crossed the
        # aux_dead_steps=5 threshold on the torch tracker
        ssf = np.asarray(tt.steps_since_fired.numpy())
        assert ssf.max() >= cfg.aux_dead_steps, ssf.max()
        assert mj[-1] < mj[0]


@pytest.mark.parametrize(
    "kw",
    [
        dict(n_models=3),                                          # BASELINE config 4
        dict(hook_points=("blocks.0.hook_resid_pre",
                          "blocks.1.hook_resid_pre",
                          "blocks.2.hook_resid_pre")),             # BASELINE config 5
        dict(activation="topk", topk_k=8, l1_coeff=0.0),           # BASELINE config 2
        dict(n_models=3,
             hook_points=("blocks.0.hook_resid_pre", "blocks.2.hook_resid_pre")),
    ],
)
def test_scaleout_configs_train_sharded(kw):
    """Every BASELINE scale-out axis trains under the full DP×TP mesh
    (8 virtual devices: 4 data × 2 model) with finite falling loss."""
    cfg = _cfg(batch_size=32, num_tokens=32 * 30, lr=3e-3, data_axis_size=4,
               model_axis_size=2, **kw)
    mesh = mesh_lib.mesh_from_cfg(cfg)
    trainer = make_trainer(cfg, "jax", buffer=SyntheticActivationSource(cfg), mesh=mesh)
    l2s = []
    for _ in range(24):
        m = jax.device_get(trainer.step())
        l2s.append(float(m["l2_loss"]))    # l2, not total: the l1 warmup
    l2s = np.asarray(l2s)                  # inflates early total loss
    assert np.all(np.isfinite(l2s))
    assert l2s[-4:].mean() < l2s[:4].mean()
    ev = np.asarray(m["explained_variance_per_source"])
    assert ev.shape == (cfg.n_sources,)
    if kw.get("activation") == "topk":
        assert float(m["l0_loss"]) == pytest.approx(8.0, abs=1e-6)


def test_profile_dir_writes_trace(tmp_path):
    cfg = _cfg(profile_dir=str(tmp_path / "prof"), num_tokens=64 * 20)
    trainer = make_trainer(cfg, "jax", buffer=SyntheticActivationSource(cfg))
    trainer.train(20)
    files = list((tmp_path / "prof").rglob("*"))
    assert any(f.is_file() for f in files), "no profiler trace written"


def test_step_time_in_logs(tmp_path):
    import json

    from crosscoder_tpu.utils.logging import MetricsLogger

    cfg = _cfg(log_backend="jsonl", checkpoint_dir=str(tmp_path),
               num_tokens=64 * 10, log_every=5)
    trainer = make_trainer(cfg, "jax", buffer=SyntheticActivationSource(cfg),
                           logger=MetricsLogger(cfg))
    trainer.train(10)
    lines = [json.loads(l) for l in (tmp_path / "metrics.jsonl").read_text().splitlines()]
    assert all("step_time_ms" in l and l["step_time_ms"] > 0 for l in lines)


def test_shard_sources_matches_dict_sharding():
    """EP-style source-axis sharding (cfg.shard_sources): a 2x4 mesh with
    W_enc/W_dec sharded over the SOURCE axis must produce the same training
    trajectory as the default dict-axis TP sharding — XLA's psum over the
    contracted source axis replaces the latent-axis collectives."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from crosscoder_tpu.config import CrossCoderConfig
    from crosscoder_tpu.parallel import mesh as mesh_lib
    from crosscoder_tpu.train import schedules
    from crosscoder_tpu.train.state import init_train_state, make_optimizer
    from crosscoder_tpu.train.trainer import make_train_step

    # 4 sources: 2 models × 2 hook points — the many-source regime the mode
    # exists for; model axis 4 puts one source slab per device
    def cfg_for(shard_sources):
        return CrossCoderConfig(
            d_in=16, dict_size=64, n_models=2,
            hook_points=("blocks.1.hook_resid_pre", "blocks.2.hook_resid_pre"),
            batch_size=32, enc_dtype="fp32", model_axis_size=4,
            data_axis_size=2, shard_sources=shard_sources, log_backend="null",
        )

    mesh = mesh_lib.make_mesh(data_axis_size=2, model_axis_size=4)
    batch = jax.device_put(
        jax.random.normal(jax.random.key(1), (32, 4, 16), dtype=jnp.float32),
        mesh_lib.batch_sharding(mesh),
    )
    scale = jnp.ones((4,), jnp.float32)

    losses = {}
    for mode in (False, True):
        cfg = cfg_for(mode)
        tx = make_optimizer(cfg, schedules.lr_schedule(cfg))
        state = init_train_state(jax.random.key(cfg.seed), cfg, tx)
        sh = mesh_lib.state_shardings(mesh, state, mode)
        state = jax.device_put(state, sh)
        step = make_train_step(cfg, mesh, tx, sh)
        track = []
        for _ in range(3):
            state, m = step(state, batch, scale)
            track.append(float(jax.device_get(m["loss"])))
        losses[mode] = track
        # the intended placement actually happened
        w_enc_sh = state.params["W_enc"].sharding.spec
        if mode:
            assert w_enc_sh[0] == "model", w_enc_sh
        else:
            assert w_enc_sh[2] == "model", w_enc_sh
    np.testing.assert_allclose(losses[False], losses[True], rtol=1e-5)


def test_shard_sources_validation():
    import pytest as _pytest

    from crosscoder_tpu.config import CrossCoderConfig

    with _pytest.raises(ValueError, match="must divide"):
        CrossCoderConfig(n_models=3, model_axis_size=2, shard_sources=True)
