"""Multi-host-safe checkpointing, proven with 2 REAL processes.

The 8-device CPU mesh every other test uses is single-process, which can
never catch the save-path crash on non-addressable leaves (VERDICT round-2
weak #3). Here two OS processes (4 virtual devices each) form one
jax.distributed cluster with params sharded across them: train → save →
restore → continue must work, with only process 0 writing files.
"""

import json
import os
import socket
import subprocess
import sys
from pathlib import Path

import pytest

_CHILD = Path(__file__).with_name("_multihost_ckpt_child.py")


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("localhost", 0))
        return s.getsockname()[1]


@pytest.mark.slow
def test_two_process_save_restore(tmp_path):
    port = _free_port()
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)          # child sets its own device count
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = str(_CHILD.parent.parent)
    procs = [
        subprocess.Popen(
            [sys.executable, str(_CHILD), str(i), str(port), str(tmp_path)],
            env=env, stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
            cwd=str(_CHILD.parent.parent),
        )
        for i in (0, 1)
    ]
    outs = []
    for p in procs:
        try:
            out, err = p.communicate(timeout=420)
        except subprocess.TimeoutExpired:
            for q in procs:
                q.kill()
            pytest.fail("multihost child timed out")
        outs.append((p.returncode, out, err))
    for rc, out, err in outs:
        assert rc == 0, f"child failed rc={rc}\nstdout:\n{out}\nstderr:\n{err[-3000:]}"
    results = [json.loads(out.strip().splitlines()[-1]) for _, out, _ in outs]
    assert all(r["ok"] for r in results)
    # SPMD: both processes computed the same losses
    assert results[0]["losses"] == results[1]["losses"]
    assert results[0]["resumed_loss"] == results[1]["resumed_loss"]
    # only process 0 wrote files
    vdir = tmp_path / "version_0"
    assert (vdir / "0.npz").exists()
    assert (vdir / "0_meta.json").exists()


_DATAPLANE_CHILD = Path(__file__).with_name("_multihost_dataplane_child.py")


@pytest.mark.slow
def test_two_process_full_data_plane(tmp_path):
    """harvest → mesh-sharded HBM store → train → checkpoint → restore →
    continue, across 2 real processes: every collective (harvest psums,
    store scatter/gather, grad reductions, checkpoint allgather) must be
    dispatched in the same order on both hosts."""
    port = _free_port()
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = str(_DATAPLANE_CHILD.parent.parent)
    procs = [
        subprocess.Popen(
            [sys.executable, str(_DATAPLANE_CHILD), str(i), str(port), str(tmp_path)],
            env=env, stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
            cwd=str(_DATAPLANE_CHILD.parent.parent),
        )
        for i in (0, 1)
    ]
    outs = []
    for p in procs:
        try:
            out, err = p.communicate(timeout=420)
        except subprocess.TimeoutExpired:
            for q in procs:
                q.kill()
            pytest.fail("dataplane child timed out (cross-process dispatch "
                        "divergence deadlocks here)")
        outs.append((p.returncode, out, err))
    for rc, out, err in outs:
        assert rc == 0, f"child failed rc={rc}\nstdout:\n{out}\nstderr:\n{err[-3000:]}"
    results = [json.loads(out.strip().splitlines()[-1]) for _, out, _ in outs]
    assert all(r["ok"] for r in results)
    assert results[0]["losses"] == results[1]["losses"]
    assert results[0]["resumed"] == results[1]["resumed"]


_SIGTERM_CHILD = Path(__file__).with_name("_multihost_sigterm_child.py")


@pytest.mark.slow
def test_one_host_sigterm_checkpoints_both_processes(tmp_path):
    """SIGTERM delivered to ONE host of a 2-process mesh: the stop-flag
    allgather must bring both processes to the same boundary, both must
    run the collective checkpoint, and both must exit 0 — previously one
    host entered the collective save while the other kept training."""
    import signal
    import time

    port = _free_port()
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = str(_SIGTERM_CHILD.parent.parent)
    # stderr to files: a chatty child must not block on a full pipe during
    # the long ready-wait phase (stdout stays a pipe — it only carries the
    # two tiny JSON lines)
    err_files = [open(tmp_path / f"child{i}.err", "w+") for i in (0, 1)]
    procs = [
        subprocess.Popen(
            [sys.executable, str(_SIGTERM_CHILD), str(i), str(port), str(tmp_path)],
            env=env, stdout=subprocess.PIPE, stderr=err_files[i], text=True,
            cwd=str(_SIGTERM_CHILD.parent.parent),
        )
        for i in (0, 1)
    ]
    # wait for both children to reach the train loop (the "ready" line),
    # then let a few steps run and SIGTERM process 0 only
    deadline = time.monotonic() + 300
    import select

    ready = [False, False]
    exited = [False, False]
    bufs = ["", ""]
    while not all(ready) and time.monotonic() < deadline:
        live = [p.stdout for i, p in enumerate(procs) if not (ready[i] or exited[i])]
        if not live:
            break
        rl, _, _ = select.select(live, [], [], 5)
        for f in rl:
            i = 0 if f is procs[0].stdout else 1
            line = f.readline()
            if line == "":             # EOF: child exited before ready
                exited[i] = True
                continue
            bufs[i] += line
            if '"ready": true' in line:
                ready[i] = True
    assert all(ready), (f"children never became ready (exited={exited}): "
                        f"{bufs} / stderr tails: "
                        f"{[open(tmp_path / f'child{i}.err').read()[-800:] for i in (0, 1)]}")
    time.sleep(5)                      # a few steps
    procs[0].send_signal(signal.SIGTERM)

    outs = []
    for i, p in enumerate(procs):
        try:
            out, _ = p.communicate(timeout=420)
        except subprocess.TimeoutExpired:
            for q in procs:
                q.kill()
            pytest.fail("child hung after one-host SIGTERM (stop not "
                        "coordinated / collective save mismatch)")
        err_files[i].seek(0)
        outs.append((p.returncode, out, err_files[i].read()))
        err_files[i].close()
    results = []
    for i, (rc, out, err) in enumerate(outs):
        assert rc == 0, f"child {i} rc={rc}\nstdout:\n{bufs[i] + out}\nstderr:\n{err[-3000:]}"
        results.append(json.loads((bufs[i] + out).strip().splitlines()[-1]))
    assert all(r["ok"] for r in results)
    # both processes stopped at the SAME step (the allgathered flag)
    assert results[0]["stopped_at"] == results[1]["stopped_at"] > 0
    # and the collective final save landed on disk (written by process 0)
    assert (tmp_path / "version_0" / "0.npz").exists()
