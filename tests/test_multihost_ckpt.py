"""Multi-host-safe checkpointing, proven with 2 REAL processes.

The 8-device CPU mesh every other test uses is single-process, which can
never catch the save-path crash on non-addressable leaves (VERDICT round-2
weak #3). Here two OS processes (4 virtual devices each) form one
jax.distributed cluster with params sharded across them: train → save →
restore → continue must work, with only process 0 writing files.
"""

import json
import os
import socket
import subprocess
import sys
from pathlib import Path

import pytest

_CHILD = Path(__file__).with_name("_multihost_ckpt_child.py")


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("localhost", 0))
        return s.getsockname()[1]


@pytest.mark.slow
def test_two_process_save_restore(tmp_path):
    port = _free_port()
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)          # child sets its own device count
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = str(_CHILD.parent.parent)
    procs = [
        subprocess.Popen(
            [sys.executable, str(_CHILD), str(i), str(port), str(tmp_path)],
            env=env, stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
            cwd=str(_CHILD.parent.parent),
        )
        for i in (0, 1)
    ]
    outs = []
    for p in procs:
        try:
            out, err = p.communicate(timeout=420)
        except subprocess.TimeoutExpired:
            for q in procs:
                q.kill()
            pytest.fail("multihost child timed out")
        outs.append((p.returncode, out, err))
    for rc, out, err in outs:
        assert rc == 0, f"child failed rc={rc}\nstdout:\n{out}\nstderr:\n{err[-3000:]}"
    results = [json.loads(out.strip().splitlines()[-1]) for _, out, _ in outs]
    assert all(r["ok"] for r in results)
    # SPMD: both processes computed the same losses
    assert results[0]["losses"] == results[1]["losses"]
    assert results[0]["resumed_loss"] == results[1]["resumed_loss"]
    # only process 0 wrote files
    vdir = tmp_path / "version_0"
    assert (vdir / "0.npz").exists()
    assert (vdir / "0_meta.json").exists()


_DATAPLANE_CHILD = Path(__file__).with_name("_multihost_dataplane_child.py")


@pytest.mark.slow
def test_two_process_full_data_plane(tmp_path):
    """harvest → mesh-sharded HBM store → train → checkpoint → restore →
    continue, across 2 real processes: every collective (harvest psums,
    store scatter/gather, grad reductions, checkpoint allgather) must be
    dispatched in the same order on both hosts."""
    port = _free_port()
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = str(_DATAPLANE_CHILD.parent.parent)
    procs = [
        subprocess.Popen(
            [sys.executable, str(_DATAPLANE_CHILD), str(i), str(port), str(tmp_path)],
            env=env, stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
            cwd=str(_DATAPLANE_CHILD.parent.parent),
        )
        for i in (0, 1)
    ]
    outs = []
    for p in procs:
        try:
            out, err = p.communicate(timeout=420)
        except subprocess.TimeoutExpired:
            for q in procs:
                q.kill()
            pytest.fail("dataplane child timed out (cross-process dispatch "
                        "divergence deadlocks here)")
        outs.append((p.returncode, out, err))
    for rc, out, err in outs:
        assert rc == 0, f"child failed rc={rc}\nstdout:\n{out}\nstderr:\n{err[-3000:]}"
    results = [json.loads(out.strip().splitlines()[-1]) for _, out, _ in outs]
    assert all(r["ok"] for r in results)
    assert results[0]["losses"] == results[1]["losses"]
    assert results[0]["resumed"] == results[1]["resumed"]
