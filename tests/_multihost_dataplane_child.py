"""Child process for the 2-process FULL-DATA-PLANE test.

Run as: python _multihost_dataplane_child.py <proc_id> <port> <ckpt_dir>

The whole production pipeline across 2 real processes (4 virtual CPU
devices each, 8-way data mesh): tiny-LM pair harvest sharded over the
process boundary → mesh-sharded HBM replay store (scatter/gather
collectives) → jitted train step → collective checkpoint → restore →
continue. This is the pod story end-to-end; the single-process 8-device
tests can never catch a cross-process dispatch-order divergence.
"""

import json
import os
import sys

proc_id = int(sys.argv[1])
port = sys.argv[2]
workdir = sys.argv[3]

os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
from crosscoder_tpu.parallel import multihost  # noqa: E402

multihost.initialize(
    coordinator_address=f"localhost:{port}", num_processes=2, process_id=proc_id
)
assert jax.device_count() == 8 and jax.local_device_count() == 4

import numpy as np  # noqa: E402
from jax.sharding import NamedSharding, PartitionSpec as P  # noqa: E402

from crosscoder_tpu.checkpoint.ckpt import Checkpointer  # noqa: E402
from crosscoder_tpu.config import CrossCoderConfig  # noqa: E402
from crosscoder_tpu.data.buffer import (  # noqa: E402
    MeshPairedActivationBuffer, make_buffer,
)
from crosscoder_tpu.models import lm  # noqa: E402
from crosscoder_tpu.parallel import mesh as mesh_lib  # noqa: E402
from crosscoder_tpu.train.trainer import Trainer  # noqa: E402

lm_cfg = lm.LMConfig.tiny()
pair = [lm.init_params(jax.random.key(i), lm_cfg) for i in (0, 1)]
tokens = np.random.default_rng(7).integers(0, 257, size=(64, 17), dtype=np.int64)

cfg = CrossCoderConfig(
    d_in=32, dict_size=64, n_models=2, batch_size=16, buffer_mult=32,
    seq_len=17, model_batch_size=8, norm_calib_batches=1,
    hook_point="blocks.1.hook_resid_pre", buffer_device="hbm",
    data_axis_size=8, model_axis_size=1, num_tokens=10**9,
    save_every=10**9, log_backend="null", checkpoint_dir=workdir,
    # prefetch=True ON PURPOSE: Trainer must disable it on a multi-process
    # mesh (the guard under test) — if the guard regresses, the prefetch
    # thread's collective serve gathers race the steps differently on each
    # host and this test deadlocks into its timeout
    prefetch=True,
)
mesh = mesh_lib.mesh_from_cfg(cfg)
sh = NamedSharding(mesh, P("data", None))


def build():
    buf = make_buffer(cfg, lm_cfg, pair, tokens, batch_sharding=sh)
    assert isinstance(buf, MeshPairedActivationBuffer), type(buf)
    return Trainer(cfg, buf, mesh=mesh, checkpointer=Checkpointer(workdir))


tr = build()
# 20 steps crosses the refill trigger (buffer 512 rows, trigger at 240),
# so incremental refill scatters interleave with serve gathers
losses = [float(jax.device_get(tr.step()["loss"])) for _ in range(20)]
assert all(np.isfinite(l) for l in losses), losses
tr.save()
tr.close()

tr2 = build()
tr2.restore(version_dir=os.path.join(workdir, "version_0"))
assert int(tr2.state.step) == 20
resumed = [float(jax.device_get(tr2.step()["loss"])) for _ in range(3)]
assert all(np.isfinite(l) for l in resumed), resumed
tr2.close()

print(json.dumps({"proc": proc_id, "losses": losses[-3:],
                  "resumed": resumed, "ok": True}))
