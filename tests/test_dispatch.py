"""ops/dispatch.py kernel-gate resolution: the ``CROSSCODER_PALLAS``
umbrella (all|off, per-kernel override wins), the one-time resolved-state
startup log, and typo validation of unknown ``CROSSCODER_*_PALLAS``
names with difflib suggestions. All CPU, tier-1."""

import pytest

from crosscoder_tpu.ops import dispatch


@pytest.fixture(autouse=True)
def _clean_gate_env(monkeypatch):
    """Each test starts from a bare env (no umbrella, no per-kernel
    gates) and a reset one-time-log latch."""
    monkeypatch.delenv(dispatch.UMBRELLA_ENV, raising=False)
    for g in dispatch.KNOWN_GATES:
        monkeypatch.delenv(g, raising=False)
    dispatch._reset_log_state()
    yield
    dispatch._reset_log_state()


def test_default_everything_off():
    for g in dispatch.KNOWN_GATES:
        assert not dispatch.resolve_gate(g)


def test_umbrella_all_enables_every_gate(monkeypatch):
    monkeypatch.setenv(dispatch.UMBRELLA_ENV, "all")
    for g in dispatch.KNOWN_GATES:
        assert dispatch.resolve_gate(g)


def test_per_kernel_env_overrides_umbrella(monkeypatch):
    monkeypatch.setenv(dispatch.UMBRELLA_ENV, "all")
    monkeypatch.setenv("CROSSCODER_QUANT_PALLAS", "0")
    assert not dispatch.resolve_gate("CROSSCODER_QUANT_PALLAS")
    assert dispatch.resolve_gate("CROSSCODER_SPARSE_GRAD_PALLAS")
    monkeypatch.setenv(dispatch.UMBRELLA_ENV, "off")
    monkeypatch.setenv("CROSSCODER_FUSED_TOPK_PALLAS", "1")
    assert dispatch.resolve_gate("CROSSCODER_FUSED_TOPK_PALLAS")
    assert not dispatch.resolve_gate("CROSSCODER_QUANT_PALLAS")


def test_malformed_umbrella_raises_with_suggestion(monkeypatch):
    monkeypatch.setenv(dispatch.UMBRELLA_ENV, "al")
    with pytest.raises(ValueError, match="did you mean 'all'"):
        dispatch.resolve_gate("CROSSCODER_QUANT_PALLAS")


def test_unknown_gate_names_get_difflib_suggestions(monkeypatch):
    monkeypatch.setenv("CROSSCODER_SPARSE_GRAD_PALLAS", "1")     # known: quiet
    monkeypatch.setenv("CROSSCODER_SPASE_GRAD_PALLAS", "1")      # typo
    warnings = dispatch.validate_env()
    assert len(warnings) == 1
    assert "CROSSCODER_SPASE_GRAD_PALLAS" in warnings[0]
    assert "did you mean CROSSCODER_SPARSE_GRAD_PALLAS?" in warnings[0]
    assert "no-op" in warnings[0]


def test_typo_warning_prints_at_first_dispatch(monkeypatch, capsys):
    """The startup log validates the env BEFORE latching the one-time
    flag: a typo'd gate name is visible on stderr at the first dispatch
    decision, with its difflib suggestion."""
    monkeypatch.setenv("CROSSCODER_BATCHTOK_PALLAS", "1")        # typo
    dispatch.hw_kernel_enabled("CROSSCODER_QUANT_PALLAS", True)
    err = capsys.readouterr().err
    assert "unknown kernel gate CROSSCODER_BATCHTOK_PALLAS" in err
    assert "did you mean CROSSCODER_BATCHTOPK_PALLAS?" in err
    assert "pallas gates" in err


def test_malformed_umbrella_does_not_latch_the_log(monkeypatch, capsys):
    """A raising umbrella must leave the one-time latch unset, so the
    retry after the operator fixes the env still logs the gate table
    (and re-runs validation) instead of silently skipping both."""
    monkeypatch.setenv(dispatch.UMBRELLA_ENV, "laa")
    with pytest.raises(ValueError, match="must be all|off"):
        dispatch.hw_kernel_enabled("CROSSCODER_QUANT_PALLAS", True)
    capsys.readouterr()
    monkeypatch.setenv(dispatch.UMBRELLA_ENV, "all")
    dispatch.hw_kernel_enabled("CROSSCODER_QUANT_PALLAS", True)
    assert "pallas gates (CROSSCODER_PALLAS=all)" in capsys.readouterr().err


def test_interpret_mode_always_allowed(monkeypatch):
    # no env at all: the interpreter (CPU tests) still runs
    assert dispatch.hw_kernel_enabled("CROSSCODER_QUANT_PALLAS", True)
    # hardware path off-TPU stays off regardless of env
    monkeypatch.setenv("CROSSCODER_QUANT_PALLAS", "1")
    import jax

    if jax.default_backend() != "tpu":
        assert not dispatch.hw_kernel_enabled("CROSSCODER_QUANT_PALLAS",
                                              False)


def test_startup_log_emits_once_with_resolved_states(monkeypatch, capsys):
    monkeypatch.setenv(dispatch.UMBRELLA_ENV, "all")
    monkeypatch.setenv("CROSSCODER_QUANT_PALLAS", "0")
    dispatch.hw_kernel_enabled("CROSSCODER_QUANT_PALLAS", True)
    err = capsys.readouterr().err
    assert "pallas gates (CROSSCODER_PALLAS=all)" in err
    assert "quant=off" in err                  # per-kernel override visible
    assert "sparse_grad=on" in err             # umbrella default visible
    # second dispatch decision: no second log line
    dispatch.hw_kernel_enabled("CROSSCODER_QUANT_PALLAS", True)
    assert "pallas gates" not in capsys.readouterr().err


def test_every_known_gate_is_actually_read_somewhere():
    """The registry and the ops modules can't drift: every KNOWN_GATES
    name appears in exactly the module that dispatches on it."""
    import pathlib

    ops_dir = pathlib.Path(dispatch.__file__).parent
    blob = "".join(p.read_text() for p in ops_dir.glob("*.py"))
    for g in dispatch.KNOWN_GATES:
        assert blob.count(g) >= 1, f"{g} registered but never read"
