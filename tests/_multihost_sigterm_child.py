"""Child for the 2-process one-host-SIGTERM test.

Run as: python _multihost_sigterm_child.py <proc_id> <port> <ckpt_dir>

The parent SIGTERMs ONLY process 0 mid-train. The coordinated stop
(`_stop_agreed` allgather in Trainer.train) must bring BOTH processes to
the same step boundary, run the collective checkpoint on both, and exit
cleanly — the exact scenario that deadlocked before round-3's fix (one
host inside process_allgather, the other still launching train steps).
"""

import json
import os
import sys

proc_id = int(sys.argv[1])
port = sys.argv[2]
workdir = sys.argv[3]

os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
from crosscoder_tpu.parallel import multihost  # noqa: E402

multihost.initialize(
    coordinator_address=f"localhost:{port}", num_processes=2, process_id=proc_id
)

import numpy as np  # noqa: E402

from crosscoder_tpu.checkpoint.ckpt import Checkpointer  # noqa: E402
from crosscoder_tpu.config import CrossCoderConfig  # noqa: E402
from crosscoder_tpu.parallel import mesh as mesh_lib  # noqa: E402
from crosscoder_tpu.train.trainer import Trainer  # noqa: E402

cfg = CrossCoderConfig(
    d_in=32, dict_size=64, n_models=2, batch_size=16,
    num_tokens=16 * 100_000, enc_dtype="fp32",
    data_axis_size=2, model_axis_size=4,
    log_backend="null", checkpoint_dir=workdir, prefetch=False,
    save_every=10**9, log_every=10**9,
)
mesh = mesh_lib.mesh_from_cfg(cfg)
tr = Trainer(cfg, mesh=mesh, checkpointer=Checkpointer(workdir))

print(json.dumps({"proc": proc_id, "ready": True}), flush=True)
# 100k steps ≈ forever on CPU: only the signal can end this loop
tr.train()
final_step = int(tr.state.step)
assert np.isfinite(float(jax.device_get(tr.state.params["W_enc"]).sum()))
print(json.dumps({"proc": proc_id, "stopped_at": final_step, "ok": True}),
      flush=True)
