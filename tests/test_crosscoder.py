"""Parity tests of the JAX crosscoder core against the torch-CPU oracle
(SURVEY.md §4 "recon-MSE+L1 parity gate") plus init-property checks."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
import torch

from crosscoder_tpu.config import CrossCoderConfig
from crosscoder_tpu.models import crosscoder as cc

from torch_oracle import oracle_decode, oracle_encode, oracle_losses

B, N, D, H = 32, 2, 16, 64


def small_cfg(**kw):
    base = dict(d_in=D, dict_size=H, n_models=N, enc_dtype="fp32", batch_size=B)
    base.update(kw)
    return CrossCoderConfig(**base)


@pytest.fixture(scope="module")
def setup():
    cfg = small_cfg()
    params = cc.init_params(jax.random.key(0), cfg)
    rng = np.random.default_rng(1)
    x = rng.normal(size=(B, N, D)).astype(np.float32)
    tp = {k: torch.from_numpy(np.asarray(v)) for k, v in params.items()}
    return cfg, params, x, tp


def test_init_properties():
    cfg = small_cfg(dec_init_norm=0.08)
    p = cc.init_params(jax.random.key(0), cfg)
    assert p["W_enc"].shape == (N, D, H)
    assert p["W_dec"].shape == (H, N, D)
    assert p["b_enc"].shape == (H,)
    assert p["b_dec"].shape == (N, D)
    # decoder rows have norm dec_init_norm per (latent, source) — reference crosscoder.py:51-53
    norms = jnp.linalg.norm(p["W_dec"], axis=-1)
    np.testing.assert_allclose(np.asarray(norms), 0.08, rtol=1e-5)
    # encoder is the decoder transpose — reference crosscoder.py:54-58
    np.testing.assert_allclose(
        np.asarray(p["W_enc"]), np.asarray(jnp.transpose(p["W_dec"], (1, 2, 0))), rtol=0
    )
    assert float(jnp.abs(p["b_enc"]).max()) == 0.0
    assert float(jnp.abs(p["b_dec"]).max()) == 0.0


def test_encode_decode_parity(setup):
    cfg, params, x, tp = setup
    f = cc.encode(params, jnp.asarray(x), cfg)
    f_t = oracle_encode(torch.from_numpy(x), tp["W_enc"], tp["b_enc"])
    np.testing.assert_allclose(np.asarray(f), f_t.numpy(), rtol=1e-5, atol=1e-5)

    y = cc.decode(params, f)
    y_t = oracle_decode(f_t, tp["W_dec"], tp["b_dec"])
    np.testing.assert_allclose(np.asarray(y), y_t.numpy(), rtol=1e-5, atol=1e-5)


def test_losses_parity(setup):
    cfg, params, x, tp = setup
    out = cc.get_losses(params, jnp.asarray(x), cfg)
    ref = oracle_losses(torch.from_numpy(x), tp["W_enc"], tp["W_dec"], tp["b_enc"], tp["b_dec"])
    np.testing.assert_allclose(float(out.l2_loss), float(ref["l2"]), rtol=1e-5)
    np.testing.assert_allclose(float(out.l1_loss), float(ref["l1"]), rtol=1e-5)
    np.testing.assert_allclose(float(out.l0_loss), float(ref["l0"]), rtol=0)
    np.testing.assert_allclose(np.asarray(out.explained_variance), ref["ev"].numpy(), rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(
        np.asarray(out.explained_variance_per_source), ref["ev_per_source"].numpy(), rtol=1e-4, atol=1e-5
    )


def test_training_loss_combines(setup):
    cfg, params, x, _ = setup
    loss, losses = cc.training_loss(params, jnp.asarray(x), 2.0, cfg)
    np.testing.assert_allclose(float(loss), float(losses.l2_loss + 2.0 * losses.l1_loss), rtol=1e-6)


def test_training_loss_rejects_l1_coeff_cfg_mismatch(setup):
    """The L1 term is compiled out when with_metrics=False AND
    cfg.l1_coeff == 0 (the static gate in get_losses), but training_loss
    multiplies the DYNAMIC l1_coeff argument — a direct caller passing a
    nonzero runtime coefficient there would silently get loss = l2 +
    coeff·0. Concretely-checkable disagreements must raise."""
    _, params, x, _ = setup
    cfg0 = small_cfg(l1_coeff=0.0)
    with pytest.raises(ValueError, match="l1_coeff"):
        cc.training_loss(params, jnp.asarray(x), 0.5, cfg0, with_metrics=False)
    with pytest.raises(ValueError, match="l1_coeff"):
        cc.training_loss(params, jnp.asarray(x), jnp.float32(0.5), cfg0,
                         with_metrics=False)
    with pytest.raises(ValueError, match="l1_coeff"):
        # np.float32 is not a python-float subclass — still concrete
        cc.training_loss(params, jnp.asarray(x), np.float32(0.5), cfg0,
                         with_metrics=False)
    # agreeing zero passes (the TopK regime this gate optimizes for) ...
    loss0, _ = cc.training_loss(params, jnp.asarray(x), 0.0, cfg0,
                                with_metrics=False)
    assert np.isfinite(float(loss0))
    # ... and a nonzero coeff against a nonzero cfg is the normal path
    cfg1 = small_cfg(l1_coeff=2.0)
    loss1, losses1 = cc.training_loss(params, jnp.asarray(x), 0.5, cfg1,
                                      with_metrics=False)
    np.testing.assert_allclose(
        float(loss1), float(losses1.l2_loss + 0.5 * losses1.l1_loss), rtol=1e-6
    )


def test_generalized_n_models():
    # the reference hardcodes n_models=2 (crosscoder.py:32); we support any N
    cfg = small_cfg(n_models=3)
    p = cc.init_params(jax.random.key(0), cfg)
    x = jax.random.normal(jax.random.key(1), (8, 3, D))
    out = cc.get_losses(p, x, cfg)
    assert out.explained_variance_per_source.shape == (3, 8)
    y = cc.forward(p, x, cfg)
    assert y.shape == (8, 3, D)


def test_multi_layer_sources():
    # multi-layer crosscoder: hooked layers stack onto the source axis
    cfg = small_cfg(
        n_models=2,
        hook_points=("blocks.6.hook_resid_pre", "blocks.13.hook_resid_pre", "blocks.20.hook_resid_pre"),
    )
    assert cfg.n_sources == 6
    p = cc.init_params(jax.random.key(0), cfg)
    assert p["W_enc"].shape == (6, D, H)


def test_fold_scaling_factors(setup):
    cfg, params, x, _ = setup
    s = np.array([0.5, 2.0], dtype=np.float32)
    folded = cc.fold_scaling_factors(params, s)
    # crosscoder trained on x*s must equal folded crosscoder on raw x (nb:cell 27)
    xs = jnp.asarray(x) * jnp.asarray(s)[None, :, None]
    y_norm = cc.forward(params, xs, cfg)            # reconstruction in normalized space
    y_raw = cc.forward(folded, jnp.asarray(x), cfg)  # reconstruction in raw space
    np.testing.assert_allclose(
        np.asarray(y_norm) / s[None, :, None], np.asarray(y_raw), rtol=1e-4, atol=1e-5
    )


def test_bf16_path_runs(setup):
    cfg = small_cfg(enc_dtype="bf16")
    p = cc.init_params(jax.random.key(0), cfg)
    x = jax.random.normal(jax.random.key(2), (B, N, D))
    out = cc.get_losses(p, x, cfg)
    # losses are fp32 regardless of compute dtype (reference crosscoder.py:104)
    assert out.l2_loss.dtype == jnp.float32
    assert np.isfinite(float(out.l2_loss))
