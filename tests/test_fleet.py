"""Multi-tenant fleet scheduler (cfg.fleet=on; docs/SCALING.md "Fleet
amortization"; train/fleet.py):

- per-tenant loss trajectories bitwise equal to SOLO runs over the same
  stream at the same seed — for both stacked (vmapped cohort) and
  bucketed (own compiled variant) tenants;
- one real gather per lockstep round: the buffer fan-out protocol adds
  ZERO host↔device transfers over a single consumer (the monkeypatched
  device_put/get harness from tests/test_refill_overlap.py, on a real
  PairedActivationBuffer);
- admission and retirement mid-run (a late tenant equals a solo run
  launched at the live stream head; survivors stay bitwise-solo);
- restore-all-tenants after a simulated preemption: the resumed fleet's
  trajectories bitwise-continue an uninterrupted run.

All CPU, tier-1; the parity test doubles as the scripts/tier1.sh fleet
smoke.
"""

import dataclasses

import numpy as np
import pytest

import jax

from crosscoder_tpu.config import CrossCoderConfig
from crosscoder_tpu.data.buffer import PairedActivationBuffer
from crosscoder_tpu.data.synthetic import SyntheticActivationSource
from crosscoder_tpu.models import lm
from crosscoder_tpu.obs.registry import MetricsRegistry
from crosscoder_tpu.train.fleet import (FleetScheduler, TenantSpec,
                                        parse_tenants, tenant_config)
from crosscoder_tpu.train.trainer import Trainer


def base_cfg(**kw):
    base = dict(
        d_in=16, dict_size=64, batch_size=64, num_tokens=64 * 1000,
        enc_dtype="fp32", log_backend="null", seed=11,
    )
    base.update(kw)
    return CrossCoderConfig(**base)


def fleet_cfg(tenants, **kw):
    return base_cfg(fleet="on", fleet_tenants=tenants, **kw)


def solo_losses(overrides, n_steps, skip_rounds=0):
    """Loss trajectory of a SOLO trainer carrying the tenant's overrides
    over the fleet's shared stream (base-seed synthetic source) — the
    bitwise baseline every fleet tenant must reproduce. ``skip_rounds``
    pre-advances the stream, modeling a tenant admitted mid-run."""
    base = base_cfg()
    buf = SyntheticActivationSource(base)
    for _ in range(skip_rounds):
        buf.next()
    tr = Trainer(dataclasses.replace(base, **overrides), buf)
    return [float(jax.device_get(tr.step()["loss"])) for _ in range(n_steps)]


def fleet_losses(fl, n_rounds):
    """Drive ``n_rounds`` lockstep rounds; per-tenant loss lists."""
    out: dict[str, list[float]] = {}
    for _ in range(n_rounds):
        mets = fl.step_all()
        for name, md in mets.items():
            out.setdefault(name, []).append(float(jax.device_get(md["loss"])))
    return out


# ---------------------------------------------------------------------------
# bitwise parity vs solo — stacked cohort AND compiled bucket


def test_fleet_parity_stacked_and_bucketed():
    """a+b differ only in seed/l1_coeff → one vmapped cohort; w differs in
    dict_size → its own bucket. Every trajectory must be BITWISE the solo
    run (also the tier-1 fleet smoke — scripts/tier1.sh runs this test)."""
    fl = FleetScheduler(
        fleet_cfg("a:seed=1;b:seed=2,l1_coeff=0.05;w:seed=1,dict_size=128"),
        checkpoint=False,
    )
    assert len(fl._cohorts) == 1 and len(fl._buckets) == 1
    got = fleet_losses(fl, 5)
    for name, ov in (
        ("a", dict(seed=1)),
        ("b", dict(seed=2, l1_coeff=0.05)),
        ("w", dict(seed=1, dict_size=128)),
    ):
        assert got[name] == solo_losses(ov, 5), name


def test_tenant_config_pins_stream_shape():
    base = fleet_cfg("a")
    with pytest.raises(ValueError, match="pinned"):
        tenant_config(base, TenantSpec("x", {"batch_size": 32}))
    with pytest.raises(ValueError, match="quant_grads"):
        tenant_config(base, TenantSpec("x", {"quant_grads": True}))
    specs = parse_tenants("a:seed=1,l1_coeff=0.02; b")
    assert specs[0].overrides == {"seed": 1, "l1_coeff": 0.02}
    assert specs[1] == TenantSpec("b", {})


# ---------------------------------------------------------------------------
# single-gather fan-out: zero extra transfers on the real buffer

SEQ = 17
HP = "blocks.2.hook_resid_pre"


@pytest.fixture(scope="module")
def lm_pair():
    cfg = lm.LMConfig.tiny()
    return cfg, [lm.init_params(jax.random.key(0), cfg),
                 lm.init_params(jax.random.key(1), cfg)]


@pytest.fixture(scope="module")
def tokens():
    rng = np.random.default_rng(7)
    return rng.integers(0, 257, size=(256, SEQ), dtype=np.int64)


def buf_cfg(**kw):
    base = dict(
        batch_size=32, buffer_mult=32, seq_len=SEQ, d_in=32, n_models=2,
        model_batch_size=4, norm_calib_batches=2, hook_point=HP, seed=3,
    )
    base.update(kw)
    return CrossCoderConfig(**base)


def test_fanout_single_gather_no_extra_transfers(lm_pair, tokens, monkeypatch):
    """Serving 3 fan-out consumers for 6 rounds performs EXACTLY the same
    number of device_put/device_get calls as one solo consumer — the
    first cursor at a position pays the gather, peers read the cache —
    and every consumer sees the byte-identical solo stream."""
    lm_cfg, params = lm_pair
    real_put, real_get = jax.device_put, jax.device_get

    def run(consumers):
        put, get = [], []
        monkeypatch.setattr(jax, "device_put",
                            lambda *a, **k: (put.append(1), real_put(*a, **k))[1])
        monkeypatch.setattr(jax, "device_get",
                            lambda x: (get.append(1), real_get(x))[1])
        try:
            b = PairedActivationBuffer(buf_cfg(), lm_cfg, params, tokens)
            for n in consumers:
                b.attach_consumer(n)
            rounds = []
            for _ in range(6):
                if consumers:
                    batches = [np.asarray(b.next_raw_for(n)) for n in consumers]
                    for peer in batches[1:]:
                        np.testing.assert_array_equal(peer, batches[0])
                    rounds.append(batches[0])
                else:
                    rounds.append(np.asarray(b.next_raw()))
            b.close()
        finally:
            monkeypatch.setattr(jax, "device_put", real_put)
            monkeypatch.setattr(jax, "device_get", real_get)
        return (len(put), len(get)), rounds

    solo_counts, solo_stream = run([])
    fan_counts, fan_stream = run(["a", "b", "c"])
    assert fan_counts == solo_counts, (fan_counts, solo_counts)
    assert solo_counts[1] > 0           # the counter saw the chunk fetches
    for i, (fan, solo) in enumerate(zip(fan_stream, solo_stream)):
        np.testing.assert_array_equal(fan, solo, err_msg=f"round {i}")


def test_fanout_lockstep_enforced():
    """A consumer more than one position behind the head (peer cache
    already advanced past it) is a protocol violation, not silent skew."""
    src = SyntheticActivationSource(base_cfg())
    src.attach_consumer("fast")
    src.attach_consumer("slow")
    src.next_for("fast")
    src.next_for("slow")      # both at 1 — cache at 0
    src.next_for("fast")      # fast at 2 — cache moved to 1
    src.next_for("fast")      # fast at 3 — cache at 2, slow (1) stranded
    with pytest.raises(RuntimeError, match="lockstep"):
        src.next_for("slow")


def test_fleet_counts_one_h2d_per_round():
    reg = MetricsRegistry()
    fl = FleetScheduler(fleet_cfg("a:seed=1;b:seed=2;c:seed=3"),
                        checkpoint=False, registry=reg)
    fleet_losses(fl, 4)
    # one upload per ROUND, not per tenant — the amortization itself
    assert reg.get_count("comm/h2d_transfers") == 4
    assert reg.get_count("tenant/admissions") == 3


# ---------------------------------------------------------------------------
# admission / retirement mid-run


def test_admission_and_retirement_mid_run():
    reg = MetricsRegistry()
    fl = FleetScheduler(fleet_cfg("a:seed=1;b:seed=2"),
                        checkpoint=False, registry=reg)
    traj = fleet_losses(fl, 3)
    fl.admit(TenantSpec("late", {"seed": 7, "dict_size": 128}))
    assert "late" in fl.active() and len(fl._buckets) == 1
    mid = fleet_losses(fl, 3)
    # a late tenant equals a solo run LAUNCHED at the live stream head
    assert mid["late"] == solo_losses(dict(seed=7, dict_size=128), 3,
                                      skip_rounds=3)
    fl.retire("b", save=False)
    assert fl.active() == ["a", "late"]
    assert not fl._buckets or fl._buckets[0].tenant.name == "late"
    tail = fleet_losses(fl, 3)
    assert "b" not in tail
    # the surviving cohort member is untouched by churn around it:
    # its full 9-round trajectory is still bitwise the solo run
    full_a = traj["a"] + mid["a"] + tail["a"]
    assert full_a == solo_losses(dict(seed=1), 9)
    assert reg.get_count("tenant/admissions") == 3
    assert reg.get_count("tenant/retirements") == 1


def test_bucket_cap_rejects_then_frees():
    fl = FleetScheduler(
        fleet_cfg("a:seed=1,dict_size=128", fleet_max_buckets=1),
        checkpoint=False,
    )
    with pytest.raises(ValueError, match="fleet_max_buckets"):
        fl.admit(TenantSpec("b", {"dict_size": 96}))
    assert fl.active() == ["a"]          # failed admission rolled back
    fl.retire("a", save=False)           # frees the only bucket slot
    fl.admit(TenantSpec("b", {"dict_size": 96}))
    assert fl.active() == ["b"]


# ---------------------------------------------------------------------------
# restore-all after a simulated preemption


def test_restore_all_after_preemption(tmp_path):
    spec = "a:seed=1;b:seed=2;w:seed=3,dict_size=128"

    ref = fleet_losses(
        FleetScheduler(fleet_cfg(spec), checkpoint=False), 8,
    )

    fl = FleetScheduler(fleet_cfg(spec, checkpoint_dir=str(tmp_path)))
    head = fleet_losses(fl, 4)
    fl.save_all()
    fl.quiesce()
    del fl                               # the preemption

    fl2 = FleetScheduler(fleet_cfg(spec, checkpoint_dir=str(tmp_path)))
    restored = fl2.restore_all()
    assert restored == {"a": 4, "b": 4, "w": 4}
    assert fl2.buffer.counter == 4       # shared stream rewound with them
    tail = fleet_losses(fl2, 4)
    for name in ("a", "b", "w"):
        assert head[name] == ref[name][:4], name
        assert tail[name] == ref[name][4:], name
