"""End-to-end entry-point tests: CLI flags → full train loop → checkpoints
→ resume (the reference's train.py flow, with the CLI actually wired)."""

import json

import jax
import numpy as np
import pytest

from crosscoder_tpu.checkpoint.ckpt import Checkpointer
from crosscoder_tpu.config import CrossCoderConfig
from crosscoder_tpu.data.tokens import load_pile_lmsys_mixed_tokens
from crosscoder_tpu.train.main import main


def _argv(tmp_path, extra=()):
    return [
        "--data-source", "synthetic",
        "--batch-size", "64",
        "--buffer-mult", "4",
        "--num-tokens", "6400",           # 100 steps
        "--d-in", "16",
        "--dict-size", "256",
        "--seq-len", "17",
        "--lr", "3e-3",
        "--log-backend", "jsonl",
        "--log-every", "20",
        "--save-every", "60",
        "--checkpoint-dir", str(tmp_path / "ckpt"),
        *extra,
    ]


def test_main_synthetic_end_to_end(tmp_path):
    trainer = main(_argv(tmp_path))
    assert trainer.step_counter == 100
    # versioned checkpoints: one at step 60 plus the finally-save
    vdir = Checkpointer.latest_version_dir(tmp_path / "ckpt")
    saves = sorted(int(p.stem) for p in vdir.glob("*.npz") if p.stem.isdigit())
    assert saves == [0, 1]
    # metrics jsonl has the reference's 9-scalar surface
    lines = [
        json.loads(ln)
        for ln in (tmp_path / "ckpt" / "metrics.jsonl").read_text().splitlines()
    ]
    assert {"loss", "l2_loss", "l1_loss", "l0_loss", "l1_coeff", "lr",
            "explained_variance", "explained_variance_A",
            "explained_variance_B"} <= set(lines[-1])
    # training made progress on the synthetic ground-truth dictionary
    assert lines[-1]["loss"] < lines[0]["loss"]


def test_main_resume_continues(tmp_path):
    main(_argv(tmp_path))
    trainer = main(_argv(tmp_path, ["--resume", "true", "--num-tokens", "7680"]))
    assert trainer.step_counter == 120          # 100 restored + 20 more
    vdir = Checkpointer.latest_version_dir(tmp_path / "ckpt")
    meta = json.loads(sorted(vdir.glob("*_meta.json"))[-1].read_text())
    assert meta["step"] == 120


def test_cli_rejects_bad_source(tmp_path):
    with pytest.raises(ValueError):
        main(_argv(tmp_path, ["--data-source", "nope"]))


def test_tokens_loader_npy_cache(tmp_path):
    cfg = CrossCoderConfig(data_dir=str(tmp_path), dataset_name="x/fake-corpus")
    want = np.arange(6 * 1024, dtype=np.int32).reshape(6, 1024)
    np.save(tmp_path / "fake-corpus.npy", want)
    got = load_pile_lmsys_mixed_tokens(cfg)
    np.testing.assert_array_equal(np.asarray(got), want)


def test_tokens_loader_accepts_reference_pt_cache(tmp_path):
    torch = pytest.importorskip("torch")
    cfg = CrossCoderConfig(data_dir=str(tmp_path), dataset_name="x/fake-corpus")
    want = np.arange(4 * 1024, dtype=np.int64).reshape(4, 1024)
    torch.save(torch.from_numpy(want), tmp_path / "fake-corpus.pt")
    got = load_pile_lmsys_mixed_tokens(cfg)
    assert got.dtype == np.int32
    np.testing.assert_array_equal(got, want)


def test_build_buffer_shard_lm_plumbing(monkeypatch):
    """--shard-lm true loads LM weights through lm.from_hf with the
    tensor-parallel shardings (and refuses a 1-wide model axis)."""
    from crosscoder_tpu.models import lm
    from crosscoder_tpu.train import main as main_mod

    lm_cfg = lm.LMConfig.tiny()
    seen = {}

    def fake_from_hf(name, cfg=None, shardings=None):
        seen[name] = shardings
        return lm.init_params(jax.random.key(0), lm_cfg), lm_cfg

    def fake_tokens(cfg, mmap=True):
        return np.random.default_rng(0).integers(
            0, 257, size=(64, cfg.seq_len), dtype=np.int64)

    monkeypatch.setattr(lm, "from_hf", fake_from_hf)
    monkeypatch.setattr(lm, "config_for", lambda name: lm_cfg)
    import crosscoder_tpu.data.tokens as tokens_mod
    monkeypatch.setattr(tokens_mod, "load_pile_lmsys_mixed_tokens", fake_tokens)

    cfg = CrossCoderConfig(
        data_source="gemma", shard_lm=True, model_names=("gemma-2-2b", "gemma-2-2b-it"),
        batch_size=16, buffer_mult=32, seq_len=17, model_batch_size=8,
        norm_calib_batches=1, hook_point="blocks.1.hook_resid_pre",
        data_axis_size=4, model_axis_size=2, log_backend="null",
        prefetch=False,
    )
    from crosscoder_tpu.parallel import mesh as mesh_lib
    mesh = mesh_lib.mesh_from_cfg(cfg)
    buf, cfg2 = main_mod.build_buffer(cfg, mesh)
    assert cfg2.d_in == lm_cfg.d_model
    assert set(seen) == {"gemma-2-2b", "gemma-2-2b-it"}
    for sh in seen.values():
        assert sh is not None and sh["layers"]["wq"].spec[2] == "model"

    # 1-wide model axis refused at CONFIG time
    with pytest.raises(ValueError, match="shard_lm"):
        cfg.replace(data_axis_size=8, model_axis_size=1)
    # and the seq-parallel harvest (replicated-params shard_map) refused too
    with pytest.raises(ValueError, match="seq_shards"):
        cfg.replace(seq_shards=4, seq_len=16)
