"""End-to-end entry-point tests: CLI flags → full train loop → checkpoints
→ resume (the reference's train.py flow, with the CLI actually wired)."""

import json

import numpy as np
import pytest

from crosscoder_tpu.checkpoint.ckpt import Checkpointer
from crosscoder_tpu.config import CrossCoderConfig
from crosscoder_tpu.data.tokens import load_pile_lmsys_mixed_tokens
from crosscoder_tpu.train.main import main


def _argv(tmp_path, extra=()):
    return [
        "--data-source", "synthetic",
        "--batch-size", "64",
        "--buffer-mult", "4",
        "--num-tokens", "6400",           # 100 steps
        "--d-in", "16",
        "--dict-size", "256",
        "--seq-len", "17",
        "--lr", "3e-3",
        "--log-backend", "jsonl",
        "--log-every", "20",
        "--save-every", "60",
        "--checkpoint-dir", str(tmp_path / "ckpt"),
        *extra,
    ]


def test_main_synthetic_end_to_end(tmp_path):
    trainer = main(_argv(tmp_path))
    assert trainer.step_counter == 100
    # versioned checkpoints: one at step 60 plus the finally-save
    vdir = Checkpointer.latest_version_dir(tmp_path / "ckpt")
    saves = sorted(int(p.stem) for p in vdir.glob("*.npz") if p.stem.isdigit())
    assert saves == [0, 1]
    # metrics jsonl has the reference's 9-scalar surface
    lines = [
        json.loads(ln)
        for ln in (tmp_path / "ckpt" / "metrics.jsonl").read_text().splitlines()
    ]
    assert {"loss", "l2_loss", "l1_loss", "l0_loss", "l1_coeff", "lr",
            "explained_variance", "explained_variance_A",
            "explained_variance_B"} <= set(lines[-1])
    # training made progress on the synthetic ground-truth dictionary
    assert lines[-1]["loss"] < lines[0]["loss"]


def test_main_resume_continues(tmp_path):
    main(_argv(tmp_path))
    trainer = main(_argv(tmp_path, ["--resume", "true", "--num-tokens", "7680"]))
    assert trainer.step_counter == 120          # 100 restored + 20 more
    vdir = Checkpointer.latest_version_dir(tmp_path / "ckpt")
    meta = json.loads(sorted(vdir.glob("*_meta.json"))[-1].read_text())
    assert meta["step"] == 120


def test_cli_rejects_bad_source(tmp_path):
    with pytest.raises(ValueError):
        main(_argv(tmp_path, ["--data-source", "nope"]))


def test_tokens_loader_npy_cache(tmp_path):
    cfg = CrossCoderConfig(data_dir=str(tmp_path), dataset_name="x/fake-corpus")
    want = np.arange(6 * 1024, dtype=np.int32).reshape(6, 1024)
    np.save(tmp_path / "fake-corpus.npy", want)
    got = load_pile_lmsys_mixed_tokens(cfg)
    np.testing.assert_array_equal(np.asarray(got), want)


def test_tokens_loader_accepts_reference_pt_cache(tmp_path):
    torch = pytest.importorskip("torch")
    cfg = CrossCoderConfig(data_dir=str(tmp_path), dataset_name="x/fake-corpus")
    want = np.arange(4 * 1024, dtype=np.int64).reshape(4, 1024)
    torch.save(torch.from_numpy(want), tmp_path / "fake-corpus.pt")
    got = load_pile_lmsys_mixed_tokens(cfg)
    assert got.dtype == np.int32
    np.testing.assert_array_equal(got, want)
