"""bench.py must never rot: the driver runs it at every round end to
produce the scored headline. This smoke runs the real script (subprocess,
CPU, tiny shapes) and checks the output contract — exactly one COMPACT
(≤2 KB: the driver truncates at 2000 chars, which is how BENCH_r05
shipped ``parsed: null``) JSON line on stdout with the headline fields
and gate booleans, full per-section detail in the artifact file."""

import json
import os
import subprocess
import sys
from pathlib import Path

import pytest

_ROOT = Path(__file__).parent.parent


@pytest.mark.slow
def test_bench_emits_one_compact_json_headline(tmp_path):
    artifact = tmp_path / "BENCH_DETAIL.json"
    env = dict(os.environ)
    env.update(
        BENCH_TINY="1", BENCH_CPU="1",
        BENCH_SECTIONS="step,e2e,harvest",
        BENCH_STEPS="4", BENCH_E2E_STEPS="4",
        BENCH_DIN="32", BENCH_DICT="256", BENCH_BATCH="64",
        BENCH_ARTIFACT=str(artifact),
        JAX_PLATFORMS="cpu",
    )
    env.pop("XLA_FLAGS", None)          # 1-device CPU: cheap and stable
    r = subprocess.run(
        [sys.executable, "bench.py"], cwd=str(_ROOT), env=env,
        capture_output=True, text=True, timeout=900,
    )
    assert r.returncode == 0, r.stderr[-3000:]
    lines = [l for l in r.stdout.strip().splitlines() if l.strip()]
    assert len(lines) == 1, f"stdout must be ONE JSON line, got {lines}"
    # the whole point of the compact contract: the line survives the
    # driver's 2000-char truncation, so "parsed" can never be null
    assert len(lines[0]) <= 2000, f"summary line is {len(lines[0])} B"
    out = json.loads(lines[0])
    for key in ("metric", "value", "unit", "vs_baseline", "gates"):
        assert key in out, key
    assert out["value"] and out["value"] > 0
    assert out["gates"]["e2e.loss_finite"] is True
    assert out["e2e"]["loss_finite"] is True
    # the harvest section's contract (speedup itself is shape-dependent:
    # toy dims are dispatch-bound, so only the fields are asserted here)
    assert 0 < out["harvest"]["padding_efficiency"] <= 1
    assert out["harvest"]["paged_step_ms"] > 0
    # full detail lands in the artifact, not on stdout
    assert out["detail"] == str(artifact)
    detail = json.loads(artifact.read_text())
    for section in ("step", "e2e", "harvest"):
        assert section in detail, section
    assert detail["e2e"]["workload"]           # detail keeps the long fields
    assert detail["harvest"]["tokens_per_sec_paged"] > 0


def test_bench_compact_summary_is_small_and_gated():
    """The pure summary projection: full-size fake section results must
    compact to ≤2 KB with the gate booleans and per-dict relu ratios."""
    sys.path.insert(0, str(_ROOT))
    try:
        import bench
    finally:
        sys.path.pop(0)

    headline = {"metric": "end-to-end acts/sec/chip (x)", "value": 25000.0,
                "unit": "activations/s/chip", "vs_baseline": 1.1,
                "compile_cache": "warm"}
    matrix = []
    for d in (2**15, 2**16, 2**17):
        matrix.append({"variant": "relu", "dict_size": d,
                       "acts_per_sec_chip": 150000.0, "step_ms": 27.3,
                       "loss_finite": True, "n_devices": 1,
                       "workload": "w" * 80})
        for v in ("topk_dense", "topk_pallas", "topk_sparse_decode",
                  "topk_sparse_bwd", "batchtopk", "batchtopk_pallas"):
            matrix.append({"variant": v, "dict_size": d,
                           "acts_per_sec_chip": 140000.0, "step_ms": 29.0,
                           "fwd_ms": 9.0, "bwd_ms": 17.2,
                           "loss_finite": True, "n_devices": 1,
                           "workload": "w" * 80})
    matrix.append({"variant": "batchtopk_pallas", "dict_size": 2**18,
                   "skipped": "unsupported at this width"})
    results = {
        "step": {"acts_per_sec_chip": 148000.0, "vs_a100_step": 1.92,
                 "workload": "w" * 120},
        "matrix": matrix,
        "configs": [{"config": f"cfg{i}", "acts_per_sec_chip": 1000.0 * i,
                     "workload": "w" * 120} for i in range(5)],
        "e2e": {"acts_per_sec_chip": 25000.0, "vs_a100_e2e": 1.1,
                "step_ms_median": 40.0, "refresh_bubble_ms": 12.0,
                "loss_finite": True, "workload": "w" * 200},
        "refill_overlap": {"gate_ok": True, "seg3_gate_ok": True,
                           "seg14_gate_ok": True, "n_steps_measured": 30},
        "harvest": {"padding_efficiency": 0.62, "paged_step_ms": 50.0,
                    "paged_speedup": 1.4, "workload": "w" * 120},
        "quant": {"roundtrip_rel_mse": 1.2e-4, "quality_gate_ok": True,
                  "grad_allreduce": {"big": "nested" * 40}},
        "obs": {"obs_overhead_frac": 0.004, "overhead_gate_ok": True,
                "spans_per_sec": 1e6},
        "dash": {"steady_s": 15.0, "vs_reference": 1.27},
        "elastic": {"remesh_ms": 1500, "bitwise_equal": True,
                    "resume_step": 6, "post_steps": 4,
                    "grow_ms": 1300, "autoscale_bitwise_equal": True,
                    "joiner_equal": True, "autoscale_cycle_s": 38.5,
                    "autoscale_resume_step": 10,
                    "workload": "w" * 80},
    }
    out = bench._compact(headline, results)
    line = json.dumps(out)
    assert len(line) <= 2000, f"{len(line)} B"
    assert out["gates"] == {
        "refill_overlap.gate_ok": True, "quant.quality_gate_ok": True,
        "obs.overhead_gate_ok": True, "e2e.loss_finite": True,
        "elastic.bitwise_equal": True,
        "elastic.autoscale_bitwise_equal": True,
    }
    assert out["elastic"]["remesh_ms"] == 1500
    # the scale-UP leg's headline numbers ride the same compact line
    assert out["elastic"]["grow_ms"] == 1300
    assert out["elastic"]["autoscale_cycle_s"] == 38.5
    assert out["step_ratio_vs_relu"]["topk_dense@32768"] == round(
        150000.0 / 140000.0, 3)
    assert out["step_ratio_vs_relu"]["batchtopk_pallas@262144"] == "skip"
    assert out["relu_acts_per_dict"] == {2**i: 150000.0
                                         for i in (15, 16, 17)}
    # a failed section surfaces as a compact error stub, not 300 chars
    out2 = bench._compact(headline, {
        "e2e": {"error": "RuntimeError: " + "x" * 290}})
    assert len(out2["e2e"]["error"]) <= 120
