"""bench.py must never rot: the driver runs it at every round end to
produce the scored headline. This smoke runs the real script (subprocess,
CPU, tiny shapes) and checks the output contract — exactly one JSON line
on stdout with the headline fields."""

import json
import os
import subprocess
import sys
from pathlib import Path

import pytest

_ROOT = Path(__file__).parent.parent


@pytest.mark.slow
def test_bench_emits_one_json_headline():
    env = dict(os.environ)
    env.update(
        BENCH_TINY="1", BENCH_CPU="1",
        BENCH_SECTIONS="step,e2e,harvest",
        BENCH_STEPS="4", BENCH_E2E_STEPS="4",
        BENCH_DIN="32", BENCH_DICT="256", BENCH_BATCH="64",
        JAX_PLATFORMS="cpu",
    )
    env.pop("XLA_FLAGS", None)          # 1-device CPU: cheap and stable
    r = subprocess.run(
        [sys.executable, "bench.py"], cwd=str(_ROOT), env=env,
        capture_output=True, text=True, timeout=900,
    )
    assert r.returncode == 0, r.stderr[-3000:]
    lines = [l for l in r.stdout.strip().splitlines() if l.strip()]
    assert len(lines) == 1, f"stdout must be ONE JSON line, got {lines}"
    out = json.loads(lines[0])
    for key in ("metric", "value", "unit", "vs_baseline"):
        assert key in out, key
    assert out["value"] and out["value"] > 0
    assert out["e2e"]["loss_finite"] is True
    # the harvest section's contract (speedup itself is shape-dependent:
    # toy dims are dispatch-bound, so only the fields are asserted here)
    assert 0 < out["harvest"]["padding_efficiency"] <= 1
    assert out["harvest"]["paged_step_ms"] > 0
