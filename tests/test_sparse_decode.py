"""Sparse TopK decode (cfg.sparse_decode) vs the dense TopK path: the
factored gather/custom-vjp decode must reproduce the dense losses AND
parameter gradients (it is the same math restricted to the k nonzero
terms; no reference counterpart — reference crosscoder.py:82-89 is always
dense)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from crosscoder_tpu.config import CrossCoderConfig
from crosscoder_tpu.models import crosscoder as cc
from crosscoder_tpu.parallel import mesh as mesh_lib


def cfgs(**kw):
    base = dict(d_in=24, dict_size=128, batch_size=64, enc_dtype="fp32",
                activation="topk", topk_k=8, l1_coeff=0.5, log_backend="null")
    base.update(kw)
    dense = CrossCoderConfig(**base)
    return dense, dense.replace(sparse_decode=True)


def _data(cfg, seed=0):
    rng = np.random.default_rng(seed)
    x = rng.normal(size=(cfg.batch_size, cfg.n_sources, cfg.d_in)).astype(np.float32)
    params = cc.init_params(jax.random.key(1), cfg)
    return params, jnp.asarray(x)


def test_losses_match_dense():
    dense_cfg, sparse_cfg = cfgs()
    params, x = _data(dense_cfg)
    ld = cc.get_losses(params, x, dense_cfg)
    ls = cc.get_losses(params, x, sparse_cfg)
    np.testing.assert_allclose(float(ld.l2_loss), float(ls.l2_loss), rtol=1e-5)
    np.testing.assert_allclose(float(ld.l1_loss), float(ls.l1_loss), rtol=1e-5)
    assert float(ld.l0_loss) == float(ls.l0_loss)
    np.testing.assert_allclose(
        np.asarray(ld.explained_variance), np.asarray(ls.explained_variance), rtol=1e-4
    )


def test_grads_match_dense():
    dense_cfg, sparse_cfg = cfgs()
    params, x = _data(dense_cfg, seed=3)

    def loss(cfg):
        def fn(p):
            l, _ = cc.training_loss(p, x, 0.5, cfg)
            return l
        return jax.grad(fn)(params)

    gd = loss(dense_cfg)
    gs = loss(sparse_cfg)
    for k in gd:
        np.testing.assert_allclose(
            np.asarray(gd[k]), np.asarray(gs[k]), rtol=2e-4, atol=1e-6, err_msg=k
        )


def test_bf16_compute_path_runs_finite():
    _, sparse_cfg = cfgs(enc_dtype="bf16")
    params, x = _data(sparse_cfg, seed=5)
    loss, losses = jax.jit(
        lambda p, xx: cc.training_loss(p, xx, 0.1, sparse_cfg)
    )(params, x)
    assert np.isfinite(float(loss))
    assert float(losses.l0_loss) <= sparse_cfg.topk_k


def test_sparse_decode_on_sharded_mesh():
    """The gather/scatter decode must compile and match under DPxTP."""
    devs = jax.devices()
    assert len(devs) == 8
    dense_cfg, sparse_cfg = cfgs(batch_size=64)
    params, x = _data(dense_cfg, seed=7)
    mesh = mesh_lib.make_mesh(data_axis_size=4, model_axis_size=2)
    shardings = mesh_lib.param_shardings(mesh, params)
    p_sh = jax.device_put(params, shardings)
    x_sh = jax.device_put(x, mesh_lib.batch_sharding(mesh))

    def fn(p, xx):
        l, _ = cc.training_loss(p, xx, 0.5, sparse_cfg)
        return l

    g_single = jax.grad(fn)(params, x)
    g_shard = jax.jit(jax.grad(fn))(p_sh, x_sh)
    for k in g_single:
        np.testing.assert_allclose(
            np.asarray(g_single[k]), np.asarray(jax.device_get(g_shard[k])),
            rtol=2e-4, atol=1e-6, err_msg=k,
        )


def test_config_rejects_sparse_without_topk():
    with pytest.raises(ValueError, match="sparse_decode"):
        CrossCoderConfig(activation="relu", sparse_decode=True)
