"""Corpus loading: disk-cache round trip and seq-len re-chunking (the
published corpus is pre-chunked at 1024; long-context harvest concatenates
whole rows, reference utils.py:180-196 has no such path)."""

import numpy as np
import pytest

from crosscoder_tpu.config import CrossCoderConfig
from crosscoder_tpu.data import tokens as tok_mod


def test_rechunk_identity_and_views():
    t = np.arange(6 * 8, dtype=np.int32).reshape(6, 8)
    assert tok_mod.rechunk(t, 8) is t
    # longer: concatenate whole rows, drop the ragged remainder
    long = tok_mod.rechunk(t, 16)
    assert long.shape == (3, 16)
    np.testing.assert_array_equal(long[0], np.arange(16))


def test_rechunk_incompatible():
    t = np.zeros((4, 8), np.int32)
    with pytest.raises(ValueError, match="must be a multiple"):
        tok_mod.rechunk(t, 6)
    # shorter sequences are rejected: the split tails would be BOS-less
    with pytest.raises(ValueError, match="must be a multiple"):
        tok_mod.rechunk(t, 4)
    with pytest.raises(ValueError, match="cannot form"):
        tok_mod.rechunk(t, 64)


def test_npy_cache_roundtrip_with_rechunk(tmp_path):
    corpus = np.arange(8 * 16, dtype=np.int32).reshape(8, 16)
    cfg = CrossCoderConfig(data_dir=str(tmp_path), dataset_name="x/demo-corpus",
                           seq_len=32, seq_shards=0)
    np.save(tmp_path / "demo-corpus.npy", corpus)
    out = tok_mod.load_pile_lmsys_mixed_tokens(cfg)
    assert out.shape == (4, 32)
    np.testing.assert_array_equal(out[0], np.arange(32))
