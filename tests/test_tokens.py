"""Corpus loading: disk-cache round trip and seq-len re-chunking (the
published corpus is pre-chunked at 1024; long-context harvest concatenates
whole rows, reference utils.py:180-196 has no such path)."""

import numpy as np
import pytest

from crosscoder_tpu.config import CrossCoderConfig
from crosscoder_tpu.data import tokens as tok_mod


def test_rechunk_identity_and_views():
    t = np.arange(6 * 8, dtype=np.int32).reshape(6, 8)
    assert tok_mod.rechunk(t, 8) is t
    # longer: concatenate whole rows, drop the ragged remainder
    long = tok_mod.rechunk(t, 16)
    assert long.shape == (3, 16)
    np.testing.assert_array_equal(long[0], np.arange(16))


def test_rechunk_incompatible():
    t = np.zeros((4, 8), np.int32)
    with pytest.raises(ValueError, match="must be a multiple"):
        tok_mod.rechunk(t, 6)
    # shorter sequences are rejected: the split tails would be BOS-less
    with pytest.raises(ValueError, match="must be a multiple"):
        tok_mod.rechunk(t, 4)
    with pytest.raises(ValueError, match="cannot form"):
        tok_mod.rechunk(t, 64)


def test_npy_cache_roundtrip_with_rechunk(tmp_path):
    corpus = np.arange(8 * 16, dtype=np.int32).reshape(8, 16)
    cfg = CrossCoderConfig(data_dir=str(tmp_path), dataset_name="x/demo-corpus",
                           seq_len=32, seq_shards=0)
    np.save(tmp_path / "demo-corpus.npy", corpus)
    out = tok_mod.load_pile_lmsys_mixed_tokens(cfg)
    assert out.shape == (4, 32)
    np.testing.assert_array_equal(out[0], np.arange(32))


# ---------------------------------------------------------------------------
# ragged lengths + distribution stats (the paged harvest runtime's inputs)


def test_valid_lengths():
    t = np.array([
        [5, 6, 7, 8],        # full
        [5, 6, 0, 0],        # trailing pads
        [5, 0, 7, 0],        # interior pad is CONTENT (only the tail trims)
        [0, 0, 0, 0],        # pure padding -> length 1 (the BOS slot)
        [5, 0, 0, 0],        # single token
    ], np.int32)
    np.testing.assert_array_equal(
        tok_mod.valid_lengths(t), [4, 2, 3, 1, 1]
    )


def test_length_stats_histogram_and_efficiency():
    lengths = np.array([1, 4, 4, 8, 8, 8])
    s = tok_mod.length_stats(lengths, seq_len=8, n_buckets=4)
    assert s["n_sampled"] == 6 and s["seq_len"] == 8
    assert sum(s["bucket_counts"]) == 6
    assert s["min_len"] == 1 and s["max_len"] == 8
    want_eff = lengths.sum() / (6 * 8)
    assert s["padding_efficiency"] == pytest.approx(want_eff, abs=1e-4)
    assert s["paged_matmul_speedup_estimate"] == pytest.approx(
        1 / want_eff, abs=0.01
    )


def test_length_stats_from_token_matrix():
    t = np.array([[3, 4, 0, 0], [3, 4, 5, 6]], np.int32)
    s = tok_mod.length_stats(t)
    assert s["seq_len"] == 4
    assert s["padding_efficiency"] == pytest.approx(6 / 8, abs=1e-4)
    with pytest.raises(ValueError, match="seq_len is required"):
        tok_mod.length_stats(np.array([1, 2]))


def test_length_stats_samples_evenly_across_ordered_corpus():
    """The sample strides the whole corpus: a corpus stored as full-length
    rows followed by ragged rows must not report 100% efficiency off a
    head sample."""
    full = np.ones((1000, 8), np.int32)
    ragged = np.ones((1000, 8), np.int32)
    ragged[:, 2:] = 0                                # length 2
    s = tok_mod.length_stats(np.vstack([full, ragged]), sample_rows=100)
    assert 0.5 < s["padding_efficiency"] < 0.75      # ~ (8+2)/16 = 0.625
    # sample_rows < n_rows < 2*sample_rows: floor-division stride would be
    # 1 (a pure head sample reporting 1.0); ceil must stride the whole span
    s = tok_mod.length_stats(np.vstack([full, ragged]), sample_rows=700)
    assert 0.5 < s["padding_efficiency"] < 0.75


def test_loader_emits_length_stats(tmp_path, capsys):
    corpus = np.arange(1, 8 * 16 + 1, dtype=np.int32).reshape(8, 16)
    cfg = CrossCoderConfig(data_dir=str(tmp_path), dataset_name="x/demo2",
                           seq_len=16)
    np.save(tmp_path / "demo2.npy", corpus)
    tok_mod.load_pile_lmsys_mixed_tokens(cfg)
    out = capsys.readouterr().err      # diagnostics ride stderr (bench contract)
    assert "padding efficiency" in out and "100.00%" in out
