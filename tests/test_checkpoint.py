"""Checkpoint tests: versioned layout (reference crosscoder.py:132-158
semantics), bit-exact resume (capability the reference lacks), and the torch
state_dict round-trip for interop with published reference checkpoints."""

import json

import jax
import numpy as np
import torch

from crosscoder_tpu.checkpoint import Checkpointer
from crosscoder_tpu.checkpoint import torch_compat
from crosscoder_tpu.config import CrossCoderConfig
from crosscoder_tpu.models import crosscoder as cc
from crosscoder_tpu.train.trainer import Trainer


def tiny_cfg(tmp_path, **kw):
    base = dict(
        d_in=16,
        dict_size=64,
        batch_size=64,
        num_tokens=64 * 100,
        enc_dtype="fp32",
        lr=1e-3,
        l1_coeff=0.1,
        log_backend="null",
        checkpoint_dir=str(tmp_path),
    )
    base.update(kw)
    return CrossCoderConfig(**base)


def test_versioned_layout(tmp_path):
    cfg = tiny_cfg(tmp_path)
    tr = Trainer(cfg, checkpointer=Checkpointer(cfg=cfg))
    tr.step()
    tr.save()
    tr.save()
    vdir = tmp_path / "version_0"
    # reference artifact naming: {v}.<weights> + {v}_cfg.json, versions increment
    assert (vdir / "0.npz").exists() and (vdir / "0_cfg.json").exists()
    assert (vdir / "1.npz").exists() and (vdir / "1_cfg.json").exists()
    # a second run scans existing dirs and claims version_1 (crosscoder.py:135-145)
    ck2 = Checkpointer(cfg=cfg)
    tr2 = Trainer(cfg, checkpointer=ck2)
    tr2.save()
    assert (tmp_path / "version_1" / "0.npz").exists()
    # cfg JSON round-trips through our config
    loaded = CrossCoderConfig.from_json(vdir / "0_cfg.json")
    assert loaded.dict_size == cfg.dict_size


def test_resume_is_bit_exact(tmp_path):
    """train 10 steps, checkpoint, train 5 more; vs restore + 5: identical."""
    cfg = tiny_cfg(tmp_path)
    tr = Trainer(cfg, checkpointer=Checkpointer(cfg=cfg))
    for _ in range(10):
        tr.step()
    tr.save()
    for _ in range(5):
        tr.step()
    params_straight = {k: np.asarray(v).copy() for k, v in jax.device_get(tr.state.params).items()}

    tr2 = Trainer(cfg, checkpointer=Checkpointer(base_dir=tmp_path))
    meta = tr2.restore()
    assert meta["step"] == 10
    assert tr2.step_counter == 10
    assert tr2.buffer.counter == 10  # pipeline state restored
    for _ in range(5):
        tr2.step()
    params_resumed = jax.device_get(tr2.state.params)
    for k in params_straight:
        np.testing.assert_array_equal(params_straight[k], np.asarray(params_resumed[k]), err_msg=k)


def test_background_save_lands_and_resumes(tmp_path):
    """save(background=True): write overlaps training; wait()/restore see
    the complete save; tmp files never linger (round-3 VERDICT weak #3 —
    the synchronous full-state write sat inside the preemption window)."""
    cfg = tiny_cfg(tmp_path)
    ck = Checkpointer(cfg=cfg)
    tr = Trainer(cfg, checkpointer=ck)
    for _ in range(3):
        tr.step()
    tr.save(background=True)
    for _ in range(2):
        tr.step()                      # steps proceed while the write runs
    ck.wait()
    vdir = tmp_path / "version_0"
    assert (vdir / "0.npz").exists() and (vdir / "0_meta.json").exists()
    assert not list(vdir.glob("*.tmp"))
    assert json.loads((vdir / "0_meta.json").read_text())["step"] == 3

    # restore() on the same instance self-serializes (no explicit wait)
    tr.save(background=True)
    tr2 = Trainer(cfg, checkpointer=ck)
    meta = tr2.restore()
    assert meta["step"] == 5
    tr.close()
    tr2.close()


def test_background_saves_serialize(tmp_path):
    """back-to-back background saves: versions appear in order, none torn."""
    cfg = tiny_cfg(tmp_path)
    ck = Checkpointer(cfg=cfg)
    tr = Trainer(cfg, checkpointer=ck)
    for i in range(3):
        tr.step()
        tr.save(background=True)
    tr.close()                         # joins the writer
    vdir = tmp_path / "version_0"
    for v in range(3):
        assert (vdir / f"{v}.npz").exists(), v
        assert json.loads((vdir / f"{v}_meta.json").read_text())["step"] == v + 1
    assert not list(vdir.glob("*.tmp"))


def test_background_save_snapshot_isolated_from_donated_steps(tmp_path, monkeypatch):
    """The background writer must serialize the state AS FETCHED, not
    views of live device buffers: on the CPU backend np.asarray(jax.Array)
    can be zero-copy, and the donated train step reuses that memory — a
    slow writer then records a LATER step's bytes under this save's meta
    (observed live: train_state at step 10 under meta step 5, poisoned by
    a NaN step in between). The writer is slowed here so any aliasing
    deterministically loses the race."""
    import time

    import crosscoder_tpu.checkpoint.ckpt as ckpt_mod

    real_savez = ckpt_mod._atomic_savez

    def slow_savez(path, arrays):
        time.sleep(0.3)                 # steps run while the write waits
        return real_savez(path, arrays)

    monkeypatch.setattr(ckpt_mod, "_atomic_savez", slow_savez)
    cfg = tiny_cfg(tmp_path)
    ck = Checkpointer(cfg=cfg)
    tr = Trainer(cfg, checkpointer=ck)
    for _ in range(3):
        tr.step()
    tr.save(background=True)
    for _ in range(10):
        tr.step()                       # donated-state reuse during the write
    ck.wait()
    vdir = tmp_path / "version_0"
    meta = json.loads((vdir / "0_meta.json").read_text())
    assert meta["step"] == 3
    with np.load(vdir / "0_train_state.npz") as z:
        assert int(z[".step"]) == 3     # NOT a later step's state
        state_wenc = z[".params['W_enc']"]
    with np.load(vdir / "0.npz") as z:
        np.testing.assert_array_equal(z["W_enc"], state_wenc.astype(np.float32))
    tr.close()


def test_torn_save_is_skipped(tmp_path):
    """A save whose meta (the completion marker, written last) is missing —
    a kill after the weights npz landed — must be invisible: restore picks
    the previous COMPLETE save instead of crashing on missing files."""
    cfg = tiny_cfg(tmp_path)
    ck = Checkpointer(cfg=cfg)
    tr = Trainer(cfg, checkpointer=ck)
    tr.step()
    tr.save()
    vdir = tmp_path / "version_0"
    # simulate the torn save: weights of save 1 present, no meta/state
    (vdir / "1.npz").write_bytes((vdir / "0.npz").read_bytes())
    assert Checkpointer.latest_save(vdir) == 0
    tr2 = Trainer(cfg, checkpointer=Checkpointer(base_dir=tmp_path))
    assert tr2.restore()["step"] == 1
    tr.close()
    tr2.close()

    # a FRESH run preempted during its very first save: version_1 holds
    # only torn artifacts (even train_state, killed before meta) — resume
    # must fall back to version_0's complete save, not crash on version_1
    v1 = tmp_path / "version_1"
    v1.mkdir()
    (v1 / "0.npz").write_bytes((vdir / "0.npz").read_bytes())
    (v1 / "0_train_state.npz").write_bytes((vdir / "0_train_state.npz").read_bytes())
    tr3 = Trainer(cfg, checkpointer=Checkpointer(base_dir=tmp_path))
    assert tr3.restore()["step"] == 1
    tr3.close()
    import pytest
    with pytest.raises(FileNotFoundError):
        Checkpointer.latest_save(v1)  # torn, not a foreign weights-only dir


def test_restore_rejects_mismatched_shapes(tmp_path):
    cfg = tiny_cfg(tmp_path)
    tr = Trainer(cfg, checkpointer=Checkpointer(cfg=cfg))
    tr.step()
    tr.save()
    cfg_bigger = tiny_cfg(tmp_path, dict_size=128)
    tr2 = Trainer(cfg_bigger, checkpointer=Checkpointer(base_dir=tmp_path))
    try:
        tr2.restore()
        raise AssertionError("expected shape-mismatch rejection")
    except ValueError as e:
        assert "shape" in str(e)


def test_load_weights_analysis_path(tmp_path):
    cfg = tiny_cfg(tmp_path)
    tr = Trainer(cfg, checkpointer=Checkpointer(cfg=cfg))
    tr.step()
    tr.save()
    params, loaded_cfg = Checkpointer.load_weights(tmp_path / "version_0")
    assert set(params) == {"W_enc", "W_dec", "b_enc", "b_dec"}
    assert params["W_enc"].shape == (2, cfg.d_in, cfg.dict_size)
    assert loaded_cfg.d_in == cfg.d_in


def test_torch_state_dict_round_trip():
    cfg = CrossCoderConfig(d_in=16, dict_size=64, enc_dtype="bf16")
    params = cc.init_params(jax.random.key(0), cfg)
    sd = torch_compat.params_to_torch_state_dict(params, cfg)
    assert sd["W_enc"].dtype == torch.bfloat16
    assert tuple(sd["W_enc"].shape) == (2, 16, 64)
    assert tuple(sd["W_dec"].shape) == (64, 2, 16)
    back = torch_compat.params_from_torch_state_dict(sd, cfg)
    for k in params:
        np.testing.assert_array_equal(
            np.asarray(params[k], dtype=np.float32), np.asarray(back[k], dtype=np.float32), err_msg=k
        )


def test_torch_file_round_trip(tmp_path):
    cfg = CrossCoderConfig(d_in=16, dict_size=64, enc_dtype="bf16")
    params = cc.init_params(jax.random.key(1), cfg)
    path = tmp_path / "cc_weights.pt"
    torch_compat.save_torch_checkpoint(params, cfg, path)
    # torch side sees the reference layout
    sd = torch.load(path)
    assert set(sd) == {"W_enc", "W_dec", "b_enc", "b_dec"}
    back = torch_compat.load_torch_checkpoint(path, cfg)
    np.testing.assert_array_equal(
        np.asarray(params["W_dec"], np.float32), np.asarray(back["W_dec"], np.float32)
    )


def test_meta_records_step_and_buffer(tmp_path):
    cfg = tiny_cfg(tmp_path)
    tr = Trainer(cfg, checkpointer=Checkpointer(cfg=cfg))
    for _ in range(3):
        tr.step()
    tr.save()
    meta = json.loads((tmp_path / "version_0" / "0_meta.json").read_text())
    assert meta["step"] == 3
    assert meta["buffer"] == {"counter": 3}


def test_restore_rejects_reordered_optimizer_state(tmp_path):
    """Train-state leaves are PATH-keyed in the checkpoint: restoring with a
    different optimizer chain (same leaf count/shapes, different structure)
    fails loudly instead of silently pairing moments with the wrong slots."""
    import optax
    import pytest

    from crosscoder_tpu.train.state import init_train_state, make_optimizer
    from crosscoder_tpu.train import schedules

    cfg = CrossCoderConfig(d_in=8, dict_size=16, checkpoint_dir=str(tmp_path),
                           enc_dtype="fp32")
    tx = make_optimizer(cfg, schedules.lr_schedule(cfg))
    state = init_train_state(jax.random.key(0), cfg, tx)
    ck = Checkpointer(cfg=cfg)
    ck.save(state, cfg)
    vdir = Checkpointer.latest_version_dir(tmp_path)

    # SAME leaf count and shapes, different pytree paths: the optimizer
    # chain reordered (adam state at chain index 0 instead of 1). The old
    # positional pairing would silently load moments into the wrong slots;
    # path-keyed pairing must refuse.
    lr_fn = schedules.lr_schedule(cfg)
    tx2 = optax.chain(
        optax.scale_by_adam(b1=cfg.beta1, b2=cfg.beta2, eps=1e-8),
        optax.clip_by_global_norm(cfg.grad_clip),
        optax.scale_by_learning_rate(lr_fn),
    )
    from crosscoder_tpu.train.state import init_train_state as _init
    n1 = len(jax.tree_util.tree_leaves(_init(jax.random.key(0), cfg, tx)))
    n2 = len(jax.tree_util.tree_leaves(_init(jax.random.key(0), cfg, tx2)))
    assert n1 == n2, "reordered chain must keep the leaf count equal"
    ck2 = Checkpointer(cfg=cfg)
    with pytest.raises(ValueError, match="missing state leaf"):
        ck2.restore(cfg, tx2, version_dir=vdir)


def test_bf16_master_checkpoint_roundtrip(tmp_path):
    """master_dtype='bf16' (the reference's exact dtype regime): npz stores
    bf16 leaves as raw void bytes, which restore must reinterpret — round-1
    code saved fine but failed to restore ('No cast function available'),
    caught by the round-2 hardware soak."""
    from crosscoder_tpu.train import schedules
    from crosscoder_tpu.train.state import init_train_state, make_optimizer

    cfg = CrossCoderConfig(d_in=8, dict_size=16, checkpoint_dir=str(tmp_path),
                           enc_dtype="bf16", master_dtype="bf16")
    tx = make_optimizer(cfg, schedules.lr_schedule(cfg))
    state = init_train_state(jax.random.key(0), cfg, tx)
    assert state.params["W_enc"].dtype == jax.numpy.bfloat16
    ck = Checkpointer(cfg=cfg)
    ck.save(state, cfg)
    vdir = Checkpointer.latest_version_dir(tmp_path)
    ck2 = Checkpointer(cfg=cfg)
    restored, meta = ck2.restore(cfg, tx, version_dir=vdir)
    assert restored.params["W_enc"].dtype == jax.numpy.bfloat16
    for a, b in zip(jax.tree_util.tree_leaves(restored),
                    jax.tree_util.tree_leaves(state)):
        np.testing.assert_array_equal(np.asarray(a, np.float32),
                                      np.asarray(b, np.float32))


def test_keep_saves_scoped_per_tenant(tmp_path):
    """Retention prunes per tenant SUBDIRECTORY, not globally: a 4-tenant
    fleet with keep_saves=3 and 5 saves each keeps exactly 3 complete
    saves under every <ckpt_dir>/tenants/<name>/ — interleaved saves from
    siblings must never count against (or reap) each other's budget."""
    from crosscoder_tpu.train import schedules
    from crosscoder_tpu.train.state import init_train_state, make_optimizer

    cfg = tiny_cfg(tmp_path, keep_saves=3)
    tx = make_optimizer(cfg, schedules.lr_schedule(cfg))
    state = init_train_state(jax.random.key(0), cfg, tx)
    names = ["a", "b", "c", "d"]
    cks = {n: Checkpointer(str(tmp_path), cfg=cfg, tenant=n) for n in names}
    for _ in range(5):
        for n in names:                 # interleave, the fleet save order
            cks[n].save(state, cfg)
    for n in names:
        vdir = tmp_path / "tenants" / n / "version_0"
        assert Checkpointer.complete_saves(vdir) == [2, 3, 4], n
        # each tenant still restores from ITS newest survivor
        restored, meta = Checkpointer(str(tmp_path), cfg=cfg,
                                      tenant=n).restore(cfg, tx)
        for a, b in zip(jax.tree_util.tree_leaves(restored.params),
                        jax.tree_util.tree_leaves(state.params)):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_tenant_name_validation(tmp_path):
    cfg = tiny_cfg(tmp_path)
    for bad in ("", "a/b", ".", ".."):
        try:
            Checkpointer(str(tmp_path), cfg=cfg, tenant=bad)
        except ValueError:
            continue
        raise AssertionError(f"tenant name {bad!r} accepted")
