"""Test environment: force CPU with 8 virtual XLA devices so every sharding
test runs an honest 8-way mesh without TPU hardware (SURVEY.md §4).

Note: the environment may pre-set JAX_PLATFORMS (e.g. to a TPU plugin) and
pre-import jax at interpreter startup, so we must both override the env var
(for subprocesses) and update the live jax config (for this process).
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (_flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_enable_x64", False)


def pytest_report_header():
    return f"jax backend: {jax.default_backend()} devices: {jax.device_count()}"
