"""Zero-bubble refill engine (cfg.refill_overlap; docs/SCALING.md
"Zero-bubble refill"):

- served-batch stream byte-identical overlap-on vs overlap-off across all
  three store placements (host RAM / single-device HBM / mesh-sharded),
  including a mid-cycle checkpoint resume;
- ``SegmentedHarvest.step_many`` (the batched k-wide sub-scan dispatch)
  bitwise-equals the narrow ``step()`` loop;
- zero-cost off: the compiled train step's HLO is byte-identical across
  the new knobs, and overlap-on adds NO host↔device transfers;
- the trainer's ticketed launch sequencer (multi-process prefetch) leaves
  the single-process loss trajectory unchanged;
- config validation of the new knobs.

All CPU, tier-1; the host-store stream-identity test doubles as the
scripts/tier1.sh smoke.
"""

import numpy as np
import pytest

import jax

from crosscoder_tpu.config import CrossCoderConfig
from crosscoder_tpu.data.buffer import PairedActivationBuffer, make_buffer
from crosscoder_tpu.models import lm

SEQ = 17          # rows_per_seq = 16
HP = "blocks.2.hook_resid_pre"


@pytest.fixture(scope="module")
def lm_pair():
    cfg = lm.LMConfig.tiny()
    pa = lm.init_params(jax.random.key(0), cfg)
    pb = lm.init_params(jax.random.key(1), cfg)
    return cfg, [pa, pb]


@pytest.fixture(scope="module")
def tokens():
    rng = np.random.default_rng(7)
    return rng.integers(0, 257, size=(256, SEQ), dtype=np.int64)


def make_cfg(**kw):
    base = dict(
        batch_size=32, buffer_mult=32, seq_len=SEQ, d_in=32, n_models=2,
        model_batch_size=4, norm_calib_batches=2, hook_point=HP, seed=3,
    )
    base.update(kw)
    return CrossCoderConfig(**base)


def _data_mesh():
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    mesh = Mesh(np.array(jax.devices()).reshape(-1, 1), ("data", "model"))
    return mesh, NamedSharding(mesh, P("data", None))


def _assert_identical_stream(off, on, n_steps):
    """Serve both buffers in lockstep; every batch must match exactly (the
    overlap engine swaps indices, never bytes, so this is equality — not
    allclose)."""
    np.testing.assert_array_equal(
        np.asarray(on.normalisation_factor),
        np.asarray(off.normalisation_factor),
    )
    for step in range(n_steps):
        a = np.asarray(off.next())
        b = np.asarray(on.next())
        np.testing.assert_array_equal(b, a, err_msg=f"step {step}")
    off.close()
    on.close()


# ---------------------------------------------------------------------------
# served-stream byte identity, all three store placements


def test_overlap_stream_identity_host(lm_pair, tokens):
    """Host-RAM store, overlap on vs off: 40 serves cross two steady-state
    shadow cycles; the stream must be byte-identical (also the tier-1
    smoke — scripts/tier1.sh runs this test before the full suite)."""
    lm_cfg, params = lm_pair
    off = PairedActivationBuffer(make_cfg(), lm_cfg, params, tokens)
    on = PairedActivationBuffer(
        make_cfg(refill_overlap="on"), lm_cfg, params, tokens
    )
    # the engine actually engaged: spare region = one steady-state refill
    # (32 seqs × 16 rows), offloaded dispatcher thread live on the host store
    assert on._spare_rows == 512 and on._store_rows == 1024 + 512
    assert on._dispatcher is not None
    _assert_identical_stream(off, on, n_steps=40)


def test_overlap_shadow_swap_rotates_row_map(lm_pair, tokens):
    """After a steady-state cycle completes, the swapped logical rows point
    at the previous spare region — index bookkeeping really happened (a
    row_map stuck at identity would mean the shadow path silently fell
    back to in-place writes)."""
    lm_cfg, params = lm_pair
    b = PairedActivationBuffer(
        make_cfg(refill_overlap="on"), lm_cfg, params, tokens
    )
    assert np.array_equal(b._row_map, np.arange(b.buffer_size))  # full fill in-place
    for _ in range(16):            # through the first steady-state cycle
        b.next()
    assert not np.array_equal(b._row_map, np.arange(b.buffer_size))
    # row map stays a bijection onto the physical store
    occupied = np.concatenate([b._row_map, b._free_rows])
    assert np.array_equal(np.sort(occupied), np.arange(b._store_rows))
    b.close()


def test_overlap_stream_identity_hbm(lm_pair, tokens):
    """Single-device HBM store (donated-scatter placement — pumps inline,
    no dispatcher thread): stream byte-identical overlap on vs off."""
    lm_cfg, params = lm_pair
    off = make_buffer(make_cfg(buffer_device="hbm"), lm_cfg, params, tokens)
    on = make_buffer(
        make_cfg(buffer_device="hbm", refill_overlap="on"), lm_cfg, params,
        tokens,
    )
    assert on._dispatcher is None          # _DISPATCH_THREAD_OK = False
    _assert_identical_stream(off, on, n_steps=40)


def test_overlap_stream_identity_mesh(lm_pair, tokens):
    """Mesh-sharded HBM store over the 8-way data axis: stream
    byte-identical overlap on vs off, batches still in the step's batch
    sharding."""
    from crosscoder_tpu.data.buffer import MeshPairedActivationBuffer

    lm_cfg, params = lm_pair
    _, sh = _data_mesh()
    off = make_buffer(make_cfg(buffer_device="hbm"), lm_cfg, params, tokens,
                      batch_sharding=sh)
    on = make_buffer(
        make_cfg(buffer_device="hbm", refill_overlap="on"), lm_cfg, params,
        tokens, batch_sharding=sh,
    )
    assert isinstance(on, MeshPairedActivationBuffer)
    assert on._dispatcher is None
    _assert_identical_stream(off, on, n_steps=40)


def test_overlap_mid_cycle_resume_matches_off(lm_pair, tokens):
    """state_dict taken MID shadow cycle equals the overlap-off snapshot
    (deferred provenance: an unfinished shadow cycle must not have touched
    _src_global), and both buffers restored from it serve identical
    streams across the next two cycles."""
    lm_cfg, params = lm_pair
    off = PairedActivationBuffer(make_cfg(), lm_cfg, params, tokens)
    on = PairedActivationBuffer(
        make_cfg(refill_overlap="on"), lm_cfg, params, tokens
    )
    for _ in range(5):                 # mid-cycle: trigger is at serve 16
        off.next(), on.next()
    on._quiesce_dispatch()
    state = off.state_dict()
    assert on.state_dict() == state
    off.load_state_dict(state)
    on.load_state_dict(state)
    _assert_identical_stream(off, on, n_steps=36)


# ---------------------------------------------------------------------------
# batched dispatch: step_many == step loop, bitwise


def test_step_many_bitwise_equals_step(lm_pair, tokens):
    lm_cfg, params = lm_pair
    tok = jax.numpy.asarray(tokens[:4])

    def run(advance):
        job = lm.SegmentedHarvest(params, tok, lm_cfg, [HP],
                                  out_dtype=jax.numpy.bfloat16)
        advance(job)
        return job

    narrow = run(lambda j: [None for _ in iter(j.step, False)])
    # one giant batched call: consumes exactly the step() budget
    wide = lm.SegmentedHarvest(params, tok, lm_cfg, [HP],
                               out_dtype=jax.numpy.bfloat16)
    used, alive = wide.step_many(1 << 30)
    assert (used, alive) == (wide.n_steps, False)
    # and a mid-size batch that straddles the model boundary
    chunked = run(lambda j: [None for _ in iter(
        lambda: j.step_many(3)[1], False)])
    want = np.asarray(narrow.result(), np.float32)
    np.testing.assert_array_equal(np.asarray(wide.result(), np.float32), want)
    np.testing.assert_array_equal(np.asarray(chunked.result(), np.float32),
                                  want)


def test_step_many_quantum_accounting(lm_pair, tokens):
    """step_many's consumed-quanta accounting matches step(): the pacing
    schedule (credits per serve) must mean the same thing on both paths."""
    lm_cfg, params = lm_pair
    tok = jax.numpy.asarray(tokens[:4])
    job = lm.SegmentedHarvest(params, tok, lm_cfg, [HP])
    total, alive = 0, True
    while alive:
        used, alive = job.step_many(2)
        assert used >= 1
        total += used
    assert total == job.n_steps


# ---------------------------------------------------------------------------
# zero-cost off


def test_step_hlo_independent_of_refill_overlap():
    """refill_overlap / refill_dispatch_batch are host-side data-plane
    knobs: the compiled train step must be byte-identical across them
    (the contracts engine pins the same invariant repo-wide via
    hlo-refill-overlap-off-identity)."""
    from crosscoder_tpu.analysis.contracts.hlo_rules import lower_step_text

    base = dict(d_in=16, dict_size=64, batch_size=32, enc_dtype="fp32",
                l1_coeff=0.02)
    off = lower_step_text(CrossCoderConfig(**base))
    on = lower_step_text(CrossCoderConfig(
        **base, refill_overlap="on", refill_dispatch_batch=8))
    assert off == on


def test_overlap_adds_no_host_device_transfers(lm_pair, tokens, monkeypatch):
    """The engine moves indices, not rows: construction + one full
    steady-state cycle performs exactly the same number of
    device_put/device_get calls with overlap on as off (host store — the
    placement where every chunk crosses the link)."""
    lm_cfg, params = lm_pair
    real_put, real_get = jax.device_put, jax.device_get

    def run(**kw):
        put, get = [], []
        monkeypatch.setattr(jax, "device_put",
                            lambda *a, **k: (put.append(1), real_put(*a, **k))[1])
        monkeypatch.setattr(jax, "device_get",
                            lambda x: (get.append(1), real_get(x))[1])
        try:
            b = PairedActivationBuffer(make_cfg(**kw), lm_cfg, params, tokens)
            for _ in range(16):        # exactly one steady-state cycle
                b.next()
            b._quiesce_dispatch()      # count offloaded drains too
            b.close()
        finally:
            monkeypatch.setattr(jax, "device_put", real_put)
            monkeypatch.setattr(jax, "device_get", real_get)
        return len(put), len(get)

    off = run()
    on = run(refill_overlap="on")
    assert on == off, (on, off)
    assert off[1] > 0          # the counter saw the chunk fetches


# ---------------------------------------------------------------------------
# ticketed launch sequencer through the trainer


def test_trainer_ticketed_prefetch_matches_unticketed(monkeypatch):
    """Force needs_launch_tickets() on in a single process: the trainer
    builds the sequencer, prefetch stays enabled, and the loss trajectory
    is identical to the unticketed run (tickets order launches; they must
    not change what is launched)."""
    from crosscoder_tpu.parallel import multihost
    from crosscoder_tpu.train.trainer import Trainer

    def cfg():
        return CrossCoderConfig(
            d_in=16, dict_size=64, batch_size=32, num_tokens=32 * 400,
            enc_dtype="fp32", lr=2e-3, l1_coeff=0.02, log_backend="null",
            prefetch=True,
        )

    def losses(tr):
        out = [float(jax.device_get(tr.step()["loss"])) for _ in range(6)]
        tr.close()
        return out

    base = losses(Trainer(cfg()))
    monkeypatch.setattr(multihost, "needs_launch_tickets", lambda: True)
    tr = Trainer(cfg())
    assert tr._sequencer is not None
    assert tr._prefetch_pool is not None     # prefetch no longer disabled
    assert losses(tr) == base


def test_trainer_sequencer_checkpoint_cycle(tmp_path, monkeypatch):
    """Ticketed runs never cancel the speculative prefetch (cancellation
    is thread-timing dependent — per-process divergence on a real pod);
    save/restore must still work with a production in flight."""
    from crosscoder_tpu.checkpoint.ckpt import Checkpointer
    from crosscoder_tpu.parallel import multihost
    from crosscoder_tpu.train.trainer import Trainer

    monkeypatch.setattr(multihost, "needs_launch_tickets", lambda: True)
    cfg = CrossCoderConfig(
        d_in=16, dict_size=64, batch_size=32, num_tokens=32 * 400,
        enc_dtype="fp32", l1_coeff=0.02, log_backend="null", prefetch=True,
        checkpoint_dir=str(tmp_path),
    )
    tr = Trainer(cfg, checkpointer=Checkpointer(cfg=cfg))
    tr.step()
    tr.save()
    tr.restore()
    tr.step()
    tr.close()


# ---------------------------------------------------------------------------
# config validation


def test_refill_overlap_config_validation():
    with pytest.raises(ValueError, match="refill_overlap"):
        make_cfg(refill_overlap="maybe")
    with pytest.raises(ValueError, match="refill_dispatch_batch"):
        make_cfg(refill_dispatch_batch=0)
    make_cfg(refill_overlap="on", refill_dispatch_batch=1)   # valid corner


# ---------------------------------------------------------------------------
# final-save quiesce (the SIGTERM/stop path): the trainer must drain the
# offloaded dispatcher BEFORE snapshotting stream state for a save


def test_save_drains_dispatcher_before_writer(tmp_path, lm_pair, tokens):
    """tr.save() with refill_overlap=on: the dispatcher drain must happen
    before the checkpoint writer sees the state — a snapshot taken while
    the pump thread mutates cycle bookkeeping could tear."""
    from crosscoder_tpu.checkpoint.ckpt import Checkpointer
    from crosscoder_tpu.train.trainer import Trainer

    lm_cfg, params = lm_pair
    cfg = make_cfg(refill_overlap="on", checkpoint_dir=str(tmp_path),
                   log_backend="null", prefetch=False)
    buf = PairedActivationBuffer(cfg, lm_cfg, params, tokens)
    assert buf._dispatcher is not None
    tr = Trainer(cfg, buffer=buf, checkpointer=Checkpointer(cfg=cfg))
    order = []
    real_q = buf._quiesce_dispatch
    real_save = tr.checkpointer.save
    buf._quiesce_dispatch = lambda: (order.append("drain"), real_q())[1]
    tr.checkpointer.save = (
        lambda *a, **k: (order.append("write"), real_save(*a, **k))[1])
    tr.step()
    tr.save()
    assert "drain" in order and "write" in order
    assert order.index("drain") < order.index("write")
    tr.close()


def test_save_survives_drain_failure_and_close_is_idempotent(
        tmp_path, lm_pair, tokens):
    """A dispatcher drain that RAISES at final-save time must not cost the
    checkpoint (that save is the whole point of the stop path): the save
    still lands, verifies, and restores to the same state; close() runs
    clean afterwards — twice (the finally + atexit double-close)."""
    from crosscoder_tpu.checkpoint.ckpt import Checkpointer
    from crosscoder_tpu.train.trainer import Trainer

    lm_cfg, params = lm_pair
    cfg = make_cfg(refill_overlap="on", checkpoint_dir=str(tmp_path),
                   log_backend="null", prefetch=False)
    buf = PairedActivationBuffer(cfg, lm_cfg, params, tokens)
    tr = Trainer(cfg, buffer=buf, checkpointer=Checkpointer(cfg=cfg))
    tr.step()
    tr.step()
    want_step = int(tr.state.step)
    want = {k: np.asarray(v, np.float32) for k, v in tr.state.params.items()}

    def boom():
        raise RuntimeError("chaos: drain torn")

    buf._quiesce_dispatch = boom
    tr.save()                                   # must not raise
    tr.close()
    tr.close()                                  # idempotent double-close

    tr2 = Trainer(cfg, buffer=PairedActivationBuffer(
        cfg, lm_cfg, params, tokens, lazy=True),
        checkpointer=Checkpointer(cfg=cfg))
    meta = tr2.restore()
    assert int(meta["step"]) == want_step
    for k in want:
        np.testing.assert_array_equal(
            np.asarray(tr2.state.params[k], np.float32), want[k], err_msg=k)
    assert np.isfinite(float(jax.device_get(tr2.step()["loss"])))
    tr2.close()
