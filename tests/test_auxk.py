"""AuxK dead-latent mitigation (cfg.aux_k — the standard TopK-SAE recipe,
Gao et al. 2024; no reference counterpart, the reference's dense ReLU never
faces mass latent death).

Oracle strategy (SURVEY.md §4): an independent numpy re-statement of the
aux-loss math, fed identical inputs, asserted against the jitted path in
fp32; plus behavioral tests — fired-tracking semantics, the no-dead-latents
noninterference guarantee, the gradient path to dead latents that the main
TopK objective cannot provide, checkpoint round-trip of the tracker, and
the sharded step on an 8-device mesh.
"""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from crosscoder_tpu.config import CrossCoderConfig
from crosscoder_tpu.models import crosscoder as cc
from crosscoder_tpu.train.trainer import Trainer
from crosscoder_tpu.train import schedules
from crosscoder_tpu.train.state import init_train_state, make_optimizer


def _cfg(**kw):
    base = dict(
        d_in=16, dict_size=64, n_models=2, batch_size=32,
        num_tokens=32 * 1000, enc_dtype="fp32", log_backend="null",
        aux_k=8, aux_dead_steps=3, l1_coeff=0.0,
    )
    base.update(kw)
    return CrossCoderConfig(**base)


def _numpy_aux_loss(params, x, dead_mask, k_aux):
    """Independent fp32 oracle of the AuxK loss: residual of the MAIN
    reconstruction, reconstructed by the top-k_aux raw pre-acts among dead
    latents through W_dec (no b_dec), normalized by the residual's power."""
    w_enc = np.asarray(params["W_enc"], np.float32)
    w_dec = np.asarray(params["W_dec"], np.float32)
    b_enc = np.asarray(params["b_enc"], np.float32)
    b_dec = np.asarray(params["b_dec"], np.float32)
    h = np.einsum("bnd,ndh->bh", x, w_enc) + b_enc
    f = np.maximum(h, 0.0)
    recon = np.einsum("bh,hnd->bnd", f, w_dec) + b_dec
    e = x - recon
    masked = np.where(dead_mask[None, :], h, -np.inf)
    order = np.argsort(-masked, axis=-1, kind="stable")[:, :k_aux]
    vals = np.take_along_axis(masked, order, axis=-1)
    vals = np.where(np.isfinite(vals), vals, 0.0)
    e_hat = np.einsum("bk,bknd->bnd", vals, w_dec[order])
    num = np.mean(np.sum((e_hat - e) ** 2, axis=(-2, -1)))
    den = np.mean(np.sum(e ** 2, axis=(-2, -1)))
    if not dead_mask.any():
        return 0.0
    return num / (den + 1e-8)


def test_aux_loss_matches_numpy_oracle():
    cfg = _cfg(activation="relu")
    rng = np.random.default_rng(0)
    params = cc.init_params(jax.random.key(1), cfg, dtype=jnp.float32)
    x = rng.standard_normal((cfg.batch_size, cfg.n_sources, cfg.d_in)).astype(np.float32)
    dead = np.zeros(cfg.dict_size, bool)
    dead[::5] = True                      # 13 dead > aux_k=8: real top-k path
    losses = cc.get_losses(params, jnp.asarray(x), cfg, dead_mask=jnp.asarray(dead))
    want = _numpy_aux_loss(params, x, dead, cfg.aux_k)
    np.testing.assert_allclose(float(losses.aux_loss), want, rtol=1e-5)


def test_aux_loss_fewer_dead_than_aux_k():
    # -inf padding rows must contribute exactly nothing
    cfg = _cfg(activation="relu")
    rng = np.random.default_rng(2)
    params = cc.init_params(jax.random.key(3), cfg, dtype=jnp.float32)
    x = rng.standard_normal((cfg.batch_size, cfg.n_sources, cfg.d_in)).astype(np.float32)
    dead = np.zeros(cfg.dict_size, bool)
    dead[[4, 17]] = True                  # 2 dead < aux_k=8
    losses = cc.get_losses(params, jnp.asarray(x), cfg, dead_mask=jnp.asarray(dead))
    want = _numpy_aux_loss(params, x, dead, cfg.aux_k)
    np.testing.assert_allclose(float(losses.aux_loss), want, rtol=1e-5)


def test_aux_loss_zero_when_nothing_dead():
    cfg = _cfg(activation="relu")
    rng = np.random.default_rng(4)
    params = cc.init_params(jax.random.key(5), cfg, dtype=jnp.float32)
    x = rng.standard_normal((cfg.batch_size, cfg.n_sources, cfg.d_in)).astype(np.float32)
    dead = np.zeros(cfg.dict_size, bool)
    losses = cc.get_losses(params, jnp.asarray(x), cfg, dead_mask=jnp.asarray(dead))
    assert float(losses.aux_loss) == 0.0


@pytest.mark.parametrize("activation,sparse", [
    ("relu", False), ("topk", False), ("topk", True), ("batchtopk", False),
])
def test_fired_matches_dense_activity(activation, sparse):
    cfg = _cfg(activation=activation, sparse_decode=sparse, topk_k=4)
    rng = np.random.default_rng(6)
    params = cc.init_params(jax.random.key(7), cfg, dtype=jnp.float32)
    x = jnp.asarray(
        rng.standard_normal((cfg.batch_size, cfg.n_sources, cfg.d_in)), jnp.float32
    )
    dead = jnp.zeros(cfg.dict_size, bool)
    losses = cc.get_losses(params, x, cfg, dead_mask=dead)
    f = cc.encode(params, x, cfg)
    want = np.asarray(jnp.any(f > 0, axis=0))
    np.testing.assert_array_equal(np.asarray(losses.fired), want)


def test_no_dead_latents_means_identical_training():
    # aux_dead_steps larger than the run: the aux term must never engage and
    # the trajectory must equal the aux-free config's exactly
    cfg_off = _cfg(activation="topk", topk_k=4, aux_k=0)
    cfg_on = _cfg(activation="topk", topk_k=4, aux_k=8, aux_dead_steps=10**6)
    losses = {}
    for name, cfg in (("off", cfg_off), ("on", cfg_on)):
        tr = Trainer(cfg)
        vals = []
        for _ in range(4):
            vals.append(float(jax.device_get(tr.step()["loss"])))
        tr.close()
        losses[name] = vals
    np.testing.assert_allclose(losses["on"], losses["off"], rtol=1e-6)


def test_aux_gives_dead_latent_a_gradient_path():
    # a latent TopK never selects gets NO gradient from the main objective;
    # with it marked dead, the aux loss must deliver one to its encoder row
    cfg = _cfg(activation="topk", topk_k=2)
    params = cc.init_params(jax.random.key(11), cfg, dtype=jnp.float32)
    # bury latent 0: huge negative encoder bias → never in the top-k
    params["b_enc"] = params["b_enc"].at[0].set(-100.0)
    rng = np.random.default_rng(12)
    x = jnp.asarray(
        rng.standard_normal((cfg.batch_size, cfg.n_sources, cfg.d_in)), jnp.float32
    )

    def loss_with(dead_mask):
        def f(p):
            loss, _ = cc.training_loss(p, x, 0.0, cfg, dead_mask=dead_mask)
            return loss
        return jax.grad(f)(params)

    grads_free = loss_with(None)
    g0_free = float(jnp.abs(grads_free["W_enc"][..., 0]).max())
    assert g0_free == 0.0, "buried latent should get no main-objective grad"

    dead = jnp.zeros(cfg.dict_size, bool).at[0].set(True)
    grads_aux = loss_with(dead)
    g0_aux = float(jnp.abs(grads_aux["W_enc"][..., 0]).max())
    assert g0_aux > 0.0, "aux loss must give the dead latent a gradient"


def test_trainer_tracks_steps_since_fired():
    cfg = _cfg(activation="topk", topk_k=4, aux_dead_steps=2)
    tr = Trainer(cfg)
    assert tr.state.aux is not None
    m = tr.step()
    since = np.asarray(jax.device_get(tr.state.aux["steps_since_fired"]))
    # after one step: fired latents at 0, silent ones at 1
    assert set(np.unique(since)).issubset({0, 1})
    assert (since == 0).sum() >= cfg.topk_k  # at least the batch's top-k fired
    for _ in range(4):
        m = tr.step()
    assert "dead_frac" in m and "aux_loss" in m
    assert np.isfinite(float(jax.device_get(m["loss"])))
    tr.close()


def test_checkpoint_roundtrips_aux_state(tmp_path):
    from crosscoder_tpu.checkpoint.ckpt import Checkpointer

    cfg = _cfg(activation="topk", topk_k=4, checkpoint_dir=str(tmp_path))
    tr = Trainer(cfg, checkpointer=Checkpointer(cfg=cfg))
    for _ in range(3):
        tr.step()
    since_before = np.asarray(jax.device_get(tr.state.aux["steps_since_fired"]))
    tr.save()
    tr.close()

    tr2 = Trainer(cfg, checkpointer=Checkpointer(cfg=cfg))
    tr2.restore()
    since_after = np.asarray(jax.device_get(tr2.state.aux["steps_since_fired"]))
    np.testing.assert_array_equal(since_after, since_before)
    assert tr2.step_counter == 3
    tr2.close()


def test_auxk_sharded_step_runs():
    # 8-device mesh, TP over the dict axis: steps_since_fired shards with
    # b_enc and the step stays finite
    from crosscoder_tpu.parallel import mesh as mesh_lib

    cfg = _cfg(activation="topk", topk_k=4, batch_size=32,
               data_axis_size=4, model_axis_size=2, aux_dead_steps=1)
    mesh = mesh_lib.mesh_from_cfg(cfg)
    tr = Trainer(cfg, mesh=mesh)
    for _ in range(3):
        m = tr.step()
    assert np.isfinite(float(jax.device_get(m["loss"])))
    assert np.isfinite(float(jax.device_get(m["aux_loss"])))
    since = tr.state.aux["steps_since_fired"]
    assert since.shape == (cfg.dict_size,)
    tr.close()


def test_auxk_with_source_sharding_matches_dict_sharding():
    """EP-style source-axis sharding (cfg.shard_sources) with AuxK: the
    replicated steps_since_fired tracker and the aux loss must produce
    the same trajectory as the default dict-axis TP sharding."""
    from crosscoder_tpu.parallel import mesh as mesh_lib

    def run(shard_sources):
        cfg = _cfg(
            activation="topk", topk_k=4, aux_dead_steps=1, n_models=2,
            hook_points=("blocks.1.hook_resid_pre", "blocks.2.hook_resid_pre"),
            data_axis_size=2, model_axis_size=4, shard_sources=shard_sources,
        )
        mesh = mesh_lib.mesh_from_cfg(cfg)
        tr = Trainer(cfg, mesh=mesh)
        losses, aux_losses = [], []
        for _ in range(3):
            m = tr.step()
            losses.append(float(jax.device_get(m["loss"])))
            # the aux term itself, not just its (warmup-scaled, tiny)
            # contribution to the total — an EP-specific mis-scaling of
            # the aux loss must fail loudly here
            aux_losses.append(float(jax.device_get(m["aux_loss"])))
        since = np.asarray(jax.device_get(tr.state.aux["steps_since_fired"]))
        tr.close()
        return losses, aux_losses, since

    l_tp, a_tp, s_tp = run(False)
    l_ep, a_ep, s_ep = run(True)
    np.testing.assert_allclose(l_ep, l_tp, rtol=1e-5)
    np.testing.assert_allclose(a_ep, a_tp, rtol=1e-5)
    assert any(a > 0 for a in a_tp)        # the aux path actually engaged
    np.testing.assert_array_equal(s_ep, s_tp)


def test_config_rejects_bad_aux_k():
    with pytest.raises(ValueError):
        _cfg(aux_k=-1)
    with pytest.raises(ValueError):
        _cfg(aux_k=10**9)


def test_aux_every_amortization_semantics():
    """cfg.aux_every > 1 (VERDICT r04 #1): the aux ranking+decode runs only
    on every Nth step, but fired-tracking (steps_since_fired) updates on
    EVERY step, and the dead_frac metric stays present throughout. The
    off-step variant must behave exactly like the on-step variant minus the
    aux loss term."""
    from crosscoder_tpu.data.synthetic import SyntheticActivationSource

    cfg = _cfg(activation="topk", topk_k=4, aux_every=3, aux_dead_steps=2,
               prefetch=False)
    tr = Trainer(cfg, SyntheticActivationSource(cfg))
    seen_keys = []
    for i in range(7):
        m = tr.step()
        seen_keys.append("aux_loss" in m)
        assert "dead_frac" in m
        # fired-tracking ran this step regardless of the aux cadence
        ssf = np.asarray(tr.state.aux["steps_since_fired"])
        assert ssf.max() <= i + 1
    # aux steps at host steps 0, 3, 6
    assert seen_keys == [True, False, False, True, False, False, True]
    assert tr._host_step == 7
    # both compiled variants exist (keys: with_metrics, aux_on, mask_refresh)
    assert (True, True, True) in tr._step_fns
    assert (True, False, True) in tr._step_fns
    tr.close()


def test_aux_every_no_dead_matches_perstep():
    """With nothing dead (aux_dead_steps beyond the horizon) the aux term
    contributes 0 either way, so an amortized run must produce the same
    trajectory as the per-step run."""
    from crosscoder_tpu.data.synthetic import SyntheticActivationSource

    outs = []
    for aux_every in (1, 4):
        cfg = _cfg(activation="topk", topk_k=4, aux_every=aux_every,
                   aux_dead_steps=10_000, prefetch=False)
        tr = Trainer(cfg, SyntheticActivationSource(cfg))
        for _ in range(6):
            m = tr.step()
        outs.append(np.asarray(jax.device_get(m["loss"]), np.float64))
        tr.close()
    np.testing.assert_allclose(outs[0], outs[1], rtol=1e-5)


def test_config_rejects_bad_aux_every():
    with pytest.raises(ValueError):
        _cfg(aux_every=0)
    with pytest.raises(ValueError):
        _cfg(aux_every=-3)


def test_aux_mask_cache_refresh_and_reuse_semantics():
    """cfg.aux_mask_every > 1: the dead mask refreshes only at the cadence
    and is REUSED in between — with aux_dead_steps=1, latents dying at
    step 1 cannot draw aux gradient until the step-3 refresh, so aux_loss
    is exactly 0 on the stale-mask steps and engages at the refresh."""
    from crosscoder_tpu.data.synthetic import SyntheticActivationSource

    cfg = _cfg(activation="topk", topk_k=4, aux_k=8, aux_dead_steps=1,
               aux_mask_every=3, prefetch=False)
    tr = Trainer(cfg, SyntheticActivationSource(cfg))
    assert "dead_mask" in tr.state.aux
    aux_losses, dead_fracs = [], []
    for _ in range(7):
        m = tr.step()
        aux_losses.append(float(jax.device_get(m["aux_loss"])))
        dead_fracs.append(float(jax.device_get(m["dead_frac"])))
    tr.close()
    # steps 0-2 use the step-0 mask (nothing dead yet: tracker starts 0);
    # the step-3 refresh sees the step-1+ deaths and engages the aux loss
    assert aux_losses[0] == 0 and aux_losses[1] == 0 and aux_losses[2] == 0
    assert dead_fracs[0] == 0 and dead_fracs[2] == 0
    assert any(a > 0 for a in aux_losses[3:]), aux_losses
    assert dead_fracs[3] > 0
    # refresh/reuse variants both compiled
    assert (True, True, True) in tr._step_fns
    assert (True, True, False) in tr._step_fns


def test_aux_mask_cache_matches_perstep_when_masks_agree():
    """With a horizon no latent ever crosses, the cached mask equals the
    per-step mask on every step, so the trajectories must be identical
    (the caching changes WHICH mask is used, never the step math)."""
    from crosscoder_tpu.data.synthetic import SyntheticActivationSource

    outs = []
    for mask_every in (1, 4):
        cfg = _cfg(activation="topk", topk_k=4, aux_k=8,
                   aux_dead_steps=10_000, aux_mask_every=mask_every,
                   prefetch=False)
        tr = Trainer(cfg, SyntheticActivationSource(cfg))
        for _ in range(6):
            m = tr.step()
        outs.append(np.asarray(jax.device_get(m["loss"]), np.float64))
        tr.close()
    np.testing.assert_allclose(outs[0], outs[1], rtol=1e-6)


def test_config_rejects_bad_aux_mask_every():
    with pytest.raises(ValueError):
        _cfg(aux_mask_every=-1)
    assert _cfg(aux_mask_every=0, log_every=50).aux_mask_cadence == 50
    assert _cfg(aux_mask_every=7).aux_mask_cadence == 7
