"""Paged harvest runtime (cfg.harvest_runtime="paged"; data/paging.py +
models/lm.run_with_cache_multi_paged + data/buffer.py routing): the page
allocator, the continuous-batching packer, the padded-vs-paged CPU parity
gates (bitwise on full-length chunks, valid-position-bitwise on mixed
lengths incl. single-token and max-length documents), the replay buffer's
stream parity, the zero-cost-off guarantees, and the config validation.
All CPU, tier-1."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from crosscoder_tpu.config import CrossCoderConfig
from crosscoder_tpu.data import paging
from crosscoder_tpu.data.buffer import make_buffer
from crosscoder_tpu.models import lm
from crosscoder_tpu.parallel import mesh as mesh_lib

SEQ = 16


# ---------------------------------------------------------------------------
# PageTable


def test_page_table_alloc_free_reuse():
    pt = paging.PageTable(n_pages=8, page_size=4)
    a = pt.alloc(0, 9)                       # 3 pages
    b = pt.alloc(1, 4)                       # 1 page
    assert len(a) == 3 and len(b) == 1
    assert pt.n_free == 4
    assert pt.pages_of(0) == a
    pt.free(0)
    assert pt.n_free == 7
    c = pt.alloc(2, 16)                      # 4 pages, reuses freed ids
    assert len(c) == 4 and pt.n_free == 3
    assert set(c) & set(a)                   # LIFO free-list reuse


def test_page_table_exhaustion_and_extend():
    pt = paging.PageTable(n_pages=2, page_size=4)
    assert pt.alloc(0, 12) is None           # needs 3 > 2: nothing taken
    assert pt.n_free == 2
    assert pt.alloc(0, 4) is not None
    assert pt.extend(0, 8) is not None       # grow to 2 pages (decode path)
    assert pt.extend(0, 8) == []             # already covered
    assert pt.extend(0, 12) is None          # pool exhausted
    with pytest.raises(ValueError):
        pt.alloc(0, 1)                       # double alloc
    with pytest.raises(KeyError):
        pt.extend(9, 4)


def test_page_table_table_array():
    pt = paging.PageTable(n_pages=8, page_size=4)
    pt.alloc(0, 8)
    pt.alloc(1, 4)
    tbl = pt.table([0, 1])
    assert tbl.shape == (2, 2) and tbl.dtype == np.int32
    assert list(tbl[0]) == pt.pages_of(0)
    assert tbl[1, 0] == pt.pages_of(1)[0] and tbl[1, 1] == 0


def test_page_table_rejects_bad_page_size():
    with pytest.raises(ValueError, match="power of two"):
        paging.PageTable(8, 3)


# ---------------------------------------------------------------------------
# packing


def test_pack_documents_first_fit():
    row, off, used = paging.pack_documents(np.array([8, 8, 4, 4, 8]), 16)
    # [8,8] -> row0; 4 -> row0 full? 8+8=16 full, so 4 -> row1 ...
    assert list(row) == [0, 0, 1, 1, 1]
    assert list(off) == [0, 8, 0, 4, 8]
    assert used == 2


def test_pack_documents_rejects_oversize():
    with pytest.raises(ValueError, match="exceeds seq_len"):
        paging.pack_documents(np.array([17]), 16)
    with pytest.raises(ValueError, match=">= 1"):
        paging.pack_documents(np.array([0]), 16)


def test_pack_chunk_full_length_is_identity():
    """All-full-length chunks pack to the identity layout — the property
    the production-corpus bit-parity gate rests on."""
    rng = np.random.default_rng(0)
    tokens = rng.integers(1, 99, size=(6, SEQ), dtype=np.int32)
    chunk = paging.pack_chunk(tokens, np.full(6, SEQ))
    assert chunk.n_rows == 6
    np.testing.assert_array_equal(chunk.tokens, tokens)
    np.testing.assert_array_equal(chunk.doc_row, np.arange(6))
    np.testing.assert_array_equal(chunk.doc_off, 0)
    np.testing.assert_array_equal(
        chunk.doc_idx, np.arange(6 * SEQ).reshape(6, SEQ)
    )
    np.testing.assert_array_equal(
        chunk.plane_idx, np.arange(6 * SEQ).reshape(6, SEQ)
    )
    assert chunk.efficiency() == 1.0


def test_pack_chunk_ragged_integrity():
    """Every real token lands exactly once on the plane; maps invert."""
    rng = np.random.default_rng(1)
    lengths = np.array([1, SEQ, 7, 3, 9, 5])
    tokens = rng.integers(1, 99, size=(6, SEQ), dtype=np.int32)
    for d, ln in enumerate(lengths):
        tokens[d, ln:] = 0
    chunk = paging.pack_chunk(tokens, lengths)
    assert chunk.n_rows < 6                  # actually packed
    flat = chunk.tokens.reshape(-1)
    for d, ln in enumerate(lengths):
        np.testing.assert_array_equal(
            flat[chunk.doc_idx[d, :ln]], tokens[d, :ln], err_msg=f"doc {d}"
        )
    # per-slot ownership: plane_idx points back at the doc token there
    pos_flat = chunk.pos.reshape(-1)
    for r in range(chunk.n_rows):
        for s in range(SEQ):
            di = int(chunk.plane_idx[r, s])
            d, t = divmod(di, SEQ)
            if di != 0 and t < lengths[d]:
                assert chunk.tokens[r, s] == tokens[d, t]
                assert pos_flat[r * SEQ + s] == t
    assert chunk.efficiency() == pytest.approx(
        lengths.sum() / (chunk.n_rows * SEQ)
    )


def test_plane_rows_bucketing():
    # granularity n_docs/8, capped at the padded count
    assert paging.plane_rows(18, 32) == 20
    assert paging.plane_rows(32, 32) == 32           # identity at full
    assert paging.plane_rows(31, 32) == 32
    assert paging.plane_rows(1, 32) == 4
    assert paging.plane_rows(6, 6) == 6
    # mesh multiple wins over granularity and may exceed n_docs
    assert paging.plane_rows(5, 6, multiple=4) == 8
    # the result is ALWAYS a multiple of `multiple`, even when the n/8
    # granularity is not (the sharded device_put divisibility contract)
    assert paging.plane_rows(50, 160, multiple=16) == 64
    for needed, docs, mult in [(10, 100, 4), (7, 33, 8), (13, 23, 2)]:
        r = paging.plane_rows(needed, docs, multiple=mult)
        assert r % mult == 0 and r >= needed


def test_padding_efficiency():
    assert paging.padding_efficiency(np.array([8, 8]), 8) == 1.0
    assert paging.padding_efficiency(np.array([4, 4]), 8) == 0.5
    assert paging.padding_efficiency(np.array([]), 8) == 1.0


# ---------------------------------------------------------------------------
# continuous batching


def test_continuous_batcher_admission_and_flush():
    rng = np.random.default_rng(2)
    cb = paging.ContinuousBatcher(seq_len=8, n_rows=2)
    docs = [rng.integers(1, 99, size=n).astype(np.int32)
            for n in (5, 3, 8, 2)]
    assert cb.admit(docs[0])                 # row0: 5
    assert cb.admit(docs[1])                 # row0: 5+3=8
    assert cb.admit(docs[2])                 # row1: 8
    assert not cb.admit(docs[3])             # nothing fits: flush signal
    chunk = cb.flush()
    assert chunk.n_docs == 3 and chunk.n_rows == 2
    assert chunk.efficiency() == 1.0         # plane completely full
    flat = chunk.tokens.reshape(-1)
    for d, doc in enumerate(docs[:3]):
        np.testing.assert_array_equal(
            flat[chunk.doc_idx[d, : len(doc)]], doc
        )
    # slots retired: the rejected doc admits now
    assert cb.admit(docs[3])
    assert cb.flush().n_docs == 1
    assert cb.flush() is None


def test_continuous_batcher_with_page_table_backpressure():
    pt = paging.PageTable(n_pages=2, page_size=4)
    cb = paging.ContinuousBatcher(seq_len=8, n_rows=4, page_table=pt)
    assert cb.admit(np.array([1, 2, 3, 4, 5], np.int32))   # 2 pages
    assert pt.n_free == 0
    assert not cb.admit(np.array([1], np.int32))           # pool exhausted
    cb.flush()
    assert pt.n_free == 2                                  # pages retired
    assert cb.admit(np.array([1], np.int32))


def test_continuous_batcher_rejects_oversize():
    cb = paging.ContinuousBatcher(seq_len=4, n_rows=1)
    with pytest.raises(ValueError, match="outside"):
        cb.admit(np.arange(5))


# ---------------------------------------------------------------------------
# paged forward parity (the tentpole gates)


@pytest.fixture(scope="module")
def lm_pair():
    cfg = lm.LMConfig.tiny()
    pa = lm.init_params(jax.random.key(1), cfg)
    pb = lm.init_params(jax.random.key(2), cfg)
    return cfg, [pa, pb]


HOOKS = ("blocks.1.hook_resid_pre", "blocks.3.hook_resid_pre")


def test_paged_full_length_bit_parity(lm_pair):
    """All-full-length chunk: the paged runtime's output is BITWISE equal
    to run_with_cache_multi — identity packing + identical op sequence."""
    cfg, params = lm_pair
    rng = np.random.default_rng(3)
    tokens = rng.integers(1, cfg.vocab_size, size=(6, SEQ), dtype=np.int64)
    want = np.asarray(lm.run_with_cache_multi(
        params, jnp.asarray(tokens), cfg, HOOKS), np.float32)
    got = np.asarray(lm.run_with_cache_multi_paged(
        params, tokens, np.full(6, SEQ), cfg, HOOKS, page_size=8), np.float32)
    np.testing.assert_array_equal(got, want)


def test_paged_mixed_length_parity(lm_pair):
    """Mixed-length chunk incl. a single-token and a max-length document:
    hook activations at valid positions are bitwise equal to the padded
    forward; pad positions come back zeroed (the valid-length mask)."""
    cfg, params = lm_pair
    rng = np.random.default_rng(4)
    lengths = np.array([1, SEQ, 7, 3, 9, 5])
    tokens = rng.integers(1, cfg.vocab_size, size=(6, SEQ), dtype=np.int64)
    for d, ln in enumerate(lengths):
        tokens[d, ln:] = 0
    want = np.asarray(lm.run_with_cache_multi(
        params, jnp.asarray(tokens), cfg, HOOKS), np.float32)
    got = np.asarray(lm.run_with_cache_multi_paged(
        params, tokens, lengths, cfg, HOOKS, page_size=8), np.float32)
    for d, ln in enumerate(lengths):
        np.testing.assert_array_equal(
            got[d, :ln], want[d, :ln], err_msg=f"doc {d}"
        )
        assert np.all(got[d, ln:] == 0.0)


def test_paged_sublayer_hooks_parity(lm_pair):
    """attn_out/mlp_out capture sites ride the paged runtime too."""
    cfg, params = lm_pair
    hooks = ("blocks.1.hook_attn_out", "blocks.2.hook_mlp_out")
    rng = np.random.default_rng(5)
    lengths = np.array([4, SEQ, 11])
    tokens = rng.integers(1, cfg.vocab_size, size=(3, SEQ), dtype=np.int64)
    for d, ln in enumerate(lengths):
        tokens[d, ln:] = 0
    want = np.asarray(lm.run_with_cache_multi(
        params, jnp.asarray(tokens), cfg, hooks), np.float32)
    got = np.asarray(lm.run_with_cache_multi_paged(
        params, tokens, lengths, cfg, hooks, page_size=4), np.float32)
    for d, ln in enumerate(lengths):
        np.testing.assert_array_equal(
            got[d, :ln], want[d, :ln], err_msg=f"doc {d}"
        )


def test_paged_with_kernel_interpret_parity(lm_pair):
    """The full paged forward with the Pallas ragged-paged-attention
    kernel (interpret mode): allclose to the padded path (online softmax
    reassociates the attention reduction)."""
    from crosscoder_tpu.ops import paged_attention as pam

    cfg, params = lm_pair
    rng = np.random.default_rng(6)
    lengths = np.array([1, SEQ, 7, 3])
    tokens = rng.integers(1, cfg.vocab_size, size=(4, SEQ), dtype=np.int64)
    for d, ln in enumerate(lengths):
        tokens[d, ln:] = 0
    want = np.asarray(lm.run_with_cache_multi(
        params, jnp.asarray(tokens), cfg, HOOKS), np.float32)
    pam.set_interpret(True)
    try:
        got = np.asarray(lm.run_with_cache_multi_paged(
            params, tokens, lengths, cfg, HOOKS, page_size=8), np.float32)
    finally:
        pam.set_interpret(False)
    for d, ln in enumerate(lengths):
        np.testing.assert_allclose(
            got[d, :ln], want[d, :ln], rtol=2e-5, atol=2e-5,
            err_msg=f"doc {d}",
        )


# ---------------------------------------------------------------------------
# replay buffer integration


def _buf_cfg(**kw):
    base = dict(
        batch_size=32, buffer_mult=16, seq_len=17, d_in=32, n_models=2,
        model_batch_size=4, norm_calib_batches=2,
        hook_point="blocks.2.hook_resid_pre", seed=3, page_size=1,
    )
    base.update(kw)
    return CrossCoderConfig(**base)


@pytest.fixture(scope="module")
def buf_inputs():
    cfg = lm.LMConfig.tiny()
    pa = lm.init_params(jax.random.key(0), cfg)
    pb = lm.init_params(jax.random.key(1), cfg)
    rng = np.random.default_rng(7)
    tokens = rng.integers(1, 257, size=(256, 17), dtype=np.int64)
    return cfg, [pa, pb], tokens


def test_buffer_paged_stream_bit_parity(buf_inputs):
    """The CPU bit-parity gate: on the (full-length) production-shaped
    corpus the paged buffer ingests and serves EXACTLY the padded
    buffer's activation stream — store bytes and served batches equal."""
    lm_cfg, params, tokens = buf_inputs
    b_pad = make_buffer(_buf_cfg(), lm_cfg, params, tokens)
    b_pag = make_buffer(_buf_cfg(harvest_runtime="paged"), lm_cfg, params,
                        tokens)
    np.testing.assert_array_equal(
        np.asarray(b_pad._store, np.float32),
        np.asarray(b_pag._store, np.float32),
    )
    np.testing.assert_array_equal(
        b_pad.normalisation_factor, b_pag.normalisation_factor
    )
    for _ in range(3):
        np.testing.assert_array_equal(
            np.asarray(b_pad.next_raw(), np.float32),
            np.asarray(b_pag.next_raw(), np.float32),
        )
    assert b_pag.padding_efficiency() == 1.0
    assert b_pad.padding_efficiency() is None


def test_buffer_paged_ragged_corpus_serves(buf_inputs):
    """A ragged corpus (trailing pads) harvests through the paged runtime
    end-to-end: serves stay finite, NO all-zero pad row ever enters the
    replay store (pad positions wrap the document's own real rows),
    telemetry reports the real-token fraction, and refill cycles keep
    working."""
    lm_cfg, params, tokens = buf_inputs
    rng = np.random.default_rng(8)
    ragged = np.array(tokens[:128])
    lens = rng.integers(2, 18, size=128)
    for d, ln in enumerate(lens):
        ragged[d, ln:] = 0
    buf = make_buffer(_buf_cfg(harvest_runtime="paged"), lm_cfg, params,
                      ragged)
    eff = buf.padding_efficiency()
    assert eff is not None and 0.1 < eff < 1.0
    store = np.asarray(buf._store, np.float32)
    row_norms = np.abs(store).sum(axis=(1, 2))
    assert (row_norms > 0).all(), "pad rows leaked into the replay store"
    # 8 serves of 32 cross the half-buffer trigger (512//2 - 32 = 224),
    # so a full incremental refill cycle completes on the ragged corpus
    for _ in range(8):
        x = np.asarray(buf.next_raw(), np.float32)
        assert np.isfinite(x).all()
        assert (np.abs(x).sum(axis=(1, 2)) > 0).all()


def test_paged_wrap_mode_recycles_real_rows(lm_pair):
    """pad_mode='wrap' (the buffer's ingestion mode): positions past a
    document's length repeat its own post-BOS rows in cycle order;
    single-token documents fall back to the BOS row."""
    cfg, params = lm_pair
    rng = np.random.default_rng(9)
    lengths = np.array([1, 4, SEQ])
    tokens = rng.integers(1, cfg.vocab_size, size=(3, SEQ), dtype=np.int64)
    for d, ln in enumerate(lengths):
        tokens[d, ln:] = 0
    got = np.asarray(lm.run_with_cache_multi_paged(
        params, tokens, lengths, cfg, HOOKS, page_size=8, pad_mode="wrap"),
        np.float32)
    # doc 1 (len 4): t=4 -> row 1, t=5 -> row 2, t=6 -> row 3, t=7 -> row 1
    for t, src in [(4, 1), (5, 2), (6, 3), (7, 1)]:
        np.testing.assert_array_equal(got[1, t], got[1, src])
    # doc 0 (len 1): everything wraps onto the BOS row
    for t in range(1, SEQ):
        np.testing.assert_array_equal(got[0, t], got[0, 0])
    # full-length doc: untouched (identity gather)
    assert np.abs(got[2]).sum() > 0
    with pytest.raises(ValueError, match="pad_mode"):
        lm.run_with_cache_multi_paged(
            params, tokens, lengths, cfg, HOOKS, page_size=8,
            pad_mode="mask")


def test_buffer_padded_never_touches_paged_runtime(buf_inputs, monkeypatch):
    """Zero-cost off: with the default runtime the paged entry point is
    unreachable from construction through serves and refills."""
    lm_cfg, params, tokens = buf_inputs

    def boom(*a, **kw):
        raise AssertionError("paged runtime reached with harvest_runtime=padded")

    monkeypatch.setattr(lm, "run_with_cache_multi_paged", boom)
    buf = make_buffer(_buf_cfg(), lm_cfg, params, tokens)
    for _ in range(4):
        buf.next_raw()


def test_step_hlo_independent_of_harvest_runtime():
    """The compiled train step must not change when the paged knobs are
    present (harvest_runtime is a data-plane selector; page_size is inert
    without it): byte-identical HLO — the same discipline as
    --quant-buffer / sparse_bwd."""
    from crosscoder_tpu.train import schedules
    from crosscoder_tpu.train.state import init_train_state, make_optimizer
    from crosscoder_tpu.train.trainer import make_train_step

    texts = []
    for extra in ({}, dict(harvest_runtime="paged", page_size=8)):
        cfg = CrossCoderConfig(d_in=8, dict_size=32, batch_size=32,
                               enc_dtype="fp32", seq_len=16, **extra)
        mesh = mesh_lib.make_mesh(devices=jax.devices()[:1])
        tx = make_optimizer(cfg, schedules.lr_schedule(cfg))
        state = jax.eval_shape(lambda k: init_train_state(k, cfg, tx),
                               jax.random.key(0))
        shardings = mesh_lib.state_shardings(mesh, state, cfg.shard_sources)
        step = make_train_step(cfg, mesh, tx, shardings)
        state_sh = jax.tree_util.tree_map(
            lambda s, sh: jax.ShapeDtypeStruct(s.shape, s.dtype, sharding=sh),
            state, shardings,
        )
        batch = jax.ShapeDtypeStruct(
            (cfg.batch_size, cfg.n_sources, cfg.d_in), jnp.float32,
            sharding=mesh_lib.batch_sharding(mesh),
        )
        scale = jax.ShapeDtypeStruct(
            (cfg.n_sources,), jnp.float32,
            sharding=NamedSharding(mesh, P()),
        )
        texts.append(step.lower(state_sh, batch, scale).as_text())
    assert texts[0] == texts[1]


# ---------------------------------------------------------------------------
# config validation


def test_config_harvest_runtime_suggestions():
    with pytest.raises(ValueError, match="did you mean 'paged'"):
        CrossCoderConfig(harvest_runtime="pagd")
    with pytest.raises(ValueError, match="padded\\|paged"):
        CrossCoderConfig(harvest_runtime="ragged")


def test_config_page_size_power_of_two():
    with pytest.raises(ValueError, match="power of two"):
        CrossCoderConfig(page_size=48)
    with pytest.raises(ValueError, match="power of two"):
        CrossCoderConfig(page_size=0)
    CrossCoderConfig(page_size=128)          # fine when padded


def test_config_paged_seq_len_constraints():
    with pytest.raises(ValueError, match="smaller than page_size"):
        CrossCoderConfig(harvest_runtime="paged", seq_len=32, page_size=64)
    with pytest.raises(ValueError, match="must divide seq_len"):
        CrossCoderConfig(harvest_runtime="paged", seq_len=96, page_size=64)
    with pytest.raises(ValueError, match="incompatible with"):
        CrossCoderConfig(harvest_runtime="paged", seq_len=1024, page_size=64,
                         seq_shards=2)
    CrossCoderConfig(harvest_runtime="paged", seq_len=1024, page_size=64)
