"""Independent torch-CPU re-statement of the reference's crosscoder math,
used as the golden oracle for parity tests (SURVEY.md §4: "port the math,
feed identical synthetic inputs, assert JAX matches to dtype tolerance").

Each function states the reference location it mirrors
(``/root/reference/crosscoder.py`` / ``trainer.py``); written as free
functions over explicit tensors, in fp32, so the oracle is unambiguous.
"""

from __future__ import annotations

import torch


def oracle_encode(x: torch.Tensor, w_enc: torch.Tensor, b_enc: torch.Tensor, relu: bool = True) -> torch.Tensor:
    # reference crosscoder.py:69-80 — einsum over (models, d_model) then bias+ReLU
    h = torch.einsum("bnd,ndh->bh", x, w_enc) + b_enc
    return torch.relu(h) if relu else h


def oracle_decode(f: torch.Tensor, w_dec: torch.Tensor, b_dec: torch.Tensor) -> torch.Tensor:
    # reference crosscoder.py:82-89
    return torch.einsum("bh,hnd->bnd", f, w_dec) + b_dec


def oracle_losses(x: torch.Tensor, w_enc, w_dec, b_enc, b_dec) -> dict:
    # reference crosscoder.py:96-130 (fp32 path)
    f = oracle_encode(x, w_enc, b_enc)
    recon = oracle_decode(f, w_dec, b_dec)
    delta = (recon - x) ** 2
    per_row = delta.sum(dim=(1, 2))
    l2 = per_row.mean()

    eps = 1e-8
    ctr = x - x.mean(0)
    tv = (ctr**2).sum(dim=(1, 2))
    ev = 1 - per_row / (tv + eps)

    n = x.shape[1]
    ev_src = []
    for i in range(n):
        num = delta[:, i, :].sum(-1)
        den = (ctr[:, i, :] ** 2).sum(-1)
        ev_src.append(1 - num / (den + eps))

    dec_norm_total = w_dec.norm(dim=-1).sum(dim=-1)  # [d_hidden]
    l1 = (f * dec_norm_total[None, :]).sum(-1).mean(0)
    l0 = (f > 0).float().sum(-1).mean()
    return {
        "l2": l2,
        "l1": l1,
        "l0": l0,
        "ev": ev,
        "ev_per_source": torch.stack(ev_src),
        "acts": f,
        "recon": recon,
    }


def oracle_lr_lambda(step: int, total_steps: int) -> float:
    # reference trainer.py:28-32
    if step < 0.8 * total_steps:
        return 1.0
    return 1.0 - (step - 0.8 * total_steps) / (0.2 * total_steps)


def oracle_l1_coeff(step: int, total_steps: int, l1_coeff: float) -> float:
    # reference trainer.py:34-39
    if step < 0.05 * total_steps:
        return l1_coeff * step / (0.05 * total_steps)
    return l1_coeff
