"""Ragged paged attention (ops/paged_attention.py): interpret-mode kernel
parity against the pure-XLA oracle, the oracle's bit-consistency with the
padded LM attention core, page-pool construction, and the dispatch/support
gates. All CPU, tier-1; also part of scripts/kernels.sh."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from crosscoder_tpu.models import lm
from crosscoder_tpu.ops import paged_attention as pa

D, S, H, KV, HD = 5, 16, 4, 2, 8
SCALE = 0.35


@pytest.fixture(scope="module")
def qkv():
    rng = np.random.default_rng(0)
    q = jnp.asarray(rng.normal(size=(D, S, H, HD)).astype(np.float32))
    k = jnp.asarray(rng.normal(size=(D, S, KV, HD)).astype(np.float32))
    v = jnp.asarray(rng.normal(size=(D, S, KV, HD)).astype(np.float32))
    return q, k, v


# mixed lengths incl. the edge docs: single-token and max-length
LENGTHS = np.array([1, 16, 7, 9, 3], np.int32)


@pytest.mark.parametrize("window,softcap", [(0, 0.0), (4, 50.0), (0, 50.0),
                                            (8, 0.0)])
def test_kernel_matches_oracle(qkv, window, softcap):
    """Online-softmax kernel == masked-softmax oracle at valid positions
    (reassociation tolerance), across global/local masks and softcap."""
    q, k, v = qkv
    lengths = jnp.asarray(LENGTHS)
    want = np.asarray(pa.ragged_attention_reference(
        q, k, v, lengths, scale=SCALE, softcap=softcap, window=window,
        is_local=bool(window),
    ))
    got = np.asarray(pa.paged_attention(
        q, k, v, lengths, page_size=8, scale=SCALE, softcap=softcap,
        window=window, interpret=True,
    ))
    for d, L in enumerate(LENGTHS):
        np.testing.assert_allclose(
            got[d, :L], want[d, :L], rtol=2e-5, atol=2e-5,
            err_msg=f"doc {d}, window {window}",
        )


def test_kernel_page_size_one_and_full(qkv):
    """Degenerate page sizes: 1 token/page (S pages) and S tokens/page
    (one page) bracket the loop structure."""
    q, k, v = qkv
    lengths = jnp.asarray(LENGTHS)
    want = np.asarray(pa.ragged_attention_reference(
        q, k, v, lengths, scale=SCALE, softcap=0.0, window=0, is_local=False,
    ))
    for page in (1, S):
        got = np.asarray(pa.paged_attention(
            q, k, v, lengths, page_size=page, scale=SCALE, window=0,
            interpret=True,
        ))
        for d, L in enumerate(LENGTHS):
            np.testing.assert_allclose(
                got[d, :L], want[d, :L], rtol=2e-5, atol=2e-5,
                err_msg=f"page {page}, doc {d}",
            )


def test_oracle_bit_matches_lm_attn_core(qkv):
    """The XLA reference is op-for-op the padded LM attention plus the
    length mask — for full-length documents the outputs must be BITWISE
    equal (the chain that makes the paged harvest's CPU parity gate
    exact)."""
    q, k, v = qkv
    cfg = lm.LMConfig.tiny().replace(
        n_heads=H, n_kv_heads=KV, head_dim=HD,
        query_pre_attn_scalar=SCALE ** -2, sliding_window=4,
    )
    full = jnp.full((D,), S, jnp.int32)
    for is_local in (False, True):
        want = lm._attn_core(q, k, v, cfg, jnp.asarray(is_local))
        got = pa.ragged_attention_reference(
            q, k, v, full, scale=cfg.query_pre_attn_scalar ** -0.5,
            softcap=cfg.attn_softcap, window=cfg.sliding_window,
            is_local=jnp.asarray(is_local),
        )
        np.testing.assert_array_equal(
            np.asarray(got), np.asarray(want), err_msg=f"is_local={is_local}"
        )


def test_paginate_kv_roundtrip(qkv):
    """The page pool + identity table reconstruct K/V exactly."""
    _, k, v = qkv
    kv_pages, tbl = pa.paginate_kv(k, v, page_size=4)
    assert kv_pages.shape == (D * 4, 2, KV, 4, HD)
    assert tbl.shape == (D, 4)
    for d in (0, 3):
        for j in range(4):
            page = kv_pages[tbl[d, j]]
            np.testing.assert_array_equal(
                np.asarray(page[0]),                    # [KV, page, hd]
                np.asarray(k[d, 4 * j: 4 * j + 4].transpose(1, 0, 2)),
            )
            np.testing.assert_array_equal(
                np.asarray(page[1]),
                np.asarray(v[d, 4 * j: 4 * j + 4].transpose(1, 0, 2)),
            )
    with pytest.raises(ValueError, match="not divisible"):
        pa.paginate_kv(k, v, page_size=5)


def test_supported_gates():
    assert pa.supported(4, 16, 4, 2, 8, 8)
    assert not pa.supported(4, 16, 4, 2, 8, 5)       # not a power of two
    assert not pa.supported(4, 16, 4, 2, 8, 32)      # page !| seq_len
    assert not pa.supported(4, 16, 3, 2, 8, 8)       # heads !| kv heads
    # VMEM budget: a huge per-doc block must be rejected
    assert not pa.supported(4, 64 * 1024, 64, 1, 256, 8)


def test_dispatch_falls_back_without_optin(qkv, monkeypatch):
    """Neither interpret mode nor the env opt-in: paged_attention must
    route to the XLA reference (identical output), never the kernel."""
    q, k, v = qkv
    lengths = jnp.asarray(LENGTHS)
    monkeypatch.delenv(pa.DISPATCH_ENV, raising=False)
    called = {}
    real = pa._rpa_call

    def spy(*a, **kw):
        called["kernel"] = True
        return real(*a, **kw)

    monkeypatch.setattr(pa, "_rpa_call", spy)
    got = pa.paged_attention(
        q, k, v, lengths, page_size=8, scale=SCALE, interpret=False,
    )
    want = pa.ragged_attention_reference(
        q, k, v, lengths, scale=SCALE, softcap=0.0, window=0, is_local=False,
    )
    np.testing.assert_array_equal(np.asarray(got), np.asarray(want))
    assert "kernel" not in called
