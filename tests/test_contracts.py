"""Static correctness plane (docs/ANALYSIS.md): engine semantics, the
per-rule mutation self-tests (every rule must be able to fail), clean
spot checks over the shipped tree, and the ``scripts/analyze.py`` CLI
contract (``--json`` = exactly one JSON document on stdout). All CPU,
tier-1; the slow HLO lattice is exercised via its builder once."""

import json
import subprocess
import sys
from pathlib import Path

import pytest

from crosscoder_tpu.analysis.contracts import (ALL_RULES, AST_RULES,
                                               MUTATIONS, PALLAS_RULES,
                                               Finding, Rule,
                                               build_source_context,
                                               run_kernel_probes, run_mutation,
                                               run_rules, vmem_summary)

REPO = Path(__file__).parent.parent


# ---------------------------------------------------------------------------
# engine semantics


def test_crashing_rule_is_a_finding_not_a_pass():
    rule = Rule(name="boom", description="always crashes",
                applies_when=lambda ctx: True,
                check=lambda ctx: 1 / 0)
    rep = run_rules([rule], ctx=None)
    assert not rep.ok
    assert rep.findings[0].rule == "boom"
    assert "harness crashed" in rep.findings[0].message


def test_allow_suppresses_but_records():
    rule = Rule(name="noisy", description="", applies_when=lambda c: True,
                check=lambda c: [Finding(rule="noisy", message="x")])
    rep = run_rules([rule], ctx=None, allow={"noisy"})
    assert rep.ok and rep.suppressed == ["noisy"] and not rep.checked


def test_inapplicable_rule_is_skipped():
    rule = Rule(name="hlo-only", description="",
                applies_when=lambda c: False, check=lambda c: [])
    rep = run_rules([rule], ctx=object())
    assert rep.skipped == ["hlo-only"] and rep.ok


# ---------------------------------------------------------------------------
# mutation self-tests: a checker that cannot fail is not a check


def test_every_rule_has_a_mutation():
    assert {r.name for r in ALL_RULES} == set(MUTATIONS)


@pytest.mark.parametrize("rule_name", sorted(MUTATIONS))
def test_mutation_fires(rule_name):
    rep = run_mutation(rule_name)
    fired = [f for f in rep.findings if f.rule == rule_name]
    assert fired, f"seeded violation for {rule_name} produced no finding"
    assert all(f.severity == "error" for f in fired)
    assert not rep.ok


# ---------------------------------------------------------------------------
# shipped tree stays clean (fast packs; the HLO lattice rides analyze.py
# in tier1.sh and the dedicated zero-cost-off tests)


def test_ast_lints_clean_on_shipped_tree():
    rep = run_rules(AST_RULES, build_source_context())
    assert rep.ok, "\n".join(str(f) for f in rep.findings)
    assert len(rep.checked) == len(AST_RULES)


def test_pallas_pack_clean_and_covers_all_seven_kernels():
    ctx = run_kernel_probes()
    rep = run_rules(PALLAS_RULES, ctx)
    assert rep.ok, "\n".join(str(f) for f in rep.findings)
    families = {c.kernel for c in ctx.calls}
    assert {"topk", "sparsify", "batchtopk", "quant", "sparse_grad",
            "paged_attention", "fused_encoder_topk"} <= families
    summary = vmem_summary(ctx)
    assert len(summary) >= 7
    assert all("MiB" in v for v in summary.values())


def test_metric_key_lint_tracks_registry_bindings():
    """The folded-in metric-key lint sees keys on ANY name bound to
    ``MetricsRegistry()`` — the old standalone script's receiver-name
    heuristic (registry/reg/r) missed e.g. ``m = MetricsRegistry()``."""
    import ast

    from crosscoder_tpu.analysis.contracts.ast_lints import collect_keys

    tree = ast.parse(
        "from crosscoder_tpu.obs.registry import MetricsRegistry\n"
        "m = MetricsRegistry()\n"
        "m.observe('rogue_histogram_key', 1.0)\n"
        "m.gauge('perf/fine', 2.0)\n"
    )
    keys = {k for _, k in collect_keys(tree)}
    assert {"rogue_histogram_key", "perf/fine"} <= keys


# ---------------------------------------------------------------------------
# CLI contract


def _run_analyze(*args):
    return subprocess.run(
        [sys.executable, str(REPO / "scripts" / "analyze.py"), *args],
        capture_output=True, text=True, cwd=REPO, timeout=300,
    )


def test_analyze_json_emits_exactly_one_document_on_stdout():
    p = _run_analyze("--json", "--skip-hlo", "--skip-pallas")
    assert p.returncode == 0, p.stdout + p.stderr
    doc = json.loads(p.stdout)          # a second document would raise
    assert doc["ok"] is True
    assert set(doc) == {"ok", "findings", "checked", "skipped",
                        "suppressed", "info"}


def test_analyze_mutate_exits_nonzero():
    p = _run_analyze("--mutate", "lint-no-stdout-print", "--json")
    assert p.returncode == 1
    doc = json.loads(p.stdout)
    assert doc["ok"] is False
    assert doc["findings"][0]["rule"] == "lint-no-stdout-print"


def test_analyze_list_names_every_rule():
    p = _run_analyze("--list")
    assert p.returncode == 0
    for rule in ALL_RULES:
        assert rule.name in p.stdout
