"""Observability plane (crosscoder_tpu/obs; docs/OBSERVABILITY.md):

- span tracer: nesting, thread-safety, Chrome trace-event schema validity
- metrics registry: all four shapes, untouched-snapshots-to-{} (the
  ResilienceCounters contract extended to perf/*)
- refill-bubble attribution: perf/refill_bubble_frac within ±0.05 of
  ground truth on a sleep-injected fake refill
- zero-cost off: step-HLO identity across cfg.obs, no extra host↔device
  transfers with obs on OR off
- profiler windows: exact [start, stop) capture, SIGUSR1 arming, legacy
  profile_dir behavior
- compile events + predicted-vs-measured comm keys in the log stream
- scripts/trace_report.py summary + malformed-trace exit code
- scripts/check_metric_keys.py namespace lint
- MetricsLogger satellites: stdout stays clean, stderr echo cadence,
  non-scalar hardening

All CPU, tier-1.
"""

import importlib.util
import json
import threading
import time
from pathlib import Path

import jax
import numpy as np
import pytest

from crosscoder_tpu.config import CrossCoderConfig
from crosscoder_tpu.obs import trace
from crosscoder_tpu.obs.profiler import ProfilerWindow, parse_profile_steps
from crosscoder_tpu.obs.registry import MetricsRegistry
from crosscoder_tpu.obs.trace import NullTracer, SpanTracer
from crosscoder_tpu.train.trainer import Trainer
from crosscoder_tpu.utils.logging import MetricsLogger

_SCRIPTS = Path(__file__).parent.parent / "scripts"


def _load_script(name):
    spec = importlib.util.spec_from_file_location(name, _SCRIPTS / f"{name}.py")
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def tiny_cfg(**kw):
    base = dict(
        d_in=16, dict_size=64, batch_size=32, num_tokens=32 * 400,
        enc_dtype="fp32", lr=2e-3, l1_coeff=0.02, log_backend="null",
    )
    base.update(kw)
    return CrossCoderConfig(**base)


# ---------------------------------------------------------------------------
# span tracer


def test_spans_nest_and_schema_is_valid(tmp_path):
    tracer = SpanTracer(tmp_path / "trace.json")
    with tracer.span("outer", step=3):
        with tracer.span("inner"):
            time.sleep(0.002)
    tracer.instant("marker", note="x")
    path = tracer.flush()
    data = json.loads(path.read_text())
    events = data["traceEvents"]
    complete = [e for e in events if e["ph"] == "X"]
    assert {e["name"] for e in complete} == {"outer", "inner"}
    for e in events:
        assert e["ph"] in ("X", "i", "M")
        if e["ph"] == "X":
            assert isinstance(e["ts"], (int, float))
            assert isinstance(e["dur"], (int, float)) and e["dur"] >= 0
            assert e["pid"] and "tid" in e
    outer = next(e for e in complete if e["name"] == "outer")
    inner = next(e for e in complete if e["name"] == "inner")
    # inner nests inside outer on the same thread track
    assert inner["tid"] == outer["tid"]
    assert outer["ts"] <= inner["ts"]
    assert inner["ts"] + inner["dur"] <= outer["ts"] + outer["dur"] + 1e-3
    assert outer["args"] == {"step": 3}


def test_tracer_is_thread_safe(tmp_path):
    tracer = SpanTracer(tmp_path / "trace.json")
    n_threads, n_spans = 8, 200
    barrier = threading.Barrier(n_threads)      # all alive together, so
                                                # thread idents are distinct

    def worker(i):
        barrier.wait()
        for j in range(n_spans):
            with tracer.span("w", thread=i):
                pass

    threads = [threading.Thread(target=worker, args=(i,)) for i in range(n_threads)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    events = [e for e in tracer.events() if e["ph"] == "X"]
    assert len(events) == n_threads * n_spans
    assert len({e["tid"] for e in events}) == n_threads
    json.loads(tracer.flush().read_text())      # serializes cleanly


def test_tracer_caps_events_and_counts_drops(tmp_path):
    tracer = SpanTracer(tmp_path / "trace.json")
    tracer.MAX_EVENTS = 10
    for _ in range(20):
        with tracer.span("s"):
            pass
    data = json.loads(tracer.flush().read_text())
    assert len(data["traceEvents"]) == 10
    assert data["dropped_events"] == 11     # 1 metadata event occupies a slot


def test_null_tracer_is_inert():
    t = NullTracer()
    with t.span("anything", k=1) as s:
        assert s is not None
    t.instant("x")
    t.close()
    # module-level hooks default to the null tracer
    assert isinstance(trace.get_tracer(), NullTracer) or True
    with trace.span("free"):
        pass


# ---------------------------------------------------------------------------
# registry


def test_registry_untouched_snapshots_empty():
    assert MetricsRegistry().snapshot() == {}


def test_registry_shapes_snapshot():
    r = MetricsRegistry()
    r.count("perf/things")
    r.count("perf/things", 2)
    r.gauge("perf/level", 0.5)
    r.ema("perf/lat_ms", 10.0)
    r.ema("perf/lat_ms", 20.0)
    for v in [1.0, 2.0, 3.0, 100.0]:
        r.observe("perf/hist", v)
    snap = r.snapshot()
    assert snap["perf/things"] == 3
    assert snap["perf/level"] == 0.5
    assert 10.0 < snap["perf/lat_ms"] < 20.0        # EMA moved toward 20
    assert snap["perf/hist_n"] == 4
    assert snap["perf/hist_p50"] == 3.0
    assert snap["perf/hist_p99"] == 100.0
    assert snap["perf/hist_max"] == 100.0
    # zero counters are dropped (reference-surface discipline)
    r2 = MetricsRegistry()
    r2.count("perf/zero", 0)
    assert r2.snapshot() == {}


def test_registry_thread_safety():
    r = MetricsRegistry()

    def worker():
        for _ in range(500):
            r.count("perf/n")
            r.observe("perf/h", 1.0)

    threads = [threading.Thread(target=worker) for _ in range(4)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert r.get_count("perf/n") == 2000
    assert r.snapshot()["perf/h_n"] == 2000


# ---------------------------------------------------------------------------
# trainer integration: bubble fraction, compile events, trace output


class SleepySource:
    """Source whose next() stalls a fixed time and otherwise costs ~zero
    (one pre-generated batch, reserved every call) — the sleep-injected
    fake refill the bubble measurement is graded against: production time
    IS the sleep, so ground truth is exactly sleep/wall."""

    def __init__(self, cfg, sleep_s):
        from crosscoder_tpu.data.synthetic import SyntheticActivationSource

        self._batch = SyntheticActivationSource(cfg).next()
        self.sleep_s = sleep_s
        self.slept = 0.0

    def next(self):
        t0 = time.perf_counter()
        time.sleep(self.sleep_s)
        self.slept += time.perf_counter() - t0      # incl. sleep overshoot
        return self._batch


def test_refill_bubble_frac_matches_ground_truth(tmp_path):
    cfg = tiny_cfg(log_every=8, save_every=10**9, checkpoint_dir=str(tmp_path),
                   log_backend="jsonl", obs="on", prefetch=False,
                   num_tokens=32 * 30)
    src = SleepySource(cfg, sleep_s=0.06)
    tr = Trainer(cfg, buffer=src, logger=MetricsLogger(cfg))
    slept_at = []

    real_log = tr.log

    def spy_log(metrics, step):
        slept_at.append(src.slept)      # sleep total at each log point
        real_log(metrics, step)

    tr.log = spy_log
    tr.train(num_steps=17)              # logs at 0, 8, 16
    lines = [json.loads(l) for l in
             (tmp_path / "metrics.jsonl").read_text().splitlines()]
    # grade the steady-state interval (the first includes compile time):
    # ground truth = slept fraction of that interval's wall-clock (the
    # per-step interval wall is the logged step_time_ms mean × 8 steps)
    rec = lines[-1]
    assert "perf/refill_bubble_frac" in rec
    frac = rec["perf/refill_bubble_frac"]
    wall_s = rec["step_time_ms"] * (17 - 1 - 8) / 1000
    truth = (slept_at[-1] - slept_at[-2]) / wall_s
    assert frac == pytest.approx(min(1.0, truth), abs=0.05), (frac, truth)


def test_obs_on_logs_compile_and_comm_keys(tmp_path):
    cfg = tiny_cfg(log_every=2, save_every=10**9, checkpoint_dir=str(tmp_path),
                   log_backend="jsonl", obs="on", num_tokens=32 * 30)
    tr = Trainer(cfg, logger=MetricsLogger(cfg))
    tr.train(num_steps=5)
    lines = [json.loads(l) for l in
             (tmp_path / "metrics.jsonl").read_text().splitlines()]
    rec = lines[-1]
    assert rec["perf/compiles"] >= 1
    assert rec["perf/compile_s_p50"] > 0
    assert "perf/step_ms" in rec and rec["perf/step_ms"] > 0
    # predicted (comm model on the ACTUAL compiled step) next to measured
    assert "comm/predicted_wire_bytes" in rec
    assert rec["comm/h2d_transfers"] >= 5
    assert rec["comm/d2h_transfers"] >= 1
    # single-device mesh: no collectives, zero predicted wire bytes
    if jax.device_count() == 1:
        assert rec["comm/predicted_wire_bytes"] == 0.0


def test_obs_run_emits_valid_trace_with_span_taxonomy(tmp_path):
    cfg = tiny_cfg(log_every=4, save_every=10**9, checkpoint_dir=str(tmp_path),
                   obs="on", num_tokens=32 * 30)
    tr = Trainer(cfg)
    tr.train(num_steps=6)
    trace_path = tmp_path / "obs" / "trace.json"
    assert trace_path.exists()
    data = json.loads(trace_path.read_text())
    names = {e["name"] for e in data["traceEvents"] if e["ph"] == "X"}
    assert {"step", "refill_wait", "compile"} <= names
    # the global tracer is restored after close
    assert isinstance(trace.get_tracer(), NullTracer)


def test_obs_spans_cover_save_and_restore(tmp_path):
    from crosscoder_tpu.checkpoint.ckpt import Checkpointer

    cfg = tiny_cfg(checkpoint_dir=str(tmp_path), obs="on",
                   num_tokens=32 * 30, save_every=10**9)
    tr = Trainer(cfg, checkpointer=Checkpointer(cfg=cfg))
    tr.step()
    tr.save()
    tr.restore()
    tr.close()
    data = json.loads((tmp_path / "obs" / "trace.json").read_text())
    names = {e["name"] for e in data["traceEvents"] if e["ph"] == "X"}
    assert {"save", "save_write", "restore"} <= names


# ---------------------------------------------------------------------------
# zero-cost off


# the contract engine's public step-lowering harness (the same one
# scripts/analyze.py sweeps the knob lattice with) — the local copy this
# file used to carry is retired
from crosscoder_tpu.analysis.contracts.hlo_rules import \
    lower_step_text as _lower_step_text  # noqa: E402


def test_step_hlo_independent_of_obs_config():
    """cfg.obs / obs_dir / profile_steps / log_print_every are host-side
    knobs: the compiled train step must be byte-identical across them."""
    texts = []
    for extra in ({}, dict(obs="on", obs_dir="/tmp/x",
                           profile_steps="3:5", log_print_every=7)):
        texts.append(_lower_step_text(tiny_cfg(**extra)))
    assert texts[0] == texts[1]


def test_obs_adds_no_host_device_transfers(monkeypatch):
    """With obs ON the telemetry is host-side only: the same number of
    device_put/device_get calls as obs off over identical stepping."""
    counts = {}
    real_put, real_get = jax.device_put, jax.device_get

    def run(obs):
        put, get = [], []
        monkeypatch.setattr(jax, "device_put",
                            lambda *a, **k: (put.append(1), real_put(*a, **k))[1])
        monkeypatch.setattr(jax, "device_get",
                            lambda x: (get.append(1), real_get(x))[1])
        try:
            tr = Trainer(tiny_cfg(obs=obs, prefetch=False))
            for _ in range(5):
                tr.step(full_metrics=False)
            tr.close()
        finally:
            monkeypatch.setattr(jax, "device_put", real_put)
            monkeypatch.setattr(jax, "device_get", real_get)
        return len(put), len(get)

    counts["off"] = run("off")
    counts["on"] = run("on")
    assert counts["on"] == counts["off"], counts
    # and the off path performs zero device_get during bare steps
    assert counts["off"][1] == 0, counts


# ---------------------------------------------------------------------------
# profiler windows


class _FakeProfiler:
    def __init__(self):
        self.calls = []

    def start_trace(self, out_dir):
        self.calls.append(("start", out_dir))

    def stop_trace(self):
        self.calls.append(("stop", None))


def test_parse_profile_steps():
    assert parse_profile_steps("") is None
    assert parse_profile_steps("3:7") == (3, 7)
    for bad in ("3", "7:3", "3:3", "a:b", "-1:4", "1:2:3"):
        with pytest.raises(ValueError):
            parse_profile_steps(bad)


def test_profiler_window_exact_steps(tmp_path, monkeypatch):
    fake = _FakeProfiler()
    monkeypatch.setattr(jax.profiler, "start_trace", fake.start_trace)
    monkeypatch.setattr(jax.profiler, "stop_trace", fake.stop_trace)
    cfg = tiny_cfg(profile_steps="2:4", profile_dir=str(tmp_path / "p"),
                   checkpoint_dir=str(tmp_path))
    pw = ProfilerWindow(cfg)
    synced = []
    pw.begin_stretch(0)
    for i in range(6):
        pw.before_step(i)
        started_now = pw._active
        pw.after_step(i, sync=lambda: synced.append(i))
        if i < 2 or i >= 4:
            assert not started_now or i == 3   # active only during [2, 4)
    assert fake.calls == [("start", str(tmp_path / "p")), ("stop", None)]
    assert synced == [3]                        # synced once, at the close
    assert pw.windows_captured == 1


def test_profiler_window_trainer_captures_configured_steps(tmp_path, monkeypatch):
    starts, stops = [], []
    monkeypatch.setattr(jax.profiler, "start_trace",
                        lambda d: starts.append(d))
    monkeypatch.setattr(jax.profiler, "stop_trace", lambda: stops.append(1))
    cfg = tiny_cfg(profile_steps="2:4", obs="on", num_tokens=32 * 30,
                   checkpoint_dir=str(tmp_path), save_every=10**9)
    tr = Trainer(cfg)
    tr.train(num_steps=6)
    assert len(starts) == 1 and len(stops) == 1


def test_profiler_sigusr1_requests_window(tmp_path, monkeypatch):
    fake = _FakeProfiler()
    monkeypatch.setattr(jax.profiler, "start_trace", fake.start_trace)
    monkeypatch.setattr(jax.profiler, "stop_trace", fake.stop_trace)
    cfg = tiny_cfg(checkpoint_dir=str(tmp_path), obs="on")
    pw = ProfilerWindow(cfg)
    assert not pw.configured            # no window configured...
    pw.begin_stretch(0)
    pw.before_step(0)
    assert fake.calls == []             # ...so nothing starts
    pw.request_window(2)                # what the SIGUSR1 handler calls
    pw.before_step(1)
    assert fake.calls and fake.calls[0][0] == "start"
    pw.after_step(1, sync=None)
    pw.before_step(2)
    pw.after_step(2, sync=None)
    assert fake.calls[-1][0] == "stop"
    assert pw.windows_captured == 1


def test_profiler_stale_window_discarded_unblocks_sigusr1(tmp_path, monkeypatch):
    """A configured absolute window whose start step already passed (a
    restore landed beyond it) is discarded, so it can neither fire at the
    wrong step nor block SIGUSR1 on-demand capture forever."""
    fake = _FakeProfiler()
    monkeypatch.setattr(jax.profiler, "start_trace", fake.start_trace)
    monkeypatch.setattr(jax.profiler, "stop_trace", fake.stop_trace)
    cfg = tiny_cfg(profile_steps="2:4", checkpoint_dir=str(tmp_path))
    pw = ProfilerWindow(cfg)
    pw.begin_stretch(100)               # resumed far past the window
    pw.before_step(100)
    pw.after_step(100, sync=None)
    assert fake.calls == []             # stale window gone, nothing started
    pw.request_window(1)                # SIGUSR1 must still work
    pw.before_step(101)
    pw.after_step(101, sync=None)
    assert [c[0] for c in fake.calls] == ["start", "stop"]


def test_legacy_profile_dir_window_still_fires(tmp_path):
    """The pre-existing behavior (profile_dir set, nothing else): a real
    jax.profiler trace of the steps-10..14 window lands on disk."""
    cfg = tiny_cfg(profile_dir=str(tmp_path / "prof"), num_tokens=32 * 30,
                   checkpoint_dir=str(tmp_path), save_every=10**9)
    tr = Trainer(cfg)
    tr.train(num_steps=16)
    files = list((tmp_path / "prof").rglob("*"))
    assert any(f.is_file() for f in files), "no profiler trace written"


# ---------------------------------------------------------------------------
# scripts/trace_report.py


def test_trace_report_summarizes(tmp_path, capsys):
    tracer = SpanTracer(tmp_path / "t.json")
    for _ in range(4):
        with tracer.span("step"):
            time.sleep(0.001)
    with tracer.span("refill_wait"):
        time.sleep(0.004)
    tracer.flush()
    mod = _load_script("trace_report")
    rc = mod.main([str(tmp_path / "t.json")])
    out = capsys.readouterr().out
    assert rc == 0
    assert "step" in out and "refill_wait" in out
    assert "refill_bubble_frac" in out
    rows, bubble = mod.summarize(mod.load_events(str(tmp_path / "t.json")))
    assert 0 < bubble < 1
    step_row = next(r for r in rows if r["span"] == "step")
    assert step_row["count"] == 4 and step_row["p50_ms"] >= 1.0


@pytest.mark.parametrize("payload", [
    "not json at all",
    '{"noTraceEvents": []}',
    '{"traceEvents": [{"ph": "X", "name": "a"}]}',       # missing ts/dur
    '{"traceEvents": [{"ph": "X", "name": "a", "ts": "x", "dur": 1}]}',
    '[42]',
])
def test_trace_report_rejects_malformed(tmp_path, payload):
    p = tmp_path / "bad.json"
    p.write_text(payload)
    mod = _load_script("trace_report")
    assert mod.main([str(p)]) != 0


# ---------------------------------------------------------------------------
# scripts/check_metric_keys.py


def test_metric_key_lint_passes_on_package():
    mod = _load_script("check_metric_keys")
    assert mod.main() == 0


def test_metric_key_lint_catches_violation():
    import ast

    mod = _load_script("check_metric_keys")
    bad = ast.parse(
        "reg.gauge('rogue_key', 1.0)\n"
        "metrics['another_rogue'] = 2\n"
        "scalars['perf/fine'] = 3\n"
        "metrics['loss'] = 0\n"
    )
    keys = [k for _, k in mod.collect_keys(bad)]
    assert set(keys) == {"rogue_key", "another_rogue", "perf/fine", "loss"}
    assert not mod.key_allowed("rogue_key")
    assert not mod.key_allowed("another_rogue")
    assert mod.key_allowed("perf/fine")
    assert mod.key_allowed("loss")
    assert mod.key_allowed("explained_variance_A")
    assert mod.key_allowed("explained_variance_3")
    assert not mod.key_allowed("perf/")          # empty tail is not a key


# ---------------------------------------------------------------------------
# MetricsLogger satellites


def test_logger_echo_goes_to_stderr_not_stdout(tmp_path, capsys):
    cfg = tiny_cfg(log_backend="jsonl", checkpoint_dir=str(tmp_path))
    logger = MetricsLogger(cfg)
    logger.log({"loss": 1.0}, step=0)
    logger.close()
    captured = capsys.readouterr()
    assert captured.out == ""                   # the bench stdout contract
    assert "loss" in captured.err


def test_logger_print_cadence(tmp_path, capsys):
    cfg = tiny_cfg(log_backend="jsonl", checkpoint_dir=str(tmp_path),
                   log_print_every=3)
    logger = MetricsLogger(cfg)
    for i in range(7):
        logger.log({"loss": float(i)}, step=i)
    logger.close()
    err = capsys.readouterr().err
    assert err.count("'loss'") == 3             # logs 0, 3, 6
    # log_print_every=0: never echo
    cfg0 = tiny_cfg(log_backend="jsonl", checkpoint_dir=str(tmp_path),
                    log_print_every=0)
    logger0 = MetricsLogger(cfg0)
    logger0.log({"loss": 1.0}, step=0)
    logger0.close()
    assert "'loss'" not in capsys.readouterr().err
    # every line still lands in the jsonl regardless of echo cadence
    lines = (tmp_path / "metrics.jsonl").read_text().strip().splitlines()
    assert len(lines) == 8


def test_logger_skips_non_scalars_with_one_warning(tmp_path, capsys):
    cfg = tiny_cfg(log_backend="jsonl", checkpoint_dir=str(tmp_path))
    logger = MetricsLogger(cfg)
    arr = np.arange(4, dtype=np.float32)
    for i in range(3):
        logger.log({"loss": 1.0, "explained_variance_per_source": arr,
                    "oops": None}, step=i)
    logger.close()
    err = capsys.readouterr().err
    assert err.count("non-scalar metric 'explained_variance_per_source'") == 1
    assert err.count("non-scalar metric 'oops'") == 1
    lines = [json.loads(l) for l in
             (tmp_path / "metrics.jsonl").read_text().splitlines()]
    assert len(lines) == 3
    for rec in lines:
        assert rec["loss"] == 1.0
        assert "explained_variance_per_source" not in rec
        assert "oops" not in rec


def test_config_validates_obs_fields():
    with pytest.raises(ValueError, match="obs"):
        tiny_cfg(obs="verbose")
    with pytest.raises(ValueError, match="log_print_every"):
        tiny_cfg(log_print_every=-1)
    with pytest.raises(ValueError, match="profile_steps"):
        tiny_cfg(profile_steps="10")
    with pytest.raises(ValueError):
        tiny_cfg(profile_steps="7:3")
    tiny_cfg(obs="on", profile_steps="3:9")     # valid combos construct
