"""Parity tests for the JAX Gemma-2 runtime against HF transformers.

The reference trusts TransformerLens for all LM execution (reference
buffer.py:81-89, nb:cell 29); our runtime replaces that layer, so these tests
gate it against the HF Gemma2 implementation on a tiny random config —
logits, per-layer residual streams (capture parity), CE loss, and the
edit/splice hook semantics used by the CE-recovered eval.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from crosscoder_tpu.models import lm

torch = pytest.importorskip("torch")
transformers = pytest.importorskip("transformers")


@pytest.fixture(scope="module")
def tiny_pair():
    """(HF Gemma2 model, our params, our cfg) with identical weights."""
    cfg = lm.LMConfig.tiny()
    hf_cfg = transformers.Gemma2Config(
        vocab_size=cfg.vocab_size,
        hidden_size=cfg.d_model,
        num_hidden_layers=cfg.n_layers,
        num_attention_heads=cfg.n_heads,
        num_key_value_heads=cfg.n_kv_heads,
        head_dim=cfg.head_dim,
        intermediate_size=cfg.d_ff,
        sliding_window=cfg.sliding_window,
        query_pre_attn_scalar=cfg.query_pre_attn_scalar,
        attn_logit_softcapping=cfg.attn_softcap,
        final_logit_softcapping=cfg.final_softcap,
        rope_theta=cfg.rope_theta,
        rms_norm_eps=cfg.rms_eps,
        attention_dropout=0.0,
        attn_implementation="eager",  # sdpa drops the logit softcap
        tie_word_embeddings=True,
    )
    torch.manual_seed(0)
    model = transformers.Gemma2ForCausalLM(hf_cfg).eval()
    params = lm.from_torch_state_dict(model.state_dict(), cfg, dtype="fp32")
    return model, params, cfg


@pytest.fixture(scope="module")
def tokens():
    rng = np.random.default_rng(1)
    return rng.integers(0, 257, size=(2, 16), dtype=np.int64)


def _hf_forward(model, tokens):
    with torch.no_grad():
        out = model(torch.from_numpy(tokens), output_hidden_states=True)
    return out


def test_logits_parity(tiny_pair, tokens):
    model, params, cfg = tiny_pair
    hf = _hf_forward(model, tokens)
    logits, _ = lm.forward(params, jnp.asarray(tokens), cfg)
    np.testing.assert_allclose(
        np.asarray(logits), hf.logits.numpy(), rtol=2e-4, atol=2e-4
    )


def test_resid_pre_capture_parity(tiny_pair, tokens):
    """blocks.L.hook_resid_pre must equal HF hidden_states[L] for every L
    (hidden_states[0] is the scaled embedding entering block 0), and the
    final resid_post must equal hidden_states[n_layers]."""
    model, params, cfg = tiny_pair
    hf = _hf_forward(model, tokens)
    hooks = [f"blocks.{i}.hook_resid_pre" for i in range(cfg.n_layers)]
    hooks.append(f"blocks.{cfg.n_layers - 1}.hook_resid_post")
    cache = lm.run_with_cache(params, jnp.asarray(tokens), cfg, hooks)
    for i in range(cfg.n_layers):
        name = hooks[i]
        np.testing.assert_allclose(
            np.asarray(cache[name]), hf.hidden_states[i].numpy(),
            rtol=2e-4, atol=2e-4, err_msg=name,
        )
    # HF's final hidden_states entry is post-final-RMSNorm; our resid_post is
    # the raw stream (TransformerLens semantics) — norm it before comparing.
    final = lm._rms_norm(cache[hooks[-1]], params["final_norm"], cfg.rms_eps)
    np.testing.assert_allclose(
        np.asarray(final), hf.hidden_states[cfg.n_layers].numpy(),
        rtol=2e-4, atol=2e-4, err_msg="final resid_post (normed)",
    )


def test_attn_mlp_out_capture_parity(tiny_pair, tokens):
    """hook_attn_out / hook_mlp_out (round-3 VERDICT missing #4: only resid
    sites parsed) must equal the HF sublayer contributions: Gemma-2 adds
    post_attention_layernorm(attn) and post_feedforward_layernorm(mlp) to
    the stream, so torch module hooks on those norms capture exactly our
    definition."""
    model, params, cfg = tiny_pair
    got_hf = {}

    def grab(name):
        def hook(mod, inp, out):
            got_hf[name] = out.detach().numpy()
        return hook

    handles = []
    for L in (0, 2):
        layer = model.model.layers[L]
        handles.append(layer.post_attention_layernorm.register_forward_hook(
            grab(f"attn{L}")))
        handles.append(layer.post_feedforward_layernorm.register_forward_hook(
            grab(f"mlp{L}")))
    try:
        _hf_forward(model, tokens)
    finally:
        for h in handles:
            h.remove()

    hooks = [f"blocks.{L}.hook_{site}" for L in (0, 2)
             for site in ("attn_out", "mlp_out")]
    cache = lm.run_with_cache(params, jnp.asarray(tokens), cfg, hooks)
    for L in (0, 2):
        np.testing.assert_allclose(
            np.asarray(cache[f"blocks.{L}.hook_attn_out"]), got_hf[f"attn{L}"],
            rtol=2e-4, atol=2e-4, err_msg=f"attn_out L{L}",
        )
        np.testing.assert_allclose(
            np.asarray(cache[f"blocks.{L}.hook_mlp_out"]), got_hf[f"mlp{L}"],
            rtol=2e-4, atol=2e-4, err_msg=f"mlp_out L{L}",
        )


def test_sublayer_hooks_sum_to_stream(tiny_pair, tokens):
    """resid_post(L) == resid_pre(L) + attn_out(L) + mlp_out(L) exactly
    (all four captured in one truncated forward; also proves the scan stops
    at L+1 for sublayer sites, not L)."""
    _, params, cfg = tiny_pair
    L = cfg.n_layers - 1                   # last layer: the edge case
    hooks = [f"blocks.{L}.hook_resid_pre", f"blocks.{L}.hook_attn_out",
             f"blocks.{L}.hook_mlp_out", f"blocks.{L}.hook_resid_post"]
    cache = lm.run_with_cache(params, jnp.asarray(tokens), cfg, hooks)
    got = (np.asarray(cache[hooks[0]]) + np.asarray(cache[hooks[1]])
           + np.asarray(cache[hooks[2]]))
    np.testing.assert_allclose(
        got, np.asarray(cache[hooks[3]]), rtol=1e-6, atol=1e-6
    )


def test_sublayer_hook_validation(tiny_pair, tokens):
    _, params, cfg = tiny_pair
    tok = jnp.asarray(tokens)
    # attn_out exists only for real layers (no virtual n_layers slot)
    with pytest.raises(ValueError, match="out of range"):
        lm.run_with_cache(params, tok, cfg, [f"blocks.{cfg.n_layers}.hook_attn_out"])
    with pytest.raises(ValueError, match="unsupported hook site"):
        lm.run_with_cache(params, tok, cfg, ["blocks.0.hook_z"])


def test_sublayer_edits(tiny_pair, tokens):
    """Edits at attn_out/mlp_out intervene on the sublayer contribution
    (the CE-splice path for sublayer-trained crosscoders): an identity
    splice leaves logits unchanged; zero-ablation changes them; the edit
    runs BEFORE same-layer capture."""
    _, params, cfg = tiny_pair
    tok = jnp.asarray(tokens)
    hp = "blocks.1.hook_attn_out"
    clean_logits, clean_cache = lm.forward(params, tok, cfg, capture=[hp])

    # identity splice: replace post-BOS positions with the clean capture
    spliced, _ = lm.forward(
        params, tok, cfg,
        edits=[lm.Edit(hp, lm.splice_edit, jnp.asarray(clean_cache[hp]))],
    )
    np.testing.assert_allclose(
        np.asarray(spliced), np.asarray(clean_logits), rtol=1e-5, atol=1e-5
    )

    # zero ablation: must actually change the logits
    zeroed, zcache = lm.forward(
        params, tok, cfg, capture=[hp], edits=[lm.Edit(hp, lm.zero_edit)]
    )
    assert np.abs(np.asarray(zeroed) - np.asarray(clean_logits)).max() > 1e-3
    # capture sees the EDITED contribution (edit-before-capture order)
    np.testing.assert_array_equal(np.asarray(zcache[hp]), 0.0)

    # mlp_out site too
    hp2 = "blocks.2.hook_mlp_out"
    zeroed2, _ = lm.forward(params, tok, cfg, edits=[lm.Edit(hp2, lm.zero_edit)])
    assert np.abs(np.asarray(zeroed2) - np.asarray(clean_logits)).max() > 1e-3


def test_ce_eval_fixed_points_at_attn_out(tiny_pair, tokens):
    """CE-recovered eval machinery at a sublayer hook: identity
    reconstruction recovers exactly 1, zero reconstruction matches the
    zero-ablation baseline (recovered 0 up to the BOS-handling delta)."""
    from crosscoder_tpu.analysis.ce_eval import get_ce_recovered_metrics

    _, params, cfg = tiny_pair
    hp = "blocks.1.hook_attn_out"
    m = get_ce_recovered_metrics(
        np.asarray(tokens), cfg, [params, params], hp, lambda x: x, chunk=2
    )
    assert m["ce_recovered_A"] == pytest.approx(1.0, abs=1e-3)
    assert m["ce_recovered_B"] == pytest.approx(1.0, abs=1e-3)
    z = get_ce_recovered_metrics(
        np.asarray(tokens), cfg, [params, params], hp, jnp.zeros_like, chunk=2
    )
    # zero reconstruction ≈ the zero-ablation baseline: recovered collapses
    # toward 0 (not exactly — splice keeps BOS clean while the ablation
    # zeroes it too, same delta the resid-site oracle documents). On a
    # random-init LM the CE DIRECTION of an ablation is noise, so only the
    # fixed-point relations are asserted, not which way CE moved.
    assert z["ce_recovered_A"] < 0.5 and z["ce_recovered_B"] < 0.5
    assert abs(z["ce_spliced_A"] - m["ce_spliced_A"]) > 1e-3


def test_ce_loss_parity(tiny_pair, tokens):
    """Our mean next-token CE matches torch cross_entropy on HF logits
    (TransformerLens return_type='loss' semantics, nb:cell 29)."""
    model, params, cfg = tiny_pair
    hf = _hf_forward(model, tokens)
    want = torch.nn.functional.cross_entropy(
        hf.logits[:, :-1].reshape(-1, cfg.vocab_size),
        torch.from_numpy(tokens)[:, 1:].reshape(-1),
    ).item()
    got = float(lm.ce_loss(params, jnp.asarray(tokens), cfg))
    assert abs(got - want) < 1e-4


def test_sliding_window_matters(tiny_pair, tokens):
    """Degenerate check that the local/global alternation is live: growing
    the window changes logits once S > window."""
    _, params, cfg = tiny_pair
    assert tokens.shape[1] > cfg.sliding_window
    wide = cfg.replace(sliding_window=4 * cfg.sliding_window)
    a, _ = lm.forward(params, jnp.asarray(tokens), cfg)
    b, _ = lm.forward(params, jnp.asarray(tokens), wide)
    assert not np.allclose(np.asarray(a), np.asarray(b), atol=1e-5)


def test_splice_identity_edit(tiny_pair, tokens):
    """Splicing the captured activation back in is a no-op — the fixed point
    the CE-recovered eval relies on (nb:cell 29: spliced == clean when the
    reconstruction is perfect)."""
    _, params, cfg = tiny_pair
    hp = "blocks.2.hook_resid_pre"
    tok = jnp.asarray(tokens)
    clean_logits, cache = lm.forward(params, tok, cfg, capture=[hp])
    edit = lm.Edit(hp, lm.splice_edit, cache[hp])
    spliced_logits, _ = lm.forward(params, tok, cfg, edits=[edit])
    np.testing.assert_allclose(
        np.asarray(spliced_logits), np.asarray(clean_logits), rtol=1e-5, atol=1e-5
    )


def test_zero_ablation_edit(tiny_pair, tokens):
    """zero_ablation_hook semantics: zeroing the hook layer changes the loss
    and equals manually zeroing via replace_edit."""
    _, params, cfg = tiny_pair
    hp = "blocks.2.hook_resid_pre"
    tok = jnp.asarray(tokens)
    clean = float(lm.ce_loss(params, tok, cfg))
    zeroed = float(lm.ce_loss(params, tok, cfg, edits=[lm.Edit(hp, lm.zero_edit)]))
    assert zeroed != pytest.approx(clean, abs=1e-6)
    zeros = jnp.zeros((tok.shape[0], tok.shape[1], cfg.d_model), jnp.float32)
    replaced = float(
        lm.ce_loss(params, tok, cfg, edits=[lm.Edit(hp, lm.replace_edit, zeros)])
    )
    assert zeroed == pytest.approx(replaced, abs=1e-6)


def test_edit_then_capture_order(tiny_pair, tokens):
    """Edits apply BEFORE capture at the same layer, matching TransformerLens
    hook ordering (the eval splices and downstream sees the spliced value)."""
    _, params, cfg = tiny_pair
    hp = "blocks.1.hook_resid_pre"
    tok = jnp.asarray(tokens)
    _, cache = lm.forward(
        params, tok, cfg, capture=[hp], edits=[lm.Edit(hp, lm.zero_edit)]
    )
    assert float(jnp.abs(cache[hp]).max()) == 0.0


def test_param_count(tiny_pair):
    _, params, cfg = tiny_pair
    got = sum(int(np.prod(v.shape)) for v in jax.tree_util.tree_leaves(params))
    assert got == lm.param_count(cfg)


def test_config_for_names():
    cfg = lm.config_for("google/gemma-2-2b")
    assert (cfg.d_model, cfg.n_layers) == (2304, 26)
    assert lm.config_for("gemma-2-2b-it") == cfg
    with pytest.raises(ValueError):
        lm.config_for("llama-3")


def test_capture_truncated_scan_matches_full():
    """run_with_cache stops at the highest hooked layer (stop_at_layer);
    captures must equal the full forward's bitwise (same scan prefix)."""
    cfg = lm.LMConfig.tiny()
    params = lm.init_params(jax.random.key(5), cfg)
    tokens = jax.numpy.asarray(
        np.random.default_rng(0).integers(0, cfg.vocab_size, size=(2, 16))
    )
    hooks = ["blocks.1.hook_resid_pre", "blocks.2.hook_resid_pre"]
    cache_fast = lm.run_with_cache(params, tokens, cfg, hooks)
    # force the full-depth path by also requesting logits
    _, cache_full = lm.forward(params, tokens, cfg, capture=hooks, return_logits=True)
    for hp in hooks:
        np.testing.assert_array_equal(
            np.asarray(cache_fast[hp], np.float32), np.asarray(cache_full[hp], np.float32)
        )


def test_run_with_cache_multi_matches_per_model():
    """One-dispatch multi-model harvest == per-model run_with_cache, stacked
    model-major (the buffer's source-axis contract)."""
    cfg = lm.LMConfig.tiny()
    pa = lm.init_params(jax.random.key(1), cfg)
    pb = lm.init_params(jax.random.key(2), cfg)
    tokens = jax.numpy.asarray(
        np.random.default_rng(3).integers(0, cfg.vocab_size, size=(2, 12))
    )
    hooks = ("blocks.1.hook_resid_pre", "blocks.2.hook_resid_pre")
    got = lm.run_with_cache_multi([pa, pb], tokens, cfg, hooks)
    want = []
    for p in (pa, pb):
        cache = lm.run_with_cache(p, tokens, cfg, hooks)
        want.extend(cache[hp] for hp in hooks)
    want = jax.numpy.stack(want, axis=2)
    np.testing.assert_array_equal(np.asarray(got, np.float32), np.asarray(want, np.float32))


def test_from_hf_local_checkpoint_roundtrip(tmp_path):
    """lm.from_hf against a locally-saved HF Gemma-2 checkpoint (no
    network): config mapping + weight conversion + logits parity vs the
    transformers forward — the load path the production entry uses
    (train/main.py build_buffer), previously never exercised (VERDICT
    round-1 missing #2)."""
    hf_cfg = transformers.Gemma2Config(
        vocab_size=257, hidden_size=32, num_hidden_layers=4,
        num_attention_heads=4, num_key_value_heads=2, head_dim=8,
        intermediate_size=64, sliding_window=8, query_pre_attn_scalar=8.0,
        attn_logit_softcapping=50.0, final_logit_softcapping=30.0,
        rope_theta=10_000.0, rms_norm_eps=1e-6,
        # eager attention: sdpa drops the attention logit softcap (same
        # reason as the tiny_pair fixture above)
        attn_implementation="eager",
    )
    torch.manual_seed(0)
    model = transformers.Gemma2ForCausalLM(hf_cfg).eval()
    ckpt = tmp_path / "tiny-gemma2"
    model.save_pretrained(ckpt)

    params, cfg = lm.from_hf(str(ckpt))
    assert cfg.d_model == 32 and cfg.n_layers == 4 and cfg.vocab_size == 257
    assert cfg.sliding_window == 8 and cfg.query_pre_attn_scalar == 8.0

    rng = np.random.default_rng(3)
    tok = rng.integers(0, 257, size=(2, 12), dtype=np.int64)
    # fp32 both sides for a tight comparison
    cfg32 = cfg.replace(dtype="fp32")
    params32 = jax.tree_util.tree_map(
        lambda x: x.astype(jnp.float32) if x.dtype == jnp.bfloat16 else x, params
    )
    logits, _ = lm.forward(params32, jnp.asarray(tok), cfg32)
    with torch.no_grad():
        want = model.float()(torch.from_numpy(tok)).logits.numpy()
    np.testing.assert_allclose(np.asarray(logits), want, rtol=2e-2, atol=2e-2)


def test_gemma2_family_named_configs():
    """All three family members map by name; the 27B's query scale is
    d_model/n_heads (144), unlike 2B/9B's head_dim (256)."""
    c27 = lm.config_for("google/gemma-2-27b")
    assert c27.d_model == 4608 and c27.n_layers == 46
    assert c27.query_pre_attn_scalar == 144.0
    assert c27.head_dim == 128 and c27.n_heads == 32
    assert lm.config_for("gemma-2-27b-it") == c27
    assert lm.config_for("gemma-2-9b").query_pre_attn_scalar == 256.0


def test_segmented_harvest_matches_monolithic():
    """SegmentedHarvest (the refill pipeline's sub-forward dispatch quanta)
    computes the same stacked capture as run_with_cache_multi — same per-layer
    op sequence, only the scan is cut into sub-scans. Covers mixed sublayer
    sites, a ragged final segment (n_scan % SEG_LAYERS != 0), and the
    pacing count contract."""
    cfg = lm.LMConfig.tiny()
    pa = lm.init_params(jax.random.key(11), cfg)
    pb = lm.init_params(jax.random.key(12), cfg)
    tokens = jax.numpy.asarray(
        np.random.default_rng(7).integers(0, cfg.vocab_size, size=(2, 12))
    )
    for hooks in (
        ("blocks.2.hook_resid_pre",),
        # mixed sites + multi-layer: n_scan = 4 → ranges (3, 1) at SEG_LAYERS=3
        ("blocks.1.hook_resid_pre", "blocks.3.hook_attn_out",
         "blocks.2.hook_mlp_out"),
    ):
        want = lm.run_with_cache_multi([pa, pb], tokens, cfg, hooks)
        job = lm.SegmentedHarvest([pa, pb], tokens, cfg, hooks)
        steps = 0
        while job.step():
            steps += 1
        assert steps + 1 == job.n_steps == lm.SegmentedHarvest.count(cfg, hooks, 2)
        np.testing.assert_allclose(
            np.asarray(job.result(), np.float32), np.asarray(want, np.float32),
            rtol=1e-5, atol=1e-5,
        )
        # result() after completion is idempotent; out_dtype is honored
        assert job.result() is job.result()
    job = lm.SegmentedHarvest([pa], tokens, cfg, ("blocks.1.hook_resid_pre",),
                              out_dtype=jax.numpy.bfloat16)
    assert job.result().dtype == jax.numpy.bfloat16
