"""Trainer tests: end-to-end loss decrease on synthetic data, sharded-vs-
single-device equivalence on the 8-virtual-device CPU mesh, metrics surface,
and one-step optimizer parity against torch Adam+clip (SURVEY.md §4)."""

import jax
import jax.numpy as jnp
import numpy as np
import torch

from crosscoder_tpu.config import CrossCoderConfig
from crosscoder_tpu.models import crosscoder as cc
from crosscoder_tpu.parallel import mesh as mesh_lib
from crosscoder_tpu.train import schedules
from crosscoder_tpu.train.state import init_train_state, make_optimizer
from crosscoder_tpu.train.trainer import Trainer, expand_metrics, make_train_step

from torch_oracle import oracle_losses


def tiny_cfg(**kw):
    base = dict(
        d_in=32,
        dict_size=256,
        batch_size=256,
        num_tokens=256 * 400,  # 400 total steps
        enc_dtype="fp32",
        lr=2e-3,
        l1_coeff=0.02,
        log_backend="null",
    )
    base.update(kw)
    return CrossCoderConfig(**base)


def run_steps(trainer: Trainer, n: int):
    out = None
    for _ in range(n):
        out = trainer.step()
    return jax.device_get(out)


def test_training_reduces_loss_and_raises_ev():
    cfg = tiny_cfg()
    tr = Trainer(cfg)
    first = jax.device_get(tr.step())
    last = run_steps(tr, 150)
    assert float(last["l2_loss"]) < 0.5 * float(first["l2_loss"])
    assert float(last["explained_variance"]) > float(first["explained_variance"])
    assert tr.step_counter == 151


def test_metrics_surface_matches_reference_keys():
    cfg = tiny_cfg()
    tr = Trainer(cfg)
    m = expand_metrics(jax.device_get(tr.step()), cfg.n_sources)
    # the reference's 9 logged scalars (trainer.py:51-61)
    assert set(m) == {
        "loss", "l2_loss", "l1_loss", "l0_loss", "l1_coeff", "lr",
        "explained_variance", "explained_variance_A", "explained_variance_B",
    }
    # l1_coeff warms up linearly from 0 (trainer.py:34-39): step 0 → 0
    assert m["l1_coeff"] == 0.0
    np.testing.assert_allclose(m["lr"], cfg.lr, rtol=1e-6)


def test_sharded_equals_single_device():
    """The same seed/batches must give the same params on a 1-device mesh and
    an 8-device DP×TP mesh (this is the N1/N2/N3 correctness gate)."""
    devs = jax.devices()
    assert len(devs) == 8, "conftest should provide 8 virtual cpu devices"

    results = {}
    for name, mesh in {
        "single": mesh_lib.make_mesh(devices=devs[:1]),
        "dp8": mesh_lib.make_mesh(data_axis_size=8, model_axis_size=1),
        "dp4_tp2": mesh_lib.make_mesh(data_axis_size=4, model_axis_size=2),
    }.items():
        cfg = tiny_cfg()
        tr = Trainer(cfg, mesh=mesh)
        run_steps(tr, 5)
        results[name] = jax.device_get(tr.state.params)

    for other in ("dp8", "dp4_tp2"):
        for k in results["single"]:
            np.testing.assert_allclose(
                results["single"][k],
                results[other][k],
                rtol=2e-4,
                atol=2e-5,
                err_msg=f"{other}:{k}",
            )


def test_one_step_optimizer_parity_with_torch():
    """One full step (loss → grads → global-norm clip 1.0 → Adam) matches the
    reference's torch pipeline (trainer.py:41-49) on identical params/batch."""
    cfg = tiny_cfg(d_in=16, dict_size=64, batch_size=32, lr=1e-3, l1_coeff=0.5)
    # force a clip-active regime by scaling up the batch
    rng = np.random.default_rng(3)
    x = (rng.normal(size=(32, 2, 16)) * 3).astype(np.float32)

    tx = make_optimizer(cfg, schedules.lr_schedule(cfg))
    state = init_train_state(jax.random.key(0), cfg, tx)
    params0 = {k: np.asarray(v).copy() for k, v in state.params.items()}  # before donation
    mesh = mesh_lib.make_mesh(devices=jax.devices()[:1])
    step_fn = make_train_step(cfg, mesh, tx, mesh_lib.state_shardings(mesh, state))
    new_state, _ = step_fn(state, jnp.asarray(x), jnp.ones((cfg.n_sources,), jnp.float32))

    # torch mirror: same params, same batch, l1_coeff at step 0 (= 0 warmup)
    tp = {k: torch.nn.Parameter(torch.from_numpy(v.copy())) for k, v in params0.items()}
    ref = oracle_losses(torch.from_numpy(x), tp["W_enc"], tp["W_dec"], tp["b_enc"], tp["b_dec"])
    l1_coeff_0 = 0.0
    loss = ref["l2"] + l1_coeff_0 * ref["l1"]
    loss.backward()
    torch.nn.utils.clip_grad_norm_(list(tp.values()), max_norm=1.0)
    opt = torch.optim.Adam(list(tp.values()), lr=cfg.lr, betas=(cfg.beta1, cfg.beta2))
    opt.step()

    for k in tp:
        np.testing.assert_allclose(
            np.asarray(new_state.params[k]), tp[k].detach().numpy(),
            rtol=1e-4, atol=1e-6, err_msg=k,
        )


def test_trainer_train_loop_runs_with_logger(tmp_path, capsys):
    cfg = tiny_cfg(log_every=5, save_every=10**9, checkpoint_dir=str(tmp_path), log_backend="jsonl")
    from crosscoder_tpu.utils.logging import MetricsLogger

    tr = Trainer(cfg, logger=MetricsLogger(cfg))
    final = tr.train(num_steps=12)
    assert "loss" in final
    logged = (tmp_path / "metrics.jsonl").read_text().strip().splitlines()
    assert len(logged) == 3  # steps 0, 5, 10


def test_prefetch_off_matches_on():
    """The one-deep prefetch worker must not change the training trajectory:
    same synthetic stream, same final params (bitwise)."""
    a = Trainer(tiny_cfg(prefetch=False))
    b = Trainer(tiny_cfg(prefetch=True))
    for _ in range(7):
        a.step()
        b.step()
    pa = jax.device_get(a.state.params)
    pb = jax.device_get(b.state.params)
    b.close()
    for k in pa:
        assert np.array_equal(np.asarray(pa[k]), np.asarray(pb[k])), k


def test_raw_bf16_source_matches_fp32_source():
    """A source serving raw bf16 + norm factors (the buffer's next_raw
    contract) trains identically to one serving pre-scaled fp32 — the
    on-device `astype(f32) * scale` is the reference's host-side math
    (reference buffer.py:123-124) moved into the compiled step."""
    cfg = tiny_cfg(num_tokens=256 * 50)
    factors = np.array([0.7, 1.3], np.float32)
    rng = np.random.default_rng(11)
    raw = [rng.standard_normal((cfg.batch_size, 2, cfg.d_in)).astype(jnp.bfloat16.dtype) for _ in range(6)]

    class RawSrc:
        normalisation_factor = factors
        def __init__(self): self.i = 0
        def next_raw(self):
            x = raw[self.i]; self.i += 1; return x

    class F32Src:
        def __init__(self): self.i = 0
        def next(self):
            x = raw[self.i].astype(np.float32) * factors[None, :, None]
            self.i += 1
            return x

    a = Trainer(cfg, buffer=RawSrc())
    b = Trainer(cfg, buffer=F32Src())
    for _ in range(6):
        a.step()
        b.step()
    pa, pb = jax.device_get(a.state.params), jax.device_get(b.state.params)
    a.close(); b.close()
    for k in pa:
        assert np.allclose(np.asarray(pa[k]), np.asarray(pb[k]), atol=1e-6), k


def test_l0_coeff_warmup_in_trainer():
    """cfg.l0_coeff trains through the jitted step with the L1-style
    warmup: step 0 applies zero L0 penalty (pre-increment convention),
    later steps a growing one; loss stays finite and L0 falls vs the
    no-penalty run over the same steps."""
    from crosscoder_tpu.train.trainer import Trainer
    from crosscoder_tpu.parallel import mesh as mesh_lib

    def run(l0_coeff):
        cfg = CrossCoderConfig(
            d_in=16, dict_size=128, n_models=2, batch_size=64,
            activation="jumprelu", jumprelu_theta=0.01,
            jumprelu_bandwidth=0.05, l1_coeff=0.0, l0_coeff=l0_coeff,
            enc_dtype="fp32", num_tokens=64 * 400, lr=1e-2,
            l1_warmup_frac=0.1, log_backend="null",
        )
        tr = Trainer(cfg, mesh=mesh_lib.mesh_from_cfg(cfg))
        m0 = tr.step()
        # warmup(0) = 0: the first step's loss must equal l2 + 0 exactly
        assert float(jax.device_get(m0["loss"])) == float(jax.device_get(m0["l2_loss"]))
        for _ in range(150):
            m = tr.step(full_metrics=False)
        m = tr.step()
        l0 = float(jax.device_get(m["l0_loss"]))
        assert np.isfinite(float(jax.device_get(m["loss"])))
        tr.close()
        return l0

    assert run(5e-2) < run(0.0)
