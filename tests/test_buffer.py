"""Tests for the paired-activation replay buffer (reference buffer.py:7-125
semantics), driven by the tiny fake-LM fixture — no real model downloads
(SURVEY.md §4 "fake-LM fixture")."""

import numpy as np
import pytest

import jax

from crosscoder_tpu.config import CrossCoderConfig
from crosscoder_tpu.data.buffer import PairedActivationBuffer
from crosscoder_tpu.models import lm


SEQ = 17          # rows_per_seq = 16
HP = "blocks.2.hook_resid_pre"


@pytest.fixture(scope="module")
def lm_pair():
    cfg = lm.LMConfig.tiny()
    pa = lm.init_params(jax.random.key(0), cfg)
    pb = lm.init_params(jax.random.key(1), cfg)
    return cfg, [pa, pb]


@pytest.fixture(scope="module")
def tokens():
    rng = np.random.default_rng(7)
    return rng.integers(0, 257, size=(256, SEQ), dtype=np.int64)


def make_cfg(**kw):
    base = dict(
        batch_size=32, buffer_mult=32, seq_len=SEQ, d_in=32, n_models=2,
        model_batch_size=4, norm_calib_batches=2, hook_point=HP, seed=3,
    )
    base.update(kw)
    return CrossCoderConfig(**base)


@pytest.fixture(scope="module")
def buf(lm_pair, tokens):
    lm_cfg, params = lm_pair
    return PairedActivationBuffer(make_cfg(), lm_cfg, params, tokens)


def test_size_accounting(buf):
    """buffer_size = batch·mult rounded down to whole (seq_len−1)-row seqs
    (reference buffer.py:15-17)."""
    assert buf.buffer_batches == 32 * 32 // 16 == 64
    assert buf.buffer_size == 64 * 16 == 1024
    assert buf._store.shape == (1024, 2, 32)


def test_first_fill_matches_direct_harvest(buf, lm_pair, tokens):
    """Store rows (harvest order) == both models' hook acts with BOS dropped,
    flattened (reference buffer.py:91-101)."""
    lm_cfg, params = lm_pair
    want = []
    for p in params:
        cache = lm.run_with_cache(p, tokens[:4], lm_cfg, [HP])
        want.append(np.asarray(cache[HP].astype(jax.numpy.bfloat16), dtype=np.float32))
    want = np.stack(want, axis=2)[:, 1:]                     # [4, S-1, 2, d]
    want = want.reshape(-1, 2, 32)
    got = buf._store[: want.shape[0]].astype(np.float32)
    np.testing.assert_allclose(got, want, rtol=1e-2, atol=1e-2)


def test_norm_factor_formula(buf, lm_pair, tokens):
    """factor = sqrt(d_in)/mean_token_norm per source, over the leading
    calib sequences, BOS included (reference buffer.py:44-63)."""
    lm_cfg, params = lm_pair
    n_seqs = 2 * 4
    norms = []
    for p in params:
        cache = lm.run_with_cache(p, tokens[:n_seqs], lm_cfg, [HP])
        acts = np.asarray(cache[HP].astype(jax.numpy.bfloat16), dtype=np.float32)
        norms.append(np.linalg.norm(acts, axis=-1).mean())
    want = np.sqrt(32) / np.asarray(norms)
    np.testing.assert_allclose(buf.normalisation_factor, want, rtol=2e-2)


def test_next_shape_dtype_and_scaling(lm_pair, tokens):
    lm_cfg, params = lm_pair
    b = PairedActivationBuffer(make_cfg(), lm_cfg, params, tokens)
    idx = b._perm[: 32].copy()
    raw = b._store[idx].astype(np.float32)
    out = b.next()
    assert out.shape == (32, 2, 32) and out.dtype == np.float32
    np.testing.assert_allclose(
        out, raw * b.normalisation_factor[None, :, None], rtol=1e-6
    )


def test_refresh_cadence_and_half_refill(lm_pair, tokens):
    """The refill cycle completes at the reference's trigger point (pointer
    passes buffer//2 − batch, reference buffer.py:121) and harvests half the
    seqs per cycle (buffer.py:70-74) — but the harvest itself now runs
    INCREMENTALLY between serves (chunks land on already-served permutation
    slots), so the trigger point only drains stragglers and re-shuffles
    instead of stalling for the whole half-buffer harvest."""
    lm_cfg, params = lm_pair
    b = PairedActivationBuffer(make_cfg(), lm_cfg, params, tokens)
    assert b.token_pointer == 64
    perm_before = b._perm.copy()
    store_before = b._store.copy()
    served = []
    for steps in range(1, 17):
        served.append(b._perm[b.pointer: b.pointer + 32].copy())
        b.next()
        if steps < 16:
            assert b.pointer == 32 * steps       # cycle not finished yet
    # trigger: after 16 serves of 32 rows the pointer passed 512 − 32
    assert b.pointer == 0
    assert b.token_pointer == 64 + 32            # half refill: 32 more seqs
    # unserved survivors (old perm tail) are byte-identical; the served
    # region was refilled with fresh rows
    survivors = perm_before[512:]
    np.testing.assert_array_equal(b._store[survivors], store_before[survivors])
    refilled = perm_before[:512]
    assert not np.array_equal(b._store[refilled], store_before[refilled])
    # no row served twice within the fill; every served position lies in
    # the refilled region
    served = np.concatenate(served)
    assert len(np.unique(served)) == len(served)
    assert set(served) <= set(refilled)


@pytest.mark.parametrize("buffer_mult", [32, 33])
def test_incremental_refill_never_corrupts_served_stream(lm_pair, tokens, buffer_mult):
    """The overlap invariant: harvest chunks written mid-cycle may only land
    on slots this fill can no longer serve, so every batch served during a
    fill is byte-identical to the store content AT fill time — the stream is
    exactly what a synchronous refresh would have served. Also probes that
    the harvest really is interleaved (token pointer advances mid-cycle,
    not in one stall at the trigger).

    buffer_mult=32 gives _cyc_tail == 0 (refill exactly covers the served
    region); 33 gives a buffer whose half-refill target exceeds the rows
    served by trigger time (_cyc_tail == 16), exercising the tail-rotation
    write path the production geometry hits (tail 3,840 at reference cfg)."""
    lm_cfg, params = lm_pair
    cfg = make_cfg(buffer_mult=buffer_mult)
    b = PairedActivationBuffer(cfg, lm_cfg, params, tokens)
    if buffer_mult == 33:
        assert b._cyc_tail > 0, "geometry no longer exercises the tail path"
    n_serve = (b.buffer_size // 2 - 32) // 32 + 1
    start_tp = b.token_pointer
    for cycle in range(2):                       # first and a survivor cycle
        snap = b._store.copy()
        perm = b._perm.copy()
        scale = b.normalisation_factor[None, :, None]
        for k in range(n_serve):
            want = snap[perm[32 * k: 32 * k + 32]].astype(np.float32) * scale
            got = b.next()
            assert np.array_equal(got, want), (cycle, k)
            if k == n_serve - 2:
                assert b.token_pointer != (start_tp + cycle * b.buffer_batches // 2) % 256, \
                    "harvest was not interleaved with serving"


def test_forced_refresh_mid_cycle_rewinds_all_dispatched_tokens(lm_pair, tokens):
    """A public refresh() mid-cycle abandons the unfinished cycle. EVERY
    sequence it dispatched — in-flight AND already drained into the store —
    is unserved (cycle rows become servable only at _finish_cycle), so the
    token stream must rewind over all of them or those sequences would be
    harvested, overwritten, and never seen (silent data gap)."""
    lm_cfg, params = lm_pair
    b = PairedActivationBuffer(make_cfg(), lm_cfg, params, tokens)
    for _ in range(6):                           # mid-cycle; harvest underway
        b.next()
    dispatched = b._cyc_seq_done
    drained = dispatched - sum(item[1] for item in b._cyc_inflight)
    assert dispatched > 0 and drained > 0        # both kinds present mid-cycle
    tp = b.token_pointer
    b.refresh()                                  # forced half refill
    assert b.token_pointer == (tp - dispatched + 32) % 256


def test_restore_on_live_buffer_keeps_checkpoint_position(lm_pair, tokens):
    """load_state_dict() on a buffer that has been serving (Trainer.restore
    path) must resume EXACTLY at the checkpoint's stream position — the
    abandoned pre-restore cycle's chunks must not rewind the restored
    pointer. The restored live buffer must equal a fresh-buffer restore."""
    lm_cfg, params = lm_pair
    donor = PairedActivationBuffer(make_cfg(), lm_cfg, params, tokens)
    for _ in range(20):                          # crosses one refresh
        donor.next()
    state = donor.state_dict()

    live = PairedActivationBuffer(make_cfg(), lm_cfg, params, tokens)
    for _ in range(6):                           # live mid-cycle, chunks in flight
        live.next()
    assert live._cyc_seq_done > 0
    live.load_state_dict(state)

    fresh = PairedActivationBuffer(make_cfg(), lm_cfg, params, tokens, lazy=True)
    fresh.load_state_dict(state)
    assert live.token_pointer == fresh.token_pointer
    np.testing.assert_array_equal(live._store, fresh._store)
    for _ in range(3):
        np.testing.assert_array_equal(live.next(), fresh.next())


def test_lazy_buffer_defers_harvest(lm_pair, tokens):
    """lazy=True skips calibration+fill (the resume path must not harvest
    the buffer twice); next() before load_state_dict is an error."""
    lm_cfg, params = lm_pair
    donor = PairedActivationBuffer(make_cfg(), lm_cfg, params, tokens)
    state = donor.state_dict()
    b = PairedActivationBuffer(make_cfg(), lm_cfg, params, tokens, lazy=True)
    assert b.token_pointer == 0 and not b._filled
    with pytest.raises(RuntimeError):
        b.next()
    b.load_state_dict(state)
    assert b.next().shape == (32, 2, 32)


def test_sharded_ragged_harvest(lm_pair, tokens):
    """model_batch_size not divisible by the mesh data axis (the default
    cfg on any 8-device TPU) must still harvest: chunks are padded to a
    fixed shard-divisible shape and results match the unsharded buffer."""
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    lm_cfg, params = lm_pair
    mesh = Mesh(np.array(jax.devices()).reshape(-1, 1), ("data", "model"))
    sh = NamedSharding(mesh, P("data", None))
    assert mesh.shape["data"] == 8
    b = PairedActivationBuffer(make_cfg(model_batch_size=3), lm_cfg, params,
                               tokens, batch_sharding=sh)
    assert b._chunk_seqs == 8
    ref = PairedActivationBuffer(make_cfg(model_batch_size=3), lm_cfg, params, tokens)
    np.testing.assert_allclose(
        b.normalisation_factor, ref.normalisation_factor, rtol=1e-3
    )
    np.testing.assert_allclose(
        b._store.astype(np.float32), ref._store.astype(np.float32),
        rtol=1e-2, atol=1e-2,   # batch-shape-dependent bf16 rounding only
    )


def test_no_repeat_within_fill(lm_pair, tokens):
    """Index-permutation serving = the reference's full-buffer shuffle:
    rows served between refreshes are distinct storage rows."""
    lm_cfg, params = lm_pair
    b = PairedActivationBuffer(make_cfg(), lm_cfg, params, tokens)
    seen = []
    for _ in range(16):
        seen.append(b._perm[b.pointer: b.pointer + 32])
        b.next()
    seen = np.concatenate(seen)
    assert len(np.unique(seen)) == len(seen)


def test_multi_source_hooks(lm_pair, tokens):
    """Two hook points × two models → n_sources=4, model-major source order
    (the N4/N8 generalization of the reference's hardcoded pair)."""
    lm_cfg, params = lm_pair
    cfg = make_cfg(hook_points=("blocks.1.hook_resid_pre", "blocks.3.hook_resid_pre"))
    b = PairedActivationBuffer(cfg, lm_cfg, params, tokens)
    assert cfg.n_sources == 4
    assert b._store.shape == (1024, 4, 32)
    cache = lm.run_with_cache(params[0], tokens[:4], lm_cfg, cfg.hook_points)
    want = np.asarray(cache[cfg.hook_points[1]].astype(jax.numpy.bfloat16), np.float32)
    got = b._store[: 4 * 16, 1].astype(np.float32).reshape(4, 16, 32)
    np.testing.assert_allclose(got, want[:, 1:], rtol=1e-2, atol=1e-2)


def test_multi_source_mixed_sites(lm_pair, tokens):
    """hook_points mixing residual and sublayer sites (round-4 hook-site
    generality): a crosscoder over {resid_pre, attn_out, mlp_out} of the
    same model pair harvests each site faithfully (store slab == the
    corresponding single-site capture)."""
    lm_cfg, params = lm_pair
    cfg = make_cfg(hook_points=("blocks.1.hook_resid_pre",
                                "blocks.1.hook_attn_out",
                                "blocks.2.hook_mlp_out"))
    b = PairedActivationBuffer(cfg, lm_cfg, params, tokens)
    assert cfg.n_sources == 6                    # 2 models × 3 sites
    assert b._store.shape == (1024, 6, 32)
    for si, hp in enumerate(cfg.hook_points):
        cache = lm.run_with_cache(params[0], tokens[:4], lm_cfg, [hp])
        want = np.asarray(cache[hp].astype(jax.numpy.bfloat16), np.float32)
        got = b._store[: 4 * 16, si].astype(np.float32).reshape(4, 16, 32)
        np.testing.assert_allclose(got, want[:, 1:], rtol=1e-2, atol=1e-2,
                                   err_msg=hp)


def test_resume_roundtrip(lm_pair, tokens):
    """state_dict → fresh buffer → load_state_dict continues the token
    stream at the saved position with the saved norm factors."""
    lm_cfg, params = lm_pair
    b1 = PairedActivationBuffer(make_cfg(), lm_cfg, params, tokens)
    for _ in range(20):                          # crosses one refresh
        b1.next()
    state = b1.state_dict()
    b2 = PairedActivationBuffer(make_cfg(), lm_cfg, params, tokens)
    b2.load_state_dict(state)
    assert b2.token_pointer == (int(state["token_pointer"]) + 64) % 256
    np.testing.assert_array_equal(b2.normalisation_factor, b1.normalisation_factor)
    out = b2.next()
    assert out.shape == (32, 2, 32)


def test_token_wraparound(lm_pair, tokens):
    """The harvest wraps at the corpus end instead of the reference's
    IndexError past its token budget."""
    lm_cfg, params = lm_pair
    b = PairedActivationBuffer(make_cfg(), lm_cfg, params, tokens[:80])
    assert b.token_pointer == 64
    for _ in range(16):                          # one full refill cycle
        b.next()
    assert b.token_pointer == (64 + 32) % 80


def test_rejects_mismatched_models(lm_pair, tokens):
    lm_cfg, params = lm_pair
    with pytest.raises(ValueError):
        PairedActivationBuffer(make_cfg(n_models=3), lm_cfg, params, tokens)


def test_resume_rewinds_to_oldest_unserved_row(lm_pair, tokens):
    """Per-row provenance: the saved token pointer equals the OLDEST
    unserved row's source sequence, so no harvested-but-unserved token is
    skipped by save/resume (mid-fill save, survivors from the first fill
    still present)."""
    lm_cfg, params = lm_pair
    b = PairedActivationBuffer(make_cfg(), lm_cfg, params, tokens)
    for _ in range(20):                          # crosses one refresh
        b.next()
    assert b.pointer > 0
    state = b.state_dict()
    oldest = int(b._src_global[b._perm[b.pointer:]].min())
    assert state["token_pointer"] == oldest % 256
    # survivors of the first fill are unserved ⇒ rewind reaches back into it
    assert oldest < 64


def test_save_before_first_fill_resumes_from_scratch(lm_pair, tokens):
    lm_cfg, params = lm_pair
    b = PairedActivationBuffer(make_cfg(), lm_cfg, params, tokens, lazy=True)
    state = b.state_dict()                       # crash-during-startup save
    assert state["normalisation_factor"] is None
    b2 = PairedActivationBuffer(make_cfg(), lm_cfg, params, tokens, lazy=True)
    b2.load_state_dict(state)
    assert b2._filled and b2.token_pointer == 64
    assert b2.next().shape == (32, 2, 32)


def test_next_raw_matches_next(lm_pair, tokens):
    """Raw-bf16 serving + on-host upcast·scale == the fp32 serve path, bit
    for bit — so the trainer's on-device scale path (trainer step_fn) is the
    same stream the reference serves (reference buffer.py:115-125)."""
    lm_cfg, params = lm_pair
    a = PairedActivationBuffer(make_cfg(), lm_cfg, params, tokens)
    b = PairedActivationBuffer(make_cfg(), lm_cfg, params, tokens)
    for _ in range(4):
        served = a.next()
        raw = b.next_raw()
        scaled = raw.astype(np.float32) * b.normalisation_factor[None, :, None]
        assert np.array_equal(served, scaled)


def test_native_and_numpy_serve_identically(lm_pair, tokens, monkeypatch):
    """The C++ gather/scatter kernels and the NumPy fallback produce the
    same buffer trajectory (fills + serves) byte-identically."""
    from crosscoder_tpu import native

    if not native.available():
        pytest.skip("native kernels unavailable")
    lm_cfg, params = lm_pair
    a = PairedActivationBuffer(make_cfg(), lm_cfg, params, tokens)
    batches_native = [a.next() for _ in range(6)]

    # force the numpy fallback and replay
    monkeypatch.setattr(native, "_lib", None)
    monkeypatch.setattr(native, "_lib_err", "forced-off for test")
    b = PairedActivationBuffer(make_cfg(), lm_cfg, params, tokens)
    batches_numpy = [b.next() for _ in range(6)]
    for x, y in zip(batches_native, batches_numpy):
        assert np.array_equal(x, y)


def test_seq_parallel_harvest_matches_dense(lm_pair):
    """cfg.seq_shards routes the harvest through forward_seq_parallel (ring
    attention over the mesh data axis) — component N5 reachable from the
    production config. The harvested store, norm factors, and served stream
    must match the dense batch-sharded path."""
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    lm_cfg, params = lm_pair
    SEQ2 = 16                                     # divisible by the 8 shards
    rng = np.random.default_rng(5)
    toks = rng.integers(0, 257, size=(256, SEQ2), dtype=np.int64)
    mesh = Mesh(np.array(jax.devices()).reshape(-1, 1), ("data", "model"))
    sh = NamedSharding(mesh, P("data", None))

    def cfg(**kw):
        return make_cfg(seq_len=SEQ2, batch_size=30, buffer_mult=30, **kw)

    b_seq = PairedActivationBuffer(
        cfg(seq_shards=8), lm_cfg, params, toks, batch_sharding=sh
    )
    b_dense = PairedActivationBuffer(cfg(), lm_cfg, params, toks)
    np.testing.assert_allclose(
        b_seq.normalisation_factor, b_dense.normalisation_factor, rtol=1e-3
    )
    np.testing.assert_allclose(
        b_seq._store.astype(np.float32), b_dense._store.astype(np.float32),
        rtol=2e-2, atol=2e-2,   # ring-order bf16 accumulation differences only
    )
    for _ in range(3):
        a, b = b_seq.next(), b_dense.next()
        np.testing.assert_allclose(a, b, rtol=2e-2, atol=2e-2)


def test_seq_shards_validation(lm_pair, tokens):
    lm_cfg, params = lm_pair
    import pytest as _pytest

    with _pytest.raises(ValueError, match="seq_shards needs a mesh"):
        PairedActivationBuffer(
            make_cfg(seq_len=16, seq_shards=8), lm_cfg, params, tokens[:, :16]
        )
    with _pytest.raises(ValueError, match="must divide seq_len"):
        make_cfg(seq_len=17, seq_shards=8)


def test_device_buffer_matches_host_buffer(lm_pair, tokens):
    """cfg.buffer_device='hbm': the HBM-resident store serves the exact
    same stream as the host-RAM buffer — same fills, same permutations,
    same bytes — with batches coming back device-resident."""
    from crosscoder_tpu.data.buffer import DevicePairedActivationBuffer

    lm_cfg, params = lm_pair
    host = PairedActivationBuffer(make_cfg(), lm_cfg, params, tokens)
    dev = DevicePairedActivationBuffer(make_cfg(), lm_cfg, params, tokens)
    np.testing.assert_array_equal(dev.normalisation_factor, host.normalisation_factor)
    np.testing.assert_array_equal(dev._store, host._store)
    for step in range(20):                       # crosses one refill cycle
        a = host.next()
        b = dev.next()
        assert isinstance(b, jax.Array)
        np.testing.assert_allclose(np.asarray(b), a, rtol=1e-6, atol=1e-7), step
    # raw serving parity too
    np.testing.assert_array_equal(
        np.asarray(dev.next_raw(), np.float32),
        host.next_raw().astype(np.float32),
    )


def test_device_buffer_ragged_chunk_scratch_row(lm_pair, tokens):
    """Ragged harvest chunks pad their scatter positions with the scratch
    row; served data must still exactly match the host path (which slices
    the padding off instead)."""
    from crosscoder_tpu.data.buffer import DevicePairedActivationBuffer

    lm_cfg, params = lm_pair
    # model_batch_size 3 does not divide the 4-seq first fill → ragged tail
    host = PairedActivationBuffer(make_cfg(model_batch_size=3), lm_cfg, params, tokens)
    dev = DevicePairedActivationBuffer(make_cfg(model_batch_size=3), lm_cfg, params, tokens)
    np.testing.assert_array_equal(dev._store, host._store)


def test_device_buffer_through_trainer(lm_pair, tokens):
    """End-to-end: the trainer consumes device-resident batches from the
    HBM buffer (prefetch on) and trains; loss matches the host-buffer
    trainer step for step."""
    from crosscoder_tpu.data.buffer import DevicePairedActivationBuffer
    from crosscoder_tpu.parallel import mesh as mesh_lib
    from crosscoder_tpu.train.trainer import Trainer

    lm_cfg, params = lm_pair
    cfg = make_cfg(dict_size=64, num_tokens=32 * 6, log_backend="null")
    mesh = mesh_lib.mesh_from_cfg(cfg)
    t_host = Trainer(cfg, PairedActivationBuffer(cfg, lm_cfg, params, tokens), mesh=mesh)
    t_dev = Trainer(cfg, DevicePairedActivationBuffer(cfg, lm_cfg, params, tokens), mesh=mesh)
    for _ in range(6):
        mh = t_host.step()
        md = t_dev.step()
        assert float(jax.device_get(mh["loss"])) == float(jax.device_get(md["loss"]))
    t_host.close()
    t_dev.close()


def test_refill_frac_quarter_reuses_activations(lm_pair, tokens):
    """refill_frac 0.25: each steady-state cycle serves half the buffer but
    re-harvests only a quarter — ~2 serves per harvested row, harvest FLOPs
    halved (the TPU-era freshness/throughput knob; 0.5 = reference parity).
    The serve stream must stay uncorrupted and the accounting exact."""
    lm_cfg, params = lm_pair
    b = PairedActivationBuffer(make_cfg(refill_frac=0.25), lm_cfg, params, tokens)
    assert b._refill_batches() == 16                 # vs 32 at parity
    tp0 = b.token_pointer
    # two full serve cycles; every served batch must match the store+perm
    # at fill time (the incremental-refill write-safety invariant)
    for cycle in range(2):
        snap = b._store.copy()
        perm = b._perm.copy()
        scale = b.normalisation_factor[None, :, None]
        for k in range(16):
            want = snap[perm[32 * k: 32 * k + 32]].astype(np.float32) * scale
            np.testing.assert_array_equal(b.next(), want)
    # 2 cycles x 1024/2 rows served = 1024 rows; harvested 2 x 16 seqs = 512
    assert b.token_pointer == (tp0 + 2 * 16) % 256


def test_refill_frac_validation():
    with pytest.raises(ValueError, match="refill_frac"):
        make_cfg(refill_frac=0.75)
    with pytest.raises(ValueError, match="refill_frac"):
        make_cfg(refill_frac=0.0)


# ---------------------------------------------------------------------------
# mesh-sharded HBM store (round-3; VERDICT round-2 missing #3)


def _data_mesh():
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    mesh = Mesh(np.array(jax.devices()).reshape(-1, 1), ("data", "model"))
    return mesh, NamedSharding(mesh, P("data", None))


def test_mesh_buffer_selected_and_matches_host(lm_pair, tokens):
    """On a multi-chip mesh, buffer_device='hbm' routes to the data-axis
    sharded store; the served stream must equal the host-RAM buffer's
    byte for byte, with batches coming back in the step's batch sharding."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    from crosscoder_tpu.data.buffer import (
        MeshPairedActivationBuffer, make_buffer,
    )

    lm_cfg, params = lm_pair
    mesh, sh = _data_mesh()
    host = PairedActivationBuffer(make_cfg(), lm_cfg, params, tokens,
                                  batch_sharding=sh)
    dev = make_buffer(make_cfg(buffer_device="hbm"), lm_cfg, params, tokens,
                      batch_sharding=sh)
    assert isinstance(dev, MeshPairedActivationBuffer)
    np.testing.assert_array_equal(dev.normalisation_factor,
                                  host.normalisation_factor)
    np.testing.assert_array_equal(dev._store, host._store)
    want_sh = NamedSharding(mesh, P("data", None, None))
    for step in range(20):                       # crosses one refill cycle
        a = host.next()
        b = dev.next()
        assert isinstance(b, jax.Array)
        assert b.sharding.is_equivalent_to(want_sh, b.ndim), step
        np.testing.assert_allclose(np.asarray(b), a, rtol=1e-6, atol=1e-7)
    np.testing.assert_array_equal(
        np.asarray(dev.next_raw(), np.float32),
        host.next_raw().astype(np.float32),
    )


def test_mesh_buffer_padded_store_and_ragged_chunks(lm_pair, tokens):
    """buffer_size not divisible by the shard count pads the store; ragged
    harvest chunks pad their scatter positions past the PADDED store. Both
    kinds of pad rows must never reach a served batch."""
    from crosscoder_tpu.data.buffer import make_buffer

    lm_cfg, params = lm_pair
    # seq_len 13 → 12 rows/seq → buffer_size 32·32//12·12 = 1020, % 8 != 0;
    # model_batch_size 3 → ragged final chunk of the first fill
    kw = dict(seq_len=13, model_batch_size=3)
    toks = tokens[:, :13]
    mesh, sh = _data_mesh()
    host = PairedActivationBuffer(make_cfg(**kw), lm_cfg, params, toks,
                                  batch_sharding=sh)
    dev = make_buffer(make_cfg(buffer_device="hbm", **kw), lm_cfg, params,
                      toks, batch_sharding=sh)
    assert dev.buffer_size % 8 != 0 and dev._store_size % 8 == 0
    np.testing.assert_array_equal(dev._store, host._store)
    for _ in range(6):
        np.testing.assert_allclose(np.asarray(dev.next()), host.next(),
                                   rtol=1e-6, atol=1e-7)


def test_mesh_buffer_resume_matches_host(lm_pair, tokens):
    """state_dict/load_state_dict through the sharded store reproduces the
    host buffer's restored stream exactly (A4 resume determinism)."""
    from crosscoder_tpu.data.buffer import make_buffer

    lm_cfg, params = lm_pair
    mesh, sh = _data_mesh()
    host = PairedActivationBuffer(make_cfg(), lm_cfg, params, tokens,
                                  batch_sharding=sh)
    dev = make_buffer(make_cfg(buffer_device="hbm"), lm_cfg, params, tokens,
                      batch_sharding=sh)
    for _ in range(5):
        host.next(), dev.next()
    state = host.state_dict()
    assert state == dev.state_dict()
    host.load_state_dict(state)
    dev.load_state_dict(state)
    for _ in range(8):
        np.testing.assert_allclose(np.asarray(dev.next()), host.next(),
                                   rtol=1e-6, atol=1e-7)


def test_mesh_buffer_through_trainer(lm_pair, tokens):
    """The trainer consumes pre-sharded batches from the mesh store on an
    8-way data mesh; loss trajectory matches the host-buffer trainer."""
    from crosscoder_tpu.data.buffer import make_buffer
    from crosscoder_tpu.parallel import mesh as mesh_lib
    from crosscoder_tpu.train.trainer import Trainer

    lm_cfg, params = lm_pair
    cfg = make_cfg(dict_size=64, num_tokens=32 * 6, log_backend="null")
    mesh = mesh_lib.mesh_from_cfg(cfg)
    assert int(mesh.shape["data"]) == 8
    sh = mesh_lib.batch_sharding(mesh)
    from jax.sharding import NamedSharding, PartitionSpec as P
    tok_sh = NamedSharding(mesh, P("data", None))
    t_host = Trainer(cfg, PairedActivationBuffer(cfg, lm_cfg, params, tokens,
                                                 batch_sharding=tok_sh),
                     mesh=mesh)
    cfg_d = cfg.replace(buffer_device="hbm")
    t_dev = Trainer(cfg_d, make_buffer(cfg_d, lm_cfg, params, tokens,
                                       batch_sharding=tok_sh), mesh=mesh)
    for _ in range(6):
        mh = t_host.step()
        md = t_dev.step()
        assert float(jax.device_get(mh["loss"])) == float(jax.device_get(md["loss"]))
    t_host.close()
    t_dev.close()
