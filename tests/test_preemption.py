"""SIGTERM preemption handling: the training loop must stop cleanly, write
a RESUMABLE checkpoint, and exit 0 (SURVEY.md §5 'failure detection' — the
reference only has save-in-finally, reference trainer.py:74-82; on TPU
VMs/pods SIGTERM is the preemption notice)."""

import json
import os
import selectors
import signal
import subprocess
import sys
import time
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent

SCRIPT = """
import os, jax
jax.config.update("jax_platforms", "cpu")
import sys
sys.path.insert(0, {repo!r})
from crosscoder_tpu.config import CrossCoderConfig
from crosscoder_tpu.train.trainer import Trainer
from crosscoder_tpu.checkpoint.ckpt import Checkpointer

cfg = CrossCoderConfig(d_in=32, dict_size=256, batch_size=256, num_tokens=256 * 100000,
                       enc_dtype="fp32", log_backend="null", checkpoint_dir={ckpt!r},
                       save_every=10**9, log_every=10**9)
tr = Trainer(cfg, checkpointer=Checkpointer(cfg=cfg))
print("READY", flush=True)
tr.train()
print("CLEAN-EXIT step", tr.step_counter, flush=True)
"""


def test_sigterm_checkpoints_and_exits_cleanly(tmp_path):
    ckpt = str(tmp_path / "ck")
    proc = subprocess.Popen(
        [sys.executable, "-u", "-c", SCRIPT.format(repo=str(REPO), ckpt=ckpt)],
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
        cwd=str(REPO),
    )
    # wait for the loop to actually start (skip warnings from jax import —
    # stderr is merged into stdout). The pipe read itself must be bounded:
    # a child that hangs before printing anything would otherwise block
    # this iteration forever and hang the suite instead of failing it.
    sel = selectors.DefaultSelector()
    sel.register(proc.stdout, selectors.EVENT_READ)
    deadline = time.monotonic() + 120
    ready, pending = False, ""
    while not ready:
        remaining = deadline - time.monotonic()
        # enforce the deadline even when the pipe keeps yielding non-READY
        # chatter — select() returning ready must not bypass the timeout
        if remaining <= 0 or not sel.select(timeout=remaining):
            proc.kill()
            proc.communicate()
            raise AssertionError("child never reported READY within deadline")
        chunk = os.read(proc.stdout.fileno(), 65536).decode(errors="replace")
        if not chunk:
            raise AssertionError("child exited before READY")
        pending += chunk
        ready = any(ln.strip() == "READY" for ln in pending.splitlines())
    sel.close()
    time.sleep(3)  # let some steps run
    proc.send_signal(signal.SIGTERM)
    out, _ = proc.communicate(timeout=120)
    assert proc.returncode == 0, out
    assert "SIGTERM" in out and "CLEAN-EXIT" in out, out

    version = Path(ckpt) / "version_0"
    metas = sorted(version.glob("*_meta.json"))
    assert metas, f"no checkpoint written under {version}"
    meta = json.loads(metas[-1].read_text())
    assert meta["step"] > 0
