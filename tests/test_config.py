"""Config round-trip, reference-cfg compatibility, and CLI tests."""

import json

import pytest

from crosscoder_tpu.config import CrossCoderConfig, get_default_cfg, parse_hook_point


def test_defaults_match_reference():
    # the reference defaults (train.py:13-35) are the parity surface
    cfg = get_default_cfg()
    assert cfg.seed == 49
    assert cfg.batch_size == 4096
    assert cfg.buffer_mult == 128
    assert cfg.lr == 5e-5
    assert cfg.num_tokens == 400_000_000
    assert cfg.l1_coeff == 2.0
    assert (cfg.beta1, cfg.beta2) == (0.9, 0.999)
    assert cfg.dict_size == 2**14
    assert cfg.seq_len == 1024
    assert cfg.enc_dtype == "bf16"
    assert cfg.hook_point == "blocks.14.hook_resid_pre"
    assert cfg.dec_init_norm == 0.08
    assert cfg.total_steps == 97_656  # trainer.py:14


def test_reference_cfg_json_loads(tmp_path):
    # shape of the published checkpoint cfg JSON (crosscoder.py:151-155):
    # the reference dict plus d_in, with a cuda device string
    ref = {
        "seed": 49, "batch_size": 4096, "buffer_mult": 128, "lr": 5e-5,
        "num_tokens": 400000000, "l1_coeff": 2, "beta1": 0.9, "beta2": 0.999,
        "dict_size": 16384, "seq_len": 1024, "enc_dtype": "bf16",
        "model_name": "gemma-2-2b", "site": "resid_pre", "device": "cuda:1",
        "model_batch_size": 4, "log_every": 100, "save_every": 30000,
        "dec_init_norm": 0.08, "hook_point": "blocks.14.hook_resid_pre",
        "wandb_project": "crosscoders", "wandb_entity": "someone", "d_in": 2304,
        "some_unknown_key": [1, 2, 3],
    }
    p = tmp_path / "cfg.json"
    p.write_text(json.dumps(ref))
    cfg = CrossCoderConfig.from_json(p)
    assert cfg.d_in == 2304
    assert cfg.device == "cuda:1"  # preserved verbatim; placement is mesh-driven
    assert cfg.extras["some_unknown_key"] == [1, 2, 3]
    # round-trip preserves every original key
    out = cfg.to_dict()
    for k, v in ref.items():
        assert out[k] == v or out[k] == float(v)


def test_parse_hook_point():
    assert parse_hook_point("blocks.14.hook_resid_pre") == (14, "resid_pre")
    assert parse_hook_point("blocks.6.hook_resid_post") == (6, "resid_post")
    with pytest.raises(ValueError):
        parse_hook_point("ln_final.hook_scale")


def test_cli_overrides():
    cfg = CrossCoderConfig.from_cli(["--dict-size", "32768", "--activation", "topk", "--topk-k", "64"])
    assert cfg.dict_size == 32768
    assert cfg.activation == "topk"
    assert cfg.topk_k == 64


def test_cli_config_json_then_flags(tmp_path):
    p = tmp_path / "c.json"
    CrossCoderConfig(dict_size=8192, lr=1e-4).to_json(p)
    cfg = CrossCoderConfig.from_cli(["--config-json", str(p), "--lr", "3e-4"])
    assert cfg.dict_size == 8192
    assert cfg.lr == 3e-4


def test_validation():
    with pytest.raises(ValueError):
        CrossCoderConfig(enc_dtype="int8")
    with pytest.raises(ValueError):
        CrossCoderConfig(activation="gelu")


def test_n_sources_multilayer():
    cfg = CrossCoderConfig(n_models=3, hook_points=("blocks.6.hook_resid_pre", "blocks.20.hook_resid_pre"))
    assert cfg.n_sources == 6
    assert cfg.resolved_hook_points() == ("blocks.6.hook_resid_pre", "blocks.20.hook_resid_pre")
