"""Benchmark: crosscoder pipeline throughput on one TPU chip.

Fourteen sections (env ``BENCH_SECTIONS``, default all; progress on
stderr).
Output contract: stdout carries exactly ONE machine-parseable JSON line,
guaranteed last and guaranteed **compact** (≤2 KB: headline, per-section
key numbers, gate booleans) — the driver truncates the line at 2000
chars, so the full per-section detail goes to an artifact file instead
($BENCH_ARTIFACT, default BENCH_DETAIL.json). Stray prints are rerouted
to stderr for the whole run:

- **step**: the bare train step on device-resident batches (round-1's
  headline; BASELINE.json config 1 — dict 2^15, batch 4096, bf16).
- **matrix**: the sparse tier at the training-step level — activation
  {relu, topk dense, topk pallas, topk+sparse_decode, topk+sparse_bwd,
  batchtopk (dense + pallas)} × dict {2^15, 2^16, 2^17} (BASELINE.json
  config 2 is TopK k=32 @ 2^15). Kernel-heavy legs also report a
  fwd/bwd split (``fwd_ms``/``bwd_ms`` of the model loss alone) — the
  sparse backward plane (cfg.sparse_bwd) only changes bwd_ms, so the
  split is the attribution the step-level number can't give.
- **configs**: all five BASELINE.json scale-out configs at the
  train-step level (ref shape / topk / 9B-width / 3-way / multi-layer).
- **e2e**: the pipeline the reference actually runs (reference
  buffer.py:66-122 + trainer.py:41-49): harvest→buffer→train, Gemma-2-2B
  shapes, interleaved incremental refill. Harvest uses REAL-SHAPE random
  weights truncated to the scanned depth (layers 0-13; the stop-at-layer
  harvest never executes layers above the hook, so FLOPs are identical to
  the full model — weights are random because this environment is
  air-gapped, which changes no matmul shapes). Reports steady-state
  acts/sec and the refresh-bubble profile (max vs median step).
- **refill_overlap**: zero-bubble refill engine A/B (docs/SCALING.md
  "Zero-bubble refill") — the e2e leg with ``refill_overlap`` off vs on
  at fine/coarse harvest segmentation; gates on bubble_frac ≤ 0.10 with
  no throughput loss.
- **harvest**: the LM-harvest side (the dominant per-step cost outside
  the crosscoder) on a mixed-length synthetic corpus: padded-vs-paged
  runtime A/B — tokens/s over REAL tokens, padding-efficiency %, and the
  paged speedup (docs/SCALING.md "Harvest cost model").
- **quant**: the int8 data-plane quality gates (docs/SCALING.md
  "Quantized data plane"): roundtrip per-row MSE on a Gemma-shaped
  heavy-tailed probe, store-byte ratio, and the quantized grad
  all-reduce's one-shot + error-feedback accuracy on the local mesh.
- **obs**: the telemetry plane's cost gates (docs/OBSERVABILITY.md):
  SpanTracer spans/s, per-step overhead of ``cfg.obs`` on vs off at the
  reference shape (gate: <1%), and the ``perf/refill_bubble_frac`` a
  standard training leg emits.
- **dash**: dashboard generation at the reference's recorded workload
  (128 seqs × 3 features, minibatch 4 — BASELINE.md: ≈19 s on A100).
- **elastic**: the recovery SLO of elastic membership
  (docs/resilience.md "Elastic membership") — the 2-process CPU
  preemption drill (``resilience/elastic_drill.py``): chaos ``die@7``
  kills one host mid-run, the survivor re-meshes and
  restore-with-respecs; reports ``remesh_ms`` (detect → resumed wall
  time) and the bitwise-equal recovery gate.
- **serve**: the online model-diffing request path (docs/SERVING.md) —
  per-request p50/p99/max latency at batch 1/8/64 through the
  continuous-batched harvest→encode loop, saturated req/s, the
  p99 ≤ 3×p50 SLO gate at batch 8, and the zero-compiles-after-warmup
  assertion (AOT bucket reuse).

Headline metric = e2e acts/sec/chip. ``vs_baseline`` divides by an
analytic single-A100 torch estimate, documented here so it stays fixed:
train step ≈ 3× forward FLOPs ⇒ 1.81 GFLOP/row at dict 2^15 ⇒ 77k rows/s
at 45% of A100 bf16 peak (312 TFLOP/s); harvest = 2 models × 2·P FLOP/row
over the layers below the hook (P = params in layers 0-13 of Gemma-2-2B
≈ 1.09 G ⇒ 4.36 GFLOP/row — a resid_pre hook at block 14 executes blocks
0-13) at the same 45% ⇒ 32.2k rows/s; serial e2e = 1/(1/77k + 1/32.2k)
≈ 22.7k rows/s. (North star: ≥8× via 8-chip DP at
per-chip parity — BASELINE.json.)

Env knobs (debug/CI only): BENCH_SECTIONS, BENCH_DICT, BENCH_BATCH,
BENCH_STEPS, BENCH_CPU=1, BENCH_MASTER_DTYPE, BENCH_QUANT=1 (e2e with
the int8 replay store), QUANT_RELMSE_BOUND, BENCH_SERVE_REPS,
BENCH_TUNE_STEPS (calibration window for the tune leg),
BENCH_ARTIFACT (detail file path).
"""

from __future__ import annotations

import contextlib
import json
import os
import sys
import time

import jax
import jax.numpy as jnp

A100_PEAK = 312e12
A100_UTIL = 0.45
BASELINE_A100_STEP = 77_000.0


def log(msg: str) -> None:
    print(msg, file=sys.stderr, flush=True)


def _sync(x) -> float:
    # sync by FETCHING a scalar, not block_until_ready — under a
    # remote-tunnel TPU client block_until_ready can return before the
    # device has executed, which fakes ~1000x speedups
    return float(jax.device_get(x))


def _harvest_flops_per_row(lm_cfg, n_layers_scanned: int, n_models: int) -> float:
    """2·params FLOP per token per scanned layer, per model."""
    d, hd = lm_cfg.d_model, lm_cfg.head_dim
    per_layer = (
        d * lm_cfg.n_heads * hd            # W_q
        + 2 * d * lm_cfg.n_kv_heads * hd   # W_k, W_v
        + lm_cfg.n_heads * hd * d          # W_o
        + 3 * d * lm_cfg.d_ff              # gate/up/down
    )
    return 2.0 * per_layer * n_layers_scanned * n_models


def _make_cfg(**overrides):
    from crosscoder_tpu.config import CrossCoderConfig

    base = dict(
        d_in=int(os.environ.get("BENCH_DIN", 2304)),
        dict_size=int(os.environ.get("BENCH_DICT", 2**15)),
        n_models=2,
        batch_size=int(os.environ.get("BENCH_BATCH", 4096)),
        enc_dtype="bf16",
        # bf16 masters+moments = the reference's exact dtype regime
        # (train.py:5: all-bf16 params and torch-Adam state); fp32 masters
        # are this framework's quality-upgrade default but a different
        # workload than the A100 baseline estimate.
        master_dtype=os.environ.get("BENCH_MASTER_DTYPE", "bf16"),
        log_backend="null",
    )
    base.update(overrides)
    return CrossCoderConfig(**base)


def bench_step(cfg, n_steps: int, warmup: int = 3) -> dict:
    """Time the donated jitted train step on device-resident batches."""
    from crosscoder_tpu.parallel import mesh as mesh_lib
    from crosscoder_tpu.train import schedules
    from crosscoder_tpu.train.state import init_train_state, make_optimizer
    from crosscoder_tpu.train.trainer import make_train_step
    from jax.sharding import NamedSharding, PartitionSpec

    n_dev = len(jax.devices())
    mesh = mesh_lib.make_mesh(data_axis_size=n_dev, model_axis_size=1)
    tx = make_optimizer(cfg, schedules.lr_schedule(cfg))
    state = init_train_state(jax.random.key(cfg.seed), cfg, tx)
    shardings = mesh_lib.state_shardings(mesh, state)
    state = jax.device_put(state, shardings)
    # production mix: metric-only reductions (l0/EV) are gated to log_every
    # steps (1% at the reference cadence), so the bare step is the
    # throughput-defining variant.
    # AuxK amortization (cfg.aux_every > 1) and dead-mask caching
    # (cfg.aux_mask_every != 1): alternate the compiled variants exactly as
    # the Trainer does, so the timed mix IS the production step cost.
    track_fired = cfg.aux_k > 0 or cfg.resample_every > 0
    cached_mask = track_fired and cfg.aux_mask_every != 1
    variants: dict = {}

    def key_of(i: int) -> tuple[bool, bool]:
        aux_on = cfg.aux_k == 0 or cfg.aux_every <= 1 or i % cfg.aux_every == 0
        refresh = not cached_mask or i % cfg.aux_mask_cadence == 0
        return (aux_on, refresh)

    def pick(i: int):
        key = key_of(i)
        fn = variants.get(key)
        if fn is None:
            fn = variants[key] = make_train_step(
                cfg, mesh, tx, shardings, with_metrics=False,
                aux_on=key[0], mask_refresh=key[1],
            )
        return fn

    batch_sh = mesh_lib.batch_sharding(mesh)
    key = jax.random.key(0)
    batches = [
        jax.device_put(
            jax.random.normal(
                jax.random.fold_in(key, i),
                (cfg.batch_size, cfg.n_sources, cfg.d_in),
                dtype=jnp.bfloat16,
            ),
            batch_sh,
        )
        for i in range(4)
    ]
    # production serve path: raw bf16 rows + on-device per-source norm scale
    # (length tracks cfg.n_sources; 0.26 ≈ the Gemma-2-2B calibration
    # factors, BASELINE.md)
    scale = jax.device_put(
        jnp.full((cfg.n_sources,), 0.26, jnp.float32),
        NamedSharding(mesh, PartitionSpec()),
    )

    for i in range(warmup):
        state, metrics = pick(i)(state, batches[i % 4], scale)
    # any variant the timed window alternates onto must already be
    # compiled, or its first hit would time a compile, not a step
    warmed = {key_of(i) for i in range(warmup)}
    for i in range(n_steps):
        if key_of(i) not in warmed:
            warmed.add(key_of(i))
            state, metrics = pick(i)(state, batches[i % 4], scale)
    _sync(metrics["loss"])

    t0 = time.perf_counter()
    for i in range(n_steps):
        state, metrics = pick(i)(state, batches[i % 4], scale)
    loss = _sync(metrics["loss"])   # one ~70ms RTT amortized over n_steps
    dt = time.perf_counter() - t0
    del state, batches
    return {
        "step_ms": round(1000 * dt / n_steps, 2),
        "acts_per_sec_chip": round(cfg.batch_size * n_steps / dt / n_dev, 1),
        "loss_finite": bool(jnp.isfinite(loss)),
        "n_devices": n_dev,
    }


@contextlib.contextmanager
def _env(overrides: dict):
    """Set env vars for one bench leg (the kernel opt-in gates —
    CROSSCODER_SPARSE_GRAD_PALLAS etc. are read at trace time), restoring
    the previous values on exit so legs can't leak into each other."""
    saved = {k: os.environ.get(k) for k in overrides}
    os.environ.update(overrides)
    try:
        yield
    finally:
        for k, v in saved.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v


def bench_fwd_bwd(cfg, n_steps: int, warmup: int = 2) -> dict:
    """Forward/backward split of the MODEL cost: the jitted bare loss
    (``training_loss``, no optimizer/collectives — so fwd+bwd < step_ms)
    and its grad, timed separately; ``bwd_ms`` is the difference. This is
    the attribution the step-level number can't give: the sparse backward
    plane (cfg.sparse_bwd, docs/SCALING.md "Sparse backward plane")
    replaces backward matmuls only, so its whole win must land in
    ``bwd_ms`` while ``fwd_ms`` stays put."""
    from crosscoder_tpu.models import crosscoder as cc

    params = cc.init_params(jax.random.key(cfg.seed), cfg)
    x = jax.random.normal(
        jax.random.key(1), (cfg.batch_size, cfg.n_sources, cfg.d_in),
        dtype=jnp.float32,
    )
    l1 = float(cfg.l1_coeff)

    def loss(p, xb):
        return cc.training_loss(p, xb, l1, cfg, with_metrics=False)[0]

    out = {}
    for name, fn in (("fwd_ms", jax.jit(loss)),
                     ("fwdbwd_ms", jax.jit(jax.grad(loss)))):
        r = None
        for _ in range(warmup):
            r = fn(params, x)
        jax.block_until_ready(r)
        t0 = time.perf_counter()
        for _ in range(n_steps):
            r = fn(params, x)
        jax.block_until_ready(r)
        out[name] = round(1000 * (time.perf_counter() - t0) / n_steps, 2)
    out["bwd_ms"] = round(out["fwdbwd_ms"] - out["fwd_ms"], 2)
    return out


def section_step() -> dict:
    cfg = _make_cfg()
    out = bench_step(cfg, int(os.environ.get("BENCH_STEPS", 50)))
    out["workload"] = (
        f"d_in {cfg.d_in}, dict {cfg.dict_size}, batch {cfg.batch_size}, "
        f"relu, bf16 compute, {cfg.master_dtype} masters"
    )
    out["vs_a100_step"] = round(out["acts_per_sec_chip"] / BASELINE_A100_STEP, 3)
    log(f"[step] {out}")
    return out


def _kernel_parity(dict_size: int) -> dict:
    """On-DEVICE parity asserts (VERDICT round-2 weak #4: CI runs the
    Pallas interpreter; a Mosaic miscompile producing plausible garbage
    would pass ``loss_finite``). Executed on the live backend right before
    the timed variants:

    - pallas TopK output == dense ``lax.top_k`` scatter, bit-exact;
    - sparse-decode loss == dense-decode loss (same math re-associated, so
      tolerance is a few fp32 ulps, max-abs-diff recorded).
    """
    import numpy as np

    from crosscoder_tpu.models import crosscoder as cc
    from crosscoder_tpu.ops import activations as act_ops
    from crosscoder_tpu.ops import topk_pallas

    k = 32
    h = jax.random.normal(jax.random.key(7), (256, dict_size), jnp.bfloat16)
    if not topk_pallas.supported(h, k):
        # unsupported width ≠ miscompile: report the skip, not a failure
        return {"dict_size": dict_size,
                "skipped": "kernel unsupported at this width"}
    out_p = jax.jit(lambda x: topk_pallas.topk(x, k))(h)
    out_d = jax.jit(lambda x: act_ops._topk_dense(x, k))(h)
    topk_ok = bool(jax.device_get(jax.jit(lambda a, b: (a == b).all())(out_p, out_d)))

    cfg_d = _make_cfg(dict_size=dict_size, activation="topk", topk_k=k,
                      l1_coeff=0.0, batch_size=256)
    cfg_s = cfg_d.replace(sparse_decode=True)
    params = cc.init_params(jax.random.key(3), cfg_d)
    x = jax.random.normal(jax.random.key(8), (256, cfg_d.n_sources, cfg_d.d_in),
                          jnp.bfloat16)
    l_d = jax.jit(lambda p, b: cc.get_losses(p, b, cfg_d).l2_loss)(params, x)
    l_s = jax.jit(lambda p, b: cc.get_losses(p, b, cfg_s).l2_loss)(params, x)
    l_d, l_s = float(jax.device_get(l_d)), float(jax.device_get(l_s))
    denom = max(abs(l_d), 1e-30)
    sparse_rel = abs(l_s - l_d) / denom
    entry = {
        "dict_size": dict_size,
        "topk_pallas_bitexact": topk_ok,
        "sparse_decode_l2_rel_diff": float(np.format_float_scientific(
            sparse_rel, precision=3, unique=False)),
        "parity_ok": bool(topk_ok and sparse_rel < 1e-4),
    }
    log(f"[parity] {entry}")
    return entry


def _encoder_hbm_bytes(cfg) -> dict:
    """Predicted step HBM traffic, fused vs dense encoder — the PR 5
    compile-span HLO cost analysis ("bytes accessed" of the compiled
    bare model loss+grad) applied to the A/B the fused megakernel
    claims: same FLOPs, [B, dict] pre-acts never round-tripping HBM.
    Reported beside wall time so the bytes win is first-class in BENCH
    output, not an inference from step_ms."""
    from crosscoder_tpu.models import crosscoder as cc

    def bytes_of(c) -> float:
        # abstract operands only: .lower() accepts ShapeDtypeStruct
        # pytrees, and a real 2^17-dict param set would add GBs of HBM
        # pressure right after the timed leg ran
        params = jax.eval_shape(lambda key: cc.init_params(key, c),
                                jax.random.key(0))
        x = jax.ShapeDtypeStruct(
            (c.batch_size, c.n_sources, c.d_in), jnp.bfloat16)

        def loss(p, xb):
            return cc.training_loss(p, xb, 0.0, c, with_metrics=False)[0]

        from crosscoder_tpu.utils import compile_cache

        compiled = jax.jit(jax.grad(loss)).lower(params, x).compile()
        return compile_cache.extract_cost(compiled)["bytes_accessed"]

    fused_b = bytes_of(cfg)
    dense_b = bytes_of(cfg.replace(fused_encoder="off",
                                   quant_encoder=False))
    out = {"hbm_bytes_fused": fused_b, "hbm_bytes_dense": dense_b}
    if dense_b > 0:
        out["hbm_bytes_ratio"] = round(fused_b / dense_b, 4)
    return out


def section_matrix() -> list[dict]:
    """The sparse tier, at the training-step level (VERDICT round-1: the
    in-code perf claims were unverifiable; BASELINE config 2 had no
    measured number). Includes the full activation zoo (VERDICT round-2
    weak #6: jumprelu/batchtopk were implemented but never measured)."""
    from crosscoder_tpu.ops import activations as act_ops

    on_tpu = jax.default_backend() == "tpu"
    # (label, cfg overrides, topk impl, env for the leg). A non-empty env
    # is a kernel opt-in gate (ships conservative-default, see
    # ops/sparse_grad.py / topk_pallas.batchtopk_kernel_enabled) — those
    # legs are TPU-only: timing the interpret path or a silent dense
    # fallback under a kernel label would be a lie.
    variants = [
        ("relu", dict(activation="relu"), "auto", {}),
        ("topk_dense", dict(activation="topk", topk_k=32, l1_coeff=0.0),
         "dense", {}),
        ("topk_pallas", dict(activation="topk", topk_k=32, l1_coeff=0.0),
         "pallas", {}),
        ("topk_sparse_decode",
         dict(activation="topk", topk_k=32, l1_coeff=0.0, sparse_decode=True),
         "auto", {}),
        # the sparse backward plane (tentpole of the scatter-accumulate PR):
        # identical forward to topk_pallas + factored tier, backward through
        # ops/sparse_grad.py — step_ms vs topk_pallas is the headline A/B,
        # bwd_ms vs topk_pallas's carries the attribution
        ("topk_sparse_bwd",
         dict(activation="topk", topk_k=32, l1_coeff=0.0, sparse_bwd="on",
              factored_decode="on"),
         "pallas", {"CROSSCODER_SPARSE_GRAD_PALLAS": "1"}),
        # the fused encoder→TopK megakernel (PR "melt the dense floor"):
        # identical math to topk_sparse_bwd with the encode+TopK+sparsify
        # chain fused so [B, dict] pre-acts never hit HBM — step_ms vs
        # topk_sparse_bwd and vs relu is the headline (ROADMAP item-2
        # target: TopK <= 1.0x ReLU at dict 2^16/2^17); the
        # encoder_hbm_* fields carry the HLO cost-analysis bytes A/B
        ("topk_fused",
         dict(activation="topk", topk_k=32, l1_coeff=0.0, sparse_bwd="on",
              factored_decode="on", fused_encoder="on"),
         "pallas", {"CROSSCODER_SPARSE_GRAD_PALLAS": "1",
                    "CROSSCODER_FUSED_TOPK_PALLAS": "1"}),
        # + the int8 block-scaled in-kernel encoder matmul (the
        # --quant-encoder quality gate rides this leg: selection
        # agreement vs the exact fused leg)
        ("topk_fused_int8",
         dict(activation="topk", topk_k=32, l1_coeff=0.0, sparse_bwd="on",
              factored_decode="on", fused_encoder="on", quant_encoder=True),
         "pallas", {"CROSSCODER_SPARSE_GRAD_PALLAS": "1",
                    "CROSSCODER_FUSED_TOPK_PALLAS": "1"}),
        ("batchtopk", dict(activation="batchtopk", topk_k=32, l1_coeff=0.0),
         "auto", {}),
        # BatchTopK through the chunked Pallas global-threshold kernels
        # (bit-identical mask; closes the "BatchTopK unkerneled at wide
        # dicts" residue)
        ("batchtopk_pallas",
         dict(activation="batchtopk", topk_k=32, l1_coeff=0.0),
         "auto", {"CROSSCODER_BATCHTOPK_PALLAS": "1"}),
        # fused BatchTopK: global bisection + emit recomputed over
        # streamed encoder tiles (FLOPs ~3x the single matmul, HBM bytes
        # ~1 masked write instead of ~7 [B, dict] round-trips)
        ("batchtopk_fused",
         dict(activation="batchtopk", topk_k=32, l1_coeff=0.0,
              fused_encoder="on"),
         "auto", {"CROSSCODER_FUSED_TOPK_PALLAS": "1"}),
        ("jumprelu", dict(activation="jumprelu", l1_coeff=0.0), "auto", {}),
        # AuxK step cost: aux_dead_steps=1 keeps the dead set non-empty so
        # aux-on steps include the full aux path (approx_max_k ranking
        # over the masked [B,H] pre-acts, dense-matmul aux decode, fired
        # scatter) — the worst case. `topk_auxk` is the production
        # recommendation (aux_every=8 amortization; quality within noise
        # of per-step, artifacts/ACT_QUALITY_r05.json); `_perstep` keeps
        # the aux loss on EVERY step (the Gao recipe, the BENCH_r05
        # 391 ms number) but caches the dead mask at log_every cadence
        # (aux_mask_every=0): reuse steps drop the tracker compare and the
        # serial dependency on the previous step's fired scatter.
        # `_perstep_exact` is the fully unamortized per-step-mask recipe
        # for comparison.
        ("topk_auxk",
         dict(activation="topk", topk_k=32, l1_coeff=0.0, aux_k=256,
              aux_dead_steps=1, aux_every=8),
         "auto", {}),
        ("topk_auxk_perstep",
         dict(activation="topk", topk_k=32, l1_coeff=0.0, aux_k=256,
              aux_dead_steps=1, aux_mask_every=0),
         "auto", {}),
        ("topk_auxk_perstep_exact",
         dict(activation="topk", topk_k=32, l1_coeff=0.0, aux_k=256,
              aux_dead_steps=1),
         "auto", {}),
        # sparse backward under the per-step AuxK recipe: the main tier
        # runs the (h, W_dec)-scoped sparse variant, the aux term reuses
        # the scatter plane when use_sparse_aux's gates pass (at B=4096,
        # aux_k=256 the 1M-pair aux list exceeds the kernel's VMEM cap,
        # so the aux VJP stays dense — the partial win of the
        # "re-measure topk_auxk_perstep" satellite; BENCH_r05: 391.43 ms)
        ("topk_auxk_perstep_sparse_bwd",
         dict(activation="topk", topk_k=32, l1_coeff=0.0, aux_k=256,
              aux_dead_steps=1, aux_mask_every=0, sparse_bwd="on",
              factored_decode="on"),
         "pallas", {"CROSSCODER_SPARSE_GRAD_PALLAS": "1"}),
    ]
    # legs that also report the fwd/bwd model-loss split (compiles two
    # extra programs per entry, so only where the split answers a
    # question: the sparse-backward A/B pair and the dense floor)
    split_fwd_bwd = {"topk_pallas", "topk_sparse_bwd", "jumprelu",
                     "batchtopk", "batchtopk_pallas", "topk_fused",
                     "topk_fused_int8", "batchtopk_fused"}
    steps = int(os.environ.get("BENCH_MATRIX_STEPS", 16))
    dicts = tuple(
        int(x) for x in os.environ.get(
            "BENCH_MATRIX_DICTS", f"{2**15},{2**16},{2**17}"
        ).split(",")
    )
    out = []
    for dict_size in dicts:
        if on_tpu:
            try:
                out.append(_kernel_parity(dict_size))
            except Exception as e:
                out.append({"dict_size": dict_size, "parity_ok": False,
                            "error": f"{type(e).__name__}: {str(e)[:200]}"})
        for label, overrides, impl, env in variants:
            if env and not on_tpu:
                continue               # kernel opt-in legs are TPU-only
            cfg = _make_cfg(dict_size=dict_size, **overrides)
            if impl == "pallas":
                from crosscoder_tpu.ops import topk_pallas

                if not on_tpu:
                    continue           # interpret mode is not a benchmark
                probe = jax.ShapeDtypeStruct((1, dict_size), jnp.bfloat16)
                if not topk_pallas.supported(probe, 32):
                    # custom BENCH_MATRIX_DICTS width outside both kernel
                    # variants: don't silently time the dense fallback
                    # under the pallas label
                    out.append({"variant": label, "dict_size": dict_size,
                                "skipped": "kernel unsupported at this width"})
                    continue
            if cfg.sparse_bwd == "on":
                # sparse_bwd="on" with an unsupported scatter shape falls
                # back to the XLA scatter — sparse math but the measured-
                # slow path; don't time it under the sparse_bwd label
                from crosscoder_tpu.ops import sparse_grad, topk_pallas

                if not (topk_pallas.sparsify_supported(dict_size, cfg.topk_k)
                        and sparse_grad.decode_grad_supported(
                            dict_size, cfg.topk_k, cfg.n_sources, cfg.d_in,
                            cfg.batch_size)):
                    out.append({"variant": label, "dict_size": dict_size,
                                "skipped": "scatter kernel unsupported at "
                                           "this shape"})
                    continue
            if label == "batchtopk_pallas":
                from crosscoder_tpu.ops import topk_pallas

                probe = jax.ShapeDtypeStruct(
                    (cfg.batch_size, dict_size), jnp.bfloat16)
                if not topk_pallas.batchtopk_supported(probe, cfg.topk_k):
                    out.append({"variant": label, "dict_size": dict_size,
                                "skipped": "batchtopk kernel unsupported at "
                                           "this width"})
                    continue
            if cfg.fused_encoder == "on":
                # forced-fused legs must actually time the megakernel,
                # not its dense fallback
                from crosscoder_tpu.ops import fused_encoder_topk as fek

                qb = cfg.quant_block if cfg.quant_encoder else 0
                if not fek.supported(cfg.batch_size,
                                     cfg.n_sources * cfg.d_in, dict_size,
                                     cfg.topk_k, jnp.bfloat16, qb):
                    out.append({"variant": label, "dict_size": dict_size,
                                "skipped": "fused kernel unsupported at "
                                           "this shape"})
                    continue
            act_ops.set_topk_impl(impl)
            try:
                with _env(env):
                    r = bench_step(cfg, steps, warmup=2)
                    entry = {"variant": label, "dict_size": dict_size, **r}
                    if label in split_fwd_bwd:
                        entry.update(bench_fwd_bwd(cfg, steps))
                    if cfg.fused_encoder == "on":
                        try:
                            entry.update(_encoder_hbm_bytes(cfg))
                        except Exception as e:   # cost analysis is best-effort
                            entry["hbm_bytes_error"] = (
                                f"{type(e).__name__}: {str(e)[:120]}")
            except Exception as e:     # one OOM must not kill the bench
                entry = {"variant": label, "dict_size": dict_size,
                         "error": f"{type(e).__name__}: {str(e)[:200]}"}
            finally:
                act_ops.set_topk_impl("auto")
            log(f"[matrix] {entry}")
            out.append(entry)
    return out


def section_configs() -> list[dict]:
    """All five BASELINE.json scale-out configs at the train-step level —
    each config's acts/s/chip on one chip (the 8× path is per-chip parity
    × DP, so the per-chip number is the comparable unit):

    1. 2-model L13, dict 2^14 (the reference's exact trained shape);
    2. dict 2^15 + TopK(k=32) via the Pallas kernel;
    3. Gemma-2-9B width (d_in 3584), dict 2^16;
    4. 3-way diff (n_models=3);
    5. multi-layer {6,13,20} jointly (n_sources = 2×3 = 6).
    """
    steps = int(os.environ.get("BENCH_CONFIG_STEPS", 12))
    hp3 = ("blocks.6.hook_resid_pre", "blocks.13.hook_resid_pre",
           "blocks.20.hook_resid_pre")
    configs = [
        ("1_ref_shape", dict(d_in=2304, dict_size=2**14)),
        ("2_topk_pallas", dict(d_in=2304, dict_size=2**15, activation="topk",
                               topk_k=32, l1_coeff=0.0)),
        ("3_9b_width", dict(d_in=3584, dict_size=2**16)),
        ("4_three_way", dict(d_in=2304, dict_size=2**14, n_models=3)),
        ("5_multilayer", dict(d_in=2304, dict_size=2**14, hook_points=hp3)),
    ]
    out = []
    for label, overrides in configs:
        try:
            r = bench_step(_make_cfg(**overrides), steps, warmup=2)
            entry = {"config": label, **r}
        except Exception as e:
            entry = {"config": label,
                     "error": f"{type(e).__name__}: {str(e)[:200]}"}
        log(f"[configs] {entry}")
        out.append(entry)
    return out


def section_e2e() -> dict:
    """harvest→buffer→train on one chip — the number the reference pipeline
    actually bounds (harvest ≈ 2.5× the train step's FLOPs per row)."""
    # Harvest-quantum granularity for THIS box: each sub-scan dispatch
    # costs ~6-8 ms of host time through the single-core axon tunnel, so
    # fine segmentation (the library default SEG_LAYERS=3, right for
    # production hosts with ~100 us dispatch) costs ~10% e2e throughput
    # here. 14 = one segment per model: ~25.0k acts/s with the refresh
    # bubble at 24-32% of a median step across runs (vs ~22.5k / 2.5% at
    # 3) — the measured frontier is in ROUND5_NOTES §2; override to
    # re-measure. Resolved at use time by SegmentedHarvest.seg_layers().
    os.environ.setdefault("CROSSCODER_SEG_LAYERS", "14")
    import numpy as np
    from jax.sharding import NamedSharding, PartitionSpec as P

    from crosscoder_tpu.data.buffer import make_buffer
    from crosscoder_tpu.models import lm
    from crosscoder_tpu.parallel import mesh as mesh_lib
    from crosscoder_tpu.train.trainer import Trainer

    overrides = {}
    e2e_act = os.environ.get("BENCH_E2E_ACTIVATION", "")
    if e2e_act == "topk":              # BASELINE config 2's e2e number
        overrides = dict(activation="topk", topk_k=32, l1_coeff=0.0)
    elif e2e_act:
        # other activations would need their own loss knobs — refuse
        # rather than silently benching a mislabeled objective
        raise ValueError(f"BENCH_E2E_ACTIVATION supports 'topk', got {e2e_act!r}")

    tiny = os.environ.get("BENCH_TINY") == "1"    # CI/debug only
    if tiny:
        hook_layer, full = 2, lm.LMConfig.tiny()
        lm_cfg = full
        cfg = _make_cfg(
            d_in=lm_cfg.d_model, dict_size=256, batch_size=256, buffer_mult=16,
            model_batch_size=4, norm_calib_batches=2, seq_len=17,
            hook_point="blocks.2.hook_resid_pre",
            num_tokens=10**12, save_every=10**9, prefetch=True,
            **overrides,
        )
    else:
        hook_layer = 14
        full = lm.LMConfig.gemma2_2b()
        # a resid_pre hook at block L executes blocks 0..L-1 and captures at
        # the virtual layer L (lm._forward_impl n_scan), so only L layers of
        # params are ever touched; dropping the rest changes no executed op,
        # saves ~7.5 GB HBM
        lm_cfg = full.replace(n_layers=hook_layer)
        cfg = _make_cfg(
            batch_size=4096, buffer_mult=32, model_batch_size=4,
            norm_calib_batches=8, seq_len=1024,
            hook_point=f"blocks.{hook_layer}.hook_resid_pre",
            num_tokens=10**12, save_every=10**9, prefetch=True,
            # 0.5 = reference-parity harvest:serve; lower trades data
            # freshness for harvest FLOPs (see cfg.refill_frac)
            refill_frac=float(os.environ.get("BENCH_REFILL_FRAC", 0.5)),
            **overrides,
        )
    n_dev = len(jax.devices())
    mesh = mesh_lib.make_mesh(data_axis_size=n_dev, model_axis_size=1)

    shape_tag = "tiny" if tiny else "gemma-2-2b"
    log(f"[e2e] initializing 2× {shape_tag}-shaped params ...")
    params = [lm.init_params(jax.random.key(i), lm_cfg) for i in (0, 1)]
    rng = np.random.default_rng(0)
    tokens = rng.integers(0, lm_cfg.vocab_size, size=(2048, cfg.seq_len),
                          dtype=np.int32)

    # store placement: HBM by default on a single chip — zero host<->device
    # row traffic. BENCH_BUFFER=host measures the host-RAM path instead
    # (on a remote-TUNNEL client that path is transfer-bound: ~75 MB/step
    # at ~7 MB/s; on a local PCIe link the cost is negligible).
    buffer_device = os.environ.get("BENCH_BUFFER", "hbm")
    cfg = cfg.replace(buffer_device=buffer_device)
    # BENCH_QUANT=1: the block-scaled int8 store (cfg.quant_buffer) — the
    # acceptance A/B is this run vs the default at equal buffer_mult
    if os.environ.get("BENCH_QUANT") == "1":
        block = 256 if cfg.d_in % 256 == 0 else 16
        cfg = cfg.replace(quant_buffer=True, quant_block=block)
    t0 = time.perf_counter()
    buffer = make_buffer(
        cfg, lm_cfg, params, tokens,
        batch_sharding=NamedSharding(mesh, P("data", None)),
    )
    fill_s = time.perf_counter() - t0
    log(f"[e2e] calibration + first fill ({buffer.buffer_size} rows): {fill_s:.1f}s")

    trainer = Trainer(cfg, buffer, mesh=mesh)
    # warmup: compile both step variants + the serve path
    m = trainer.step()
    _sync(m["loss"])
    m = trainer.step(full_metrics=False)
    _sync(m["loss"])

    # phase A — steady-state throughput: enqueue, sync once at the end
    n_steps = int(os.environ.get("BENCH_E2E_STEPS", 40))
    t0 = time.perf_counter()
    for _ in range(n_steps):
        m = trainer.step(full_metrics=False)
    loss = _sync(m["loss"])
    dt = time.perf_counter() - t0

    # phase B — per-step profile (per-step sync adds one RTT to every step
    # equally; the refresh bubble shows up as max − median)
    times = []
    for _ in range(16):
        t1 = time.perf_counter()
        m = trainer.step(full_metrics=False)
        _sync(m["loss"])
        times.append(1000 * (time.perf_counter() - t1))
    trainer.close()
    times_sorted = sorted(times)
    median_ms = times_sorted[len(times) // 2]

    harvest_flops = _harvest_flops_per_row(full, hook_layer, cfg.n_models)
    a100_harvest = A100_PEAK * A100_UTIL / harvest_flops
    a100_e2e = 1.0 / (1.0 / BASELINE_A100_STEP + 1.0 / a100_harvest)
    acts = cfg.batch_size * n_steps / dt / n_dev
    out = {
        "acts_per_sec_chip": round(acts, 1),
        "vs_a100_e2e": round(acts / a100_e2e, 3),
        "a100_e2e_estimate": round(a100_e2e, 1),
        "harvest_gflop_per_row": round(harvest_flops / 1e9, 2),
        "first_fill_s": round(fill_s, 1),
        "step_ms_median": round(median_ms, 2),
        "step_ms_max": round(max(times), 2),
        "refresh_bubble_ms": round(max(times) - median_ms, 2),
        "n_steps_measured": n_steps,
        "loss_finite": bool(jnp.isfinite(loss)),
        "buffer_device": buffer_device,
        "quant_buffer": cfg.quant_buffer,
        "store_mbytes": round(buffer.store_nbytes() / 2**20, 1),
        "refill_frac": cfg.refill_frac,
        "workload": (
            f"{shape_tag} pair → blocks.{hook_layer} harvest → {buffer_device} "
            f"buffer(mult {cfg.buffer_mult}) → train dict {cfg.dict_size}, "
            f"batch {cfg.batch_size}"
        ),
    }
    log(f"[e2e] {out}")
    return out


def section_refill_overlap() -> dict:
    """Zero-bubble refill engine A/B (docs/SCALING.md "Zero-bubble
    refill"): the ``e2e`` harvest→buffer→train leg run with
    ``refill_overlap`` off vs on, at fine (SEG_LAYERS=3) and coarse
    (SEG_LAYERS=14) harvest segmentation. Per leg: the measured refill
    bubble fraction (obs ``take_blocked_s() / wall`` — exactly what
    ``perf/refill_bubble_frac`` logs), the max/median step ratio (the
    refresh spike), and acts/s/chip. Gate (ISSUE 14 acceptance): with
    overlap ON, bubble_frac <= 0.10 AND acts/s no worse than overlap-off
    at both segmentations."""
    import tempfile

    import numpy as np
    from jax.sharding import NamedSharding, PartitionSpec as P

    from crosscoder_tpu.data.buffer import make_buffer
    from crosscoder_tpu.models import lm
    from crosscoder_tpu.parallel import mesh as mesh_lib
    from crosscoder_tpu.train.trainer import Trainer

    tiny = os.environ.get("BENCH_TINY") == "1"    # CI/debug only
    if tiny:
        lm_cfg = lm.LMConfig.tiny()
        # dict_size is deliberately large relative to the tiny LM: the leg
        # needs the train step to dominate harvest compute per cycle, or
        # there is no window to hide the refill in (on real TPUs the e2e
        # config is train-dominated; see docs/SCALING.md cost model)
        base = dict(
            d_in=lm_cfg.d_model, dict_size=4096, batch_size=256,
            buffer_mult=16, model_batch_size=4, norm_calib_batches=2,
            seq_len=17, hook_point="blocks.2.hook_resid_pre",
        )
    else:
        hook_layer = 14
        # only the executed blocks' params, as in section_e2e
        lm_cfg = lm.LMConfig.gemma2_2b().replace(n_layers=hook_layer)
        base = dict(
            batch_size=4096, buffer_mult=32, model_batch_size=4,
            norm_calib_batches=8, seq_len=1024,
            hook_point=f"blocks.{hook_layer}.hook_resid_pre",
        )
    n_dev = len(jax.devices())
    mesh = mesh_lib.make_mesh(data_axis_size=n_dev, model_axis_size=1)
    params = [lm.init_params(jax.random.key(i), lm_cfg) for i in (0, 1)]
    rng = np.random.default_rng(0)
    tokens = rng.integers(0, lm_cfg.vocab_size,
                          size=(2048, base["seq_len"]), dtype=np.int32)

    n_steps = int(os.environ.get("BENCH_OVERLAP_STEPS", 48 if tiny else 32))
    seg_saved = os.environ.get("CROSSCODER_SEG_LAYERS")
    out: dict = {}
    try:
        # resolved at use time by SegmentedHarvest.seg_layers(): fine
        # segmentation = many dispatch quanta/serve (the host-cost regime
        # the overlap engine exists for), coarse = the device-bound regime
        for seg in (3, 14):
            os.environ["CROSSCODER_SEG_LAYERS"] = str(seg)
            for ov in ("off", "on"):
                cfg = _make_cfg(
                    **base, num_tokens=10**12, save_every=10**9,
                    prefetch=True, obs="on", refill_overlap=ov,
                    checkpoint_dir=tempfile.mkdtemp(),
                )
                buffer = make_buffer(
                    cfg, lm_cfg, params, tokens,
                    batch_sharding=NamedSharding(mesh, P("data", None)),
                )
                trainer = Trainer(cfg, buffer, mesh=mesh)
                m = trainer.step()            # compile both variants
                _sync(m["loss"])
                m = trainer.step(full_metrics=False)
                _sync(m["loss"])
                trainer._obs.take_blocked_s()   # reset the accumulator
                # per-step sync on every step of both arms: the sync RTT
                # cancels in the A/B, and per-step times expose the
                # refresh spike as max - median
                times = []
                t0 = time.perf_counter()
                for _ in range(n_steps):
                    t1 = time.perf_counter()
                    m = trainer.step(full_metrics=False)
                    _sync(m["loss"])
                    times.append(1000 * (time.perf_counter() - t1))
                wall = time.perf_counter() - t0
                blocked = trainer._obs.take_blocked_s()
                trainer.close()
                median_ms = sorted(times)[len(times) // 2]
                leg = {
                    "bubble_frac": round(min(1.0, blocked / wall), 4),
                    "acts_per_sec_chip": round(
                        cfg.batch_size * n_steps / wall / n_dev, 1),
                    "step_ms_median": round(median_ms, 2),
                    "step_ms_max": round(max(times), 2),
                    "max_over_median": round(max(times) / median_ms, 2),
                }
                log(f"[refill_overlap] seg{seg} overlap={ov}: {leg}")
                out[f"seg{seg}_{ov}"] = leg
            on, off = out[f"seg{seg}_on"], out[f"seg{seg}_off"]
            out[f"seg{seg}_gate_ok"] = bool(
                on["bubble_frac"] <= 0.10
                and on["acts_per_sec_chip"] >= off["acts_per_sec_chip"])
    finally:
        if seg_saved is None:
            os.environ.pop("CROSSCODER_SEG_LAYERS", None)
        else:
            os.environ["CROSSCODER_SEG_LAYERS"] = seg_saved
    out["n_steps_measured"] = n_steps
    out["gate_ok"] = bool(out.get("seg3_gate_ok")
                          and out.get("seg14_gate_ok"))
    log(f"[refill_overlap] gate_ok={out['gate_ok']}")
    return out


def section_harvest() -> dict:
    """The LM-harvest side on a mixed-length synthetic corpus — the
    dominant per-step cost outside the crosscoder, invisible in every
    BENCH_*.json before this section. A/B of the padded forward
    (run_with_cache_multi: every document padded to seq_len) vs the paged
    runtime (run_with_cache_multi_paged: documents packed into a dense
    token plane, per-document ragged attention — docs/SCALING.md "Harvest
    cost model"). Tokens/s counts REAL tokens for both arms, so the
    speedup is exactly the padding waste reclaimed."""
    import numpy as np

    from crosscoder_tpu.data import paging
    from crosscoder_tpu.models import lm

    tiny = os.environ.get("BENCH_TINY") == "1"    # CI/debug only
    if tiny:
        lm_cfg = lm.LMConfig.tiny()
        S, n_docs, reps, page = 16, 16, 2, 8
        hook = f"blocks.{lm_cfg.n_layers}.hook_resid_pre"
    else:
        # mid shape in the production FLOP regime — attention ~4% of the
        # per-token cost (Gemma-2-2B at seq 1024 is ~5%), matmuls dominate
        # — small enough that the CPU fallback finishes in seconds
        lm_cfg = lm.LMConfig(
            vocab_size=1024, d_model=384, n_layers=4, n_heads=6,
            n_kv_heads=2, head_dim=64, d_ff=1536, sliding_window=64,
            query_pre_attn_scalar=64.0, dtype="fp32",
        )
        S = int(os.environ.get("BENCH_HARVEST_SEQ", 128))
        n_docs = int(os.environ.get("BENCH_HARVEST_DOCS", 32))
        reps = int(os.environ.get("BENCH_HARVEST_STEPS", 4))
        page = 32
        hook = f"blocks.{lm_cfg.n_layers}.hook_resid_pre"
    params = [lm.init_params(jax.random.key(i), lm_cfg) for i in (0, 1)]
    rng = np.random.default_rng(5)
    # chat-shaped mixed-length corpus (most documents well under seq_len,
    # a few at it — the LmSys half of the production mix): ~40% padding
    # efficiency, inside the acceptance criterion's <= 60% regime;
    # single-token and max-length docs included
    lengths = rng.integers(max(1, S // 16), max(2, (5 * S) // 8), size=n_docs)
    lengths[0], lengths[1] = 1, S
    tokens = rng.integers(1, lm_cfg.vocab_size, size=(n_docs, S), dtype=np.int64)
    for d, ln in enumerate(lengths):
        tokens[d, ln:] = 0
    hooks = (hook,)
    eff = paging.padding_efficiency(lengths, S)

    def run_padded():
        return lm.run_with_cache_multi(params, jnp.asarray(tokens), lm_cfg, hooks)

    def run_paged():
        # packing runs per call — the host-side cost is part of the runtime
        return lm.run_with_cache_multi_paged(
            params, tokens, lengths, lm_cfg, hooks, page_size=page,
        )

    times = {}
    for name, fn in (("padded", run_padded), ("paged", run_paged)):
        jax.block_until_ready(fn())                   # compile
        t0 = time.perf_counter()
        for _ in range(reps):
            r = fn()
        jax.block_until_ready(r)
        times[name] = (time.perf_counter() - t0) / reps
    real_tokens = int(lengths.sum())
    out = {
        "padding_efficiency": round(eff, 4),
        "padded_step_ms": round(1000 * times["padded"], 2),
        "paged_step_ms": round(1000 * times["paged"], 2),
        "tokens_per_sec_padded": round(real_tokens / times["padded"], 1),
        "tokens_per_sec_paged": round(real_tokens / times["paged"], 1),
        "paged_speedup": round(times["padded"] / times["paged"], 3),
        "page_size": page,
        "workload": (
            f"2 models x {n_docs} docs, seq {S}, d_model {lm_cfg.d_model}, "
            f"{lm_cfg.n_layers} layers, mixed lengths "
            f"[{int(lengths.min())}, {int(lengths.max())}]"
        ),
    }
    log(f"[harvest] {out}")
    return out


def section_quant() -> dict:
    """The int8 data-plane quality gates (docs/SCALING.md "Quantized data
    plane"), recorded in the bench JSON so every round carries them:

    - roundtrip: per-row relative MSE of quantize→dequantize on a
      Gemma-2-2B-shaped activation probe ([4096 rows, 2 sources, d_in
      2304], heavy-tailed like calibrated residual streams), gated at
      QUANT_RELMSE_BOUND (1e-3): ~2x above the probe's measured 4.7e-4
      so outlier-distribution drift trips the gate, and still an order of
      magnitude below any arm-to-arm delta the `_act_quality` probe
      family resolves.
    - store bytes: quantized/bf16 ratio at the production block size
      (the HBM budget table's headline number).
    - grad all-reduce: quantized-mean vs exact-mean relative error on an
      8-virtual-device CPU mesh (compile+execute of the real
      parallel/quant_ar exchange), plus the error-feedback check — the
      RUNNING MEAN of compressed gradients converges to the exact mean.
    """
    import numpy as np
    from jax.sharding import Mesh

    from crosscoder_tpu.ops import quant
    from crosscoder_tpu.parallel import quant_ar

    block, d_in, n_sources, rows = 256, 2304, 2, 4096
    bound = float(os.environ.get("QUANT_RELMSE_BOUND", 1e-3))
    rng = np.random.default_rng(11)
    # heavy-tailed rows: gaussian bulk + sparse outlier features, the shape
    # that breaks per-TENSOR scaling and the reason scales are per block
    x = rng.normal(size=(rows, n_sources, d_in)).astype(np.float32)
    outliers = rng.random((1, n_sources, d_in)) < 0.01
    x = x * (1.0 + 9.0 * outliers)
    q, s = jax.device_get(quant.quantize_blocks(jnp.asarray(x), block))
    deq = quant.dequantize_np(np.asarray(q), np.asarray(s), np.float32)
    err = np.sum((deq - x) ** 2, axis=(-2, -1))
    power = np.sum(x ** 2, axis=(-2, -1))
    rel_mse = float(np.mean(err / power))

    store_ratio = quant.store_bytes((rows, n_sources, d_in), block) / (
        2.0 * rows * n_sources * d_in
    )

    # quantized grad all-reduce vs the exact mean, on however many devices
    # this process has (8 virtual in CI, 1 on a lone TPU chip → skipped)
    n_dev = len(jax.devices())
    ar = {}
    if n_dev >= 2:
        mesh = Mesh(np.asarray(jax.devices()), ("data",))
        g = rng.normal(size=(n_dev, 8, d_in)).astype(np.float32)
        ef0 = np.zeros((n_dev, quant_ar.padded_len(8 * d_in, n_dev, block)),
                       np.float32)
        fn = quant_ar.quantized_pmean_fn(mesh, block)
        exact = g.mean(axis=0)
        got, ef1 = fn(jnp.asarray(g), jnp.asarray(ef0))
        got = np.asarray(jax.device_get(got))
        one_shot = float(np.abs(got - exact).max() / np.abs(exact).max())
        # error feedback: same gradient re-reduced with the carried
        # residual — the running mean must converge on the exact mean
        acc, ef_dev = np.zeros_like(exact), jnp.asarray(ef0)
        steps = 8
        for i in range(steps):
            out, ef_dev = fn(jnp.asarray(g), ef_dev)
            acc += np.asarray(jax.device_get(out))[0]
        ef_mean = float(np.abs(acc / steps - exact).max() / np.abs(exact).max())
        ar = {
            "n_devices": n_dev,
            "one_shot_rel_err": round(one_shot, 7),
            "ef_running_mean_rel_err": round(ef_mean, 7),
            "ef_improves": bool(ef_mean < one_shot),
        }

    out = {
        "block": block,
        "roundtrip_rel_mse": float(np.format_float_scientific(
            rel_mse, precision=3, unique=False)),
        "rel_mse_bound": bound,
        "quality_gate_ok": bool(rel_mse < bound),
        "store_bytes_ratio_vs_bf16": round(store_ratio, 4),
        "grad_allreduce": ar,
        "workload": f"[{rows}, {n_sources}, {d_in}] heavy-tailed probe, "
                    f"block {block}",
    }
    log(f"[quant] {out}")
    return out


def section_obs() -> dict:
    """Observability-plane gates (docs/OBSERVABILITY.md), recorded every
    round so tracer cost can never silently regress:

    - **spans/s**: raw SpanTracer record throughput (enter + exit +
      event append + registry EMA);
    - **per-step overhead**: the Trainer stepped with obs off vs on at the
      reference shape on a fixed pre-generated batch (so both arms time
      step dispatch + telemetry, not synthetic-data generation). Gate:
      <1% step-time overhead (``overhead_gate_ok``).
    - **bubble fraction**: a short standard training leg with obs on —
      the ``perf/refill_bubble_frac`` the plane emits at every log point.
    """
    import tempfile

    from crosscoder_tpu.data.synthetic import SyntheticActivationSource
    from crosscoder_tpu.obs.trace import SpanTracer
    from crosscoder_tpu.train.trainer import Trainer

    tiny = os.environ.get("BENCH_TINY") == "1"    # CI/debug only
    shape = dict(d_in=32, dict_size=256, batch_size=64) if tiny else {}

    # tracer microbenchmark
    tracer = SpanTracer(os.path.join(tempfile.mkdtemp(), "t.json"))
    n_spans = 20_000
    t0 = time.perf_counter()
    for _ in range(n_spans):
        with tracer.span("bench"):
            pass
    spans_per_sec = n_spans / (time.perf_counter() - t0)

    class FixedSource:
        """One pre-generated batch, re-served — production cost ~0, so
        the on/off A/B isolates the telemetry on the step path."""

        def __init__(self, cfg):
            self._batch = SyntheticActivationSource(cfg).next()

        def next(self):
            return self._batch

    steps = int(os.environ.get("BENCH_OBS_STEPS", 20 if tiny else 16))
    step_ms = {"off": float("inf"), "on": float("inf")}
    # two rounds per arm, min taken: the first Trainer in a process pays
    # one-time backend/init costs that would masquerade as (negative)
    # overhead on fast-step shapes
    for _round in range(2):
        for mode in ("off", "on"):
            cfg = _make_cfg(**shape, num_tokens=10**12, save_every=10**9,
                            obs=mode, prefetch=False,
                            checkpoint_dir=tempfile.mkdtemp())
            tr = Trainer(cfg, buffer=FixedSource(cfg))
            for _ in range(5):
                m = tr.step(full_metrics=False)
            _sync(m["loss"])
            t0 = time.perf_counter()
            for _ in range(steps):
                m = tr.step(full_metrics=False)
            _sync(m["loss"])
            step_ms[mode] = min(
                step_ms[mode], 1000 * (time.perf_counter() - t0) / steps
            )
            tr.close()
    overhead = step_ms["on"] / step_ms["off"] - 1.0

    # bubble fraction on a standard (synthetic-production) training leg
    cfg = _make_cfg(**shape, num_tokens=10**12, save_every=10**9, obs="on",
                    log_every=8, prefetch=False,
                    checkpoint_dir=tempfile.mkdtemp())
    tr = Trainer(cfg)
    tr.train(num_steps=17)                      # logs at 0, 8, 16
    bubble = tr._obs.registry.get_gauge("perf/refill_bubble_frac")

    out = {
        "spans_per_sec": round(spans_per_sec, 1),
        "span_overhead_us": round(1e6 / spans_per_sec, 3),
        "step_ms_obs_off": round(step_ms["off"], 3),
        "step_ms_obs_on": round(step_ms["on"], 3),
        "obs_overhead_frac": round(overhead, 5),
        "overhead_gate_ok": bool(overhead < 0.01),
        "refill_bubble_frac": (round(float(bubble), 4)
                               if bubble is not None else None),
        "workload": (f"{'tiny' if tiny else 'reference'} shape, "
                     f"{steps}-step on/off A/B on a fixed batch"),
    }
    log(f"[obs] {out}")
    return out


def section_dash() -> dict:
    """Dashboard generation at the reference's recorded sae_vis workload:
    128 seqs × 3 features, minibatch 4 (BASELINE.md: fwd 14.08 s + feature
    acts 3.71 s ≈ 19 s total on A100)."""
    import numpy as np

    from crosscoder_tpu.analysis.dashboards import FeatureVisConfig, FeatureVisData
    from crosscoder_tpu.models import crosscoder as cc
    from crosscoder_tpu.models import lm

    tiny = os.environ.get("BENCH_TINY") == "1"    # CI/debug only
    if tiny:
        hook_layer, lm_cfg = 2, lm.LMConfig.tiny()
        cfg = _make_cfg(d_in=lm_cfg.d_model, dict_size=256, enc_dtype="fp32")
        n_seqs, seq_len = 16, 24
    else:
        hook_layer = 14
        lm_cfg = lm.LMConfig.gemma2_2b().replace(n_layers=hook_layer)
        cfg = _make_cfg(dict_size=2**14, enc_dtype="bf16")   # published shape
        n_seqs, seq_len = 128, 1024
    params = [lm.init_params(jax.random.key(i), lm_cfg) for i in (0, 1)]
    cc_params = cc.init_params(jax.random.key(2), cfg)
    rng = np.random.default_rng(1)
    tokens = rng.integers(0, lm_cfg.vocab_size, size=(n_seqs, seq_len), dtype=np.int32)
    vis_cfg = FeatureVisConfig(
        hook_point=f"blocks.{hook_layer}.hook_resid_pre",
        features=(7, 11, 13), minibatch_size_tokens=4,
    )

    def run() -> float:
        t0 = time.perf_counter()
        FeatureVisData.create(cc_params, cfg, lm_cfg, params, tokens, vis_cfg)
        return time.perf_counter() - t0

    first = run()
    warm = run()
    out = {
        # includes whatever trace/compile cost remains; depends on the
        # persistent compile cache state (headline compile_cache field)
        "first_call_s": round(first, 2),
        "steady_s": round(warm, 2),
        "reference_a100_s": 19.0,
        "vs_reference": round(19.0 / warm, 2),
        "workload": f"{n_seqs} seqs × 3 features, minibatch 4, "
                    f"{'tiny' if tiny else 'gemma-2-2b'} shapes",
    }
    log(f"[dash] {out}")
    return out


def section_elastic() -> dict:
    """Recovery SLO of elastic membership (docs/resilience.md "Elastic
    membership"): the 2-process preemption drill — chaos ``die@7`` kills
    one host mid-run; the survivor must detect, re-mesh over its local
    devices, restore-with-respec, and finish with a post-remesh loss
    trajectory bitwise equal to a clean restart. The drill always runs
    CPU subprocesses with their own virtual-device worlds, so this leg
    behaves identically on a TPU box."""
    from crosscoder_tpu.resilience.elastic_drill import (run_autoscale_drill,
                                                         run_drill)

    report = run_drill()
    out = {
        "remesh_ms": report["remesh_ms"],
        "bitwise_equal": bool(report["bitwise_equal"]),
        "resume_step": report["resume_step"],
        "post_steps": len(report["post_losses"]),
        "workload": "2-proc CPU drill: die@7 → detect → remesh → "
                    "respec-restore → bitwise-equal finish; then the full "
                    "autoscale cycle (die → shrink → return-grant → "
                    "debounced rejoin → grow → bitwise-equal finish)",
    }
    # scale-UP SLO (docs/resilience.md "Elastic scale-up"): the full
    # grow/shrink/grow cycle, with the grow recovery (boundary save +
    # rendezvous + wider-world re-formation + restore) timed separately
    # from the end-to-end drill wall time
    t0 = time.perf_counter()
    cycle = run_autoscale_drill()
    out.update({
        "grow_ms": cycle["grow_ms"],
        "autoscale_bitwise_equal": bool(cycle["bitwise_equal"]),
        "joiner_equal": bool(cycle["joiner_equal"]),
        "autoscale_cycle_s": round(time.perf_counter() - t0, 2),
        "autoscale_resume_step": cycle["resume_step"],
    })
    log(f"[elastic] {out}")
    return out


def section_fleet() -> dict:
    """Fleet amortization A/B (docs/SCALING.md "Fleet amortization"): N
    shape-identical tenants trained as ONE vmapped cohort off one harvest
    stream vs N sequential solo runs, each paying its own calibration,
    fill, and per-step refill harvest. Reported as aggregate acts/s/chip
    both ways plus ``harvest_amortization`` (their ratio — the
    sweep-level speedup). Gate (ISSUE 17 acceptance): ratio >= 3.0 with
    every loss finite; dict is kept small so harvest dominates, the
    regime the fleet exists for."""
    import numpy as np
    from jax.sharding import NamedSharding, PartitionSpec as P

    from crosscoder_tpu.data.buffer import make_buffer
    from crosscoder_tpu.models import lm
    from crosscoder_tpu.parallel import mesh as mesh_lib
    from crosscoder_tpu.train.fleet import FleetScheduler
    from crosscoder_tpu.train.trainer import Trainer

    tiny = os.environ.get("BENCH_TINY") == "1"    # CI/debug only
    n_tenants = int(os.environ.get("BENCH_FLEET_TENANTS", 4))
    n_steps = int(os.environ.get("BENCH_FLEET_STEPS", 40))
    if tiny:
        # 12 scanned layers: deep enough that the harvest (the shared
        # cost) dominates the tiny crosscoder step, the production regime
        hook_layer, lm_cfg = 12, lm.LMConfig.tiny(n_layers=12)
        shape = dict(d_in=lm_cfg.d_model, dict_size=64, batch_size=256,
                     buffer_mult=16, model_batch_size=4,
                     norm_calib_batches=2, seq_len=17,
                     hook_point="blocks.12.hook_resid_pre")
    else:
        hook_layer = 14
        lm_cfg = lm.LMConfig.gemma2_2b().replace(n_layers=hook_layer)
        shape = dict(dict_size=2048, batch_size=4096, buffer_mult=32,
                     model_batch_size=4, norm_calib_batches=8,
                     seq_len=1024,
                     hook_point=f"blocks.{hook_layer}.hook_resid_pre")
    n_dev = len(jax.devices())
    mesh = mesh_lib.make_mesh(data_axis_size=n_dev, model_axis_size=1)
    batch_sh = NamedSharding(mesh, P("data", None))
    params = [lm.init_params(jax.random.key(i), lm_cfg) for i in (0, 1)]
    rng = np.random.default_rng(0)
    tokens = rng.integers(0, lm_cfg.vocab_size,
                          size=(2048, shape["seq_len"]), dtype=np.int32)

    def cfg_for(**kw):
        return _make_cfg(num_tokens=10**12, save_every=10**9,
                         **{**shape, **kw})

    # N sequential solo runs: each pays its own per-step refill harvest —
    # exactly the cost the fleet amortizes. Steady-state measurement:
    # compiles and the first fill stay outside the timed window on BOTH
    # sides (acts/s is a rate; one-time setup is reported separately).
    solo_wall = 0.0
    fill_s = 0.0
    losses = []
    for i in range(n_tenants):
        cfg = cfg_for(seed=i + 1)
        t0 = time.perf_counter()
        buf = make_buffer(cfg, lm_cfg, params, tokens,
                          batch_sharding=batch_sh)
        tr = Trainer(cfg, buf, mesh=mesh)
        for _ in range(4):
            tr.step(full_metrics=False)       # warmup: compile + serve path
        fill_s += time.perf_counter() - t0
        t0 = time.perf_counter()
        for _ in range(n_steps):
            m = tr.step(full_metrics=False)
        losses.append(_sync(m["loss"]))
        solo_wall += time.perf_counter() - t0
        tr.close()
        log(f"[fleet] solo {i + 1}/{n_tenants}: "
            f"cumulative {solo_wall:.1f}s steady + {fill_s:.1f}s setup")

    tenants = ";".join(f"t{i}:seed={i + 1}" for i in range(n_tenants))
    cfg = cfg_for(fleet="on", fleet_tenants=tenants)
    t0 = time.perf_counter()
    buf = make_buffer(cfg, lm_cfg, params, tokens, batch_sharding=batch_sh)
    fl = FleetScheduler(cfg, buffer=buf, mesh=mesh, checkpoint=False)
    for _ in range(4):
        fl.step_all(full_metrics=False)
    fleet_fill_s = time.perf_counter() - t0
    mets: dict = {}
    t0 = time.perf_counter()
    for _ in range(n_steps):
        mets = fl.step_all(full_metrics=False)
    losses += [_sync(mets[n]["loss"]) for n in fl.active()]
    fleet_wall = time.perf_counter() - t0
    buf.close()

    total_acts = n_tenants * n_steps * cfg.batch_size
    fleet_agg = total_acts / fleet_wall / n_dev
    solo_agg = total_acts / solo_wall / n_dev
    ratio = fleet_agg / solo_agg
    finite = all(bool(jnp.isfinite(x)) for x in losses)
    out = {
        "n_tenants": n_tenants,
        "n_steps": n_steps,
        "agg_acts_per_sec_chip": round(fleet_agg, 1),
        "solo_agg_acts_per_sec_chip": round(solo_agg, 1),
        "harvest_amortization": round(ratio, 2),
        "fleet_gate_ok": bool(ratio >= 3.0 and finite),
        "loss_finite": finite,
        "solo_setup_s": round(fill_s, 1),
        "fleet_setup_s": round(fleet_fill_s, 1),
        "workload": (
            f"{n_tenants}× seed tenants as one vmapped cohort off one "
            f"{'tiny' if tiny else 'gemma-2-2b'}-shaped harvest stream vs "
            f"{n_tenants} sequential solo runs (dict {cfg.dict_size}, "
            f"batch {cfg.batch_size})"
        ),
    }
    log(f"[fleet] {out}")
    return out


def section_serve() -> dict:
    """The serving path's latency SLO (docs/SERVING.md): per-request
    p50/p99/max through the continuous-batched harvest→encode loop at
    batch 1/8/64, saturated req/s, and the two gates the path promises —
    p99 <= 3*p50 at batch 8 (tail discipline: with AOT buckets and
    deadline flushes there is no legitimate source of a fat tail at a
    fixed batch) and ZERO compiles after warmup (every request hits a
    prewarmed bucket executable). Tiny-LM shapes: the section measures
    the engine's batching/dispatch machinery, which is shape-independent;
    the harvest cost model for real shapes is section ``harvest``."""
    from crosscoder_tpu.serve import smoke as serve_smoke

    tiny = os.environ.get("BENCH_TINY") == "1"    # CI/debug only
    reps = int(os.environ.get("BENCH_SERVE_REPS", 8 if tiny else 30))
    t0 = time.perf_counter()
    eng, cfg, lm_cfg, _lm_params, _cc_params = serve_smoke.build_engine(
        serve_max_batch=64)
    warm_compiles = eng.warmup()
    warmup_s = time.perf_counter() - t0
    log(f"[serve] warmup: {warm_compiles} executables over "
        f"{len(eng.buckets)} buckets in {warmup_s:.1f}s")

    legs = [serve_smoke.latency_leg(eng, cfg, lm_cfg, b, reps)
            for b in (1, 8, 64)]
    at8 = next(l for l in legs if l["batch"] == 8)
    out = {
        "batches": {str(l["batch"]): {k: l[k] for k in
                                      ("p50_ms", "p99_ms", "max_ms")}
                    for l in legs},
        "req_s_saturated": legs[-1]["req_s"],   # batch-64 = packed planes
        "p50_ms_b8": at8["p50_ms"],
        "p99_ms_b8": at8["p99_ms"],
        "serve_gate_ok": at8["p99_ms"] <= 3.0 * at8["p50_ms"],
        "warmup_s": round(warmup_s, 1),
        "warmup_compiles": warm_compiles,
        "compiles_after_warmup": eng.compiles_after_warmup,
        "zero_compiles_ok": eng.compiles_after_warmup == 0,
    }
    log(f"[serve] {out}")
    return out


def section_tune() -> dict:
    """The autotuner end to end (docs/TUNING.md): the full two-stage
    search over the train data-plane lattice at the bench shape, the
    tuned-vs-default measured comparison, and the stage-1 serve-p99
    prediction for the serve knob ladder. Gates: the pinned winner's
    measured acts/s/chip ≥ the default knobs' (holds by construction —
    the default candidate is always calibrated and the winner is chosen
    on measured score) and stage-1 pricing added exactly ONE step
    compile for the whole data-plane lattice (the ``aot_get`` reuse the
    zero-cost-off contract promises)."""
    import tempfile

    from crosscoder_tpu.obs.registry import MetricsRegistry
    from crosscoder_tpu.tune import tune
    from crosscoder_tpu.tune.lattice import (default_axes, enumerate_lattice,
                                             rank_candidates)
    from crosscoder_tpu.utils import compile_cache

    tiny = os.environ.get("BENCH_TINY") == "1"    # CI/debug only
    shape = dict(d_in=32, dict_size=256, batch_size=64) if tiny else {}
    cfg = _make_cfg(**shape, num_tokens=10**12, save_every=10**9,
                    prefetch=False, checkpoint_dir=tempfile.mkdtemp())
    axes = {
        "prefetch": (False, True),
        "refill_frac": (0.25, 0.5),
        "refill_dispatch_batch": (4, 8),
    }
    steps = int(os.environ.get("BENCH_TUNE_STEPS", 3 if tiny else 8))
    reg = MetricsRegistry()

    def tune_step_compiles() -> int:
        return sum(1 for k in compile_cache._AOT_CACHE
                   if isinstance(k, tuple) and k and k[0] == "tune_step")

    before = tune_step_compiles()
    out_path = os.path.join(tempfile.mkdtemp(), "TUNED.json")
    art = tune(cfg, "train", axes=axes, top_k=2, out_path=out_path,
               steps=steps, warmup=1, seed=0, registry=reg)
    pricing_compiles = tune_step_compiles() - before

    default_knobs = {k: getattr(cfg, k) for k in axes}
    rows = art.search.get("candidates", [])
    default_row = next((r for r in rows if r.get("knobs") == default_knobs),
                       None)
    tuned_score = float(art.measured.get("score", 0.0))
    default_score = (float(default_row["measured_score"])
                     if default_row and default_row.get("measured_score")
                     is not None else None)

    # serve objective: stage-1 ranking over the bucket/wait/page ladder
    # (prediction only — the measured serve p99 is section ``serve``'s
    # job; here we report what the tuner would pin and why)
    scfg = cfg.replace(serve="on")
    serve_cands, _ = enumerate_lattice(scfg, default_axes(scfg, "serve"))
    serve_ranked = rank_candidates(serve_cands, "serve", 1, seed=0)
    serve_default = {k: getattr(scfg, k)
                     for k in ("serve_max_batch", "serve_max_wait_ms",
                               "page_size")}
    sdef = next((c for c in serve_ranked if c.knobs == serve_default), None)
    out = {
        "tuned_knobs": art.knobs,
        "tuned_acts_per_sec_chip": round(tuned_score, 2),
        "default_acts_per_sec_chip": (round(default_score, 2)
                                      if default_score is not None else None),
        "tuned_vs_default": (round(tuned_score / default_score, 4)
                             if default_score else None),
        "tune_gate_ok": bool(default_score is None
                             or tuned_score >= default_score),
        "pricing_step_compiles": pricing_compiles,
        "aot_reuse_ok": pricing_compiles <= 1,
        "rejected_contract": reg.get_count("tune/rejected_contract"),
        "n_candidates": art.search["n_candidates"],
        "serve_p99_tuned_ms": (round(-serve_ranked[0].score, 3)
                               if serve_ranked else None),
        "serve_p99_default_ms": (round(-sdef.score, 3)
                                 if sdef is not None else None),
        "serve_knobs_tuned": serve_ranked[0].knobs if serve_ranked else None,
        "artifact": out_path,
        "workload": (f"{'tiny' if tiny else 'reference'} shape, "
                     f"{len(axes)}-knob lattice, {steps}-step windows"),
    }
    log(f"[tune] {out}")
    return out


def section_compile_cache() -> dict:
    """The persistent AOT tier end to end (docs/SCALING.md "Persistent
    compile cache"): two REAL processes run the serve warmup against one
    ``compile_cache_dir`` — the first cold (populates the tier), the
    second warm. Gates: the warm process performs ZERO XLA compiles
    (the whole bucket ladder deserializes from disk) and its warmup
    wall is ≤ 0.3× the cold process's."""
    import subprocess
    import tempfile

    cache_dir = tempfile.mkdtemp(prefix="bench_compile_cache_")
    here = os.path.dirname(os.path.abspath(__file__))
    env = dict(os.environ)
    env["PYTHONPATH"] = here + os.pathsep + env.get("PYTHONPATH", "")

    def one(tag: str) -> dict:
        proc = subprocess.run(
            [sys.executable, "-m", "crosscoder_tpu.serve.warm_start",
             "--cache-dir", cache_dir],
            capture_output=True, text=True, cwd=here, env=env, timeout=900)
        if proc.returncode != 0:
            raise RuntimeError(
                f"{tag} warm_start failed: {proc.stderr[-300:]}")
        return json.loads(proc.stdout.strip().splitlines()[-1])["warm_start"]

    cold = one("cold")
    warm = one("warm")
    speedup = (cold["warmup_ms"] / warm["warmup_ms"]
               if warm["warmup_ms"] else float("inf"))
    out = {
        "cold_warmup_ms": cold["warmup_ms"],
        "warm_warmup_ms": warm["warmup_ms"],
        "warm_vs_cold": round(warm["warmup_ms"] / cold["warmup_ms"], 4)
        if cold["warmup_ms"] else None,
        "cold_compiles": cold["compiles"],
        "warm_compiles": warm["compiles"],
        "disk_entries": warm["disk_entries"],
        "warm_disk_hits": warm["disk_hits"],
        "warm_speedup": round(speedup, 2),
        "zero_compiles_warm_ok": warm["compiles"] == 0,
        "warm_wall_gate_ok": warm["warmup_ms"] <= 0.3 * cold["warmup_ms"],
        "workload": "tiny-LM serve warmup ladder, 2 processes, 1 cache dir",
    }
    log(f"[compile_cache] {out}")
    return out


# stdout-summary projection: per section, the fields worth the 2 KB line
_SUMMARY_KEYS = {
    "step": ("acts_per_sec_chip", "vs_a100_step"),
    "e2e": ("acts_per_sec_chip", "vs_a100_e2e", "step_ms_median",
            "refresh_bubble_ms", "loss_finite"),
    "refill_overlap": ("gate_ok", "seg3_gate_ok", "seg14_gate_ok"),
    "harvest": ("padding_efficiency", "paged_step_ms", "paged_speedup"),
    "quant": ("roundtrip_rel_mse", "quality_gate_ok"),
    "obs": ("obs_overhead_frac", "overhead_gate_ok"),
    "dash": ("steady_s", "vs_reference"),
    "elastic": ("remesh_ms", "bitwise_equal", "grow_ms",
                "autoscale_cycle_s"),
    "fleet": ("agg_acts_per_sec_chip", "solo_agg_acts_per_sec_chip",
              "harvest_amortization", "fleet_gate_ok"),
    "serve": ("p50_ms_b8", "p99_ms_b8", "req_s_saturated",
              "serve_gate_ok", "zero_compiles_ok"),
    "tune": ("tuned_acts_per_sec_chip", "default_acts_per_sec_chip",
             "tuned_vs_default", "serve_p99_tuned_ms",
             "serve_p99_default_ms", "tune_gate_ok", "aot_reuse_ok"),
    "compile_cache": ("cold_warmup_ms", "warm_warmup_ms", "warm_vs_cold",
                      "warm_compiles", "disk_entries",
                      "zero_compiles_warm_ok", "warm_wall_gate_ok"),
}
_GATES = (("refill_overlap", "gate_ok"), ("quant", "quality_gate_ok"),
          ("obs", "overhead_gate_ok"), ("e2e", "loss_finite"),
          ("elastic", "bitwise_equal"),
          ("elastic", "autoscale_bitwise_equal"),
          ("fleet", "fleet_gate_ok"),
          ("serve", "serve_gate_ok"), ("serve", "zero_compiles_ok"),
          ("tune", "tune_gate_ok"), ("tune", "aot_reuse_ok"),
          ("compile_cache", "zero_compiles_warm_ok"),
          ("compile_cache", "warm_wall_gate_ok"))


def _compact(headline: dict, results: dict) -> dict:
    """The ≤2 KB stdout summary: headline + per-section key numbers +
    gate booleans + per-dict step-time ratios vs relu. Everything else
    lives in the detail artifact."""
    out = dict(headline)
    out["gates"] = {f"{name}.{key}": bool(sec[key])
                    for name, key in _GATES
                    if isinstance(sec := results.get(name), dict)
                    and key in sec}
    for name, keys in _SUMMARY_KEYS.items():
        sec = results.get(name)
        if not isinstance(sec, dict):
            continue
        if "error" in sec:
            out[name] = {"error": sec["error"][:120]}
        else:
            out[name] = {k: sec[k] for k in keys if k in sec}
    matrix = results.get("matrix")
    if isinstance(matrix, list):
        relu = {e.get("dict_size"): e.get("acts_per_sec_chip")
                for e in matrix if e.get("variant") == "relu"}
        out["relu_acts_per_dict"] = relu
        ratios = {}
        for e in matrix:
            if e.get("variant") == "relu":
                continue
            key = f"{e.get('variant', '?')}@{e.get('dict_size', '?')}"
            acts = e.get("acts_per_sec_chip")
            base = relu.get(e.get("dict_size"))
            if acts and base:
                ratios[key] = round(base / acts, 3)   # >1 = slower than relu
            else:
                ratios[key] = "skip" if "skipped" in e else "err"
        out["step_ratio_vs_relu"] = ratios
    configs = results.get("configs")
    if isinstance(configs, list):
        out["configs"] = {e.get("config", "?"):
                          e.get("acts_per_sec_chip",
                                "skip" if "skipped" in e else "err")
                          for e in configs}
    # the driver truncates the line at 2000 chars — drop the widest
    # tables first rather than ship an unparseable line
    for drop in ("step_ratio_vs_relu", "configs", "relu_acts_per_dict"):
        if len(json.dumps(out)) <= 1900:
            break
        out.pop(drop, None)
    return out


def main() -> None:
    # Output contract: stdout carries EXACTLY ONE machine-parseable JSON
    # line, emitted last AND compact — the driver truncates it at 2000
    # chars (BENCH_r05 shipped "parsed": null because the full-detail
    # line was ~8 KB). Full per-section detail goes to the artifact file.
    # Library/trainer progress prints go through plain print() → reroute
    # the whole module-level stdout to stderr for the run and write the
    # summary to the real stream at the very end.
    real_stdout = sys.stdout
    sys.stdout = sys.stderr
    try:
        headline, results = _run_sections()
        artifact = os.environ.get("BENCH_ARTIFACT", "BENCH_DETAIL.json")
        detail = dict(headline)
        detail.update(results)
        with open(artifact, "w") as f:
            json.dump(detail, f, indent=1, default=str)
        summary = _compact(headline, results)
        summary["detail"] = artifact
    finally:
        sys.stdout = real_stdout
    line = json.dumps(summary)
    assert len(line) <= 2000, (
        f"summary line is {len(line)} B; the driver caps at 2000")
    print(line, flush=True)


def _run_sections() -> dict:
    if os.environ.get("BENCH_CPU") == "1":
        jax.config.update("jax_platforms", "cpu")
    # persistent compile cache: the bench's wall time is dominated by
    # remote compiles (~30-60s each through the tunnel); a warm cache
    # turns a ~12 min run into ~4 min ($JAX_COMPILE_CACHE="" disables).
    from crosscoder_tpu.utils import compile_cache

    cache_dir = compile_cache.enable()
    try:
        cache_state = ("warm" if cache_dir and os.listdir(cache_dir) else
                       "cold" if cache_dir else "disabled")
    except OSError:
        cache_state = "cold"
    sections = os.environ.get(
        "BENCH_SECTIONS",
        "step,matrix,configs,e2e,refill_overlap,harvest,quant,obs,dash,"
        "elastic,fleet,serve,tune,compile_cache"
    ).split(",")
    results: dict = {}
    for name, fn in (("step", section_step), ("matrix", section_matrix),
                     ("configs", section_configs),
                     ("e2e", section_e2e),
                     ("refill_overlap", section_refill_overlap),
                     ("harvest", section_harvest),
                     ("quant", section_quant), ("obs", section_obs),
                     ("dash", section_dash),
                     ("elastic", section_elastic),
                     ("fleet", section_fleet),
                     ("serve", section_serve),
                     ("tune", section_tune),
                     ("compile_cache", section_compile_cache)):
        if name not in sections:
            continue
        try:
            results[name] = fn()
        except Exception as e:
            results[name] = {"error": f"{type(e).__name__}: {str(e)[:300]}"}
            log(f"[{name}] FAILED: {results[name]['error']}")

    e2e = results.get("e2e", {})
    step = results.get("step", {})
    if "acts_per_sec_chip" in e2e:
        headline = {
            "metric": "end-to-end harvest→buffer→train acts/sec/chip "
                      f"({e2e['workload']})",
            "value": e2e["acts_per_sec_chip"],
            "unit": "activations/s/chip",
            "vs_baseline": e2e["vs_a100_e2e"],
        }
    else:   # e2e skipped/failed: fall back to round-1's step-only headline
        headline = {
            "metric": "crosscoder train acts/sec/chip "
                      f"({step.get('workload', 'step section failed')})",
            "value": step.get("acts_per_sec_chip"),
            "unit": "activations/s/chip",
            "vs_baseline": step.get("vs_a100_step"),
        }
    headline["compile_cache"] = cache_state
    return headline, results


if __name__ == "__main__":
    main()
