"""Benchmark: crosscoder training-step throughput on one TPU chip.

Workload = BASELINE.json's headline config: Gemma-2-2B-shaped activations
(d_in 2304, n_models 2), batch 4096 rows/step (reference train.py:15),
dict_size 2^15, bf16 compute — the full train step (fwd, losses, bwd,
global-norm clip, Adam, schedules) as one donated jitted function.

Metric: activation rows consumed per second per chip.

``vs_baseline``: the reference publishes no throughput numbers
(BASELINE.md), so the denominator is an analytic single-A100 estimate for
the same torch workload, documented here so it stays fixed across rounds:
train step ≈ 3× forward FLOPs; forward ≈ 4·B·H·n·d FLOP ⇒ 1.81 GFLOP/row at
dict 2^15; A100 bf16 peak 312 TFLOP/s at a generous 45% utilization for
eager torch einsums ⇒ ~77k rows/s. vs_baseline = measured / 77_000.
(North star: ≥8× via 8-chip DP at per-chip parity — BASELINE.json.)

Prints exactly ONE JSON line.

Env knobs (debug/CI only; defaults are the headline workload): BENCH_DICT,
BENCH_BATCH, BENCH_STEPS, BENCH_CPU=1 (force the CPU backend).
"""

from __future__ import annotations

import json
import os
import time

import jax
import jax.numpy as jnp

BASELINE_A100_ACTS_PER_SEC = 77_000.0


def main() -> None:
    if os.environ.get("BENCH_CPU") == "1":
        jax.config.update("jax_platforms", "cpu")
    from crosscoder_tpu.config import CrossCoderConfig
    from crosscoder_tpu.parallel import mesh as mesh_lib
    from crosscoder_tpu.train import schedules
    from crosscoder_tpu.train.state import init_train_state, make_optimizer
    from crosscoder_tpu.train.trainer import make_train_step

    cfg = CrossCoderConfig(
        d_in=2304,
        dict_size=int(os.environ.get("BENCH_DICT", 2**15)),
        n_models=2,
        batch_size=int(os.environ.get("BENCH_BATCH", 4096)),
        enc_dtype="bf16",
        # bf16 masters+moments = the reference's exact dtype regime
        # (train.py:5: all-bf16 params and torch-Adam state); fp32 masters
        # are this framework's quality-upgrade default but a different
        # workload than the A100 baseline estimate.
        master_dtype=os.environ.get("BENCH_MASTER_DTYPE", "bf16"),
        log_backend="null",
    )
    n_dev = len(jax.devices())
    mesh = mesh_lib.make_mesh(data_axis_size=n_dev, model_axis_size=1)

    tx = make_optimizer(cfg, schedules.lr_schedule(cfg))
    state = init_train_state(jax.random.key(cfg.seed), cfg, tx)
    shardings = mesh_lib.state_shardings(mesh, state)
    state = jax.device_put(state, shardings)
    # production mix: metric-only reductions (l0/EV) are gated to log_every
    # steps (1% at the reference cadence), so the bare step is the
    # throughput-defining variant
    step_fn = make_train_step(cfg, mesh, tx, shardings, with_metrics=False)

    batch_sh = mesh_lib.batch_sharding(mesh)
    key = jax.random.key(0)
    batches = [
        jax.device_put(
            jax.random.normal(
                jax.random.fold_in(key, i),
                (cfg.batch_size, cfg.n_sources, cfg.d_in),
                dtype=jnp.bfloat16,
            ),
            batch_sh,
        )
        for i in range(4)
    ]
    # production serve path: raw bf16 rows + on-device per-source norm scale
    # (length tracks cfg.n_sources so future configs can't shape-mismatch;
    # 0.26 ≈ the Gemma-2-2B calibration factors, BASELINE.md)
    from jax.sharding import NamedSharding, PartitionSpec

    scale = jax.device_put(
        jnp.full((cfg.n_sources,), 0.26, jnp.float32),
        NamedSharding(mesh, PartitionSpec()),
    )

    # warmup / compile. NB: sync by FETCHING a scalar, not block_until_ready —
    # under a remote-tunnel TPU client block_until_ready can return before
    # the device has executed, which fakes ~1000x speedups; a device_get is
    # an honest round-trip on every backend.
    for i in range(3):
        state, metrics = step_fn(state, batches[i % 4], scale)
    float(jax.device_get(metrics["loss"]))

    n_steps = int(os.environ.get("BENCH_STEPS", 50))
    t0 = time.perf_counter()
    for i in range(n_steps):
        state, metrics = step_fn(state, batches[i % 4], scale)
    float(jax.device_get(metrics["loss"]))   # one ~70ms RTT amortized over n_steps
    dt = time.perf_counter() - t0

    acts_per_sec = cfg.batch_size * n_steps / dt
    per_chip = acts_per_sec / n_dev
    print(
        json.dumps(
            {
                "metric": (
                    f"crosscoder train acts/sec/chip (d_in {cfg.d_in}, dict {cfg.dict_size}, "
                    f"bf16 compute, {cfg.master_dtype} masters)"
                ),
                "value": round(per_chip, 1),
                "unit": "activations/s/chip",
                "vs_baseline": round(per_chip / BASELINE_A100_ACTS_PER_SEC, 3),
                "n_devices": n_dev,
                "step_ms": round(1000 * dt / n_steps, 2),
                "loss_finite": bool(jnp.isfinite(metrics["loss"]).item()),
            }
        )
    )


if __name__ == "__main__":
    main()
